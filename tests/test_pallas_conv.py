"""Fused Pallas conv backward-data + BN affine ≡ the unfused path.

The fused conv→BN op (``ops/pallas_conv.py``, the ``hl_cuda_cudnn``
fused conv/BN tier) must be numerically interchangeable with the plain
``lax.conv_general_dilated`` + batch-norm composition it replaces —
forward, running-stat updates, and gradients through every input, across
the 3×3 stride-1 family including edge shapes.  The network-level
peephole must fire exactly on the linear-conv→batch-norm pattern.  Runs
in Pallas interpret mode on CPU (same dispatch gate as hardware).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax

from paddle_tpu.ops import nn_ops, pallas_conv

EPS = 1e-5


@pytest.fixture
def rng():
    return np.random.RandomState(0)


def _inputs(rng, n, h, w, cin, cout, with_cb=True):
    x = jnp.asarray(rng.randn(n, h, w, cin).astype(np.float32)) * 0.5
    wt = jnp.asarray(rng.randn(3, 3, cin, cout).astype(np.float32)) * 0.1
    cb = (jnp.asarray(rng.randn(cout).astype(np.float32)) * 0.1
          if with_cb else None)
    scale = jnp.asarray(rng.rand(cout).astype(np.float32) + 0.5)
    bias = jnp.asarray(rng.randn(cout).astype(np.float32)) * 0.2
    rm = jnp.asarray(rng.randn(cout).astype(np.float32)) * 0.1
    rv = jnp.asarray(rng.rand(cout).astype(np.float32) + 0.5)
    return x, wt, cb, scale, bias, rm, rv


def _reference(x, w, cb, scale, bias, rm, rv, momentum=0.9,
               is_training=True):
    """Plain-jax oracle: lax conv + textbook batch norm, autodiffed."""
    dn = lax.conv_dimension_numbers(x.shape, w.shape,
                                    ("NHWC", "HWIO", "NHWC"))
    z = lax.conv_general_dilated(x, w, (1, 1), [(1, 1), (1, 1)],
                                 dimension_numbers=dn)
    if cb is not None:
        z = z + cb
    if not is_training:
        return (z - rm) * lax.rsqrt(rv + EPS) * scale + bias, rm, rv
    m = jnp.mean(z, (0, 1, 2))
    v = jnp.maximum(jnp.mean(jnp.square(z), (0, 1, 2)) - m * m, 0.0)
    y = (z - m) * lax.rsqrt(v + EPS) * scale + bias
    return y, momentum * rm + (1 - momentum) * m, \
        momentum * rv + (1 - momentum) * v


def _assert_close(got, want, rtol=2e-5, atol=2e-5):
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=rtol, atol=atol)


# ------------------------------------------------------------- dispatch
def test_dispatch_gate():
    ok = pallas_conv.fusable
    w3 = (3, 3, 64, 64)
    x4 = (2, 8, 8, 64)
    assert ok(x4, w3, 1, [(1, 1), (1, 1)], 1, 1, "NHWC")
    assert ok(x4, w3, 1, "SAME", 1, 1, "NHWC")
    assert ok(x4, w3, (1, 1), 1, (1, 1), 1, "NHWC")
    assert not ok(x4, w3, 2, 1, 1, 1, "NHWC")           # stride
    assert not ok(x4, w3, 1, 0, 1, 1, "NHWC")           # VALID pad
    assert not ok(x4, w3, 1, 1, 2, 1, "NHWC")           # dilation
    assert not ok(x4, w3, 1, 1, 1, 2, "NHWC")           # groups
    assert not ok(x4, (5, 5, 64, 64), 1, 2, 1, 1, "NHWC")  # 5×5
    assert not ok(x4, w3, 1, 1, 1, 1, "NCHW")           # layout
    assert not ok((2, 8, 8, 48), (3, 3, 48, 64), 1, 1, 1, 1,
                  "NHWC")                               # Cin % 64
    assert not ok((2, 8, 8, 64), (3, 3, 64, 48), 1, 1, 1, 1,
                  "NHWC")                               # Cout % 64
    # ResNet-50's whole 3×3 family tiles; a hypothetical giant doesn't
    assert pallas_conv.fused_ok(56, 56, 64, 64)
    assert pallas_conv.fused_ok(28, 28, 128, 128)
    assert pallas_conv.fused_ok(14, 14, 256, 256)
    assert pallas_conv.fused_ok(7, 7, 512, 512)
    assert not pallas_conv.fused_ok(224, 224, 256, 256)  # VMEM


# --------------------------------------------------- fused ≡ reference
@pytest.mark.parametrize("shape", [
    (2, 5, 7, 64, 64),      # odd H/W, the smallest fused channels
    (1, 4, 4, 128, 64),     # Cin ≠ Cout, contracting
    (2, 3, 3, 64, 128),     # expanding, spatial == kernel
])
def test_fused_forward_and_stats_match_reference(rng, shape):
    n, h, w, cin, cout = shape
    args = _inputs(rng, n, h, w, cin, cout)
    assert pallas_conv.fusable((n, h, w, cin), (3, 3, cin, cout),
                               1, 1, 1, 1, "NHWC")
    got = nn_ops.conv2d_bn(*args, eps=EPS, is_training=True, padding=1)
    want = _reference(*args)
    for g, r in zip(got, want):
        _assert_close(g, r)


def test_fused_gradients_match_reference(rng):
    n, h, w, cin, cout = 2, 5, 7, 64, 64
    x, wt, cb, scale, bias, rm, rv = _inputs(rng, n, h, w, cin, cout)
    cot = jnp.asarray(rng.randn(n, h, w, cout).astype(np.float32))

    def loss_fused(x, wt, cb, scale, bias):
        y, _, _ = nn_ops.conv2d_bn(x, wt, cb, scale, bias, rm, rv,
                                   eps=EPS, is_training=True, padding=1)
        return jnp.sum(y * cot)

    def loss_ref(x, wt, cb, scale, bias):
        y, _, _ = _reference(x, wt, cb, scale, bias, rm, rv)
        return jnp.sum(y * cot)

    args = (x, wt, cb, scale, bias)
    g_fused = jax.grad(loss_fused, argnums=(0, 1, 2, 3, 4))(*args)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2, 3, 4))(*args)
    # conv bias pre-BN is analytically gradient-free (BN subtracts the
    # mean), so both sides are f32 noise around 0 — compare by atol
    # scaled to the other gradients' magnitude
    names = ["dx", "dw", "dconv_bias", "dscale", "dbias"]
    for name, gf, gr in zip(names, g_fused, g_ref):
        tol = dict(rtol=3e-4, atol=1e-3) if name == "dconv_bias" \
            else dict(rtol=3e-4, atol=3e-5)
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   err_msg=name, **tol)


def test_fused_gradients_no_conv_bias(rng):
    n, h, w, cin, cout = 1, 4, 6, 64, 64
    x, wt, _, scale, bias, rm, rv = _inputs(rng, n, h, w, cin, cout,
                                            with_cb=False)
    cot = jnp.asarray(rng.randn(n, h, w, cout).astype(np.float32))

    def loss(fn, x, wt, scale, bias):
        y, _, _ = fn(x, wt, None, scale, bias, rm, rv)
        return jnp.sum(y * cot)

    fused = lambda *a: nn_ops.conv2d_bn(*a, eps=EPS, is_training=True,
                                        padding=1)
    ref = lambda *a: _reference(*a)
    argnums = (0, 1, 2, 3)
    g_fused = jax.grad(lambda *a: loss(fused, *a), argnums=argnums)(
        x, wt, scale, bias)
    g_ref = jax.grad(lambda *a: loss(ref, *a), argnums=argnums)(
        x, wt, scale, bias)
    for gf, gr in zip(g_fused, g_ref):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   rtol=3e-4, atol=3e-5)


# ------------------------------------------------- fallback equivalence
@pytest.mark.parametrize("shape", [
    (2, 5, 5, 48, 64),      # Cin off-tile → plain path
    (2, 5, 5, 3, 16),       # the resnet_cifar10 stem shapes
])
def test_edge_channels_fall_back_and_match(rng, shape):
    n, h, w, cin, cout = shape
    args = _inputs(rng, n, h, w, cin, cout)
    assert not pallas_conv.fusable((n, h, w, cin), (3, 3, cin, cout),
                                   1, 1, 1, 1, "NHWC")
    got = nn_ops.conv2d_bn(*args, eps=EPS, is_training=True, padding=1)
    want = _reference(*args)
    for g, r in zip(got, want):
        _assert_close(g, r)


def test_eval_mode_matches_composition(rng):
    n, h, w, c = 2, 5, 7, 64
    args = _inputs(rng, n, h, w, c, c)
    got = nn_ops.conv2d_bn(*args, eps=EPS, is_training=False, padding=1)
    want = _reference(*args, is_training=False)
    for g, r in zip(got, want):
        _assert_close(g, r)


def test_fused_matches_under_bf16_policy(rng):
    """The production-default bf16 policy: fused and unfused paths agree
    within bf16 rounding (both compute the conv in bf16)."""
    from paddle_tpu.utils import FLAGS

    FLAGS.set("bf16_activations", True)
    try:
        n, h, w, c = 2, 4, 4, 64
        x, wt, cb, scale, bias, rm, rv = _inputs(rng, n, h, w, c, c)
        y, _, _ = nn_ops.conv2d_bn(x, wt, cb, scale, bias, rm, rv,
                                   eps=EPS, is_training=True, padding=1)
        z = nn_ops.conv2d(x, wt, stride=1, padding=1) + cb
        y2, _, _ = nn_ops.batch_norm(z, scale, bias, rm, rv, eps=EPS,
                                     is_training=True)
        np.testing.assert_allclose(np.asarray(y, np.float32),
                                   np.asarray(y2, np.float32),
                                   rtol=3e-2, atol=3e-2)
    finally:
        FLAGS.set("bf16_activations", False)


# ----------------------------------------------------- network peephole
def _build_net(conv_act=None, filter_size=3, stride=1, padding=1,
               second_consumer=False, channels=64):
    from paddle_tpu.config import dsl
    from paddle_tpu.config.dsl import config_scope
    from paddle_tpu.data.feeder import dense_vector
    from paddle_tpu.layers.network import NeuralNetwork

    img_sz = 6
    with config_scope():
        img = dsl.data("image", dense_vector(channels * img_sz * img_sz),
                       height=img_sz, width=img_sz)
        conv = dsl.img_conv(
            img, filter_size=filter_size, num_filters=channels,
            stride=stride, padding=padding, num_channels=channels,
            act=conv_act or dsl.LinearActivation(), name="c1")
        bn = dsl.batch_norm(conv, act=dsl.ReluActivation(), name="bn1")
        if second_consumer:
            out = dsl.addto([bn, conv], name="sum")
            cfg = dsl.topology(out)
        else:
            cfg = dsl.topology(bn)
    return NeuralNetwork(cfg)


def test_peephole_fires_on_intended_pattern():
    from paddle_tpu.config.dsl import ReluActivation

    assert _build_net()._conv_bn_fuse == {"bn1": "c1"}
    # anything off-pattern must NOT fire
    assert _build_net(conv_act=ReluActivation())._conv_bn_fuse == {}
    assert _build_net(filter_size=5, padding=2)._conv_bn_fuse == {}
    assert _build_net(stride=2)._conv_bn_fuse == {}
    assert _build_net(padding=0)._conv_bn_fuse == {}
    # conv consumed by a second layer keeps its standalone value
    assert _build_net(second_consumer=True)._conv_bn_fuse == {}


def test_peephole_respects_non_layer_consumers():
    """Consumers that read values by name outside layer input lists —
    evaluators here — must block the fusion, or the conv's value would
    be missing from the forward values dict when they look it up."""
    from paddle_tpu.config import dsl
    from paddle_tpu.config.dsl import config_scope
    from paddle_tpu.data.feeder import dense_vector
    from paddle_tpu.layers.network import NeuralNetwork

    with config_scope():
        img = dsl.data("image", dense_vector(64 * 6 * 6), height=6,
                       width=6)
        conv = dsl.img_conv(img, filter_size=3, num_filters=64, stride=1,
                            padding=1, num_channels=64,
                            act=dsl.LinearActivation(), name="c1")
        bn = dsl.batch_norm(conv, act=dsl.ReluActivation(), name="bn1")
        cfg = dsl.topology(bn)
    cfg.evaluators.append({"type": "value_printer", "name": "vp",
                           "input_layer_name": "c1"})
    assert NeuralNetwork(cfg)._conv_bn_fuse == {}


def test_peephole_network_gradients_match_unfused(rng):
    net = _build_net()
    assert net._conv_bn_fuse == {"bn1": "c1"}
    params = net.init_params(seed=1)
    buffers = net.init_buffers()
    feed = {"image": jnp.asarray(
        rng.randn(4, 64 * 6 * 6).astype(np.float32))}

    def run(params, fuse):
        saved = net._conv_bn_fuse
        net._conv_bn_fuse = saved if fuse else {}
        try:
            values, bufs = net.forward(params, feed, dict(buffers),
                                       is_training=True)
        finally:
            net._conv_bn_fuse = saved
        return values, bufs

    v1, b1 = run(params, True)
    v0, b0 = run(params, False)
    # the conv's standalone value is fused away; outputs and the
    # running-stat buffer updates are unchanged
    assert "c1" not in v1 and "c1" in v0
    _assert_close(v1["bn1"], v0["bn1"])
    for k in b0:
        _assert_close(b1[k], b0[k])

    def loss(params, fuse):
        values, _ = run(params, fuse)
        return jnp.sum(values["bn1"] ** 2)

    g1 = jax.grad(lambda p: loss(p, True))(params)
    g0 = jax.grad(lambda p: loss(p, False))(params)
    for k in sorted(g0):
        tol = dict(rtol=3e-4, atol=1e-3) if k.endswith("c1.wbias") \
            else dict(rtol=3e-4, atol=3e-4)
        np.testing.assert_allclose(np.asarray(g1[k]), np.asarray(g0[k]),
                                   err_msg=k, **tol)


def test_peephole_eval_forward_matches(rng):
    net = _build_net()
    params = net.init_params(seed=2)
    buffers = net.init_buffers()
    feed = {"image": jnp.asarray(
        rng.randn(2, 64 * 6 * 6).astype(np.float32))}
    v1, _ = net.forward(params, feed, dict(buffers), is_training=False)
    saved = net._conv_bn_fuse
    net._conv_bn_fuse = {}
    try:
        v0, _ = net.forward(params, feed, dict(buffers),
                            is_training=False)
    finally:
        net._conv_bn_fuse = saved
    _assert_close(v1["bn1"], v0["bn1"])


def test_second_consumer_keeps_conv_value(rng):
    """Off-pattern network (conv feeds BN *and* addto): values flow as
    before — the conv's output is materialized and consumed twice."""
    net = _build_net(second_consumer=True)
    params = net.init_params(seed=3)
    buffers = net.init_buffers()
    feed = {"image": jnp.asarray(
        rng.randn(2, 64 * 6 * 6).astype(np.float32))}
    values, _ = net.forward(params, feed, dict(buffers),
                            is_training=True)
    assert "c1" in values and "sum" in values
    assert np.isfinite(np.asarray(values["sum"])).all()

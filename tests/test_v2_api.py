"""End-to-end v2-API tests — the SURVEY §7 minimum slice.

Mirrors ``test_TrainerOnePass.cpp`` (real trainer over sample data, cost
decreases) and v2 API tests (``python/paddle/v2/tests``).
"""

import numpy as np
import pytest

import paddle_tpu.v2 as paddle
from paddle_tpu.config.dsl import config_scope
from paddle_tpu.trainer import events as ev


@pytest.mark.slow  # heavyweight e2e; fast lane skips (--runslow)
def test_mnist_mlp_trains():
    with config_scope():
        images = paddle.layer.data("pixel", paddle.data_type.dense_vector(784))
        label = paddle.layer.data("label", paddle.data_type.integer_value(10))
        h1 = paddle.layer.fc(images, size=64, act=paddle.activation.Relu())
        h2 = paddle.layer.fc(h1, size=64, act=paddle.activation.Relu())
        probs = paddle.layer.fc(h2, size=10, act=paddle.activation.Softmax())
        cost = paddle.layer.classification_cost(probs, label)

        trainer = paddle.trainer.SGD(
            cost, update_equation=paddle.optimizer.Momentum(
                learning_rate=0.05, momentum=0.9))

        costs = []

        def handler(event):
            if isinstance(event, ev.EndPass):
                costs.append(event.metrics["cost"])

        reader = paddle.reader.batch(
            paddle.reader.shuffle(paddle.dataset.mnist.train(n_synth=512),
                                  1024, seed=0), 64)
        from paddle_tpu.utils import FLAGS

        FLAGS.set("save_dir", "")
        trainer.train(reader, num_passes=4, event_handler=handler,
                      feeding={"pixel": 0, "label": 1})
        assert costs[-1] < costs[0] * 0.6, costs

        # evaluator path
        metrics = trainer.test(
            paddle.reader.batch(paddle.dataset.mnist.test(n_synth=128), 64),
            feeding={"pixel": 0, "label": 1},
            evaluators=[paddle.evaluator.classification_error()])
        assert "classification_error" in metrics
        # synthetic blobs share train/test prototypes → near-perfect test
        # accuracy; also guards the evaluator seeing the prediction layer
        # (not the cost output, which made error ≈ chance)
        assert metrics["classification_error"] < 0.2


@pytest.mark.slow  # heavyweight e2e; fast lane skips (--runslow)
def test_uci_housing_regression():
    with config_scope():
        x = paddle.layer.data("x", paddle.data_type.dense_vector(13))
        y = paddle.layer.data("y", paddle.data_type.dense_vector(1))
        pred = paddle.layer.fc(x, size=1, act=paddle.activation.Linear())
        cost = paddle.layer.square_error_cost(pred, y)
        trainer = paddle.trainer.SGD(
            cost, update_equation=paddle.optimizer.Adam(learning_rate=0.01))
        costs = []

        def handler(event):
            if isinstance(event, ev.EndPass):
                costs.append(event.metrics["cost"])

        from paddle_tpu.utils import FLAGS

        FLAGS.set("save_dir", "")
        reader = paddle.reader.batch(paddle.dataset.uci_housing.train(), 32)
        trainer.train(reader, num_passes=12, event_handler=handler,
                      feeding={"x": 0, "y": 1})
        assert costs[-1] < costs[0] * 0.3, costs


@pytest.mark.slow  # heavyweight e2e; fast lane skips (--runslow)
def test_sequence_lstm_classification():
    """Stacked-LSTM-style sentiment classifier on synthetic IMDB."""
    with config_scope():
        word = paddle.layer.data(
            "word", paddle.data_type.integer_value_sequence(200))
        label = paddle.layer.data("label", paddle.data_type.integer_value(2))
        emb = paddle.layer.embedding(word, size=16)
        lstm = paddle.networks.simple_lstm(emb, size=16)
        pooled = paddle.layer.pooling(lstm, paddle.pooling.Max())
        probs = paddle.layer.fc(pooled, size=2,
                                act=paddle.activation.Softmax())
        cost = paddle.layer.classification_cost(probs, label)
        trainer = paddle.trainer.SGD(
            cost, update_equation=paddle.optimizer.Adam(learning_rate=0.01))

        def synth():
            rng = np.random.RandomState(3)
            for _ in range(128):
                y = int(rng.randint(2))
                length = int(rng.randint(4, 12))
                lo, hi = (2, 100) if y == 0 else (100, 198)
                yield rng.randint(lo, hi, length), y

        costs = []

        def handler(event):
            if isinstance(event, ev.EndPass):
                costs.append(event.metrics["cost"])

        from paddle_tpu.utils import FLAGS

        FLAGS.set("save_dir", "")
        reader = paddle.reader.batch(synth, 32)
        trainer.train(reader, num_passes=8, event_handler=handler,
                      feeding={"word": 0, "label": 1})
        assert costs[-1] < costs[0] * 0.5, costs


def test_inference_api():
    with config_scope():
        x = paddle.layer.data("x", paddle.data_type.dense_vector(4))
        out = paddle.layer.fc(x, size=3, act=paddle.activation.Softmax())
        inf = paddle.inference.Inference(out)
        batch = [[np.ones(4, np.float32)] for _ in range(5)]
        from paddle_tpu.data.feeder import DataFeeder, dense_vector

        feeder = DataFeeder([("x", dense_vector(4))])
        probs = inf.infer([feeder.convert(batch)])
        assert probs.shape == (5, 3)
        np.testing.assert_allclose(probs.sum(-1), np.ones(5), rtol=1e-5)


def test_checkpoint_roundtrip(tmp_path):
    with config_scope():
        x = paddle.layer.data("x", paddle.data_type.dense_vector(4))
        y = paddle.layer.data("y", paddle.data_type.dense_vector(1))
        pred = paddle.layer.fc(x, size=1)
        cost = paddle.layer.square_error_cost(pred, y)
        trainer = paddle.trainer.SGD(
            cost, update_equation=paddle.optimizer.SGD(learning_rate=0.1))
        feed = {"x": np.ones((4, 4), np.float32),
                "y": np.zeros((4, 1), np.float32)}
        import jax.numpy as jnp

        feed = {k: jnp.asarray(v) for k, v in feed.items()}
        trainer.core.train_one_batch(feed)
        path = trainer.core.save(str(tmp_path), 0)

        trainer2 = paddle.trainer.SGD(
            cost, update_equation=paddle.optimizer.SGD(learning_rate=0.1))
        trainer2.core.load(path)
        for k in trainer.core.params:
            np.testing.assert_allclose(
                np.asarray(trainer.core.params[k]),
                np.asarray(trainer2.core.params[k]))
        assert trainer2.core.samples_seen == trainer.core.samples_seen


def test_config_declared_evaluators_run_in_test_job(tmp_path):
    """v1 configs call *_evaluator(...) at config time; --job=test must
    instantiate and stream them (reference Evaluator::create from
    ModelConfig)."""
    import jax.numpy as jnp
    from paddle_tpu.config import dsl
    from paddle_tpu.config.dsl import config_scope
    from paddle_tpu.layers import NeuralNetwork
    from paddle_tpu.trainer.trainer import Trainer

    with config_scope():
        from paddle_tpu.data.feeder import dense_vector, integer_value
        x = dsl.data_layer("x", dense_vector(8))
        y = dsl.data_layer("y", integer_value(3))
        pred = dsl.fc_layer(x, size=3, act=dsl.SoftmaxActivation(),
                            name="pred")
        dsl.classification_error_evaluator(pred, label=y)
        dsl.sum_evaluator(pred)
        cfg = dsl.topology(dsl.classification_cost(pred, y))
    assert len(cfg.evaluators) == 2
    assert cfg.evaluators[0]["type"] == "classification_error"
    assert cfg.evaluators[0]["label_layer_name"] == "y"

    net = NeuralNetwork(cfg)
    tr = Trainer(net)
    rng = np.random.RandomState(0)

    def reader():
        for _ in range(3):
            yield [(rng.randn(8).astype(np.float32),
                    int(rng.randint(3)))]

    from paddle_tpu.data.feeder import DataFeeder, dense_vector, \
        integer_value
    feeder = DataFeeder([("x", dense_vector(8)), ("y", integer_value(3))])
    metrics = tr.test(reader, feeder, label_name="y")
    assert "classification_error" in metrics
    assert 0.0 <= metrics["classification_error"] <= 1.0
    assert "sum" in metrics or any("sum" in k for k in metrics)


def test_v2_namespace_parity():
    """Reference python/paddle/v2/__init__.py __all__ — every module."""
    import paddle_tpu.v2 as v2

    ref_all = ['optimizer', 'layer', 'activation', 'parameters', 'init',
               'trainer', 'event', 'data_type', 'attr', 'pooling',
               'dataset', 'reader', 'topology', 'networks', 'infer',
               'plot', 'evaluator', 'image', 'master', 'model']
    missing = [n for n in ref_all if not hasattr(v2, n)]
    assert not missing, f"missing v2 modules: {missing}"


def test_v2_topology_wrapper():
    from paddle_tpu.config.dsl import config_scope
    from paddle_tpu.config import dsl
    from paddle_tpu.v2.topology import Topology

    with config_scope():
        a = dsl.data_layer("a", size=4)
        out = dsl.fc_layer(input=[a], size=2, name="out")
        topo = Topology(out)
        assert topo.proto().output_layer_names == ["out"]
        assert list(topo.data_layers()) == ["a"]
        assert topo.get_layer_proto("out").size == 2
        assert topo.get_layer_proto("nope") is None


def test_v2_model_save_load_with_election(tmp_path):
    import os

    import numpy as np

    from paddle_tpu.distributed import Master
    from paddle_tpu.v2 import model
    from paddle_tpu.v2.parameters import Parameters

    params = Parameters()
    params["w"] = np.arange(6, dtype=np.float32).reshape(2, 3)
    # no master: plain save
    p = model.save_model(params, str(tmp_path / "m.tar"))
    assert p and os.path.exists(p)
    loaded = Parameters()
    loaded["w"] = np.zeros((2, 3), np.float32)
    model.load_model(loaded, p)
    np.testing.assert_array_equal(np.asarray(loaded["w"]),
                                  np.asarray(params["w"]))
    # with master: exactly one of two trainers wins the election
    # (distinct trainer ids; the same id re-asking keeps winning)
    m = Master(timeout_s=5, failure_max=3)
    wins = [model.save_model(params, str(tmp_path / "dist"), master=m,
                             trainer=tid)
            for tid in ("trainer-a", "trainer-b")]
    assert sum(1 for w in wins if w) == 1


def test_v2_master_client_tcp():
    from paddle_tpu.distributed import Master
    from paddle_tpu.v2 import master as v2_master

    m = Master(timeout_s=5, failure_max=3)
    port = m.serve(0)
    c = v2_master.client(f"127.0.0.1:{port}", timeout_sec=5.0)
    c.set_dataset(["t0", "t1"])
    tid, payload = c.get_task()
    assert payload in ("t0", "t1")
    c.task_finished(tid)
    c.close()


@pytest.mark.slow  # heavyweight e2e; fast lane skips (--runslow)
def test_recommender_system_trains():
    """Dual-tower MovieLens recommender (test_recommender_system.py):
    cos-sim rating regression over id/bag/text-conv features.  Reuses
    the demo's model/sample/feeding definitions so test and demo can't
    drift."""
    import importlib.util
    import os

    from paddle_tpu.utils import FLAGS

    demo_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "..", "demo", "recommender", "train.py")
    spec = importlib.util.spec_from_file_location(
        "recommender_demo_train", demo_path)
    train_mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(train_mod)

    with config_scope():
        cost, _score = train_mod.build_model(train_mod.movielens_meta(),
                                             emb=8, hidden=16)
        trainer = paddle.trainer.SGD(
            cost, update_equation=paddle.optimizer.Adam(learning_rate=1e-3))

        FLAGS.set("save_dir", "")
        reader = paddle.batch(
            paddle.reader.map_readers(
                train_mod.to_sample, paddle.dataset.movielens.train()), 32)
        costs = []

        def handler(event):
            if isinstance(event, ev.EndPass):
                costs.append(event.metrics["cost"])

        trainer.train(reader, num_passes=3, event_handler=handler,
                      feeding=train_mod.FEEDING)
        assert costs[-1] < costs[0], costs


def test_compat_paddle_v2_alias():
    """Reference v2 scripts (`import paddle.v2 as paddle`) run against
    paddle_tpu.v2 through the compat alias."""
    from paddle_tpu.compat import install

    install()
    import importlib

    import paddle.v2 as ref_paddle
    # the era's deep-import form (importlib avoids shadowing this test
    # file's own `paddle` global with the alias root)
    importlib.import_module("paddle.v2.dataset.mnist")
    from paddle.v2.dataset import mnist
    from paddle.v2.networks import simple_gru

    assert ref_paddle.layer is paddle.layer
    assert callable(simple_gru)
    assert callable(mnist.train)
    assert callable(ref_paddle.batch)

"""End-to-end v2-API tests — the SURVEY §7 minimum slice.

Mirrors ``test_TrainerOnePass.cpp`` (real trainer over sample data, cost
decreases) and v2 API tests (``python/paddle/v2/tests``).
"""

import numpy as np
import pytest

import paddle_tpu.v2 as paddle
from paddle_tpu.config.dsl import config_scope
from paddle_tpu.trainer import events as ev


def test_mnist_mlp_trains():
    with config_scope():
        images = paddle.layer.data("pixel", paddle.data_type.dense_vector(784))
        label = paddle.layer.data("label", paddle.data_type.integer_value(10))
        h1 = paddle.layer.fc(images, size=64, act=paddle.activation.Relu())
        h2 = paddle.layer.fc(h1, size=64, act=paddle.activation.Relu())
        probs = paddle.layer.fc(h2, size=10, act=paddle.activation.Softmax())
        cost = paddle.layer.classification_cost(probs, label)

        trainer = paddle.trainer.SGD(
            cost, update_equation=paddle.optimizer.Momentum(
                learning_rate=0.05, momentum=0.9))

        costs = []

        def handler(event):
            if isinstance(event, ev.EndPass):
                costs.append(event.metrics["cost"])

        reader = paddle.reader.batch(
            paddle.reader.shuffle(paddle.dataset.mnist.train(n_synth=512),
                                  1024, seed=0), 64)
        from paddle_tpu.utils import FLAGS

        FLAGS.set("save_dir", "")
        trainer.train(reader, num_passes=4, event_handler=handler,
                      feeding={"pixel": 0, "label": 1})
        assert costs[-1] < costs[0] * 0.6, costs

        # evaluator path
        metrics = trainer.test(
            paddle.reader.batch(paddle.dataset.mnist.test(n_synth=128), 64),
            feeding={"pixel": 0, "label": 1},
            evaluators=[paddle.evaluator.classification_error()])
        assert "classification_error" in metrics
        # synthetic blobs share train/test prototypes → near-perfect test
        # accuracy; also guards the evaluator seeing the prediction layer
        # (not the cost output, which made error ≈ chance)
        assert metrics["classification_error"] < 0.2


def test_uci_housing_regression():
    with config_scope():
        x = paddle.layer.data("x", paddle.data_type.dense_vector(13))
        y = paddle.layer.data("y", paddle.data_type.dense_vector(1))
        pred = paddle.layer.fc(x, size=1, act=paddle.activation.Linear())
        cost = paddle.layer.square_error_cost(pred, y)
        trainer = paddle.trainer.SGD(
            cost, update_equation=paddle.optimizer.Adam(learning_rate=0.01))
        costs = []

        def handler(event):
            if isinstance(event, ev.EndPass):
                costs.append(event.metrics["cost"])

        from paddle_tpu.utils import FLAGS

        FLAGS.set("save_dir", "")
        reader = paddle.reader.batch(paddle.dataset.uci_housing.train(), 32)
        trainer.train(reader, num_passes=12, event_handler=handler,
                      feeding={"x": 0, "y": 1})
        assert costs[-1] < costs[0] * 0.3, costs


def test_sequence_lstm_classification():
    """Stacked-LSTM-style sentiment classifier on synthetic IMDB."""
    with config_scope():
        word = paddle.layer.data(
            "word", paddle.data_type.integer_value_sequence(200))
        label = paddle.layer.data("label", paddle.data_type.integer_value(2))
        emb = paddle.layer.embedding(word, size=16)
        lstm = paddle.networks.simple_lstm(emb, size=16)
        pooled = paddle.layer.pooling(lstm, paddle.pooling.Max())
        probs = paddle.layer.fc(pooled, size=2,
                                act=paddle.activation.Softmax())
        cost = paddle.layer.classification_cost(probs, label)
        trainer = paddle.trainer.SGD(
            cost, update_equation=paddle.optimizer.Adam(learning_rate=0.01))

        def synth():
            rng = np.random.RandomState(3)
            for _ in range(128):
                y = int(rng.randint(2))
                length = int(rng.randint(4, 12))
                lo, hi = (2, 100) if y == 0 else (100, 198)
                yield rng.randint(lo, hi, length), y

        costs = []

        def handler(event):
            if isinstance(event, ev.EndPass):
                costs.append(event.metrics["cost"])

        from paddle_tpu.utils import FLAGS

        FLAGS.set("save_dir", "")
        reader = paddle.reader.batch(synth, 32)
        trainer.train(reader, num_passes=8, event_handler=handler,
                      feeding={"word": 0, "label": 1})
        assert costs[-1] < costs[0] * 0.5, costs


def test_inference_api():
    with config_scope():
        x = paddle.layer.data("x", paddle.data_type.dense_vector(4))
        out = paddle.layer.fc(x, size=3, act=paddle.activation.Softmax())
        inf = paddle.inference.Inference(out)
        batch = [[np.ones(4, np.float32)] for _ in range(5)]
        from paddle_tpu.data.feeder import DataFeeder, dense_vector

        feeder = DataFeeder([("x", dense_vector(4))])
        probs = inf.infer([feeder.convert(batch)])
        assert probs.shape == (5, 3)
        np.testing.assert_allclose(probs.sum(-1), np.ones(5), rtol=1e-5)


def test_checkpoint_roundtrip(tmp_path):
    with config_scope():
        x = paddle.layer.data("x", paddle.data_type.dense_vector(4))
        y = paddle.layer.data("y", paddle.data_type.dense_vector(1))
        pred = paddle.layer.fc(x, size=1)
        cost = paddle.layer.square_error_cost(pred, y)
        trainer = paddle.trainer.SGD(
            cost, update_equation=paddle.optimizer.SGD(learning_rate=0.1))
        feed = {"x": np.ones((4, 4), np.float32),
                "y": np.zeros((4, 1), np.float32)}
        import jax.numpy as jnp

        feed = {k: jnp.asarray(v) for k, v in feed.items()}
        trainer.core.train_one_batch(feed)
        path = trainer.core.save(str(tmp_path), 0)

        trainer2 = paddle.trainer.SGD(
            cost, update_equation=paddle.optimizer.SGD(learning_rate=0.1))
        trainer2.core.load(path)
        for k in trainer.core.params:
            np.testing.assert_allclose(
                np.asarray(trainer.core.params[k]),
                np.asarray(trainer2.core.params[k]))
        assert trainer2.core.samples_seen == trainer.core.samples_seen


def test_config_declared_evaluators_run_in_test_job(tmp_path):
    """v1 configs call *_evaluator(...) at config time; --job=test must
    instantiate and stream them (reference Evaluator::create from
    ModelConfig)."""
    import jax.numpy as jnp
    from paddle_tpu.config import dsl
    from paddle_tpu.config.dsl import config_scope
    from paddle_tpu.layers import NeuralNetwork
    from paddle_tpu.trainer.trainer import Trainer

    with config_scope():
        from paddle_tpu.data.feeder import dense_vector, integer_value
        x = dsl.data_layer("x", dense_vector(8))
        y = dsl.data_layer("y", integer_value(3))
        pred = dsl.fc_layer(x, size=3, act=dsl.SoftmaxActivation(),
                            name="pred")
        dsl.classification_error_evaluator(pred, label=y)
        dsl.sum_evaluator(pred)
        cfg = dsl.topology(dsl.classification_cost(pred, y))
    assert len(cfg.evaluators) == 2
    assert cfg.evaluators[0]["type"] == "classification_error"
    assert cfg.evaluators[0]["label_layer_name"] == "y"

    net = NeuralNetwork(cfg)
    tr = Trainer(net)
    rng = np.random.RandomState(0)

    def reader():
        for _ in range(3):
            yield [(rng.randn(8).astype(np.float32),
                    int(rng.randint(3)))]

    from paddle_tpu.data.feeder import DataFeeder, dense_vector, \
        integer_value
    feeder = DataFeeder([("x", dense_vector(8)), ("y", integer_value(3))])
    metrics = tr.test(reader, feeder, label_name="y")
    assert "classification_error" in metrics
    assert 0.0 <= metrics["classification_error"] <= 1.0
    assert "sum" in metrics or any("sum" in k for k in metrics)

"""Regenerate the committed block-sparse attention roofline dumps.

Produces ``attn_t2048_causal_before.json`` (legacy full-grid flash
attention: every KV block DMA'd, compute-only skip) and
``attn_t2048_causal_after.json`` (round-19 pair-table block-sparse
kernels) for the causal T=2048 transformer workload — the artifact pair
``bench.py --attribution_diff --check`` replays in tier-1
(tests/test_attribution_diff.py) to machine-verify the ≥30 %
attention-region HBM-byte reduction this PR claims.

Run from the repo root (CPU is fine — the Pallas kernels execute in
interpret mode, whose grid loops and block DMAs land in the optimized
HLO the costmodel parses, so the attributed bytes track the real
block-level traffic):

    JAX_PLATFORMS=cpu python benchmark/rooflines/make_attention_dumps.py

Shapes are CPU-sized in width (model_dim 256, 2 layers, batch 4) but
FULL LENGTH in time (T=2048, the bench row's context) — the skip
fraction under measure is a property of the (T / block) causal grid,
not of the model width.
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "..", ".."))

HERE = os.path.dirname(os.path.abspath(__file__))


def build_workload():
    import jax

    from paddle_tpu.core.sequence import SequenceBatch
    from paddle_tpu.models import transformer_text_classifier

    import bench

    cfg = transformer_text_classifier(
        vocab_size=4000, model_dim=256, num_heads=4, num_layers=2,
        ffn_dim=512, num_classes=2, max_len=2048, causal=True)
    trainer = bench._mk_trainer(cfg, lr=1e-3)
    rng = np.random.RandomState(0)
    b, t, v = 4, 2048, 4000
    feed = {"data": SequenceBatch(
        jax.numpy.asarray(rng.randint(0, v, (b, t)).astype(np.int32)),
        jax.numpy.asarray(np.full((b,), t, np.int32))),
        "label": jax.numpy.asarray(
            rng.randint(0, 2, (b,)).astype(np.int32))}
    return trainer, feed


def main():
    from paddle_tpu.observe import costmodel
    from paddle_tpu.utils import FLAGS

    for flag, name in ((False, "attn_t2048_causal_before.json"),
                       (True, "attn_t2048_causal_after.json")):
        FLAGS.set("flash_block_sparse", flag)
        costmodel.clear_cache()
        trainer, feed = build_workload()
        report = costmodel.analyze_trainer_step(trainer, feed)
        if report is None:
            raise SystemExit("cost attribution unavailable")
        path = os.path.join(HERE, name)
        costmodel.dump_report(report, path)
        attn = [r for r in report["regions"]
                if r["region"].startswith("attn")]
        print(f"{name}: attn bytes "
              f"{sum(r['bytes'] for r in attn) / 1e9:.3f} GB, "
              f"flops {sum(r['flops'] for r in attn) / 1e9:.2f} G")
    FLAGS.set("flash_block_sparse", True)


if __name__ == "__main__":
    main()

"""Regenerate the committed block-sparse attention roofline dumps.

Produces ``attn_t2048_causal_before.json`` (legacy full-grid flash
attention: every KV block DMA'd, compute-only skip) and
``attn_t2048_causal_after.json`` (round-19 pair-table block-sparse
kernels) for the causal T=2048 transformer workload — the artifact pair
``bench.py --attribution_diff --check`` replays in tier-1
(tests/test_attribution_diff.py) to machine-verify the ≥30 %
attention-region HBM-byte reduction this PR claims.

Round 20 closes the round-19 caveat ("the serving kernels have no
attributed-traffic row yet"): ``attn_decode_dense.json`` vs
``attn_decode_paged.json`` attribute ONE serving decode step through
the SAME ``paged_decode_attention`` kernel, varying only the page
table — "dense" reserves every row's full max-context window (the
contiguous-cache serving layout: table width ``t_max / page``),
"paged" right-sizes the table to the pages the row's tokens actually
occupy (the page-pool allocator's contract) — via
``costmodel.analyze_fn`` (no trainer on the decode path).  Holding the
kernel constant isolates the data structure, and the attributed
attn-region traffic scales with the table window (the 2048-vs-256
token shapes here: an 8x window, an 87% byte-and-FLOP cut), which is
what ``--attribution_diff --check`` replays in tier-1.

Run from the repo root (CPU is fine — the Pallas kernels execute in
interpret mode, whose grid loops and block DMAs land in the optimized
HLO the costmodel parses, so the attributed bytes track the real
block-level traffic):

    JAX_PLATFORMS=cpu python benchmark/rooflines/make_attention_dumps.py

Shapes are CPU-sized in width (model_dim 256, 2 layers, batch 4) but
FULL LENGTH in time (T=2048, the bench row's context) — the skip
fraction under measure is a property of the (T / block) causal grid,
not of the model width.
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "..", ".."))

HERE = os.path.dirname(os.path.abspath(__file__))


def build_workload():
    import jax

    from paddle_tpu.core.sequence import SequenceBatch
    from paddle_tpu.models import transformer_text_classifier

    import bench

    cfg = transformer_text_classifier(
        vocab_size=4000, model_dim=256, num_heads=4, num_layers=2,
        ffn_dim=512, num_classes=2, max_len=2048, causal=True)
    trainer = bench._mk_trainer(cfg, lr=1e-3)
    rng = np.random.RandomState(0)
    b, t, v = 4, 2048, 4000
    feed = {"data": SequenceBatch(
        jax.numpy.asarray(rng.randint(0, v, (b, t)).astype(np.int32)),
        jax.numpy.asarray(np.full((b,), t, np.int32))),
        "label": jax.numpy.asarray(
            rng.randint(0, 2, (b,)).astype(np.int32))}
    return trainer, feed


def build_decode_step(right_sized: bool):
    """One decode step over a shared KV pool, serving-shaped: B=8 rows,
    T_max=2048 context, rows 256 tokens deep.  The structural contrast
    under measure is **window proportionality**, kernel held constant:
    a dense contiguous-cache layout must hand the kernel every row's
    full max-context window (table width 2048/16 = 128 pages), while
    the page-pool allocator's table maps exactly the 256/16 = 16 pages
    the row's tokens occupy.  The kernel's grid — and with it the
    attributed block traffic and FLOPs — scales with the table width,
    so the diff pins the 8x window ratio the allocator buys.  (Per-page
    DMA constant factors are inflated by interpret mode on CPU — the
    round-19 caveat — but the RATIO is a property of the data
    structure, which is what the ``--attribution_diff`` replay pins.)"""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.ops.pallas_attention import paged_decode_attention

    b, h, d, page = 8, 4, 64, 16
    t_max, t_used = 2048, 256
    # dense-cache semantics: every row reserves the whole window
    max_pages = (t_used if right_sized else t_max) // page
    n_pages = b * (t_max // page) + 1
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(b, 1, h, d).astype(np.float32))
    k_pages = jnp.asarray(
        rng.randn(n_pages, page, h, d).astype(np.float32))
    v_pages = jnp.asarray(
        rng.randn(n_pages, page, h, d).astype(np.float32))
    tables = jnp.asarray(
        rng.permutation(n_pages - 1)[: b * max_pages].reshape(
            b, max_pages).astype(np.int32) + 1)
    lengths = jnp.asarray(np.full((b,), t_used, np.int32))

    def step(q, k_pages, v_pages, tables, lengths):
        with jax.named_scope("attn_decode"):
            return paged_decode_attention(q, k_pages, v_pages, tables,
                                          lengths)

    return step, (q, k_pages, v_pages, tables, lengths)


def main():
    from paddle_tpu.observe import costmodel
    from paddle_tpu.utils import FLAGS

    for flag, name in ((False, "attn_t2048_causal_before.json"),
                       (True, "attn_t2048_causal_after.json")):
        FLAGS.set("flash_block_sparse", flag)
        costmodel.clear_cache()
        trainer, feed = build_workload()
        report = costmodel.analyze_trainer_step(trainer, feed)
        if report is None:
            raise SystemExit("cost attribution unavailable")
        path = os.path.join(HERE, name)
        costmodel.dump_report(report, path)
        attn = [r for r in report["regions"]
                if r["region"].startswith("attn")]
        print(f"{name}: attn bytes "
              f"{sum(r['bytes'] for r in attn) / 1e9:.3f} GB, "
              f"flops {sum(r['flops'] for r in attn) / 1e9:.2f} G")
    FLAGS.set("flash_block_sparse", True)

    for right_sized, name in ((False, "attn_decode_dense.json"),
                              (True, "attn_decode_paged.json")):
        costmodel.clear_cache()
        step, args = build_decode_step(right_sized)
        report = costmodel.analyze_fn(step, args, known=["attn_decode"])
        if report is None:
            raise SystemExit("decode cost attribution unavailable")
        costmodel.dump_report(report, os.path.join(HERE, name))
        attn = [r for r in report["regions"]
                if r["region"].startswith("attn")]
        print(f"{name}: attn bytes "
              f"{sum(r['bytes'] for r in attn) / 1e6:.2f} MB, "
              f"flops {sum(r['flops'] for r in attn) / 1e6:.2f} M")


if __name__ == "__main__":
    main()

"""Synthetic data providers for the benchmark configs
(stands in for ``benchmark/paddle/image/provider.py`` /
``benchmark/paddle/rnn/provider.py``, which generate/load real data)."""

import numpy as np

from paddle_tpu.data.feeder import (dense_vector, integer_value,
                                    integer_value_sequence)
from paddle_tpu.data.provider import provider


def _image_types(settings, **kwargs):
    h = kwargs.get("height", 32)
    w = kwargs.get("width", 32)
    c = 3 if kwargs.get("color", True) else 1
    settings.input_types = [dense_vector(h * w * c),
                            integer_value(kwargs.get("num_class", 10))]
    settings.kw = kwargs


@provider(init_hook=_image_types, should_shuffle=False)
def process(settings, _file):
    kw = settings.kw
    h, w = kw.get("height", 32), kw.get("width", 32)
    c = 3 if kw.get("color", True) else 1
    nc = kw.get("num_class", 10)
    n = kw.get("num_samples", 2048)
    rng = np.random.RandomState(0)
    for _ in range(n):
        yield (rng.uniform(-1, 1, h * w * c).astype(np.float32),
               int(rng.randint(nc)))


def _rnn_types(settings, **kwargs):
    settings.input_types = [
        integer_value_sequence(kwargs.get("vocab_size", 30000)),
        integer_value(2)]
    settings.kw = kwargs


@provider(init_hook=_rnn_types, should_shuffle=False)
def process_rnn(settings, _file):
    kw = settings.kw
    vocab = kw.get("vocab_size", 30000)
    maxlen = kw.get("maxlen", 100)
    n = kw.get("num_samples", 2048)
    pad = kw.get("pad_seq", True)
    rng = np.random.RandomState(0)
    for _ in range(n):
        ln = maxlen if pad else int(rng.randint(maxlen // 2, maxlen + 1))
        yield (rng.randint(0, vocab, ln).astype(np.int64).tolist(),
               int(rng.randint(2)))

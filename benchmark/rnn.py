"""IMDB LSTM benchmark config (reference ``benchmark/paddle/rnn/rnn.py``).

Baseline rows (reference benchmark/README.md:124-126, bs=128, 1× K40m):
hidden 256 → 110 ms/batch, 512 → 261 ms, 1280 → 1007 ms.  ``bench.py``
measures both hidden=512 (fused Pallas LSTM) and hidden=1280 (past the
kernel's VMEM gate → the lax.scan path, logged by ops/recurrent_ops.py);
run this config with ``--config_args hidden_size=1280`` for the
big-hidden row.
"""

num_class = 2
vocab_size = 30000
fixedlen = 100
batch_size = get_config_arg('batch_size', int, 128)
lstm_num = get_config_arg('lstm_num', int, 2)
hidden_size = get_config_arg('hidden_size', int, 512)
pad_seq = get_config_arg('pad_seq', bool, True)

args = {'vocab_size': vocab_size, 'pad_seq': pad_seq, 'maxlen': fixedlen}
define_py_data_sources2(None, None, module="provider", obj="process_rnn",
                        args=args)

settings(
    batch_size=batch_size,
    learning_rate=2e-3,
    learning_method=AdamOptimizer(),
    regularization=L2Regularization(8e-4),
    gradient_clipping_threshold=25)

net = data("data", integer_value_sequence(vocab_size))
net = embedding(net, size=128)
from paddle_tpu.v2.networks import simple_lstm
for i in range(lstm_num):
    net = simple_lstm(net, size=hidden_size, name=f"lstm{i}")
net = last_seq(net)
net = fc(net, size=num_class, act=SoftmaxActivation())
lab = data("label", integer_value(num_class))
loss = classification_cost(net, lab)
outputs(loss)

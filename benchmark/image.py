"""Image benchmark config: one file drives all reference image workloads
(``benchmark/paddle/image/{alexnet,googlenet,vgg,smallnet_mnist_cifar}.py``)
via ``--config_args model=alexnet|googlenet|vgg|smallnet|resnet``."""

model = get_config_arg('model', str, 'smallnet')
batch_size = get_config_arg('batch_size', int, 64)
num_samples = get_config_arg('num_samples', int, 2048)

dims = {'smallnet': (32, 10), 'resnet_cifar10': (32, 10),
        'alexnet': (227, 1000), 'googlenet': (224, 1000),
        'vgg': (224, 1000), 'resnet': (224, 1000)}
side, num_class = dims[model]

args = {'height': side, 'width': side, 'color': True,
        'num_class': num_class, 'num_samples': num_samples}
define_py_data_sources2(None, None, module="provider", obj="process",
                        args=args)

settings(
    batch_size=batch_size,
    learning_rate=0.01 / batch_size,
    learning_method=MomentumOptimizer(0.9),
    regularization=L2Regularization(0.0005 * batch_size))

from paddle_tpu.models import image as M

img = data('data', dense_vector(side * side * 3), height=side, width=side)
builders = {'smallnet': M.smallnet_mnist_cifar, 'alexnet': M.alexnet,
            'googlenet': M.googlenet,
            'vgg': lambda i, n: M.vgg(i, 19, n),
            'resnet': lambda i, n: M.resnet(i, 50, n),
            'resnet_cifar10': lambda i, n: M.resnet_cifar10(i, 32, n)}
net = builders[model](img, num_class)
lab = data('label', integer_value(num_class))
loss = classification_cost(net, lab)
outputs(loss)

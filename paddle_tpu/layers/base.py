"""Layer base class and registry.

Equivalent of ``paddle/gserver/layers/Layer.h:62`` (base) and the
``REGISTER_LAYER`` macro (``:31``, registrar ``:260``).

TPU-first contract: a layer is **stateless and functional** — it declares
parameter shapes from its :class:`LayerConfig` and computes
``forward(params, inputs)`` as a pure jax function.  There is no
``backward()``: the whole network's forward is traced and autodiffed as one
XLA computation, which replaces the reference's per-layer hand-written
gradients while keeping the per-layer *configuration* surface identical.

Batch-norm-style running statistics live in a separate ``buffers`` pytree
(returned updated from forward), and dropout randomness comes from a
per-layer folded PRNG key — both threaded by the NeuralNetwork.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config.model_config import LayerConfig, ModelConfig, ParameterConfig
from ..core.sequence import SequenceBatch, like, value_of
from ..ops import get_activation
from ..ops.nn_ops import dropout as dropout_op
from ..utils import ConfigError, Registry, enforce

LAYERS: Registry = Registry("layer")


def register_layer(*names: str):
    def deco(cls):
        LAYERS.register_value(names[0], cls, *names[1:])
        cls.layer_type = names[0]
        return cls

    return deco


@dataclasses.dataclass
class ForwardContext:
    """Per-call context threaded through layer forwards."""

    is_training: bool = True
    rng: Optional[jax.Array] = None
    buffers: Dict[str, Any] = dataclasses.field(default_factory=dict)
    new_buffers: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def layer_rng(self, name: str) -> jax.Array:
        if self.rng is None:
            return jax.random.PRNGKey(0)
        return jax.random.fold_in(self.rng, abs(hash(name)) % (2 ** 31))


class Layer:
    """Base layer. Subclasses override ``param_specs`` and ``forward``."""

    layer_type = ""

    def __init__(self, conf: LayerConfig, model: ModelConfig):
        self.conf = conf
        self.name = conf.name
        self.model = model

    # ---- parameters ------------------------------------------------------
    def param_specs(self) -> List[ParameterConfig]:
        """Parameter configs this layer owns (weights then bias)."""
        return []

    def weight_name(self, i: int = 0) -> str:
        inp = self.conf.inputs[i]
        return inp.input_parameter_name or f"_{self.name}.w{i}"

    def bias_name(self) -> str:
        return self.conf.bias_parameter_name or f"_{self.name}.wbias"

    def _weight_spec(self, i: int, shape: Sequence[int], **kw) -> ParameterConfig:
        return ParameterConfig(
            name=self.weight_name(i), size=int(np.prod(shape)),
            dims=list(shape), **kw)

    def _bias_spec(self, shape: Sequence[int], **kw) -> ParameterConfig:
        return ParameterConfig(
            name=self.bias_name(), size=int(np.prod(shape)),
            dims=list(shape), initial_std=0.0, **kw)

    # ---- execution -------------------------------------------------------
    def forward(self, params: Dict[str, jax.Array], inputs: List[Any],
                ctx: ForwardContext) -> Any:
        raise NotImplementedError

    def apply_activation(self, out: Any) -> Any:
        act = get_activation(self.conf.active_type or None)
        if self.conf.active_type == "sequence_softmax" and isinstance(out, SequenceBatch):
            return out.with_data(act(out.data, mask=out.mask()))
        if isinstance(out, SequenceBatch):
            return out.with_data(act(out.data))
        return act(out)

    def apply_dropout(self, out: Any, ctx: ForwardContext) -> Any:
        if self.conf.drop_rate > 0:
            data = value_of(out)
            data = dropout_op(data, ctx.layer_rng(self.name + "/drop"),
                              rate=self.conf.drop_rate,
                              is_training=ctx.is_training)
            return like(out, data)
        return out

    def apply_extras(self, out: Any, ctx: ForwardContext) -> Any:
        """Dropout + backward error clip WITHOUT the activation — for
        layers whose activation happens inside their own kernel
        (lstm_step/gru_step gates)."""
        out = self.apply_dropout(out, ctx)
        t = self.conf.error_clipping_threshold
        if t > 0:
            out = like(out, _clip_error(value_of(out), t))
        return out

    def finalize(self, out: Any, ctx: ForwardContext) -> Any:
        """Activation then dropout, matching Layer::forwardActivation order."""
        return self.apply_extras(self.apply_activation(out), ctx)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _clip_error(x, t):
    """Identity whose backward clips the output-gradient to ±t — the
    reference's per-layer error clipping (``Layer.cpp``
    backwardActivation, ``ExtraLayerAttribute.error_clipping_threshold``)."""
    return x


def _clip_error_fwd(x, t):
    return x, None


def _clip_error_bwd(t, _res, dy):
    return (jnp.clip(dy, -t, t),)


_clip_error.defvjp(_clip_error_fwd, _clip_error_bwd)


def cast_layer_output(layer: "Layer", out: Any) -> Any:
    """Normalize a layer's float outputs to the policy output dtype.

    Under ``--bf16_activations`` this is what keeps the whole graph's
    activations bf16: any layer that promoted to fp32 (e.g. by adding an
    fp32 bias) is cast back at the engine boundary, so scan carries stay
    dtype-stable and activation HBM traffic is halved.  Cost layers are
    exempt (losses accumulate fp32).
    """
    from ..core.dtypes import current_policy

    odt = current_policy().output_dtype
    if odt == jnp.float32 or getattr(layer, "is_cost", False):
        return out

    def cast(v):
        data = value_of(v)
        if hasattr(data, "astype") and hasattr(data, "dtype") \
                and jnp.issubdtype(data.dtype, jnp.floating) \
                and data.dtype != odt:
            return like(v, data.astype(odt))
        return v

    if isinstance(out, dict):
        return {k: cast(v) for k, v in out.items()}
    return cast(out)


def init_parameter(key: jax.Array, spec: ParameterConfig) -> jax.Array:
    """Initialize one parameter per ``ParameterConfig`` semantics
    (initial_strategy/mean/std/smart — ``paddle/parameter/Parameter.cpp``)."""
    shape = tuple(spec.dims) if spec.dims else (spec.size,)
    std = spec.initial_std
    if spec.initial_smart and len(shape) >= 2:
        # fan-in = all dims but the output (last) one — for fc (in, out)
        # that's `in` (reference semantics, Parameter.cpp initial_smart);
        # for HWIO conv weights it's KH*KW*Cin, which 1/sqrt(shape[0])
        # got badly wrong (1x1 convs initialized at std=1 → activations
        # grew ~8x per layer and deep resnets overflowed at init)
        std = 1.0 / np.sqrt(np.prod(shape[:-1]))
    if std == 0.0:
        base = jnp.zeros(shape, jnp.float32)
    elif spec.initial_strategy == 1:
        base = jax.random.uniform(key, shape, jnp.float32, -std, std)
    else:
        base = std * jax.random.normal(key, shape, jnp.float32)
    return base + spec.initial_mean

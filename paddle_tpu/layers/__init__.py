from .base import LAYERS, ForwardContext, Layer, init_parameter, register_layer
from .network import NeuralNetwork
from .recurrent_group import RecurrentGroup

__all__ = [
    "LAYERS",
    "ForwardContext",
    "Layer",
    "NeuralNetwork",
    "RecurrentGroup",
    "init_parameter",
    "register_layer",
]

"""Image layers: conv, pool, norm, batch-norm, and shape glue.

Reference: ``ExpandConvLayer`` (type ``exconv``), ``ConvTransLayer``
(``exconvt``), ``CudnnConvLayer`` (``cudnn_conv`` — same math here, XLA owns
the kernel choice), ``PoolLayer``/``CudnnPoolLayer`` (``pool``),
``NormLayer`` (``norm``, cmrnorm-projection), ``BatchNormalizationLayer`` /
``CudnnBatchNormLayer`` (``batch_norm``/``cudnn_batch_norm``),
``MaxOutLayer``, ``BlockExpandLayer``, ``SpatialPyramidPoolLayer``,
``PadLayer``, ``CropLayer``, ``RotateLayer``, ``SwitchOrderLayer``,
``BilinearInterpLayer``, ``Conv3DLayer``/``DeConv3DLayer``.

Geometry attrs mirror ``ConvConfig``/``PoolConfig`` in ModelConfig.proto:
channels, filter_size(_y), stride(_y), padding(_y), num_filters, img_size(_y),
groups, pool_size(_y), output_x/_y (caffe_mode floor arithmetic).

Internal layout is **NHWC** (TPU lane-friendly); inputs arriving as the
reference's flat [B, C*H*W] rows are reshaped (CHW order preserved), and
outputs flatten back the same way when a dense layer consumes them.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config.model_config import ParameterConfig
from ..core.sequence import SequenceBatch, like, value_of
from ..ops import nn_ops
from ..utils import ConfigError, enforce
from .base import ForwardContext, Layer, register_layer


def conv_out_size(img: int, filt: int, pad: int, stride: int,
                  caffe_mode: bool = True) -> int:
    """``cnn_output_size`` (paddle/math/MathUtil): floor (caffe) or ceil."""
    if caffe_mode:
        return (img + 2 * pad - filt) // stride + 1
    return (img + 2 * pad - filt + stride - 1) // stride + 1


def to_nhwc(v: jax.Array, channels: int, height: int, width: int) -> jax.Array:
    """Accept [B, C*H*W] flat rows (reference layout) or already-NHWC."""
    if v.ndim == 2:
        b = v.shape[0]
        return jnp.moveaxis(v.reshape(b, channels, height, width), 1, -1)
    if v.ndim == 4:
        return v
    raise ConfigError(f"cannot interpret image input of rank {v.ndim}")


class _ImgLayer(Layer):
    """Shared geometry helpers."""

    def geo(self, key: str, default=None):
        val = self.conf.attrs.get(key, default)
        if val is None:
            raise ConfigError(f"layer {self.name}: missing conv attr {key!r}")
        return val


class DeferredBN:
    """Value published by a batch-norm layer whose normalize+activation
    apply pass is DEFERRED into its consuming conv's input pipeline (the
    forward conv+BN fusion, ``nn_ops.affine_act_conv2d``): the raw input
    ``z`` plus the folded per-channel affine, so the consumer forms
    ``act(a·z + c)`` tile-by-tile in VMEM instead of reading a
    materialized activation from HBM.  ``act``/``training`` are static
    pytree aux data — they gate kernel dispatch, not values."""

    __slots__ = ("z", "a", "c", "act", "training")

    def __init__(self, z, a, c, act: str, training: bool):
        self.z = z
        self.a = a
        self.c = c
        self.act = act
        self.training = training


jax.tree_util.register_pytree_node(
    DeferredBN,
    lambda d: ((d.z, d.a, d.c), (d.act, d.training)),
    lambda aux, ch: DeferredBN(ch[0], ch[1], ch[2], aux[0], aux[1]),
)


@register_layer("exconv", "cudnn_conv", "conv", "mkldnn_conv")
class ConvLayer(_ImgLayer):
    def _shapes(self):
        c = self.geo("channels")
        f = self.geo("filter_size")
        fy = self.conf.attrs.get("filter_size_y", f)
        nf = self.geo("num_filters")
        groups = self.conf.attrs.get("groups", 1)
        return c, f, fy, nf, groups

    def param_specs(self):
        c, f, fy, nf, groups = self._shapes()
        # HWIO layout
        specs = [self._weight_spec(0, (fy, f, c // groups, nf),
                                   initial_smart=True)]
        if self.conf.with_bias:
            specs.append(self._bias_spec((nf,)))
        return specs

    def geometry(self):
        """(channels, (h, w) img size, (sy, sx) stride, (py, px) pad,
        groups) — shared by :meth:`forward` and the fused conv→BN path
        in :class:`BatchNormLayer`."""
        c = self.geo("channels")
        h = self.geo("img_size_y", self.conf.attrs.get("img_size"))
        w = self.geo("img_size")
        stride = (self.conf.attrs.get("stride_y", self.conf.attrs.get("stride", 1)),
                  self.conf.attrs.get("stride", 1))
        pad = (self.conf.attrs.get("padding_y", self.conf.attrs.get("padding", 0)),
               self.conf.attrs.get("padding", 0))
        return c, (h, w), stride, pad, self.conf.attrs.get("groups", 1)

    def forward(self, params, inputs, ctx):
        c, (h, w), stride, pad, groups = self.geometry()
        v = value_of(inputs[0])
        if isinstance(v, DeferredBN):
            # the producing batch-norm deferred its apply pass into this
            # conv's input pipeline (forward conv+BN fusion): stream the
            # affine(+act) through the fused conv instead of reading a
            # materialized activation
            out = nn_ops.affine_act_conv2d(
                to_nhwc(v.z, c, h, w), v.a, v.c,
                params[self.weight_name(0)], act=v.act,
                is_training=v.training, stride=stride,
                padding=[(pad[0], pad[0]), (pad[1], pad[1])],
                groups=groups)
        else:
            out = nn_ops.conv2d(
                to_nhwc(v, c, h, w), params[self.weight_name(0)],
                stride=stride,
                padding=[(pad[0], pad[0]), (pad[1], pad[1])],
                groups=groups)
        if self.conf.with_bias:
            out = out + params[self.bias_name()]
        return self.finalize(like(inputs[0], out), ctx)


@register_layer("exconvt", "cudnn_convt")
class ConvTransLayer(_ImgLayer):
    def param_specs(self):
        c = self.geo("channels")
        f = self.geo("filter_size")
        fy = self.conf.attrs.get("filter_size_y", f)
        nf = self.geo("num_filters")
        specs = [self._weight_spec(0, (fy, f, nf, c), initial_smart=True)]
        if self.conf.with_bias:
            specs.append(self._bias_spec((nf,)))
        return specs

    def forward(self, params, inputs, ctx):
        c = self.geo("channels")
        h = self.geo("img_size_y", self.conf.attrs.get("img_size"))
        w = self.geo("img_size")
        x = to_nhwc(value_of(inputs[0]), c, h, w)
        stride = self.conf.attrs.get("stride", 1)
        pad = self.conf.attrs.get("padding", 0)
        out = nn_ops.conv2d_transpose(
            x, params[self.weight_name(0)], stride=stride,
            padding=[(pad, pad), (pad, pad)])
        if self.conf.with_bias:
            out = out + params[self.bias_name()]
        return self.finalize(like(inputs[0], out), ctx)


@register_layer("pool", "cudnn_pool", "mkldnn_pool")
class PoolLayer(_ImgLayer):
    def forward(self, params, inputs, ctx):
        c = self.geo("channels")
        h = self.geo("img_size_y", self.conf.attrs.get("img_size"))
        w = self.geo("img_size")
        x = to_nhwc(value_of(inputs[0]), c, h, w)
        ptype = self.geo("pool_type", "max-projection")
        kind = "max" if "max" in ptype else "avg"
        window = (self.conf.attrs.get("size_y", self.conf.attrs.get("pool_size", 2)),
                  self.conf.attrs.get("pool_size", 2))
        stride = (self.conf.attrs.get("stride_y", self.conf.attrs.get("stride", 2)),
                  self.conf.attrs.get("stride", 2))
        pad = (self.conf.attrs.get("padding_y", self.conf.attrs.get("padding", 0)),
               self.conf.attrs.get("padding", 0))
        out = nn_ops.pool2d(x, kind, window=window, stride=stride,
                            padding=list(pad))
        return self.finalize(like(inputs[0], out), ctx)


@register_layer("norm")
class NormLayer(_ImgLayer):
    """cmrnorm-projection (cross-map LRN)."""

    def forward(self, params, inputs, ctx):
        c = self.geo("channels")
        h = self.geo("img_size_y", self.conf.attrs.get("img_size"))
        w = self.geo("img_size")
        x = to_nhwc(value_of(inputs[0]), c, h, w)
        size = self.conf.attrs.get("norm_size", 5)
        scale = self.conf.attrs.get("scale", 1e-4)
        pow_ = self.conf.attrs.get("pow", 0.75)
        # gserver semantics: scale is already divided by size in config_parser
        out = nn_ops.lrn(x, n=size, k=1.0, alpha=scale, beta=pow_)
        return self.finalize(like(inputs[0], out), ctx)


@register_layer("batch_norm", "cudnn_batch_norm", "mkldnn_batch_norm")
class BatchNormLayer(_ImgLayer):
    """Batch normalization with running-stat buffers.

    The reference stores moving mean/var as extra non-learnable parameters
    (use_global_stats at inference); here they live in the buffers pytree.
    """

    def param_specs(self):
        c = self.conf.attrs.get("channels", self.conf.size)
        specs = [self._weight_spec(0, (c,), initial_mean=1.0, initial_std=0.0)]
        if self.conf.with_bias:
            specs.append(self._bias_spec((c,)))
        return specs

    def buffer_specs(self):
        c = self.conf.attrs.get("channels", self.conf.size)
        return {
            self.name + ".mean": jnp.zeros((c,), jnp.float32),
            self.name + ".var": jnp.ones((c,), jnp.float32),
        }

    def forward(self, params, inputs, ctx):
        c = self.conf.attrs.get("channels", self.conf.size)
        v = value_of(inputs[0])
        img = v
        was_flat = v.ndim == 2 and self.conf.attrs.get("img_size") is not None
        if was_flat:
            h = self.geo("img_size_y", self.conf.attrs.get("img_size"))
            w = self.geo("img_size")
            img = to_nhwc(v, c, h, w)
        bias = params.get(self.bias_name())
        if bias is None:
            bias = jnp.zeros((c,), jnp.float32)
        rm = ctx.buffers.get(self.name + ".mean", jnp.zeros((c,), jnp.float32))
        rv = ctx.buffers.get(self.name + ".var", jnp.ones((c,), jnp.float32))
        momentum = self.conf.attrs.get("moving_average_fraction", 0.9)
        use_global = self.conf.attrs.get("use_global_stats", None)
        training = ctx.is_training if use_global is None else not use_global
        y, nrm, nrv = nn_ops.batch_norm(
            img, params[self.weight_name(0)], bias, rm, rv,
            momentum=momentum, is_training=training)
        ctx.new_buffers[self.name + ".mean"] = nrm
        ctx.new_buffers[self.name + ".var"] = nrv
        return self.finalize(like(inputs[0], y), ctx)

    def _bn_args(self, params):
        """(scale, bias, momentum) shared by all forward paths."""
        c = self.conf.attrs.get("channels", self.conf.size)
        bias = params.get(self.bias_name())
        if bias is None:
            bias = jnp.zeros((c,), jnp.float32)
        return params[self.weight_name(0)], bias, \
            self.conf.attrs.get("moving_average_fraction", 0.9)

    def forward_deferred(self, params, inputs, ctx):
        """Publish the folded affine instead of applying it (forward
        conv+BN fusion, network peephole): this BN's sole consumer is a
        fusable conv, which receives the raw input z plus the folded
        per-channel (a, c) and streams ``act(a·z + c)`` through its
        input pipeline — the normalize+act apply pass never touches
        HBM.  Running-stat buffers update exactly as :meth:`forward`;
        eval mode folds the running stats the same way (the consumer
        then takes the exact unfused composition)."""
        c = self.conf.attrs.get("channels", self.conf.size)
        v = value_of(inputs[0])
        img = v
        if v.ndim == 2 and self.conf.attrs.get("img_size") is not None:
            h = self.geo("img_size_y", self.conf.attrs.get("img_size"))
            w = self.geo("img_size")
            img = to_nhwc(v, c, h, w)
        scale, bias, momentum = self._bn_args(params)
        rm = ctx.buffers.get(self.name + ".mean",
                             jnp.zeros((c,), jnp.float32))
        rv = ctx.buffers.get(self.name + ".var",
                             jnp.ones((c,), jnp.float32))
        use_global = self.conf.attrs.get("use_global_stats", None)
        training = ctx.is_training if use_global is None else not use_global
        a, cc, nrm, nrv = nn_ops.bn_folded_affine(
            img, scale, bias, rm, rv, momentum=momentum,
            is_training=training)
        ctx.new_buffers[self.name + ".mean"] = nrm
        ctx.new_buffers[self.name + ".var"] = nrv
        act = "relu" if self.conf.active_type == "relu" else ""
        return DeferredBN(img, a, cc, act, training)

    def forward_fused(self, params, conv, conv_inputs, ctx):
        """Execute the fused conv→BN pair (network peephole): ``conv``
        is the producing :class:`ConvLayer`, ``conv_inputs`` its inputs.
        Semantics are exactly conv-forward (linear act, gated) followed
        by :meth:`forward`; ``nn_ops.conv2d_bn`` dispatches the Pallas
        fused-backward path when the shapes tile and falls back to the
        identical unfused composition otherwise (and in eval mode).
        A :class:`DeferredBN` input composes the FORWARD fusion into the
        same pair — the upstream BN's affine(+ReLU) becomes the chain
        op's input prologue."""
        c, (h, w), stride, pad, groups = conv.geometry()
        v = value_of(conv_inputs[0])
        in_affine = None
        if isinstance(v, DeferredBN):
            in_affine = (v.a, v.c, v.act)
            v = v.z
        x = to_nhwc(v, c, h, w)
        cw = params[conv.weight_name(0)]
        cb = params.get(conv.bias_name()) if conv.conf.with_bias else None
        scale, bias, momentum = self._bn_args(params)
        rm = ctx.buffers.get(self.name + ".mean",
                             jnp.zeros((cw.shape[3],), jnp.float32))
        rv = ctx.buffers.get(self.name + ".var",
                             jnp.ones((cw.shape[3],), jnp.float32))
        use_global = self.conf.attrs.get("use_global_stats", None)
        training = ctx.is_training if use_global is None else not use_global
        y, nrm, nrv = nn_ops.conv2d_bn(
            x, cw, cb, scale, bias, rm, rv, momentum=momentum,
            is_training=training, stride=stride,
            padding=[(pad[0], pad[0]), (pad[1], pad[1])], groups=groups,
            in_affine=in_affine)
        ctx.new_buffers[self.name + ".mean"] = nrm
        ctx.new_buffers[self.name + ".var"] = nrv
        return self.finalize(like(conv_inputs[0], y), ctx)


@register_layer("maxout")
class MaxOutLayer(_ImgLayer):
    def forward(self, params, inputs, ctx):
        c = self.geo("channels")
        h = self.geo("img_size_y", self.conf.attrs.get("img_size"))
        w = self.geo("img_size")
        x = to_nhwc(value_of(inputs[0]), c, h, w)
        return self.finalize(
            like(inputs[0], nn_ops.maxout(x, self.geo("groups"))), ctx)


@register_layer("blockexpand")
class BlockExpandLayer(_ImgLayer):
    def forward(self, params, inputs, ctx):
        c = self.geo("channels")
        h = self.geo("img_size_y", self.conf.attrs.get("img_size"))
        w = self.geo("img_size")
        x = to_nhwc(value_of(inputs[0]), c, h, w)
        out = nn_ops.block_expand(
            x, self.geo("block_y"), self.geo("block_x"),
            self.geo("stride_y"), self.geo("stride_x"),
            self.conf.attrs.get("padding_y", 0), self.conf.attrs.get("padding_x", 0))
        b, s, d = out.shape
        return SequenceBatch(data=out, length=jnp.full((b,), s, jnp.int32))


@register_layer("spp")
class SppLayer(_ImgLayer):
    def forward(self, params, inputs, ctx):
        c = self.geo("channels")
        h = self.geo("img_size_y", self.conf.attrs.get("img_size"))
        w = self.geo("img_size")
        x = to_nhwc(value_of(inputs[0]), c, h, w)
        out = nn_ops.spatial_pyramid_pool(
            x, self.geo("pyramid_height"),
            "max" if "max" in self.conf.attrs.get("pool_type", "max") else "avg")
        return self.finalize(like(inputs[0], out), ctx)


@register_layer("pad")
class PadLayer(_ImgLayer):
    def forward(self, params, inputs, ctx):
        c = self.geo("channels")
        h = self.geo("img_size_y", self.conf.attrs.get("img_size"))
        w = self.geo("img_size")
        x = to_nhwc(value_of(inputs[0]), c, h, w)
        pc = self.conf.attrs.get("pad_c", [0, 0])
        ph = self.conf.attrs.get("pad_h", [0, 0])
        pw = self.conf.attrs.get("pad_w", [0, 0])
        out = jnp.pad(x, [(0, 0), tuple(ph), tuple(pw), tuple(pc)])
        return like(inputs[0], out)


@register_layer("crop")
class CropLayer(_ImgLayer):
    def forward(self, params, inputs, ctx):
        c = self.geo("channels")
        h = self.geo("img_size_y", self.conf.attrs.get("img_size"))
        w = self.geo("img_size")
        x = to_nhwc(value_of(inputs[0]), c, h, w)
        offs = self.conf.attrs.get("crop_offsets", [0, 0])
        shape = self.conf.attrs["crop_shape"]  # [H, W]
        out = x[:, offs[0]:offs[0] + shape[0], offs[1]:offs[1] + shape[1], :]
        return like(inputs[0], out)


@register_layer("rotate")
class RotateLayer(_ImgLayer):
    def forward(self, params, inputs, ctx):
        h = self.geo("height")
        w = self.geo("width")
        from ..ops.nn_ops import rotate

        return like(inputs[0], rotate(value_of(inputs[0]), h, w))


@register_layer("switch_order")
class SwitchOrderLayer(_ImgLayer):
    def forward(self, params, inputs, ctx):
        return like(inputs[0], nn_ops.switch_order(
            value_of(inputs[0]), self.conf.attrs.get("to", "NHWC")))


@register_layer("bilinear_interp")
class BilinearInterpLayer(_ImgLayer):
    def forward(self, params, inputs, ctx):
        c = self.geo("channels")
        h = self.geo("img_size_y", self.conf.attrs.get("img_size"))
        w = self.geo("img_size")
        x = to_nhwc(value_of(inputs[0]), c, h, w)
        out = nn_ops.bilinear_interp(
            x, self.geo("out_size_y"), self.geo("out_size_x"))
        return like(inputs[0], out)


@register_layer("cross-channel-norm")
class CrossChannelNormLayer(_ImgLayer):
    """Per-position L2 normalization across channels with a learned
    per-channel scale (``CrossChannelNormLayer.cpp``; SSD conv4_3 norm):
    ``out[c, s] = scale[c] * x[c, s] / sqrt(sum_c x[c, s]^2 + 1e-6)``."""

    def param_specs(self):
        c = self.geo("channels")
        return [self._weight_spec(0, (c,), initial_mean=1.0,
                                  initial_std=0.0)]

    def forward(self, params, inputs, ctx):
        c = self.geo("channels")
        v = value_of(inputs[0])
        b = v.shape[0]
        x = v.reshape(b, c, -1)  # [B, C, spatial]
        norm = jnp.sqrt(jnp.sum(x * x, axis=1, keepdims=True) + 1e-6)
        out = x / norm * params[self.weight_name(0)][None, :, None]
        return self.finalize(like(inputs[0], out.reshape(v.shape)), ctx)

"""3-D image layers: conv3d, deconv3d, pool3d.

Reference: ``Conv3DLayer`` (``paddle/gserver/layers/Conv3DLayer.cpp``),
``DeConv3DLayer`` (``DeConv3DLayer.cpp``), ``Pool3DLayer``
(``Pool3DLayer.cpp``).  Geometry attrs mirror the 3-D extensions of
``ConvConfig``/``PoolConfig`` (``filter_size_z``/``stride_z``/``padding_z``,
``config_parser.py:908-966``).

Layout is **NDHWC** internally (TPU lane-friendly); the reference's flat
[B, C*D*H*W] rows are reshaped with CDHW order preserved, mirroring
``to_nhwc`` in :mod:`.conv`.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..core.sequence import like, value_of
from ..ops import nn_ops
from ..utils import ConfigError
from .base import Layer, register_layer
from .conv import conv_out_size


def to_ndhwc(v, channels: int, depth: int, height: int, width: int):
    """Accept [B, C*D*H*W] flat rows (reference layout) or already-NDHWC."""
    if v.ndim == 2:
        b = v.shape[0]
        return jnp.moveaxis(v.reshape(b, channels, depth, height, width),
                            1, -1)
    if v.ndim == 5:
        return v
    raise ConfigError(f"cannot interpret 3-D image input of rank {v.ndim}")


class _Img3DLayer(Layer):
    def geo(self, key: str, default=None):
        val = self.conf.attrs.get(key, default)
        if val is None:
            raise ConfigError(f"layer {self.name}: missing 3-D attr {key!r}")
        return val

    def _triple(self, key: str, default):
        """(z, y, x) triple from attrs ``key_z``/``key_y``/``key``."""
        base = self.conf.attrs.get(key, default)
        return (self.conf.attrs.get(key + "_z", base),
                self.conf.attrs.get(key + "_y", base),
                base)

    def _geometry(self):
        c = self.geo("channels")
        d = self.geo("img_size_z", self.conf.attrs.get("depth"))
        h = self.geo("img_size_y", self.conf.attrs.get("img_size"))
        w = self.geo("img_size")
        return c, d, h, w


@register_layer("conv3d")
class Conv3DLayer(_Img3DLayer):
    def param_specs(self):
        c = self.geo("channels")
        nf = self.geo("num_filters")
        groups = self.conf.attrs.get("groups", 1)
        fz, fy, fx = self._triple("filter_size", None)
        specs = [self._weight_spec(0, (fz, fy, fx, c // groups, nf),
                                   initial_smart=True)]
        if self.conf.with_bias:
            specs.append(self._bias_spec((nf,)))
        return specs

    def forward(self, params, inputs, ctx):
        c, d, h, w = self._geometry()
        x = to_ndhwc(value_of(inputs[0]), c, d, h, w)
        stride = self._triple("stride", 1)
        pad = self._triple("padding", 0)
        out = nn_ops.conv3d(x, params[self.weight_name(0)], stride=stride,
                            padding=[(p, p) for p in pad])
        if self.conf.with_bias:
            out = out + params[self.bias_name()]
        return self.finalize(like(inputs[0], out), ctx)


@register_layer("deconv3d")
class DeConv3DLayer(_Img3DLayer):
    def param_specs(self):
        c = self.geo("channels")
        nf = self.geo("num_filters")
        fz, fy, fx = self._triple("filter_size", None)
        specs = [self._weight_spec(0, (fz, fy, fx, nf, c),
                                   initial_smart=True)]
        if self.conf.with_bias:
            specs.append(self._bias_spec((nf,)))
        return specs

    def forward(self, params, inputs, ctx):
        c, d, h, w = self._geometry()
        x = to_ndhwc(value_of(inputs[0]), c, d, h, w)
        stride = self._triple("stride", 1)
        pad = self._triple("padding", 0)
        out = nn_ops.conv3d_transpose(
            x, params[self.weight_name(0)], stride=stride,
            padding=[(p, p) for p in pad])
        if self.conf.with_bias:
            out = out + params[self.bias_name()]
        return self.finalize(like(inputs[0], out), ctx)


@register_layer("pool3d")
class Pool3DLayer(_Img3DLayer):
    def forward(self, params, inputs, ctx):
        c, d, h, w = self._geometry()
        x = to_ndhwc(value_of(inputs[0]), c, d, h, w)
        ptype = self.geo("pool_type", "max-projection")
        kind = "max" if "max" in ptype else "avg"
        window = self._triple("pool_size", 2)
        stride = self._triple("stride", 2)
        pad = self._triple("padding", 0)
        out = nn_ops.pool3d(x, kind, window=window, stride=stride,
                            padding=pad)
        return self.finalize(like(inputs[0], out), ctx)


def conv3d_out_shape(d, h, w, filt, pad, stride, caffe_mode=True):
    """Output (D, H, W) for a z/y/x triple of filter/pad/stride."""
    return tuple(conv_out_size(i, f, p, s, caffe_mode)
                 for i, f, p, s in zip((d, h, w), filt, pad, stride))

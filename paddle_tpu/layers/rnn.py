"""Recurrent layers: lstmemory, grumemory, recurrent, and step variants.

Reference: ``LstmLayer`` (type ``lstmemory``, with peephole "check" weights —
``paddle/gserver/layers/LstmLayer.cpp``), ``GatedRecurrentLayer``
(``gated_recurrent``), ``RecurrentLayer`` (``recurrent``), ``MDLstmLayer``
(not ported — 2-D LSTM, rarely used), plus step layers ``lstm_step`` /
``gru_step`` used inside recurrent groups.

Convention parity: like the reference, ``lstmemory`` expects its input
already projected to 4H by an upstream fc/mixed layer (the v1 DSL's
``lstmemory`` wraps exactly that); ``gated_recurrent`` expects 3H.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.sequence import SequenceBatch, like, value_of
from ..ops import recurrent_ops
from ..ops.recurrent_ops import LstmState
from ..utils import ConfigError, enforce
from .base import ForwardContext, Layer, register_layer


@register_layer("lstmemory")
class LstmLayer(Layer):
    """Input: sequence of [B, T, 4H] pre-projected gates; output [B, T, H].

    Parameters: recurrent weight [H, 4H], bias [7H] = 4H gate bias + 3H
    peephole checks (reference bias layout in LstmLayer.cpp).
    """

    def param_specs(self):
        h = self.conf.size
        specs = [self._weight_spec(0, (h, 4 * h), initial_smart=True)]
        if self.conf.with_bias:
            specs.append(self._bias_spec((7 * h,)))
        return specs

    def forward(self, params, inputs, ctx):
        seq = inputs[0]
        enforce(isinstance(seq, SequenceBatch), "lstmemory needs sequence input")
        h = self.conf.size
        w_hh = params[self.weight_name(0)]
        bias = params.get(self.bias_name()) if self.conf.with_bias else None
        gate_bias = check_i = check_f = check_o = None
        if bias is not None:
            gate_bias = bias[: 4 * h]
            check_i = bias[4 * h: 5 * h]
            check_f = bias[5 * h: 6 * h]
            check_o = bias[6 * h: 7 * h]
        out, _ = recurrent_ops.lstm_sequence(
            seq, None, w_hh, gate_bias, check_i, check_f, check_o,
            reverse=self.conf.attrs.get("reversed", False),
            gate_act=self.conf.attrs.get("active_gate_type", "sigmoid"),
            cell_act=self.conf.attrs.get("active_state_type", "tanh"),
            out_act=self.conf.active_type or "tanh")
        return out


@register_layer("gated_recurrent", "grumemory")
class GatedRecurrentLayer(Layer):
    """Input [B, T, 3H] pre-projected; recurrent weight [H, 3H]."""

    def param_specs(self):
        h = self.conf.size
        specs = [self._weight_spec(0, (h, 3 * h), initial_smart=True)]
        if self.conf.with_bias:
            specs.append(self._bias_spec((3 * h,)))
        return specs

    def forward(self, params, inputs, ctx):
        seq = inputs[0]
        enforce(isinstance(seq, SequenceBatch), "grumemory needs sequence input")
        h = self.conf.size
        out, _ = recurrent_ops.gru_sequence(
            seq, None, params[self.weight_name(0)],
            params.get(self.bias_name()) if self.conf.with_bias else None,
            reverse=self.conf.attrs.get("reversed", False),
            gate_act=self.conf.attrs.get("active_gate_type", "sigmoid"),
            act=self.conf.active_type or "tanh")
        return out


@register_layer("recurrent")
class RecurrentLayer(Layer):
    """Simple recurrence over a pre-projected sequence (``RecurrentLayer``)."""

    def param_specs(self):
        h = self.conf.size
        specs = [self._weight_spec(0, (h, h), initial_smart=True)]
        if self.conf.with_bias:
            specs.append(self._bias_spec((h,)))
        return specs

    def forward(self, params, inputs, ctx):
        seq = inputs[0]
        out, _ = recurrent_ops.simple_rnn(
            seq, params[self.weight_name(0)],
            params.get(self.bias_name()) if self.conf.with_bias else None,
            reverse=self.conf.attrs.get("reversed", False),
            act=self.conf.active_type or "tanh")
        return out


@register_layer("lstm_step")
class LstmStepLayer(Layer):
    """Single LSTM step for recurrent groups (``LstmStepLayer``).

    Inputs: [0] projected gates [B, 4H]; [1] prev state c [B, H] (as the
    second output convention).  Output: h; cell state exposed via attrs.
    """

    def param_specs(self):
        h = self.conf.size
        specs = [self._weight_spec(0, (h, 4 * h), initial_smart=True)]
        if self.conf.with_bias:
            specs.append(self._bias_spec((7 * h,)))
        return specs

    def forward(self, params, inputs, ctx):
        x = value_of(inputs[0])
        h_prev = value_of(inputs[1])
        c_prev = value_of(inputs[2])
        h = self.conf.size
        bias = params.get(self.bias_name()) if self.conf.with_bias else None
        gb = ci = cf = co = None
        if bias is not None:
            gb, ci, cf, co = (bias[:4 * h], bias[4 * h:5 * h],
                              bias[5 * h:6 * h], bias[6 * h:7 * h])
            x = x + gb
        state, out = recurrent_ops.lstm_gate_step(
            x, LstmState(h=h_prev, c=c_prev), params[self.weight_name(0)],
            ci, cf, co)
        # expose (h, c); network stores tuple outputs by name suffix
        return {"out": like(inputs[0], out), "state": like(inputs[0], state.c)}


@register_layer("gru_step")
class GruStepLayer(Layer):
    def param_specs(self):
        h = self.conf.size
        specs = [self._weight_spec(0, (h, 3 * h), initial_smart=True)]
        if self.conf.with_bias:
            specs.append(self._bias_spec((3 * h,)))
        return specs

    def forward(self, params, inputs, ctx):
        x = value_of(inputs[0])
        h_prev = value_of(inputs[1])
        bias = params.get(self.bias_name()) if self.conf.with_bias else None
        if bias is not None:
            x = x + bias
        out = recurrent_ops.gru_unit(
            x, h_prev, params[self.weight_name(0)],
            gate_act=self.conf.attrs.get("active_gate_type", "sigmoid"),
            act=self.conf.active_type or "tanh")
        return like(inputs[0], out)

"""Recurrent layers: lstmemory, grumemory, recurrent, and step variants.

Reference: ``LstmLayer`` (type ``lstmemory``, with peephole "check" weights —
``paddle/gserver/layers/LstmLayer.cpp``), ``GatedRecurrentLayer``
(``gated_recurrent``), ``RecurrentLayer`` (``recurrent``), ``MDLstmLayer``
(not ported — 2-D LSTM, rarely used), plus step layers ``lstm_step`` /
``gru_step`` used inside recurrent groups.

Convention parity: like the reference, ``lstmemory`` expects its input
already projected to 4H by an upstream fc/mixed layer (the v1 DSL's
``lstmemory`` wraps exactly that); ``gated_recurrent`` expects 3H.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.sequence import SequenceBatch, like, value_of
import numpy as np

from ..ops import recurrent_ops
from ..ops import get_activation
from ..ops.recurrent_ops import LstmState
from ..utils import ConfigError, enforce
from .base import ForwardContext, Layer, register_layer


@register_layer("lstmemory")
class LstmLayer(Layer):
    """Input: sequence of [B, T, 4H] pre-projected gates; output [B, T, H].

    Parameters: recurrent weight [H, 4H], bias [7H] = 4H gate bias + 3H
    peephole checks (reference bias layout in LstmLayer.cpp).
    """

    def param_specs(self):
        h = self.conf.size
        specs = [self._weight_spec(0, (h, 4 * h), initial_smart=True)]
        if self.conf.with_bias:
            specs.append(self._bias_spec((7 * h,)))
        return specs

    def forward(self, params, inputs, ctx):
        seq = inputs[0]
        enforce(isinstance(seq, SequenceBatch), "lstmemory needs sequence input")
        h = self.conf.size
        w_hh = params[self.weight_name(0)]
        bias = params.get(self.bias_name()) if self.conf.with_bias else None
        gate_bias = check_i = check_f = check_o = None
        if bias is not None:
            gate_bias = bias[: 4 * h]
            check_i = bias[4 * h: 5 * h]
            check_f = bias[5 * h: 6 * h]
            check_o = bias[6 * h: 7 * h]
        # reference routing (hl_lstm_ops.cuh:60,65): active_type acts on
        # the candidate input, active_state_type on the cell output
        out, _ = recurrent_ops.lstm_sequence(
            seq, None, w_hh, gate_bias, check_i, check_f, check_o,
            reverse=self.conf.attrs.get("reversed", False),
            gate_act=self.conf.attrs.get("active_gate_type", "sigmoid"),
            cell_act=self.conf.active_type or "tanh",
            out_act=self.conf.attrs.get("active_state_type", "tanh"))
        return out


@register_layer("gated_recurrent", "grumemory")
class GatedRecurrentLayer(Layer):
    """Input [B, T, 3H] pre-projected; recurrent weight [H, 3H]."""

    def param_specs(self):
        h = self.conf.size
        specs = [self._weight_spec(0, (h, 3 * h), initial_smart=True)]
        if self.conf.with_bias:
            specs.append(self._bias_spec((3 * h,)))
        return specs

    def forward(self, params, inputs, ctx):
        seq = inputs[0]
        enforce(isinstance(seq, SequenceBatch), "grumemory needs sequence input")
        h = self.conf.size
        out, _ = recurrent_ops.gru_sequence(
            seq, None, params[self.weight_name(0)],
            params.get(self.bias_name()) if self.conf.with_bias else None,
            reverse=self.conf.attrs.get("reversed", False),
            gate_act=self.conf.attrs.get("active_gate_type", "sigmoid"),
            act=self.conf.active_type or "tanh")
        return out


@register_layer("recurrent")
class RecurrentLayer(Layer):
    """Simple recurrence over a pre-projected sequence (``RecurrentLayer``)."""

    def param_specs(self):
        h = self.conf.size
        specs = [self._weight_spec(0, (h, h), initial_smart=True)]
        if self.conf.with_bias:
            specs.append(self._bias_spec((h,)))
        return specs

    def forward(self, params, inputs, ctx):
        seq = inputs[0]
        out, _ = recurrent_ops.simple_rnn(
            seq, params[self.weight_name(0)],
            params.get(self.bias_name()) if self.conf.with_bias else None,
            reverse=self.conf.attrs.get("reversed", False),
            act=self.conf.active_type or "tanh")
        return out


@register_layer("lstm_step")
class LstmStepLayer(Layer):
    """Single LSTM step for recurrent groups (``LstmStepLayer.cpp``).

    Reference contract (init: ``CHECK_EQ(2U, inputLayers_.size())``):
    inputs [0] gates [B, 4H] — already containing EVERY contribution,
    recurrent included (no weight matrix on this layer) — and [1] the
    previous cell state c [B, H].  The 3H bias parameter holds the
    peephole checks (checkIg/checkFg/checkOg, ``:83-101``).
    Outputs: h, with the new cell exposed as ``.state``.
    """

    def param_specs(self):
        h = self.conf.size
        if self.conf.with_bias:
            return [self._bias_spec((3 * h,))]
        return []

    def forward(self, params, inputs, ctx):
        x = value_of(inputs[0])
        c_prev = value_of(inputs[1])
        h = self.conf.size
        checks = params.get(self.bias_name()) if self.conf.with_bias else None
        ci = cf = co = None
        if checks is not None:
            ci, cf, co = checks[:h], checks[h:2 * h], checks[2 * h:3 * h]
        state, out = recurrent_ops.lstm_gate_step(
            x, LstmState(h=jnp.zeros_like(c_prev), c=c_prev), None,
            ci, cf, co,
            gate_act=self.conf.attrs.get("active_gate_type", "sigmoid"),
            cell_act=self.conf.active_type or "tanh",
            out_act=self.conf.attrs.get("active_state_type", "tanh"))
        # expose (h, c); network stores dict outputs by name suffix
        return {"out": self.apply_extras(like(inputs[0], out), ctx),
                "state": like(inputs[0], state.c)}


@register_layer("gru_step")
class GruStepLayer(Layer):
    def param_specs(self):
        h = self.conf.size
        specs = [self._weight_spec(0, (h, 3 * h), initial_smart=True)]
        if self.conf.with_bias:
            specs.append(self._bias_spec((3 * h,)))
        return specs

    def forward(self, params, inputs, ctx):
        x = value_of(inputs[0])
        h_prev = value_of(inputs[1])
        bias = params.get(self.bias_name()) if self.conf.with_bias else None
        if bias is not None:
            x = x + bias
        out = recurrent_ops.gru_unit(
            x, h_prev, params[self.weight_name(0)],
            gate_act=self.conf.attrs.get("active_gate_type", "sigmoid"),
            act=self.conf.active_type or "tanh")
        return self.apply_extras(like(inputs[0], out), ctx)


@register_layer("mdlstmemory")
class MDLstmLayer(Layer):
    """2-D multi-dimensional LSTM (``MDLstmLayer.cpp``; Graves MD-LSTM).

    Input: pre-projected gates over an H×W grid — dense [B, H*W*(3+nd)*D]
    or SequenceBatch [B, H*W, (3+nd)*D] with nd=2 — gate column layout
    [inode | ig | fg×nd | og] (``forwardGate2OutputSequence``).  Output is
    the [B, H, W, D] hidden grid flattened to [B, H*W*D].

    Parameters: recurrent weight [D, (3+nd)D] shared across dims
    (``forwardOneSequence`` multiplies every predecessor by the same W);
    bias [(5+2nd)D] = local gate bias (3+nd)D + peephole checks
    checkIg(D) + checkFg(nd·D) + checkOg(D).

    TPU mapping: ``lax.scan`` over rows carrying the previous row's
    (h, c) [W, D], inner ``lax.scan`` over columns carrying (h, c) of the
    left neighbour — the reference's CoordIterator grid walk with the
    same data dependencies, vmapped over the batch.  Non-default
    directions flip the grid before/after the scan.
    """

    ND = 2

    def param_specs(self):
        d = self.conf.size
        nd = self.ND
        specs = [self._weight_spec(0, (d, (3 + nd) * d), initial_smart=True)]
        if self.conf.with_bias:
            specs.append(self._bias_spec(((5 + 2 * nd) * d,)))
        return specs

    def forward(self, params, inputs, ctx):
        d = self.conf.size
        nd = self.ND
        gw = (3 + nd) * d
        height = self.conf.attrs.get("height")
        width = self.conf.attrs.get("width")
        v = value_of(inputs[0])
        if v.ndim == 3:                      # SequenceBatch frames
            b = v.shape[0]
            enforce(height is not None and width is not None,
                    "mdlstmemory on sequences needs height/width attrs")
            x = v.reshape(b, height, width, gw)
        else:
            b = v.shape[0]
            if height is None or width is None:
                hw = v.shape[1] // gw
                side = int(np.sqrt(hw))
                enforce(side * side == hw,
                        "mdlstmemory: supply height/width attrs for "
                        "non-square grids")
                height = width = side
            x = v.reshape(b, height, width, gw)

        w = params[self.weight_name(0)]
        bias = params.get(self.bias_name()) if self.conf.with_bias else None
        if bias is not None:
            local = bias[:gw]
            check_ig = bias[gw:gw + d]
            check_fg = bias[gw + d:gw + d + nd * d].reshape(nd, d)
            check_og = bias[gw + (1 + nd) * d:gw + (2 + nd) * d]
        else:
            local = jnp.zeros((gw,))
            check_ig = check_og = jnp.zeros((d,))
            check_fg = jnp.zeros((nd, d))

        directions = self.conf.attrs.get("directions", [True, True])
        gate_act = get_activation(
            self.conf.attrs.get("active_gate_type", "sigmoid"))
        state_act = get_activation(
            self.conf.attrs.get("active_state_type", "tanh"))
        node_act = get_activation(self.conf.active_type or "tanh")

        # canonicalize walk order to top-left → bottom-right
        flip_axes = [i + 1 for i, fwd in enumerate(directions) if not fwd]
        if flip_axes:
            x = jnp.flip(x, axis=flip_axes)

        def cell(carry_left, xg_and_up):
            h_left, c_left = carry_left
            xg, h_up, c_up = xg_and_up
            g = xg + local + h_up @ w + h_left @ w
            inode = g[:d]
            ig = g[d:2 * d] + c_up * check_ig + c_left * check_ig
            fg0 = g[2 * d:3 * d] + c_up * check_fg[0]
            fg1 = g[3 * d:4 * d] + c_left * check_fg[1]
            og = g[4 * d:5 * d]
            c = (gate_act(fg0) * c_up + gate_act(fg1) * c_left
                 + node_act(inode) * gate_act(ig))
            h = state_act(c) * gate_act(og + c * check_og)
            return (h, c), (h, c)

        def row_step(carry_row, x_row):
            h_row, c_row = carry_row                     # [W, D] previous row
            zero = jnp.zeros((d,), x_row.dtype)
            (_, _), (hs, cs) = jax.lax.scan(
                cell, (zero, zero), (x_row, h_row, c_row))
            return (hs, cs), hs

        def one_image(img):
            init = (jnp.zeros((width, d), img.dtype),
                    jnp.zeros((width, d), img.dtype))
            _, h_grid = jax.lax.scan(row_step, init, img)
            return h_grid                                # [H, W, D]

        out = jax.vmap(one_image)(x)
        if flip_axes:
            out = jnp.flip(out, axis=flip_axes)
        out = out.reshape(b, height * width * d)
        if isinstance(inputs[0], SequenceBatch):
            out = out.reshape(b, height * width, d)
            return like(inputs[0], out)
        return out

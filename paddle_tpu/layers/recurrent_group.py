"""Recurrent layer groups — the ``RecurrentGradientMachine`` equivalent.

Reference: ``paddle/gserver/gradientmachines/RecurrentGradientMachine.cpp``
runs a sub-``ModelConfig`` once per timestep over variable-length sequences,
wiring ScatterAgent/GatherAgent layers for frame I/O and "memory" links that
feed a layer's frame-``t`` output into frame ``t+1``
(``config_parser.py:367`` RecurrentLayerGroupBegin).

TPU-first re-design: the per-step sub-network is **traced once** and driven
by ``lax.scan`` over the padded time axis.  Memories are scan carries;
in-links are scanned inputs; out-links are stacked scan outputs.  Masking
freezes carries past each sequence's length, reproducing the reference's
variable-length semantics without dynamic shapes.  Beam-search generation
lives in :mod:`paddle_tpu.layers.beam_search` as a ``lax.while_loop``.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from ..config.model_config import ModelConfig, SubModelConfig
from ..core.sequence import NestedSequenceBatch, SequenceBatch, value_of
from ..utils import ConfigError, enforce, layer_stack
from .base import LAYERS, ForwardContext, Layer, cast_layer_output


class RecurrentGroup:
    """Executes one SubModelConfig with lax.scan."""

    # Epilogue hoisting (see :meth:`_split_scan_epilogue`); class attr so
    # tests can compare hoisted vs in-scan execution.
    HOIST = True
    # scan unroll for the sequential phase (amortizes per-step loop
    # overhead; same knob as ops/recurrent_ops._UNROLL)
    UNROLL = 1

    def __init__(self, sub: SubModelConfig, model: ModelConfig):
        self.sub = sub
        self.model = model
        self.layers: Dict[str, Layer] = {}
        self.order: List[str] = []
        lmap = model.layer_map()
        for ln in sub.layer_names:
            conf = lmap[ln]
            if conf.type == "data":
                continue
            self.layers[ln] = LAYERS.get(conf.type)(conf, model)
            self.order.append(ln)
        self.in_links = list(sub.in_links)
        self.out_links = list(sub.out_links)
        self.memories = list(sub.memories)

    # ------------------------------------------------- epilogue hoisting
    def _producer_of(self, iname: str) -> Optional[str]:
        """Group-layer that produces value name ``iname`` (handles the
        ``layer.subkey`` convention for dict outputs), else None."""
        if iname in self.layers:
            return iname
        if "." in iname:
            head = iname.split(".", 1)[0]
            if head in self.layers:
                return head
        return None

    # Layer types whose forward is pointwise over leading axes (operate
    # on the trailing feature dim only), so running them once on a
    # stacked [B, T, ...] SequenceBatch is identical to running them
    # per-frame.  Sequence-aware types (pooling, last_seq, expand,
    # concat, ...) must NOT be hoisted — on a stacked batch they would
    # reduce over time.
    POINTWISE_TYPES = frozenset({
        "fc", "mkldnn_fc", "mixed", "addto", "scaling", "clip",
        "slope_intercept", "power", "get_output", "maxid", "print",
        "dot_prod", "interpolation",
    })
    # projection/operator types inside a mixed layer that look across
    # the time axis — a mixed layer carrying one is NOT pointwise
    _SEQ_PROJ_TYPES = frozenset({"context", "conv", "convt"})

    def _is_pointwise(self, conf) -> bool:
        if conf.type not in self.POINTWISE_TYPES:
            return False
        if conf.type == "mixed":
            for inp in conf.inputs:
                proj = getattr(inp, "proj", None)
                if proj is not None and proj.type in self._SEQ_PROJ_TYPES:
                    return False
        return True

    def _split_scan_epilogue(self) -> Tuple[set, List[str]]:
        """Split the step layers into (scan set, hoisted suffix).

        A layer must run inside the scan iff a memory depends on it
        (transitively) or its type is not time-pointwise.  Everything
        else can run AFTER the scan, once, over the whole stacked time
        axis.  XLA then batches the hoisted matmuls over T*B instead of
        issuing T sequential ones; for decoder output projections
        ([B,H]×[H,V] per step, V≫H) this is the difference between
        MXU-bound and latency-bound.  This is an optimization the
        reference's step-by-step ``RecurrentGradientMachine.cpp`` cannot
        express.
        """
        # memories may bind a dict sub-output ("lstm_out.state"): seed
        # with the PRODUCER layer, not the raw value name
        need = set()
        for m in self.memories:
            p = self._producer_of(m["layer_name"])
            if p is None:
                raise ConfigError(
                    f"group {self.sub.name}: memory layer "
                    f"{m['layer_name']!r} is not produced by the group")
            need.add(p)
        for n in self.order:      # non-pointwise layers stay in the scan
            if not self._is_pointwise(self.layers[n].conf):
                need.add(n)
        changed = True
        while changed:
            changed = False
            for n in list(need):
                for iname in self.layers[n].conf.input_names():
                    p = self._producer_of(iname)
                    if p is not None and p not in need:
                        need.add(p)
                        changed = True
        hoisted = [n for n in self.order if n not in need]
        return need, hoisted

    def _memory_init(self, mem: Dict[str, Any], values: Dict[str, Any],
                     batch: int, dtype) -> jax.Array:
        boot = mem.get("boot_layer_name")
        if boot:
            return value_of(values[boot]).astype(dtype)
        size = mem.get("size", 0)
        if not size:
            # dotted memory names ("lstm_out.state") size like their head
            size = self.model.find_layer(
                mem["layer_name"].split(".", 1)[0]).size
        init = jnp.zeros((batch, size), dtype)
        bias = mem.get("boot_bias")
        if bias is not None:
            init = init + bias
        return init

    def _forward_layers(self, names: List[str], values: Dict[str, Any],
                        outer: Dict[str, Any], params: Dict[str, jax.Array],
                        ctx: ForwardContext) -> None:
        """Run ``names`` (already topo-ordered) in place over ``values``."""
        for name in names:
            layer = self.layers[name]
            # named_scope keys the step layer's compiled regions back to
            # it for cost attribution ("." separator: XLA's op_name
            # sanitizer strips "@" and everything after it)
            with layer_stack.guard(name + "@" + self.sub.name), \
                    jax.named_scope(name + "." + self.sub.name):
                inputs = []
                for iname in layer.conf.input_names():
                    if iname in values:
                        inputs.append(values[iname])
                    elif iname in outer:  # static (read-only) outer input
                        inputs.append(outer[iname])
                    else:
                        raise ConfigError(
                            f"group {self.sub.name}: input {iname!r} not found")
                out = cast_layer_output(layer, layer.forward(params, inputs, ctx))
            if isinstance(out, dict):
                for k, v in out.items():
                    values[name if k == "out" else f"{name}.{k}"] = v
            else:
                values[name] = out

    def step(self, params: Dict[str, jax.Array], frame: Dict[str, Any],
             mems: List[jax.Array], outer: Dict[str, Any],
             ctx: ForwardContext,
             order: Optional[List[str]] = None
             ) -> Tuple[List[jax.Array], Dict[str, Any]]:
        """One timestep: returns (new memory values, all step outputs)."""
        values: Dict[str, Any] = dict(frame)
        for mem, mval in zip(self.memories, mems):
            values[mem.get("link_name", mem["layer_name"] + "@pre")] = mval
        self._forward_layers(self.order if order is None else order,
                             values, outer, params, ctx)
        new_mems = [value_of(values[m["layer_name"]]) for m in self.memories]
        return new_mems, values

    def run(self, params: Dict[str, jax.Array], values: Dict[str, Any],
            ctx: ForwardContext) -> None:
        """Scan the group over its in-link sequences; writes out-link
        sequences into ``values``."""
        enforce(self.in_links, f"group {self.sub.name} has no in_links")
        if isinstance(values[self.in_links[0]], NestedSequenceBatch):
            return self._run_nested(params, values, ctx)
        seqs = []
        for l in self.in_links:
            s = values[l]
            enforce(isinstance(s, SequenceBatch),
                    f"in_link {l!r} must be a sequence")
            seqs.append(s)
        from ..core.dtypes import current_policy

        t = seqs[0].max_len
        b = seqs[0].batch_size
        length = seqs[0].length
        # carries/mask in the policy output dtype: under
        # --bf16_activations the whole scan body runs bf16 (layer outputs
        # are bf16), so a fp32 carry would destabilize the scan dtype
        fdt = current_policy().output_dtype
        mask = seqs[0].mask(fdt)  # [B, T]
        dtype = seqs[0].data.dtype

        mems0 = [self._memory_init(m, values, b, fdt)
                 for m in self.memories]

        # scanned inputs: [T, B, ...]
        xs = {l: jnp.moveaxis(s.data, 1, 0) for l, s in zip(self.in_links, seqs)}
        m_t = jnp.moveaxis(mask, 1, 0)
        if self.sub.reversed:
            xs = {k: v[::-1] for k, v in xs.items()}
            m_t = m_t[::-1]

        outer = values

        scan_set, hoisted = (self._split_scan_epilogue() if self.HOIST
                             else (set(self.order), []))
        hoist_set = set(hoisted)
        # classify out-links by PRODUCER (an out-link can be a dict
        # sub-output like "lstm_out.state")
        hoist_outs = [o for o in self.out_links
                      if (self._producer_of(o) or o) in hoist_set]
        # hoisted layers that (transitively) feed a hoisted out-link;
        # the rest are dead past the scan and are dropped entirely —
        # except side-effect layers (print), which must still run
        live = {self._producer_of(o) or o for o in hoist_outs}
        live |= {n for n in hoisted if self.layers[n].conf.type == "print"}
        for n in reversed(hoisted):
            if n in live:
                for iname in self.layers[n].conf.input_names():
                    p = self._producer_of(iname)
                    if p is not None and p in hoist_set:
                        live.add(p)
        hoisted = [n for n in hoisted if n in live]
        hoist_set = set(hoisted)
        # values the epilogue reads out of the scan: in-scan layer
        # outputs (incl. dict sub-outputs) and memory pre-values
        mem_links = {m.get("link_name", m["layer_name"] + "@pre")
                     for m in self.memories}
        boundary: set = set()
        frames_used: set = set()
        for n in hoisted:
            for iname in self.layers[n].conf.input_names():
                p = self._producer_of(iname)
                if p is not None and p in scan_set:
                    boundary.add(iname)
                elif iname in mem_links:
                    boundary.add(iname)
                elif iname in self.in_links:
                    frames_used.add(iname)

        scan_order = [n for n in self.order if n in scan_set]
        scan_outs = [o for o in self.out_links if o not in set(hoist_outs)]

        def scan_fn(carry, inp):
            mems = carry
            frame_inputs = {l: inp[l] for l in self.in_links}
            m = inp["__mask__"][:, None]
            new_mems, step_vals = self.step(params, frame_inputs, mems,
                                            outer, ctx, order=scan_order)
            kept = [m * nm + (1 - m) * om for nm, om in zip(new_mems, mems)]
            outs = {}
            for o in scan_outs:
                d = value_of(step_vals[o])
                mb = (m > 0).reshape((b,) + (1,) * (d.ndim - 1))
                # where, not multiply: keeps integer out-links (maxid,
                # sampling ids) in their own dtype
                outs[o] = jnp.where(mb, d, jnp.zeros((), d.dtype))
            for bname in boundary:
                outs["__b__" + bname] = value_of(step_vals[bname])
            return kept, outs

        inp = dict(xs)
        inp["__mask__"] = m_t
        _, stacked = jax.lax.scan(scan_fn, mems0, inp,
                                  unroll=self.UNROLL)

        for o in scan_outs:
            data = jnp.moveaxis(stacked[o], 0, 1)  # [B, T, ...]
            if self.sub.reversed:
                data = data[:, ::-1]
            values[o] = SequenceBatch(data=data, length=length)

        if hoisted:
            # Run the time-pointwise suffix ONCE over the whole stacked
            # sequence, as ordinary [B, T, ...] SequenceBatch layers in
            # batch-major layout.  The boundary tensors crossing the
            # scan→epilogue cut are small ([T, B, H] hidden states); the
            # big epilogue products (decoder softmax projections,
            # [B, T, V]) are produced directly in their consumer layout —
            # profiling showed the old per-frame vmap forced a [T, B, V]
            # stack + transpose + reshape worth ~20% of the seq2seq step.
            vals: Dict[str, Any] = {}
            for bname in boundary:
                d = stacked.pop("__b__" + bname)
                if self.sub.reversed:
                    d = d[::-1]
                vals[bname] = SequenceBatch(data=jnp.moveaxis(d, 0, 1),
                                            length=length)
            for l in frames_used:
                vals[l] = values[l] if isinstance(values[l], SequenceBatch) \
                    else SequenceBatch(data=jnp.moveaxis(xs[l], 0, 1),
                                       length=length)
            self._forward_layers(hoisted, vals, outer, params, ctx)
            mask2 = mask > 0                       # [B, T]
            for o in hoist_outs:
                v = vals[o]
                d = value_of(v)
                mb = mask2.reshape(mask2.shape + (1,) * (d.ndim - 2))
                d = jnp.where(mb, d, jnp.zeros((), d.dtype))
                values[o] = SequenceBatch(data=d, length=length)
            # expose dict sub-outputs of hoisted out-link producers
            # (e.g. 'dec_prob.logits' for the fused-CE peephole);
            # unmasked — consumers mask by length themselves
            outp = {self._producer_of(o) or o for o in hoist_outs}
            for k, v in vals.items():
                if "." in k and k.split(".", 1)[0] in outp \
                        and k not in values:
                    d = value_of(v)
                    values[k] = SequenceBatch(data=d, length=length) \
                        if d.ndim >= 2 and d.shape[:2] == (b, t) else v

    def _run_nested(self, params: Dict[str, jax.Array],
                    values: Dict[str, Any], ctx: ForwardContext) -> None:
        """Nested in-links (LoD level 2): the group steps over
        SUBSEQUENCES — each scan frame is a whole ``SequenceBatch`` that
        the step's sequence-aware layers (pooling, last_seq, recurrent
        layers, nested groups) consume — exactly how
        ``RecurrentGradientMachine`` sequences over
        ``subSequenceStartPositions`` when in-links carry sub-sequence
        info (``RecurrentGradientMachine.cpp`` createInFrameInfo /
        ``test_RecurrentGradientMachine.cpp`` sequence_nest_rnn.conf).
        Memories still carry [B, size] state across subsequences."""
        seqs: List[NestedSequenceBatch] = []
        for l in self.in_links:
            s = values[l]
            enforce(isinstance(s, NestedSequenceBatch),
                    f"in_link {l!r}: all in-links of a nested group must "
                    "be nested sequences")
            seqs.append(s)
        from ..core.dtypes import current_policy

        b = seqs[0].batch_size
        num_subseq = seqs[0].num_subseq
        fdt = current_policy().output_dtype
        outer_mask = seqs[0].subseq_mask(fdt)                # [B, S]

        mems0 = [self._memory_init(m, values, b, fdt)
                 for m in self.memories]

        # scanned inputs: SequenceBatch pytrees with leading S axis
        xs = {l: SequenceBatch(data=jnp.moveaxis(s.data, 1, 0),
                               length=jnp.moveaxis(
                                   s.sub_length *
                                   s.subseq_mask(jnp.int32), 1, 0))
              for l, s in zip(self.in_links, seqs)}
        m_t = jnp.moveaxis(outer_mask, 1, 0)                 # [S, B]
        if self.sub.reversed:
            xs = {k: SequenceBatch(data=v.data[::-1],
                                   length=v.length[::-1])
                  for k, v in xs.items()}
            m_t = m_t[::-1]

        outer = values

        def scan_fn(carry, inp):
            mems = carry
            frame_inputs = {l: inp[l] for l in self.in_links}
            m = inp["__mask__"]                              # [B]
            new_mems, step_vals = self.step(params, frame_inputs, mems,
                                            outer, ctx)
            kept = [m[:, None] * nm + (1 - m[:, None]) * om
                    for nm, om in zip(new_mems, mems)]
            outs = {}
            for o in self.out_links:
                v = step_vals[o]
                d = value_of(v)
                mb = (m > 0).reshape((b,) + (1,) * (d.ndim - 1))
                d = jnp.where(mb, d, jnp.zeros((), d.dtype))
                if isinstance(v, SequenceBatch):             # seq out-link
                    outs[o] = SequenceBatch(
                        data=d, length=v.length * (m > 0).astype(jnp.int32))
                else:
                    outs[o] = d
            return kept, outs

        inp: Dict[str, Any] = dict(xs)
        inp["__mask__"] = m_t
        _, stacked = jax.lax.scan(scan_fn, mems0, inp)
        for o in self.out_links:
            v = stacked[o]
            if isinstance(v, SequenceBatch):
                # [S, B, T, ...] → nested [B, S, T, ...]
                data = jnp.moveaxis(v.data, 0, 1)
                sub_len = jnp.moveaxis(v.length, 0, 1)
                if self.sub.reversed:
                    data, sub_len = data[:, ::-1], sub_len[:, ::-1]
                values[o] = NestedSequenceBatch(
                    data=data, num_subseq=num_subseq, sub_length=sub_len)
            else:
                data = jnp.moveaxis(v, 0, 1)                 # [B, S, ...]
                if self.sub.reversed:
                    data = data[:, ::-1]
                values[o] = SequenceBatch(data=data, length=num_subseq)

"""Recurrent layer groups — the ``RecurrentGradientMachine`` equivalent.

Reference: ``paddle/gserver/gradientmachines/RecurrentGradientMachine.cpp``
runs a sub-``ModelConfig`` once per timestep over variable-length sequences,
wiring ScatterAgent/GatherAgent layers for frame I/O and "memory" links that
feed a layer's frame-``t`` output into frame ``t+1``
(``config_parser.py:367`` RecurrentLayerGroupBegin).

TPU-first re-design: the per-step sub-network is **traced once** and driven
by ``lax.scan`` over the padded time axis.  Memories are scan carries;
in-links are scanned inputs; out-links are stacked scan outputs.  Masking
freezes carries past each sequence's length, reproducing the reference's
variable-length semantics without dynamic shapes.  Beam-search generation
lives in :mod:`paddle_tpu.layers.beam_search` as a ``lax.while_loop``.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from ..config.model_config import ModelConfig, SubModelConfig
from ..core.sequence import NestedSequenceBatch, SequenceBatch, value_of
from ..utils import ConfigError, enforce, layer_stack
from .base import LAYERS, ForwardContext, Layer


class RecurrentGroup:
    """Executes one SubModelConfig with lax.scan."""

    def __init__(self, sub: SubModelConfig, model: ModelConfig):
        self.sub = sub
        self.model = model
        self.layers: Dict[str, Layer] = {}
        self.order: List[str] = []
        lmap = model.layer_map()
        for ln in sub.layer_names:
            conf = lmap[ln]
            if conf.type == "data":
                continue
            self.layers[ln] = LAYERS.get(conf.type)(conf, model)
            self.order.append(ln)
        self.in_links = list(sub.in_links)
        self.out_links = list(sub.out_links)
        self.memories = list(sub.memories)

    def _memory_init(self, mem: Dict[str, Any], values: Dict[str, Any],
                     batch: int, dtype) -> jax.Array:
        boot = mem.get("boot_layer_name")
        if boot:
            return value_of(values[boot])
        size = mem.get("size", 0)
        if not size:
            size = self.model.find_layer(mem["layer_name"]).size
        init = jnp.zeros((batch, size), dtype)
        bias = mem.get("boot_bias")
        if bias is not None:
            init = init + bias
        return init

    def step(self, params: Dict[str, jax.Array], frame: Dict[str, Any],
             mems: List[jax.Array], outer: Dict[str, Any],
             ctx: ForwardContext) -> Tuple[List[jax.Array], Dict[str, Any]]:
        """One timestep: returns (new memory values, all step outputs)."""
        values: Dict[str, Any] = dict(frame)
        for mem, mval in zip(self.memories, mems):
            values[mem.get("link_name", mem["layer_name"] + "@pre")] = mval
        for name in self.order:
            layer = self.layers[name]
            with layer_stack.guard(name + "@" + self.sub.name):
                inputs = []
                for iname in layer.conf.input_names():
                    if iname in values:
                        inputs.append(values[iname])
                    elif iname in outer:  # static (read-only) outer input
                        inputs.append(outer[iname])
                    else:
                        raise ConfigError(
                            f"group {self.sub.name}: input {iname!r} not found")
                out = layer.forward(params, inputs, ctx)
            if isinstance(out, dict):
                for k, v in out.items():
                    values[name if k == "out" else f"{name}.{k}"] = v
            else:
                values[name] = out
        new_mems = [value_of(values[m["layer_name"]]) for m in self.memories]
        return new_mems, values

    def run(self, params: Dict[str, jax.Array], values: Dict[str, Any],
            ctx: ForwardContext) -> None:
        """Scan the group over its in-link sequences; writes out-link
        sequences into ``values``."""
        enforce(self.in_links, f"group {self.sub.name} has no in_links")
        if isinstance(values[self.in_links[0]], NestedSequenceBatch):
            return self._run_nested(params, values, ctx)
        seqs = []
        for l in self.in_links:
            s = values[l]
            enforce(isinstance(s, SequenceBatch),
                    f"in_link {l!r} must be a sequence")
            seqs.append(s)
        t = seqs[0].max_len
        b = seqs[0].batch_size
        length = seqs[0].length
        mask = seqs[0].mask(jnp.float32)  # [B, T]
        dtype = seqs[0].data.dtype

        mems0 = [self._memory_init(m, values, b, jnp.float32)
                 for m in self.memories]

        # scanned inputs: [T, B, ...]
        xs = {l: jnp.moveaxis(s.data, 1, 0) for l, s in zip(self.in_links, seqs)}
        m_t = jnp.moveaxis(mask, 1, 0)
        if self.sub.reversed:
            xs = {k: v[::-1] for k, v in xs.items()}
            m_t = m_t[::-1]

        outer = values

        def scan_fn(carry, inp):
            mems = carry
            frame_inputs = {l: inp[l] for l in self.in_links}
            m = inp["__mask__"][:, None]
            new_mems, step_vals = self.step(params, frame_inputs, mems,
                                            outer, ctx)
            kept = [m * nm + (1 - m) * om for nm, om in zip(new_mems, mems)]
            outs = {}
            for o in self.out_links:
                d = value_of(step_vals[o])
                mb = (m > 0).reshape((b,) + (1,) * (d.ndim - 1))
                # where, not multiply: keeps integer out-links (maxid,
                # sampling ids) in their own dtype
                outs[o] = jnp.where(mb, d, jnp.zeros((), d.dtype))
            return kept, outs

        inp = dict(xs)
        inp["__mask__"] = m_t
        _, stacked = jax.lax.scan(scan_fn, mems0, inp)
        for o in self.out_links:
            data = jnp.moveaxis(stacked[o], 0, 1)  # [B, T, ...]
            if self.sub.reversed:
                data = data[:, ::-1]
            values[o] = SequenceBatch(data=data, length=length)

    def _run_nested(self, params: Dict[str, jax.Array],
                    values: Dict[str, Any], ctx: ForwardContext) -> None:
        """Nested in-links (LoD level 2): the group steps over
        SUBSEQUENCES — each scan frame is a whole ``SequenceBatch`` that
        the step's sequence-aware layers (pooling, last_seq, recurrent
        layers, nested groups) consume — exactly how
        ``RecurrentGradientMachine`` sequences over
        ``subSequenceStartPositions`` when in-links carry sub-sequence
        info (``RecurrentGradientMachine.cpp`` createInFrameInfo /
        ``test_RecurrentGradientMachine.cpp`` sequence_nest_rnn.conf).
        Memories still carry [B, size] state across subsequences."""
        seqs: List[NestedSequenceBatch] = []
        for l in self.in_links:
            s = values[l]
            enforce(isinstance(s, NestedSequenceBatch),
                    f"in_link {l!r}: all in-links of a nested group must "
                    "be nested sequences")
            seqs.append(s)
        b = seqs[0].batch_size
        num_subseq = seqs[0].num_subseq
        outer_mask = seqs[0].subseq_mask(jnp.float32)        # [B, S]

        mems0 = [self._memory_init(m, values, b, jnp.float32)
                 for m in self.memories]

        # scanned inputs: SequenceBatch pytrees with leading S axis
        xs = {l: SequenceBatch(data=jnp.moveaxis(s.data, 1, 0),
                               length=jnp.moveaxis(
                                   s.sub_length *
                                   s.subseq_mask(jnp.int32), 1, 0))
              for l, s in zip(self.in_links, seqs)}
        m_t = jnp.moveaxis(outer_mask, 1, 0)                 # [S, B]
        if self.sub.reversed:
            xs = {k: SequenceBatch(data=v.data[::-1],
                                   length=v.length[::-1])
                  for k, v in xs.items()}
            m_t = m_t[::-1]

        outer = values

        def scan_fn(carry, inp):
            mems = carry
            frame_inputs = {l: inp[l] for l in self.in_links}
            m = inp["__mask__"]                              # [B]
            new_mems, step_vals = self.step(params, frame_inputs, mems,
                                            outer, ctx)
            kept = [m[:, None] * nm + (1 - m[:, None]) * om
                    for nm, om in zip(new_mems, mems)]
            outs = {}
            for o in self.out_links:
                v = step_vals[o]
                d = value_of(v)
                mb = (m > 0).reshape((b,) + (1,) * (d.ndim - 1))
                d = jnp.where(mb, d, jnp.zeros((), d.dtype))
                if isinstance(v, SequenceBatch):             # seq out-link
                    outs[o] = SequenceBatch(
                        data=d, length=v.length * (m > 0).astype(jnp.int32))
                else:
                    outs[o] = d
            return kept, outs

        inp: Dict[str, Any] = dict(xs)
        inp["__mask__"] = m_t
        _, stacked = jax.lax.scan(scan_fn, mems0, inp)
        for o in self.out_links:
            v = stacked[o]
            if isinstance(v, SequenceBatch):
                # [S, B, T, ...] → nested [B, S, T, ...]
                data = jnp.moveaxis(v.data, 0, 1)
                sub_len = jnp.moveaxis(v.length, 0, 1)
                if self.sub.reversed:
                    data, sub_len = data[:, ::-1], sub_len[:, ::-1]
                values[o] = NestedSequenceBatch(
                    data=data, num_subseq=num_subseq, sub_length=sub_len)
            else:
                data = jnp.moveaxis(v, 0, 1)                 # [B, S, ...]
                if self.sub.reversed:
                    data = data[:, ::-1]
                values[o] = SequenceBatch(data=data, length=num_subseq)

"""SSD detection layers: priorbox, multibox_loss, detection_output.

Reference: ``PriorBox.cpp``, ``MultiBoxLossLayer.cpp``,
``DetectionOutputLayer.cpp`` (+ ``DetectionUtil.cpp``).  The math lives in
:mod:`paddle_tpu.ops.detection_ops`; these layers adapt the config-driven
input conventions:

- ``priorbox``: inputs [feature, image]; geometry comes from attrs
  (the DSL records the feature map and image dims at config time — the
  reference reads them from Argument frame sizes at runtime).  Output is
  the constant [1, P*8] prior tensor.
- ``multibox_loss``: inputs [priorbox, label, loc..., conf...]
  (``input_num`` loc layers then conf layers).  Labels are a padded
  SequenceBatch [B, G, 6] (class,xmin,ymin,xmax,ymax,difficult).
- ``detection_output``: inputs [priorbox, loc, conf] (the reference
  concatenates multiple loc/conf inputs at config time via concat layers;
  single concatenated inputs here).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.sequence import SequenceBatch, value_of
from ..ops import detection_ops
from ..utils import ConfigError, enforce
from .base import Layer, register_layer


def _priors_from_attrs(conf) -> np.ndarray:
    a = conf.attrs
    for k in ("layer_width", "layer_height", "image_width", "image_height"):
        if a.get(k) is None:
            raise ConfigError(f"priorbox layer: missing attr {k!r}")
    return detection_ops.prior_boxes(
        a["layer_height"], a["layer_width"],
        a["image_height"], a["image_width"],
        a.get("min_size", [1.0]), a.get("max_size", []),
        a.get("aspect_ratio", []), a.get("variance", [0.1, 0.1, 0.2, 0.2]))


@register_layer("priorbox")
class PriorBoxLayer(Layer):
    def forward(self, params, inputs, ctx):
        priors = _priors_from_attrs(self.conf)
        return jnp.asarray(priors.reshape(1, -1))


def _prior_tensor(v) -> jnp.ndarray:
    """Priors are batch-independent; accept [1|B, P*8] or [P*8] and
    return [P, 8]."""
    v = value_of(v)
    if v.ndim == 2:
        v = v[0]
    return v.reshape(-1, 8)


def _as_loc(v) -> jnp.ndarray:
    """[B, ...] conv output (NHWC or flat prior-major) -> [B, P, 4]."""
    v = value_of(v)
    return v.reshape(v.shape[0], -1, 4)


def _as_conf(v, num_classes: int) -> jnp.ndarray:
    v = value_of(v)
    return v.reshape(v.shape[0], -1, num_classes)


@register_layer("multibox_loss")
class MultiBoxLossLayer(Layer):
    def forward(self, params, inputs, ctx):
        a = self.conf.attrs
        num_classes = a["num_classes"]
        input_num = a.get("input_num", (len(inputs) - 2) // 2)
        priors = _prior_tensor(inputs[0])
        label = inputs[1]
        enforce(isinstance(label, SequenceBatch),
                "multibox_loss label must be a sequence of GT box rows")
        locs = jnp.concatenate(
            [_as_loc(v) for v in inputs[2:2 + input_num]], axis=1)
        confs = jnp.concatenate(
            [_as_conf(v, num_classes)
             for v in inputs[2 + input_num:2 + 2 * input_num]], axis=1)
        loss = detection_ops.multibox_loss(
            confs, locs, priors, label.data, label.length,
            num_classes=num_classes,
            overlap_threshold=a.get("overlap_threshold", 0.5),
            neg_overlap=a.get("neg_overlap", 0.5),
            neg_pos_ratio=a.get("neg_pos_ratio", 3.0),
            background_id=a.get("background_id", 0))
        # MultiBoxLossLayer.cpp assigns the full (already numMatches-
        # normalized) loss to every output row; NeuralNetwork.loss then
        # sums rows / batchSize, recovering exactly `loss` — same
        # objective and gradient scale as the reference
        b = value_of(inputs[2]).shape[0]
        return jnp.full((b, 1), loss)


@register_layer("detection_output")
class DetectionOutputLayer(Layer):
    def forward(self, params, inputs, ctx):
        a = self.conf.attrs
        num_classes = a["num_classes"]
        input_num = a.get("input_num", 1)
        priors = _prior_tensor(inputs[0])
        locs = jnp.concatenate(
            [_as_loc(v) for v in inputs[1:1 + input_num]], axis=1)
        confs = jnp.concatenate(
            [_as_conf(v, num_classes)
             for v in inputs[1 + input_num:1 + 2 * input_num]], axis=1)
        return detection_ops.detection_output(
            confs, locs, priors, num_classes=num_classes,
            background_id=a.get("background_id", 0),
            conf_threshold=a.get("confidence_threshold", 0.01),
            nms_top_k=a.get("nms_top_k", 400),
            nms_threshold=a.get("nms_threshold", 0.45),
            keep_top_k=a.get("keep_top_k", 200))

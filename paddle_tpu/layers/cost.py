"""Cost layers.

Reference: ``paddle/gserver/layers/CostLayer.cpp`` (registered type strings
kept: ``multi-class-cross-entropy``, ``multi_class_cross_entropy_with_selfnorm``,
``soft_binary_class_cross_entropy``, ``square_error``, ``rank-cost``,
``lambda_cost``, ``multi_binary_label_cross_entropy``, ``huber_regression``,
``huber_classification``, ``smooth_l1``, ``sum_cost``), plus ``CRFLayer``
(``crf``), ``CRFDecodingLayer`` (``crf_decoding``), ``CTCLayer`` (``ctc``),
``WarpCTCLayer`` (``warp_ctc``), ``CrossEntropyOverBeam``.

A cost layer outputs **per-example cost** [B, 1]; masking/sequence weighting
happens here; the network sums cost-layer outputs into the scalar objective
(``Argument::sum`` equivalent).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.sequence import SequenceBatch, like, value_of
from ..ops import crf_ops, loss_ops
from ..utils import enforce
from .base import ForwardContext, Layer, register_layer


def _per_example(out, template):
    return like(template, out.reshape(-1, 1))


class _CostBase(Layer):
    is_cost = True

    def weighted(self, cost, inputs):
        """Apply optional per-example weight input (3rd input)."""
        if len(inputs) > 2 and inputs[2] is not None:
            w = value_of(inputs[2]).reshape(-1)
            cost = cost * w
        coeff = self.conf.attrs.get("coeff", 1.0)
        return cost * coeff


def _masked_flatten_seq(x, label):
    """For sequence inputs, flatten time into batch with mask weights."""
    if isinstance(x, SequenceBatch):
        v = x.data
        b, t = v.shape[:2]
        mask = x.mask(jnp.float32).reshape(b * t)
        lab = value_of(label)
        if lab.ndim >= 2 and lab.shape[:2] == (b, t):
            lab = lab.reshape((b * t,) + lab.shape[2:])
        return v.reshape((b * t,) + v.shape[2:]), lab, mask
    return value_of(x), value_of(label), None


@register_layer("multi-class-cross-entropy")
class CrossEntropyCost(_CostBase):
    _logits_value = None  # set by the network when the producer's
    #                       '.logits' sub-output is available

    def forward(self, params, inputs, ctx):
        logits = self._logits_value
        self._logits_value = None
        if logits is not None:
            # fused logits path: one pass fwd (logsumexp+gather), one
            # bf16 pass bwd — see loss_ops.softmax_ce_fused.  Runs on
            # the native [B, T, V] layout: flattening first costs a
            # full-tensor relayout copy on TPU.
            z = value_of(logits)
            lab = value_of(inputs[1]).reshape(z.shape[:-1])
            mask = logits.mask(jnp.float32).reshape(-1) \
                if isinstance(logits, SequenceBatch) else None
            cost = loss_ops.softmax_ce_fused(z, lab).reshape(-1)
        else:
            x, label, mask = _masked_flatten_seq(inputs[0], inputs[1])
            cost = loss_ops.cross_entropy(x, label.reshape(-1))
        if mask is not None:
            cost = cost * mask
        return _per_example(self.weighted(cost, inputs), inputs[0])


@register_layer("multi_class_cross_entropy_with_selfnorm")
class CrossEntropySelfNormCost(_CostBase):
    """CE + alpha * log(Z)^2 self-normalization (CostLayer.cpp)."""

    def forward(self, params, inputs, ctx):
        x, label, mask = _masked_flatten_seq(inputs[0], inputs[1])
        logz = jnp.log(jnp.sum(x, axis=-1) + 1e-8)
        cost = loss_ops.cross_entropy(x, label.reshape(-1)) + \
            self.conf.attrs.get("softmax_selfnorm_alpha", 0.1) * jnp.square(logz)
        if mask is not None:
            cost = cost * mask
        return _per_example(self.weighted(cost, inputs), inputs[0])


@register_layer("soft_binary_class_cross_entropy")
class SoftBinaryCrossEntropyCost(_CostBase):
    def forward(self, params, inputs, ctx):
        x = value_of(inputs[0])
        label = value_of(inputs[1])
        eps = 1e-8
        p = jnp.clip(x, eps, 1 - eps)
        cost = -jnp.sum(label * jnp.log(p) + (1 - label) * jnp.log1p(-p), axis=-1)
        return _per_example(self.weighted(cost, inputs), inputs[0])


@register_layer("square_error", "mse", "regression_cost")
class SquareErrorCost(_CostBase):
    def forward(self, params, inputs, ctx):
        x, label, mask = _masked_flatten_seq(inputs[0], inputs[1])
        cost = loss_ops.square_error(x, label)
        if mask is not None:
            cost = cost * mask
        return _per_example(self.weighted(cost, inputs), inputs[0])


@register_layer("rank-cost")
class RankingCost(_CostBase):
    def forward(self, params, inputs, ctx):
        cost = loss_ops.rank_loss(value_of(inputs[0]), value_of(inputs[1]),
                                  value_of(inputs[2]))
        coeff = self.conf.attrs.get("coeff", 1.0)
        return _per_example(cost * coeff, inputs[0])


@register_layer("lambda_cost")
class LambdaCost(_CostBase):
    def forward(self, params, inputs, ctx):
        scores = inputs[0]
        gains = inputs[1]
        enforce(isinstance(scores, SequenceBatch), "lambda_cost needs sequences")
        cost = loss_ops.lambda_cost(
            scores.data[..., 0] if scores.data.ndim == 3 else scores.data,
            value_of(gains)[..., 0] if value_of(gains).ndim == 3 else value_of(gains),
            scores.mask(), self.conf.attrs.get("NDCG_num", 5))
        return _per_example(cost, inputs[0])


@register_layer("multi_binary_label_cross_entropy")
class MultiBinaryLabelCrossEntropyCost(_CostBase):
    def forward(self, params, inputs, ctx):
        cost = loss_ops.multi_binary_label_cross_entropy(
            value_of(inputs[0]), value_of(inputs[1]))
        return _per_example(self.weighted(cost, inputs), inputs[0])


@register_layer("huber_regression")
class HuberRegressionCost(_CostBase):
    def forward(self, params, inputs, ctx):
        cost = loss_ops.huber_loss(value_of(inputs[0]), value_of(inputs[1]),
                                   self.conf.attrs.get("delta", 1.0))
        return _per_example(self.weighted(cost, inputs), inputs[0])


@register_layer("huber_classification")
class HuberClassificationCost(_CostBase):
    def forward(self, params, inputs, ctx):
        cost = loss_ops.huber_classification_cost(
            value_of(inputs[0]), value_of(inputs[1]))
        return _per_example(self.weighted(cost, inputs), inputs[0])


@register_layer("smooth_l1")
class SmoothL1Cost(_CostBase):
    def forward(self, params, inputs, ctx):
        cost = loss_ops.smooth_l1_loss(value_of(inputs[0]), value_of(inputs[1]))
        return _per_example(self.weighted(cost, inputs), inputs[0])


@register_layer("sum_cost")
class SumCost(_CostBase):
    def forward(self, params, inputs, ctx):
        x = value_of(inputs[0])
        return _per_example(jnp.sum(x.reshape(x.shape[0], -1), axis=-1), inputs[0])


@register_layer("crf")
class CRFCost(_CostBase):
    """Linear-chain CRF NLL (``CRFLayer``); weight [N+2, N]."""

    def param_specs(self):
        n = self.conf.size
        return [self._weight_spec(0, (n + 2, n), initial_std=0.01)]

    def forward(self, params, inputs, ctx):
        emissions = inputs[0]
        labels = inputs[1]
        enforce(isinstance(emissions, SequenceBatch), "crf needs sequences")
        lab = labels if isinstance(labels, SequenceBatch) else \
            SequenceBatch(data=value_of(labels), length=emissions.length)
        cost = crf_ops.crf_nll(emissions, lab, params[self.weight_name(0)])
        return _per_example(self.weighted(cost, inputs), emissions)


@register_layer("crf_decoding")
class CRFDecodingLayer(Layer):
    def param_specs(self):
        n = self.conf.size
        return [self._weight_spec(0, (n + 2, n), initial_std=0.01)]

    def forward(self, params, inputs, ctx):
        emissions = inputs[0]
        decoded = crf_ops.crf_decode(emissions, params[self.weight_name(0)])
        if len(inputs) > 1:  # label given → output per-position error
            lab = value_of(inputs[1])
            err = (decoded.data != lab[..., : decoded.data.shape[1]]).astype(jnp.float32)
            return SequenceBatch(data=err * decoded.mask(), length=decoded.length)
        return decoded


@register_layer("ctc", "warp_ctc")
class CTCCost(_CostBase):
    def forward(self, params, inputs, ctx):
        logits = inputs[0]
        labels = inputs[1]
        enforce(isinstance(logits, SequenceBatch) and isinstance(labels, SequenceBatch),
                "ctc needs sequence logits and labels")
        cost = crf_ops.ctc_loss(
            logits, labels,
            blank=self.conf.attrs.get("blank", 0),
            norm_by_times=self.conf.attrs.get("norm_by_times", False))
        return _per_example(cost, logits)


@register_layer("cross_entropy_over_beam")
class CrossEntropyOverBeamCost(Layer):
    """Globally-normalized beam cross-entropy
    (``CrossEntropyOverBeam.cpp``; Andor et al., "Globally Normalized
    Transition-Based Neural Networks").

    Inputs come in groups of three per beam expansion, mirroring the
    reference's ``BeamInput`` triples: (candidate path scores [B, K],
    candidate ids [B, K], gold id [B]).  Scores are **accumulated** path
    scores at that expansion (our in-graph ``beam_gen`` decoder tracks
    them directly; the reference reconstructs the accumulation from
    per-expansion scores + parent rows host-side —
    ``CostForOneSequence::globallyNormalizedScore``).

    Per sequence: follow the gold id through the expansions; at the first
    expansion where gold leaves the beam (``calValidExpandStep``), the
    cost is computed there with the gold path appended as an extra
    candidate (``goldAsExtraPath_``); if gold survives to the last
    expansion the cost is the softmax CE over the final beam at gold's
    slot.  Cost = -log softmax(path scores)[gold].
    """

    def forward(self, params, inputs, ctx):
        enforce(len(inputs) % 3 == 0,
                "cross_entropy_over_beam takes (scores, ids, gold) triples")
        n_exp = len(inputs) // 3
        triples = [(value_of(inputs[3 * i]), value_of(inputs[3 * i + 1]),
                    value_of(inputs[3 * i + 2])) for i in range(n_exp)]
        b = triples[0][0].shape[0]

        # state per sequence: cost once gold drops out (frozen), else the
        # final-beam CE; gold_alive tracks beam membership
        alive = jnp.ones((b,), bool)
        frozen_cost = jnp.zeros((b,), jnp.float32)
        gold_score = jnp.zeros((b,), jnp.float32)
        for scores, ids, gold in triples:
            scores = scores.astype(jnp.float32)
            gold_i = gold.reshape(b).astype(ids.dtype)
            hit = ids == gold_i[:, None]                       # [B, K]
            in_beam = jnp.any(hit, axis=1)
            g_here = jnp.sum(jnp.where(hit, scores, 0.0), axis=1)
            gold_score = jnp.where(alive & in_beam, g_here, gold_score)
            # CE at this expansion with gold as the extra path
            ext = jnp.concatenate([scores, gold_score[:, None]], axis=1)
            lse_ext = jax.nn.logsumexp(ext, axis=1)
            drop_cost = -(gold_score - lse_ext)
            dropping = alive & (~in_beam)
            frozen_cost = jnp.where(dropping, drop_cost, frozen_cost)
            alive = alive & in_beam
        scores, ids, gold = triples[-1]
        scores = scores.astype(jnp.float32)
        lse = jax.nn.logsumexp(scores, axis=1)
        final_cost = -(gold_score - lse)
        cost = jnp.where(alive, final_cost, frozen_cost)
        return cost[:, None]

"""NeuralNetwork: config-driven executor over the layer registry.

Equivalent of ``paddle/gserver/gradientmachines/NeuralNetwork.cpp`` — but
where the reference loops layers twice (``forward:245`` / ``backward:295``
with hand-written per-layer gradients), here :meth:`forward` is a **pure
traceable function** and the backward pass is jax autodiff over the whole
graph, so the entire fwd+bwd+update compiles into one XLA computation
(the SURVEY §7 north-star jit path).

Handles: topological execution, parameter creation/sharing
(``input_parameter_name``), static parameters, batch-norm buffers, cost
aggregation (``Argument::sum``), and recurrent-group sub-models (delegated
to :class:`paddle_tpu.layers.recurrent_group.RecurrentGroup`).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config.model_config import LayerConfig, ModelConfig, ParameterConfig
from ..core.sequence import SequenceBatch, value_of
from ..utils import ConfigError, enforce, global_stat, layer_stack
from .base import (
    LAYERS,
    ForwardContext,
    Layer,
    cast_layer_output,
    init_parameter,
)
from . import common, conv, cost, rnn, seq  # noqa: F401  (register layers)
from . import detection, image3d  # noqa: F401  (register layers)
from . import beam_search  # noqa: F401  (registers beam_gen)
from . import attention  # noqa: F401  (registers flash-attention layers)


class NeuralNetwork:
    """Builds and executes a ModelConfig as a functional graph."""

    def __init__(self, config: ModelConfig):
        self.config = config
        self.layers: Dict[str, Layer] = {}
        self.order: List[str] = []
        sub_layer_names: Set[str] = set()
        self.group_of: Dict[str, str] = {}
        for sm in config.sub_models:
            if sm.name == "root":
                continue
            for ln in sm.layer_names:
                sub_layer_names.add(ln)
                self.group_of[ln] = sm.name

        from .recurrent_group import RecurrentGroup

        self.groups: Dict[str, "RecurrentGroup"] = {}
        for sm in config.sub_models:
            if sm.name != "root" and not sm.is_generating:
                self.groups[sm.name] = RecurrentGroup(sm, config)
        self.gen_groups = {
            sm.name: sm for sm in config.sub_models
            if sm.name != "root" and sm.is_generating
        }
        self._decoders: Dict[str, Any] = {}

        for lconf in config.layers:
            if lconf.name in sub_layer_names and lconf.type != "data":
                continue  # executed inside its recurrent group
            cls = LAYERS.get(lconf.type)
            self.layers[lconf.name] = cls(lconf, config)
            self.order.append(lconf.name)

        # parameter specs (merge layer-declared with config-declared)
        declared = {p.name: p for p in config.parameters}
        self.param_specs: Dict[str, ParameterConfig] = {}
        self._collect_specs(self.layers.values(), declared)
        for g in self.groups.values():
            self._collect_specs(g.layers.values(), declared)
        for sm in self.gen_groups.values():
            from .beam_search import BeamSearchDecoder
            dec = BeamSearchDecoder(sm, config)
            self._decoders[sm.name] = dec
            self._collect_specs(dec.group.layers.values(), declared)
        self.static_params: Set[str] = {
            n for n, s in self.param_specs.items() if s.is_static}

        self.data_layers = [n for n in self.order
                            if self.layers[n].conf.type == "data"]
        self.cost_layers = [
            n for n in self.order
            if getattr(self.layers[n], "is_cost", False)]
        self.output_names = config.output_layer_names or (
            [self.order[-1]] if self.order else [])

        # classification-cost logits peephole: when a multi-class CE
        # cost reads a softmax-activated fc, route it the layer's
        # '.logits' sub-output so the fused logits-CE path can run (the
        # softmax output is then dead in training and XLA removes it)
        lmap = config.layer_map()
        self._cost_logit_alias: Dict[str, str] = {}
        for cname in self.cost_layers:
            lyr = self.layers[cname]
            if lyr.conf.type != "multi-class-cross-entropy" \
                    or not lyr.conf.inputs:
                continue
            pname = lyr.conf.inputs[0].input_layer_name
            pconf = lmap.get(pname)
            if pconf is not None \
                    and pconf.type in ("fc", "mkldnn_fc") \
                    and pconf.active_type == "softmax" \
                    and pconf.drop_rate == 0 \
                    and pconf.error_clipping_threshold == 0:
                self._cost_logit_alias[cname] = pname + ".logits"

        # conv→BN fusion peepholes: a batch-norm whose sole producer is
        # a linear 3×3 stride-1 pad-1 conv routes through the fused
        # conv+BN op (ops/nn_ops.py::conv2d_bn — the Pallas backward-
        # data kernel with the BN-backward affine folded into its input
        # pipeline), and a batch-norm whose sole consumer is a fusable
        # conv defers its normalize+act apply pass into that conv's
        # input prologue (nn_ops.affine_act_conv2d) so the normalized
        # activation never round-trips HBM.  Pattern-matched once at
        # build time on the static config — the resolution itself lives
        # in :func:`paddle_tpu.analysis.netcheck.fusion_plan` (pure
        # function of the config, shared with the static verifier so
        # the PT-SHAPE census can never drift from the gauge below);
        # the ops re-gate on shapes/dtype at trace time and fall back
        # to the exact unfused composition, so firing is always
        # semantics-preserving.  Kill switches: --conv_bn_fuse (bwd),
        # --conv_bn_fuse_fwd (fwd).
        from ..analysis import netcheck
        from ..utils import FLAGS

        self._conv_bn_fuse, self._bn_conv_fuse = netcheck.fusion_plan(
            config, root_layers=set(self.layers),
            output_names=self.output_names,
            fuse_bwd=bool(FLAGS.get("conv_bn_fuse")),
            fuse_fwd=bool(FLAGS.get("conv_bn_fuse_fwd")))

        # fused-pair census: how many conv/BN pairs THIS topology
        # resolved at build time, per direction and kernel family —
        # ResNet-50 pins 16 Pallas-3×3 + 16 GEMM-1×1 forward pairs (the
        # round-7 resolution; its bwd entries are all evicted into fwd
        # chains).  The bench artifact reads these back through the
        # JSONL sink; gauges reflect the most recently built network.
        from ..observe import gauge
        fwd3 = sum(1 for cv in self._bn_conv_fuse
                   if lmap[cv].attrs.get("filter_size") == 3)
        pairs = gauge("network_conv_bn_fused_pairs",
                      "conv/BN pairs resolved by the build-time fusion "
                      "peepholes of the last-built network")
        pairs.set(len(self._conv_bn_fuse), direction="bwd", kernel="3x3")
        pairs.set(fwd3, direction="fwd", kernel="3x3")
        pairs.set(len(self._bn_conv_fuse) - fwd3,
                  direction="fwd", kernel="1x1")

        # build-time precision census: which compute/output dtypes the
        # op policy resolved to when each network was built (the
        # trainer may still override per-step via policy_scope — this
        # records the flag-resolved default the bench stamp also
        # reads).  A monotonic per-policy counter, like the fused-pair
        # census above: a process that builds under two policies (the
        # bench precision A/B) keeps both series honest.
        from ..core.dtypes import current_policy, dtype_name
        from ..observe import counter
        pol = current_policy()
        counter("network_builds_total",
                "networks built, labeled by the op-policy dtypes "
                "resolved at build time").inc(
            compute=dtype_name(pol.compute_dtype),
            output=dtype_name(pol.output_dtype))

    def verify(self) -> list:
        """Config-time whole-graph verification — the
        :mod:`paddle_tpu.analysis.netcheck` abstract interpreter over
        this network's config (symbolic shapes + policy-resolved
        dtypes, no tracing).  Returns the issue list;
        ``netcheck.errors(...)`` filters the trace-fatal subset.  The
        reference verified its proto config before any kernel ran;
        this is that check for the rebuild."""
        from ..analysis import netcheck
        from ..core.dtypes import current_policy, dtype_name

        pol = current_policy()
        return netcheck.check_model(
            self.config, policy=(dtype_name(pol.compute_dtype),
                                 dtype_name(pol.output_dtype)))

    def _collect_specs(self, layers, declared) -> None:
        for layer in layers:
            for spec in layer.param_specs():
                if spec.name in declared:
                    d = declared[spec.name]
                    if not d.dims:
                        d.dims = spec.dims
                    d.size = d.size or spec.size
                    spec = d
                if spec.name in self.param_specs:
                    prev = self.param_specs[spec.name]
                    enforce(prev.dims == spec.dims,
                            f"shared parameter {spec.name} shape mismatch: "
                            f"{prev.dims} vs {spec.dims}")
                    continue
                self.param_specs[spec.name] = spec

    # ------------------------------------------------------------- params
    def init_params(self, seed: int = 1) -> Dict[str, jax.Array]:
        key = jax.random.PRNGKey(seed)
        params = {}
        for i, (name, spec) in enumerate(sorted(self.param_specs.items())):
            params[name] = init_parameter(jax.random.fold_in(key, i), spec)
        return params

    def init_buffers(self) -> Dict[str, jax.Array]:
        buffers: Dict[str, jax.Array] = {}
        for coll in [self.layers, *[g.layers for g in self.groups.values()]]:
            for layer in coll.values():
                if hasattr(layer, "buffer_specs"):
                    buffers.update(layer.buffer_specs())
        return buffers

    def lr_scales(self, params: Dict[str, jax.Array]) -> Dict[str, float]:
        """Per-parameter learning-rate scale (ParameterConfig.learning_rate);
        0 for static parameters."""
        return {
            n: 0.0 if n in self.static_params
            else self.param_specs[n].learning_rate
            for n in params
        }

    def _ancestors(self, targets) -> Set[str]:
        """Main-graph layers (transitively) needed to produce ``targets``
        — inference pruning, the ``core.prune`` / capi
        create-for-inference equivalent.  Group out-links pull in the
        whole group: its in-links, memory boot layers, and every outer
        value its step layers read."""
        needed: Set[str] = set()
        stack = [t for t in targets]
        seen: Set[str] = set()
        while stack:
            v = stack.pop()
            if v in seen:
                continue
            seen.add(v)
            base = v.split(".", 1)[0]
            if base not in self.layers:
                base = v
            if base in self.layers:
                needed.add(base)
                stack.extend(self.layers[base].conf.input_names())
                continue
            gname = self.group_of.get(v)
            if gname is None:
                continue
            grp = self.groups.get(gname)
            sub = grp.sub if grp is not None else self.gen_groups[gname]
            stack.extend(sub.in_links)
            # beam-search groups read encoder context as static inputs
            # (deliberately NOT in_links, dsl.py GeneratedInput wiring)
            stack.extend(sub.generator.get("static_inputs", ()))
            step_layers = (grp.layers if grp is not None
                           else self._decoders[gname].group.layers)
            inner = set(step_layers) | set(sub.layer_names)
            mem_links = set()
            for m in sub.memories:
                mem_links.add(m.get("link_name",
                                    m["layer_name"] + "@pre"))
                if m.get("boot_layer_name"):
                    stack.append(m["boot_layer_name"])
            for lyr in step_layers.values():
                for iname in lyr.conf.input_names():
                    head = iname.split(".", 1)[0]
                    if head not in inner and iname not in mem_links \
                            and iname not in sub.in_links:
                        stack.append(iname)
        return needed

    # ------------------------------------------------------------ forward
    def forward(self, params: Dict[str, jax.Array], feed: Dict[str, Any],
                buffers: Optional[Dict[str, jax.Array]] = None,
                is_training: bool = True,
                rng: Optional[jax.Array] = None,
                only: Optional[Sequence[str]] = None
                ) -> Tuple[Dict[str, Any], Dict[str, jax.Array]]:
        """Run all layers; returns (all outputs by name, updated buffers).

        ``only``: restrict execution to the ancestors of these value
        names — data layers outside the cone need no feed (inference on
        a training config)."""
        ctx = ForwardContext(is_training=is_training, rng=rng,
                             buffers=buffers or {})
        values: Dict[str, Any] = {}
        done_groups: Set[str] = set()
        needed = self._ancestors(only) if only is not None else None
        # conv→BN pairs active for THIS call: the conv is skipped and the
        # BN executes the fused op — unless the conv's own value was
        # explicitly requested (it then must exist standalone)
        targets = set(only) if only is not None else set()
        fuse = {bn: cv for bn, cv in self._conv_bn_fuse.items()
                if (needed is None or bn in needed) and cv not in targets}
        fused_convs = set(fuse.values())
        # BNs whose apply pass defers into their consuming conv this
        # call (forward fusion) — inactive when the BN's own value is an
        # explicit target (it must then materialize standalone)
        defer = {bn for cv, bn in self._bn_conv_fuse.items()
                 if (needed is None or cv in needed) and bn not in targets}
        for name in self.order:
            if needed is not None and name not in needed:
                continue
            if name in fused_convs:
                continue  # produced inside its batch-norm partner
            layer = self.layers[name]
            if layer.conf.type == "data":
                if name not in feed:
                    raise ConfigError(f"missing feed for data layer {name!r}")
                values[name] = feed[name]
                continue
            # run any recurrent group whose inputs are all ready lazily:
            # groups appear in order via their output layers.
            # jax.named_scope threads the layer name into XLA's op_name
            # metadata so the compiled executable's fused regions key
            # back to THIS layer (observe/costmodel.py attribution);
            # scope cost is trace-time only, nothing per step.
            with layer_stack.guard(name), jax.named_scope(name):
                if name in defer:
                    # forward conv+BN fusion: publish (z, a, c) — the
                    # consuming conv applies the affine in its input
                    # pipeline (no activation materialized here)
                    inputs = self._gather(layer.conf.input_names(),
                                          params, values, ctx,
                                          done_groups)
                    values[name] = layer.forward_deferred(params, inputs,
                                                          ctx)
                    continue
                src = fuse.get(name)
                if src is not None:
                    conv = self.layers[src]
                    cinputs = self._gather(conv.conf.input_names(),
                                           params, values, ctx,
                                           done_groups)
                    out = cast_layer_output(
                        layer, layer.forward_fused(params, conv,
                                                   cinputs, ctx))
                else:
                    inputs = self._gather(layer.conf.input_names(),
                                          params, values, ctx,
                                          done_groups)
                    if name in self._cost_logit_alias:
                        # hand the cost its producer's logits when the
                        # graph exposed them (None → cost falls back to
                        # probs)
                        layer._logits_value = values.get(
                            self._cost_logit_alias[name])
                    out = cast_layer_output(
                        layer, layer.forward(params, inputs, ctx))
            if isinstance(out, dict):
                for k, v in out.items():
                    values[name if k == "out" else f"{name}.{k}"] = v
            else:
                values[name] = out
        # declared outputs that are group out-links with no downstream
        # consumer still need their group to run
        for name in (self.output_names if only is None else only):
            gname = self.group_of.get(name)
            if name in values or gname is None or gname in done_groups:
                continue
            grp = self.groups.get(gname)
            out_links = grp.out_links if grp is not None \
                else self.gen_groups[gname].out_links
            if name in out_links:
                self._run_producer(name, params, values, ctx, done_groups)
        ctx.buffers.update(ctx.new_buffers)
        return values, ctx.buffers

    def _gather(self, names, params, values, ctx, done_groups):
        """Resolve input values, running lazy group producers on demand."""
        vals = []
        for iname in names:
            if iname not in values:
                self._run_producer(iname, params, values, ctx, done_groups)
            vals.append(values[iname])
        return vals

    def _run_producer(self, name: str, params, values, ctx, done_groups):
        """Produce a value coming from a recurrent-group output link."""
        group_name = self.group_of.get(name)
        if group_name is None or group_name in done_groups:
            raise ConfigError(f"layer input {name!r} has no producer")
        group = self.groups.get(group_name)
        if group is None:
            sm = self.gen_groups.get(group_name)
            if sm is None:
                raise ConfigError(f"no producer for group {group_name!r}")
            dec = self._decoders.get(group_name)
            if dec is None:   # decoders are prebuilt in __init__
                from .beam_search import BeamSearchDecoder
                dec = self._decoders[group_name] = \
                    BeamSearchDecoder(sm, self.config)
            bundle = dec.generate(params, values, ctx)
            for link in sm.out_links:
                values[link] = bundle
        else:
            group.run(params, values, ctx)
        done_groups.add(group_name)

    # --------------------------------------------------------------- loss
    def loss(self, params: Dict[str, jax.Array], feed: Dict[str, Any],
             buffers: Optional[Dict[str, jax.Array]] = None,
             is_training: bool = True, rng: Optional[jax.Array] = None
             ) -> Tuple[jax.Array, Tuple[Dict[str, Any], Dict[str, jax.Array]]]:
        """Scalar objective = mean per-example total cost (TrainerInternal
        ``Argument::sum`` / batchSize convention)."""
        values, new_buffers = self.forward(params, feed, buffers,
                                           is_training, rng)
        enforce(self.cost_layers, "network has no cost layer")
        total = None
        for cname in self.cost_layers:
            out = values[cname]
            v = value_of(out)
            c = jnp.sum(v) / v.shape[0]
            total = c if total is None else total + c
        return total, (values, new_buffers)

    def outputs(self, values: Dict[str, Any]) -> Dict[str, Any]:
        return {n: values[n] for n in self.output_names if n in values}

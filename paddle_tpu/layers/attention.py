"""Attention-family layers backed by the Pallas flash-attention kernel.

The reference pattern for a hand kernel is kernel → layer → config
(``paddle/cuda/src/hl_cuda_lstm.cu`` → ``LstmLayer`` → DSL
``lstmemory``); this module is the same wiring for the repo's flash
attention (:mod:`paddle_tpu.ops.pallas_attention`): the kernel is
reachable from a config file via ``scaled_dot_product_attention`` /
``multi_head_attention``, with ``layer_norm`` and ``position_embedding``
alongside so a full transformer block can be declared in the v1/v2 DSL.

These three types go beyond the 2017 reference's layer set (it predates
transformers) — they are the TPU-era counterpart of what ``lstmemory``
was to its era: the hot-path sequence mixer, hand-kernelled.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..config.model_config import ParameterConfig
from ..core.dtypes import current_policy, record_op_precision
from ..core.sequence import SequenceBatch, like, value_of
from ..ops.pallas_attention import (flash_attention,
                                    flash_attention_packed,
                                    packed_tileable,
                                    record_attention_dispatch,
                                    segments_from_lengths)
from ..utils import enforce
from .base import Layer, register_layer


def _seq_parts(x):
    """(data [B, T, D], lengths [B] or None) from a layer input."""
    if isinstance(x, SequenceBatch):
        return x.data, x.length
    return value_of(x), None


@register_layer("scaled_dot_product_attention", "multi_head_attention",
                "flash_attention")
class MultiHeadAttentionLayer(Layer):
    """Multi-head scaled-dot-product attention over padded sequences.

    One input = self-attention (a packed [D_in, 3·size] q/k/v projection
    — one MXU matmul instead of three); three inputs = (query, key,
    value) cross-attention with per-input projections.  An output
    projection ``_{name}.wo`` [size, size] merges the heads; bias (if
    any) is added after it.  Attrs: ``num_heads`` (must divide size),
    ``causal``, ``block_q``/``block_k`` (Pallas tile sizes).

    Padded keys are masked inside the kernel via the scalar-prefetched
    lengths of the key sequence; queries keep their own lengths on the
    output SequenceBatch.

    ``packed=True`` (self-attention only): the padded batch is packed
    into ONE ``[1, B·T]`` token axis with per-token segment ids derived
    from the sequence lengths, and attention runs through
    :func:`flash_attention_packed` — padding and cross-sequence blocks
    do zero work (block-sparse path: not even DMA).  Padding positions
    of the output are exact zeros (they were arbitrary garbage on the
    padded path; both are masked downstream).  The
    ``--attention_packing=false`` kill switch makes the layer ignore
    the attr and run the exact padded per-row lowering.
    """

    def param_specs(self):
        size = self.conf.size
        heads = self.conf.attrs.get("num_heads", 1)
        enforce(size % heads == 0,
                f"attention size {size} not divisible by num_heads {heads}")
        ins = self.conf.inputs
        enforce(len(ins) in (1, 3),
                "attention takes 1 input (self) or 3 (q, k, v), got "
                f"{len(ins)}")
        specs = []
        if len(ins) == 1:
            din = self.model.find_size(ins[0].input_layer_name)
            specs.append(self._weight_spec(0, (din, 3 * size),
                                           initial_smart=True))
        else:
            for i, inp in enumerate(ins):
                din = self.model.find_size(inp.input_layer_name)
                specs.append(self._weight_spec(i, (din, size),
                                               initial_smart=True))
        specs.append(ParameterConfig(
            name=f"_{self.name}.wo", size=size * size, dims=[size, size],
            initial_smart=True))
        if self.conf.with_bias:
            specs.append(self._bias_spec((size,)))
        return specs

    def forward(self, params, inputs, ctx):
        size = self.conf.size
        heads = self.conf.attrs.get("num_heads", 1)
        dh = size // heads
        # policy compute dtype for the projections AND the kernel's
        # q/k/v: without the explicit cast a bf16 activation against an
        # fp32 weight silently PROMOTES the matmul to fp32 (jnp
        # promotion), so the fused tier never saw bf16 inputs.  The
        # flash kernel accumulates in f32 internally regardless.
        pol = current_policy()
        record_op_precision("attention")
        cd = pol.compute_dtype
        if len(inputs) == 1:
            x, q_len = _seq_parts(inputs[0])
            qkv = x.astype(cd) @ params[self.weight_name(0)].astype(cd)
            q, k, v = jnp.split(qkv, 3, axis=-1)   # [B, T, 3·size]
            kv_len = q_len
        else:
            xq, q_len = _seq_parts(inputs[0])
            xk, kv_len = _seq_parts(inputs[1])
            xv, v_len = _seq_parts(inputs[2])
            del v_len  # value lengths follow the key sequence
            q = xq.astype(cd) @ params[self.weight_name(0)].astype(cd)
            k = xk.astype(cd) @ params[self.weight_name(1)].astype(cd)
            v = xv.astype(cd) @ params[self.weight_name(2)].astype(cd)

        b, tq = q.shape[0], q.shape[1]
        tk = k.shape[1]
        split = lambda a, t: a.reshape(b, t, heads, dh)
        causal = bool(self.conf.attrs.get("causal", False))
        block_q = int(self.conf.attrs.get("block_q", 512))
        block_k = int(self.conf.attrs.get("block_k", 512))
        packed = bool(self.conf.attrs.get("packed", False))
        # packed blocks clamp to the slot width (one row's T) so the
        # static cross-row compaction stays usable when T < block
        pbq, pbk = min(block_q, tq), min(block_k, tq)
        if packed:
            from ..utils import FLAGS
            enforce(len(inputs) == 1,
                    "packed attention requires self-attention "
                    f"(1 input), layer {self.name} has {len(inputs)}")
            if not FLAGS.attention_packing:
                # kill switch: ignore the attr, run the exact padded
                # per-row lowering below
                record_attention_dispatch(
                    "unpacked", "kill_switch:attention_packing")
                packed = False
            elif not FLAGS.flash_block_sparse or not FLAGS.flash_kernel:
                # the packed kernel IS the block-sparse pair grid; with
                # it (or the flash kernel) disabled, the honest revert
                # is the padded per-row lowering — the op-level dense
                # fallback over the flattened [1, B·T] axis would build
                # an O((B·T)²) score matrix
                flag = "flash_kernel" if not FLAGS.flash_kernel \
                    else "flash_block_sparse"
                record_attention_dispatch(
                    "unpacked", f"kill_switch:{flag}(packed)")
                packed = False
            elif not packed_tileable(b * tq, pbq, pbk):
                # the flattened axis would miss the Pallas tiling gate
                # and the op-level dense fallback on [1, B·T] builds an
                # O((B·T)²) score matrix — the padded per-row lowering
                # is the honest fallback here too
                record_attention_dispatch(
                    "unpacked", "untileable(packed flatten)")
                packed = False
        if packed:
            lengths = kv_len if kv_len is not None \
                else jnp.full((b,), tq, jnp.int32)
            seg = segments_from_lengths(lengths, b, tq)
            pack = lambda a: a.reshape(1, b * tq, heads, dh)
            # slot = T: rows occupy fixed T-token slots in the flat
            # layout, so cross-row block pairs are statically dead and
            # leave the kernel's iteration space entirely (blocks
            # clamped to the slot width above keep the hint usable)
            out = flash_attention_packed(
                pack(q), pack(k), pack(v), seg, causal, pbq, pbk, tq)
        else:
            out = flash_attention(
                split(q, tq), split(k, tk), split(v, tk), kv_len,
                causal, block_q, block_k)
        out = out.reshape(b, tq, size) \
            @ params[f"_{self.name}.wo"].astype(cd)
        out = out.astype(pol.output_dtype)
        if self.conf.with_bias:
            out = out + params[self.bias_name()].astype(out.dtype)
        out = like(inputs[0], out) if isinstance(inputs[0], SequenceBatch) \
            else out
        return self.finalize(out, ctx)


@register_layer("layer_norm")
class LayerNormLayer(Layer):
    """Per-position layer normalization with learned gain/bias.

    Normalizes the last (feature) dim of [B, ..., size]; gain is the
    weight of input 0, bias the layer bias (on unless bias_attr=False).
    """

    def param_specs(self):
        specs = [self._weight_spec(0, (self.conf.size,), initial_mean=1.0,
                                   initial_std=0.0)]
        if self.conf.with_bias:
            specs.append(self._bias_spec((self.conf.size,)))
        return specs

    def forward(self, params, inputs, ctx):
        x = value_of(inputs[0])
        eps = self.conf.attrs.get("epsilon", 1e-5)
        xf = x.astype(jnp.float32)
        mu = xf.mean(axis=-1, keepdims=True)
        var = jnp.square(xf - mu).mean(axis=-1, keepdims=True)
        y = (xf - mu) / jnp.sqrt(var + eps)
        y = y * params[self.weight_name(0)]
        if self.conf.with_bias:
            y = y + params[self.bias_name()]
        return self.finalize(like(inputs[0], y.astype(x.dtype)), ctx)


@register_layer("position_embedding")
class PositionEmbeddingLayer(Layer):
    """Adds a learned [max_len, size] position table to a sequence input
    (sliced to the batch's T, so bucketed batches share one parameter)."""

    def param_specs(self):
        max_len = self.conf.attrs["max_len"]
        return [self._weight_spec(0, (max_len, self.conf.size),
                                  initial_std=0.01)]

    def forward(self, params, inputs, ctx):
        x = value_of(inputs[0])
        table = params[self.weight_name(0)]
        t = x.shape[1]
        enforce(t <= table.shape[0],
                f"sequence length {t} exceeds position_embedding max_len "
                f"{table.shape[0]}")
        out = x + table[:t][None, :, :].astype(x.dtype)
        return self.finalize(like(inputs[0], out), ctx)

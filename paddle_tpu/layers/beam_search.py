"""Beam-search generation — ``RecurrentGradientMachine::generateSequence``
(``RecurrentGradientMachine.cpp:539``) and the SWIG ``SequenceGenerator``
(``paddle/api/SequenceGenerator.cpp:38-96``) re-designed for XLA.

The reference expands beams host-side per step with ``hl_top_k`` kernels and
EosIdCheck layers.  Here the whole decode is ONE ``lax.scan`` with a fixed
trip count (``max_length``): each step flattens [B, K] beams into the batch
dim, runs the traced step sub-network once, scores candidates with
``lax.top_k`` over K·V, gathers memories by parent beam, and freezes
finished beams by forcing their only continuation to EOS at zero cost.
Compiles into the same program as the encoder — no host round-trips.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp

from ..config.model_config import ModelConfig, SubModelConfig
from ..core.sequence import SequenceBatch, value_of
from ..utils import ConfigError, enforce
from .base import ForwardContext, Layer, register_layer
from .recurrent_group import RecurrentGroup

NEG_INF = -1e9


def eos_frozen_logits(logp: jax.Array, alive: jax.Array,
                      eos_id: int) -> jax.Array:
    """Freeze finished rows: a row whose ``alive`` flag dropped may only
    continue with EOS at zero cost.  ``logp`` is ``[..., V]``, ``alive``
    its leading shape.  Shared by the beam decoder (closed beams) and
    the serving decode loop (finished / padded batch slots must sample
    EOS deterministically, never garbage from an inactive row)."""
    vocab = logp.shape[-1]
    eos_only = jnp.full((vocab,), NEG_INF,
                        logp.dtype).at[eos_id].set(0.0)
    return jnp.where(alive[..., None], logp, eos_only)


class BeamSearchDecoder:
    """Executes a generating SubModelConfig."""

    def __init__(self, sub: SubModelConfig, model: ModelConfig):
        enforce(sub.is_generating and sub.generator,
                f"{sub.name} is not a generating group")
        self.sub = sub
        self.gen = sub.generator
        # reuse the group step machinery (layers, memories)
        self.group = RecurrentGroup(sub, model)

    # ------------------------------------------------------------- helpers
    def _tile_beams(self, v, k: int):
        """[B, ...] → [B*K, ...] (repeat each row K times)."""
        def rep(x):
            return jnp.repeat(x, k, axis=0)
        if isinstance(v, SequenceBatch):
            return SequenceBatch(rep(v.data), rep(v.length))
        if hasattr(v, "ndim") and getattr(v, "ndim", 0) >= 1:
            return rep(v)
        return v

    # ------------------------------------------------------------ generate
    def generate(self, params: Dict[str, jax.Array],
                 values: Dict[str, Any], ctx: ForwardContext) -> Dict:
        g = self.gen
        k = int(g["beam_size"])
        vocab = int(g["vocab_size"])
        max_len = int(g["max_length"])
        eos_id = int(g["eos_id"])
        bos_id = int(g["bos_id"])

        # batch size from any boot/static value
        b = None
        for m in self.group.memories:
            boot = m.get("boot_layer_name")
            if boot and boot in values:
                b = value_of(values[boot]).shape[0]
                break
        if b is None:
            for s in g.get("static_inputs", ()):
                if s in values:
                    b = value_of(values[s]).shape[0]
                    break
        enforce(b is not None, "beam search needs a boot or static input "
                               "to infer batch size")

        # beam-tiled outer context (encoder states etc.)
        outer = {name: self._tile_beams(v, k) for name, v in values.items()}

        mems0 = [self._tile_beams(
            self.group._memory_init(m, values, b, jnp.float32), k)
            for m in self.group.memories]

        placeholder = g["placeholder"]
        prob_name = g["prob_layer"]
        group = self.group

        batch_idx = jnp.arange(b)[:, None]                  # [B, 1]

        # a config round-tripped through to_json/from_json carries the
        # serialization markers ("<callable ...>"), not live hooks —
        # hooks are code and only exist when built from the source .py
        adjust = g.get("candidate_adjust")
        adjust = adjust if callable(adjust) else None
        drop = g.get("candidate_drop")
        drop = drop if callable(drop) else None

        def step_fn(carry, t):
            last_ids, scores, alive, mems, tokens = carry
            new_mems, step_vals = group.step(
                params, {placeholder: last_ids.reshape(-1)}, mems, outer,
                ctx)
            probs = value_of(step_vals[prob_name])          # [B*K, V]
            logp = jnp.log(jnp.maximum(probs, 1e-20))
            logp = logp.reshape(b, k, vocab)
            # user candidate hooks (RecurrentGradientMachine.h:73-112),
            # applied to live candidates before the finished-beam freeze
            # so hooks can never resurrect a closed beam
            if adjust is not None:
                logp = adjust(logp, tokens, t)
            if drop is not None:
                logp = jnp.where(drop(logp, tokens, t), NEG_INF, logp)
            # finished beams may only continue with EOS at zero cost
            logp = eos_frozen_logits(logp, alive, eos_id)
            cand = scores[:, :, None] + logp                # [B, K, V]
            top_scores, top_idx = jax.lax.top_k(
                cand.reshape(b, k * vocab), k)              # [B, K]
            parent = top_idx // vocab
            token = top_idx % vocab

            # gather state by parent beam
            def regather(x):
                shaped = x.reshape((b, k) + x.shape[1:])
                return shaped[batch_idx, parent].reshape(
                    (b * k,) + x.shape[1:])
            mems_g = [jax.tree_util.tree_map(regather, m_)
                      for m_ in new_mems]
            tokens_g = tokens[batch_idx, parent]            # [B, K, T]
            tokens_g = tokens_g.at[:, :, t].set(token)
            alive_g = alive[batch_idx, parent] & (token != eos_id)
            return (token, top_scores, alive_g, mems_g, tokens_g), None

        tokens0 = jnp.zeros((b, k, max_len), jnp.int32)
        # beam 0 starts live, others at -inf so step 1 yields K distinct
        scores0 = jnp.tile(jnp.asarray([0.0] + [NEG_INF] * (k - 1),
                                       jnp.float32), (b, 1))
        carry0 = (jnp.full((b, k), bos_id, jnp.int32), scores0,
                  jnp.ones((b, k), bool), mems0, tokens0)
        (last, scores, alive, _, tokens), _ = jax.lax.scan(
            step_fn, carry0, jnp.arange(max_len))

        # sequence length = position of first EOS (inclusive) else max_len
        is_eos = tokens == eos_id                            # [B, K, T]
        any_eos = jnp.any(is_eos, axis=-1)
        first_eos = jnp.argmax(is_eos, axis=-1)
        lengths = jnp.where(any_eos, first_eos + 1, max_len).astype(jnp.int32)
        return {"ids": tokens, "lengths": lengths, "scores": scores,
                "beam_size": k}


@register_layer("beam_gen")
class BeamGenLayer(Layer):
    """Root-visible handle of a generating group: its first input is the
    bundle the decoder wrote; exposes ids (as a nested SequenceBatch
    [B, K, T]) plus ``.scores`` / ``.lengths`` extra outputs."""

    def forward(self, params, inputs, ctx):
        bundle = inputs[0]
        enforce(isinstance(bundle, dict) and "ids" in bundle,
                "beam_gen input must be the generation bundle")
        return {"out": bundle["ids"], "scores": bundle["scores"],
                "lengths": bundle["lengths"]}

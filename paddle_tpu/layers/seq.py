"""Sequence layers.

Reference: ``SequencePoolLayer`` family (types ``average``, ``max``,
``seqlastins``, ``seqfirstins``), ``ExpandLayer`` (``expand``),
``SequenceConcatLayer`` (``seqconcat``), ``SequenceReshapeLayer``
(``seqreshape``), ``SequenceSliceLayer`` (``seq_slice``), ``SubSequenceLayer``
(``subseq``), ``KmaxSeqScoreLayer`` (``kmax_seq_score``),
``SequenceLastInstanceLayer``, ``MaxIdLayer`` (``maxid``),
``SamplingIdLayer`` (``sampling_id``), ``EosIdCheckLayer`` (``eos_id``),
``GetOutputLayer``, ``SequenceToBatch`` scheduling is obsolete on TPU (the
padded layout + masks replace it).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.sequence import NestedSequenceBatch, SequenceBatch, like, value_of
from ..ops import embedding_ops, sequence_ops
from ..utils import ConfigError, enforce
from .base import ForwardContext, Layer, register_layer


def _as_seq(x) -> SequenceBatch:
    enforce(isinstance(x, (SequenceBatch, NestedSequenceBatch)),
            "layer requires a sequence input")
    return x


class _PoolBase(Layer):
    pool_type = "average"

    def forward(self, params, inputs, ctx):
        seq = _as_seq(inputs[0])
        stride = self.conf.attrs.get("stride", -1)
        if stride > 0:
            # strided pooling: pool over windows of `stride` timesteps,
            # producing a shorter sequence (reference seqlastins w/ stride)
            seq = _strided_reshape(seq, stride)
            pooled = jax.vmap(
                lambda d, l: _pool_window(d, l, self.pool_type))(
                    seq.data, seq.sub_length)
            return SequenceBatch(data=pooled, length=seq.num_subseq)
        if isinstance(seq, NestedSequenceBatch):
            # pool the inner level → sequence of per-subseq vectors
            flat = seq.flatten_to_subseq()
            pooled = sequence_ops.sequence_pool(flat, self.pool_type)
            b, s = seq.data.shape[:2]
            return SequenceBatch(
                data=pooled.reshape((b, s) + pooled.shape[1:]),
                length=seq.num_subseq)
        out = sequence_ops.sequence_pool(seq, self.pool_type)
        return self.finalize(out, ctx)


def _pool_window(data, lengths, pool_type):
    sb = SequenceBatch(data=data, length=lengths)
    return sequence_ops.sequence_pool(sb, pool_type)


def _strided_reshape(seq: SequenceBatch, stride: int) -> NestedSequenceBatch:
    b, t = seq.data.shape[:2]
    n = (t + stride - 1) // stride
    pad = n * stride - t
    data = jnp.pad(seq.data, [(0, 0), (0, pad)] + [(0, 0)] * (seq.data.ndim - 2))
    data = data.reshape((b, n, stride) + seq.data.shape[2:])
    starts = jnp.arange(n, dtype=jnp.int32)[None, :] * stride
    sub_len = jnp.clip(seq.length[:, None] - starts, 0, stride)
    num_sub = (seq.length + stride - 1) // stride
    return NestedSequenceBatch(data=data, num_subseq=num_sub, sub_length=sub_len)


@register_layer("average")
class AverageLayer(_PoolBase):
    @property
    def pool_type(self):
        t = self.conf.attrs.get("average_strategy", "average")
        return {"average": "average", "sum": "sum", "squarerootn": "sqrt"}.get(t, "average")


@register_layer("max")
class MaxPoolSeqLayer(_PoolBase):
    pool_type = "max"


@register_layer("seqlastins")
class SequenceLastInstanceLayer(_PoolBase):
    pool_type = "last"


@register_layer("seqfirstins")
class SequenceFirstInstanceLayer(_PoolBase):
    pool_type = "first"


@register_layer("expand")
class ExpandLayer(Layer):
    """Broadcast non-sequence rows over the time axis of the second input."""

    def forward(self, params, inputs, ctx):
        x = value_of(inputs[0])
        template = _as_seq(inputs[1])
        if isinstance(template, NestedSequenceBatch):
            t = template.data.shape[1]
            data = jnp.broadcast_to(x[:, None], (x.shape[0], t) + x.shape[1:])
            return SequenceBatch(data=data, length=template.num_subseq)
        return sequence_ops.seq_expand(x, template)


@register_layer("seqconcat")
class SequenceConcatLayer(Layer):
    def forward(self, params, inputs, ctx):
        return sequence_ops.sequence_concat(_as_seq(inputs[0]), _as_seq(inputs[1]))


@register_layer("seqreshape")
class SequenceReshapeLayer(Layer):
    def forward(self, params, inputs, ctx):
        return sequence_ops.sequence_reshape(_as_seq(inputs[0]), self.conf.size)


@register_layer("seq_slice")
class SequenceSliceLayer(Layer):
    def forward(self, params, inputs, ctx):
        seq = _as_seq(inputs[0])
        offsets = value_of(inputs[1]).reshape(-1).astype(jnp.int32) \
            if len(inputs) > 1 else jnp.zeros_like(seq.length)
        sizes = value_of(inputs[2]).reshape(-1).astype(jnp.int32) \
            if len(inputs) > 2 else seq.length - offsets
        return sequence_ops.sequence_slice(seq, offsets, sizes)


@register_layer("subseq")
class SubSequenceLayer(Layer):
    def forward(self, params, inputs, ctx):
        seq = _as_seq(inputs[0])
        offsets = value_of(inputs[1]).reshape(-1).astype(jnp.int32)
        sizes = value_of(inputs[2]).reshape(-1).astype(jnp.int32)
        return sequence_ops.sequence_slice(seq, offsets, sizes)


@register_layer("sub_nested_seq")
class SubNestedSequenceLayer(Layer):
    """Select subsequences of a nested sequence by per-sequence indices
    (``SubNestedSequenceLayer``)."""

    def forward(self, params, inputs, ctx):
        nested = inputs[0]
        enforce(isinstance(nested, NestedSequenceBatch),
                "sub_nested_seq needs a nested sequence")
        sel = value_of(inputs[1]).astype(jnp.int32)  # [B, K] indices, -1 pad
        k = sel.shape[1]
        safe = jnp.maximum(sel, 0)
        data = jnp.take_along_axis(
            nested.data,
            safe.reshape(safe.shape + (1,) * (nested.data.ndim - 2)), axis=1)
        sub_len = jnp.take_along_axis(nested.sub_length, safe, axis=1)
        valid = sel >= 0
        sub_len = jnp.where(valid, sub_len, 0)
        return NestedSequenceBatch(
            data=data, num_subseq=jnp.sum(valid.astype(jnp.int32), axis=1),
            sub_length=sub_len)


@register_layer("kmax_seq_score")
class KmaxSeqScoreLayer(Layer):
    def forward(self, params, inputs, ctx):
        seq = _as_seq(inputs[0])
        return like(seq, sequence_ops.kmax_seq_score(
            seq, self.conf.attrs.get("beam_size", 1)))


@register_layer("maxid")
class MaxIdLayer(Layer):
    def forward(self, params, inputs, ctx):
        x = inputs[0]
        out = sequence_ops.max_id(value_of(x),
                                  self.conf.attrs.get("beam_size", 1))
        return like(x, out)


@register_layer("sampling_id")
class SamplingIdLayer(Layer):
    def forward(self, params, inputs, ctx):
        out = embedding_ops.sampling_id(
            ctx.layer_rng(self.name), value_of(inputs[0]))
        return like(inputs[0], out)


@register_layer("eos_id")
class EosIdCheckLayer(Layer):
    """1 where input id == eos_id (``EosIdCheckLayer``)."""

    def forward(self, params, inputs, ctx):
        ids = value_of(inputs[0])
        eos = self.conf.attrs["eos_id"]
        return like(inputs[0], (ids == eos).astype(jnp.float32))


@register_layer("get_output")
class GetOutputLayer(Layer):
    """Pass-through selecting a named output of the input layer
    (``GetOutputLayer``) — outputs here are single-valued, so identity."""

    def forward(self, params, inputs, ctx):
        return inputs[0]


@register_layer("gather_agent")
class GatherAgentLayer(Layer):
    """Recurrent-group plumbing: concatenates per-step frames back into a
    sequence.  Executed implicitly by the TPU recurrent-group scan
    (:mod:`paddle_tpu.layers.recurrent_group`); standalone use is identity."""

    def forward(self, params, inputs, ctx):
        return inputs[0]


@register_layer("scatter_agent")
class ScatterAgentLayer(Layer):
    def forward(self, params, inputs, ctx):
        return inputs[0]


@register_layer("row_conv")
class RowConvLayer(Layer):
    """Lookahead row convolution (``RowConvLayer.cpp``, DeepSpeech2):
    ``out[t] = sum_{i<ctx} in[t+i] * W[i]`` per feature, within each
    sequence.  W is [context_length, size]."""

    def param_specs(self):
        ctx_len = self.conf.attrs.get("context_length", 1)
        return [self._weight_spec(0, (ctx_len, self.conf.size),
                                  initial_smart=True)]

    def forward(self, params, inputs, ctx):
        seq = _as_seq(inputs[0])
        w = params[self.weight_name(0)]
        ctx_len = w.shape[0]
        # zero out padding so lookahead past the sequence end contributes 0
        x = seq.masked_data(0.0)  # [B, T, D]
        out = jnp.zeros_like(x)
        for i in range(ctx_len):
            # shift left by i: x[:, t+i]; positions past T-i are zero
            shifted = jnp.pad(x[:, i:], ((0, 0), (0, i), (0, 0)))
            out = out + shifted * w[i]
        return self.finalize(seq.with_data(out), ctx)

"""Dense / glue layers.

Covers the reference families (``paddle/gserver/layers``): DataLayer,
FullyConnectedLayer, MixedLayer + projections (FullMatrix, Identity, DotMul,
Scaling, Table, Context, Slice — ``paddle/gserver/layers/Projection.h``
family), AddtoLayer, ConcatenateLayer, embedding (TableProjection as a
layer), SelectiveFc, InterpolationLayer, OuterProdLayer, PowerLayer,
ScalingLayer, SlopeInterceptLayer, ConvexCombinationLayer, CosSimLayer,
CosSimVecMatLayer, SumToOneNormLayer, RowL2NormLayer, TransLayer,
ResizeLayer, ClipLayer, ScaleShiftLayer, ParameterReluLayer, MultiplexLayer,
DotProdLayer, FeatureMapExpandLayer, TensorLayer, NCELayer,
HierarchicalSigmoidLayer, PrintLayer, DataNormLayer.

Layer *type strings* match the reference's registered names so configs
translate 1:1.
"""

from __future__ import annotations

from typing import Any, Dict, List

import jax
import jax.numpy as jnp
import numpy as np

from ..config.model_config import LayerConfig, ParameterConfig
from ..core.sequence import SequenceBatch, like, value_of
from ..ops import embedding_ops, math_ops, sequence_ops
from ..parallel import sparse as psparse
from ..utils import ConfigError, enforce
from .base import ForwardContext, Layer, register_layer


def map_value(fn, x):
    return like(x, fn(value_of(x)))


def flatten_image(v: jax.Array) -> jax.Array:
    """NHWC image tensor → flat [B, C*H*W] rows in the reference's CHW
    element order (so fc weights keep reference-compatible layout)."""
    if v.ndim == 4:
        return jnp.moveaxis(v, -1, 1).reshape(v.shape[0], -1)
    return v.reshape(v.shape[0], -1)


def _flat_apply(fn, x):
    """Apply a [N, D] → [N, D'] function across batch (and time) dims.

    SequenceBatch data [B, T, D] is applied per-timestep; raw arrays of
    rank > 2 (image tensors) are flattened to [B, C*H*W] like the reference's
    row-matrix layout.
    """
    v = value_of(x)
    if isinstance(x, SequenceBatch) and v.ndim > 2:
        lead = v.shape[:-1]
        out = fn(v.reshape(-1, v.shape[-1]))
        out = out.reshape(lead + out.shape[1:])
    elif v.ndim > 2:
        out = fn(flatten_image(v))
    else:
        out = fn(v)
    return like(x, out)


@register_layer("data")
class DataLayer(Layer):
    """Feed entry point (``DataLayer.cpp``); value comes from the feed dict."""

    def forward(self, params, inputs, ctx):
        raise ConfigError("data layers are fed, not computed")


@register_layer("fc", "mkldnn_fc")
class FullyConnectedLayer(Layer):
    """``FullyConnectedLayer``: out = act(sum_i x_i W_i + b)."""

    def param_specs(self):
        specs = []
        for i, inp in enumerate(self.conf.inputs):
            in_size = self.conf.attrs.get(f"input_size{i}") or \
                self.model.find_size(inp.input_layer_name)
            specs.append(self._weight_spec(
                i, (in_size, self.conf.size), initial_smart=True))
        if self.conf.with_bias:
            specs.append(self._bias_spec((self.conf.size,)))
        return specs

    def forward(self, params, inputs, ctx):
        out = None
        for i, x in enumerate(inputs):
            w = params[self.weight_name(i)]
            y = _flat_apply(lambda v: math_ops.matmul(v, w), x)
            out = y if out is None else like(y, value_of(out) + value_of(y))
        if self.conf.with_bias:
            # add in the activation dtype: promoting a bf16 [B,T,V]
            # matmul output to f32 here costs a full convert+copy pass
            # in BOTH directions (cast_layer_output re-casts right after)
            out = map_value(
                lambda v: v + params[self.bias_name()].astype(v.dtype), out)
        if self.conf.active_type == "softmax" and self.conf.drop_rate == 0:
            # expose pre-activation as '.logits' so classification costs
            # can take the fused logits path (XLA dead-code-eliminates
            # whichever output goes unused)
            return {"out": self.finalize(out, ctx), "logits": out}
        return self.finalize(out, ctx)


@register_layer("embedding")
class EmbeddingLayer(Layer):
    """Table lookup (v1: table_projection; kept as a first-class layer)."""

    def param_specs(self):
        vocab = self.conf.attrs["vocab_size"]
        return [self._weight_spec(0, (vocab, self.conf.size),
                                  initial_smart=True,
                                  sharded=self.conf.attrs.get("sharded", False))]

    def forward(self, params, inputs, ctx):
        name = self.weight_name(0)
        ids = value_of(inputs[0])
        entry = psparse.exchange_entry(name)
        if entry is not None:
            # sparse gradient exchange: this trace routes the lookup
            # through the batch's prefetched (rows, block) pair, so
            # autodiff yields a [K, D] block cotangent instead of the
            # dense [V, D] table gradient (parallel/sparse.py)
            rows, block = entry
            out = psparse.lookup_rows(rows, block, ids)
        else:
            out = embedding_ops.lookup_table(params[name], ids)
        return self.finalize(like(inputs[0], out), ctx)


def _add_flat_bias(out: jax.Array, bias: jax.Array) -> jax.Array:
    """Add a per-element bias stored in the reference's flat CHW order to
    an output that may be in NHWC image layout (googlenet's inception
    ``concat_layer(bias_attr=True)`` owns a bias of size C*H*W)."""
    if out.ndim == 4 and bias.ndim == 1 \
            and bias.size == out.shape[1] * out.shape[2] * out.shape[3]:
        b, h, w, c = out.shape
        bias = jnp.moveaxis(bias.reshape(c, h, w), 0, -1)
    return out + bias


@register_layer("addto")
class AddtoLayer(Layer):
    def forward(self, params, inputs, ctx):
        out = value_of(inputs[0])
        for x in inputs[1:]:
            out = out + value_of(x)
        if self.conf.with_bias:
            out = _add_flat_bias(out, params[self.bias_name()])
        return self.finalize(like(inputs[0], out), ctx)

    def param_specs(self):
        return [self._bias_spec((self.conf.size,))] if self.conf.with_bias else []


@register_layer("concat")
class ConcatLayer(Layer):
    def forward(self, params, inputs, ctx):
        vals = [value_of(x) for x in inputs]
        out = jnp.concatenate(vals, axis=-1)
        if self.conf.with_bias:   # googlenet inception: concat+bias+relu
            out = _add_flat_bias(out, params[self.bias_name()])
        return self.finalize(like(inputs[0], out), ctx)

    def param_specs(self):
        return [self._bias_spec((self.conf.size,))] \
            if self.conf.with_bias else []


@register_layer("mixed")
class MixedLayer(Layer):
    """``MixedLayer``: sum of per-input projections (+ optional operators).

    Projection types (per input's ProjConfig): fc, identity, dot_mul,
    scaling, table, context, slice; operator 'dot_mul_operator' over two
    inputs via attrs.
    """

    def param_specs(self):
        specs = []
        for i, inp in enumerate(self.conf.inputs):
            p = inp.proj
            if p is None:
                continue
            # shared sizing rule (ProjConfig.resolved_output_size);
            # the conf.size fallback is only sound for sum-of-projections
            # (mixed) where every projection output IS the layer size —
            # Concat2Layer.param_specs rejects unresolved sizes upfront
            psize = p.resolved_output_size() or self.conf.size
            if p.type == "fc":
                specs.append(self._weight_spec(
                    i, (p.input_size, psize), initial_smart=True))
            elif p.type == "trans_fc":
                # TransposedFullMatrixProjection: W is [out, in], applied
                # transposed (trainer_config_helpers trans_full_matrix_projection)
                specs.append(self._weight_spec(
                    i, (psize, p.input_size), initial_smart=True))
            elif p.type == "dot_mul":
                specs.append(self._weight_spec(i, (psize,),
                                               initial_mean=1.0, initial_std=0.0))
            elif p.type == "scaling":
                specs.append(self._weight_spec(i, (1,), initial_mean=1.0,
                                               initial_std=0.0))
            elif p.type == "table":
                specs.append(self._weight_spec(
                    i, (p.input_size, psize), initial_smart=True))
            elif p.type == "context" and p.trainable_padding:
                begin = max(0, -p.context_start)
                end = max(0, p.context_start + p.context_length - 1)
                specs.append(self._weight_spec(
                    i, (begin + end, p.input_size)))
        if self.conf.with_bias:
            specs.append(self._bias_spec((self.conf.size,)))
        return specs

    def _project(self, params, inputs, i):
        """Apply input *i*'s projection; returns ``(y, template)`` where
        template is non-None when the projection dictates sequence
        structure (context projection)."""
        x = inputs[i]
        p = self.conf.inputs[i].proj
        v = value_of(x)
        template = None
        if p.type == "fc":
            y = value_of(_flat_apply(
                lambda t: math_ops.matmul(t, params[self.weight_name(i)]), x))
        elif p.type == "trans_fc":
            y = value_of(_flat_apply(lambda t: math_ops.matmul(
                t, params[self.weight_name(i)].T), x))
        elif p.type == "identity":
            y = v
        elif p.type == "dot_mul":
            y = v * params[self.weight_name(i)]
        elif p.type == "scaling":
            y = v * params[self.weight_name(i)][0]
        elif p.type == "table":
            y = embedding_ops.lookup_table(params[self.weight_name(i)], v)
        elif p.type == "context":
            enforce(isinstance(x, SequenceBatch),
                    "context projection needs a sequence input")
            pad_w = params.get(self.weight_name(i)) if p.trainable_padding else None
            y = value_of(sequence_ops.context_projection(
                x, p.context_start, p.context_length, pad_w))
            template = x
        elif p.type == "slice":
            slices = getattr(p, "slices", None) or \
                [(p.slice_begin, p.slice_end)]
            y = jnp.concatenate([v[..., b:e] for b, e in slices], axis=-1)
        else:
            raise ConfigError(f"unknown projection type {p.type!r}")
        return y, template

    def forward(self, params, inputs, ctx):
        out = None
        template = inputs[0]
        for i, x in enumerate(inputs):
            if self.conf.inputs[i].proj is None:  # operator input — consumed
                continue                          # by the operators loop below
            y, tmpl = self._project(params, inputs, i)
            if tmpl is not None:
                template = tmpl
            out = y if out is None else out + y
        if self.conf.attrs.get("dot_mul_operator"):
            out = value_of(inputs[0]) * value_of(inputs[1]) * \
                self.conf.attrs.get("dotmul_scale", 1.0)
        for op in self.conf.attrs.get("operators", []):
            out = self._apply_operator(op, inputs, out)
        if self.conf.with_bias:
            out = out + params[self.bias_name()]
        return self.finalize(like(template, out), ctx)

    def _apply_operator(self, op: Dict[str, Any], inputs, out):
        """Operator inside a mixed layer (``ConvOperator``/``DotMulOperator``
        — operators read other inputs' values, own no parameters)."""
        kind = op["type"]
        ia, ib = op.get("input_indices", (0, 1))
        a = value_of(inputs[ia])
        b = value_of(inputs[ib])
        if kind == "dot_mul":
            y = a * b * op.get("scale", 1.0)
        elif kind == "conv":
            from ..ops import nn_ops
            from .conv import to_nhwc
            c = op["channels"]
            h = op.get("img_size_y", op.get("img_size"))
            w = op.get("img_size")
            fh = op.get("filter_size_y", op["filter_size"])
            fw = op["filter_size"]
            nf = op["num_filters"]
            x = to_nhwc(a, c, h, w)
            # the filter comes from a layer's VALUE with one filter PER
            # SAMPLE (ConvOperator.cpp:61 requires ins_[1] height ==
            # batchSize; :72 offsets wgtData by weightOffset_*batchId) —
            # vmap a conv over the batch so each sample uses its own filter
            filt = b.reshape(b.shape[0], nf, c, fh, fw) \
                    .transpose(0, 3, 4, 2, 1)           # [B, fh, fw, c, nf]
            stride = (op.get("stride_y", op.get("stride", 1)),
                      op.get("stride", 1))
            padding = [(op.get("padding_y", op.get("padding", 0)),) * 2,
                       (op.get("padding", 0),) * 2]

            def conv_one(xi, fi):
                return nn_ops.conv2d(xi[None], fi, stride=stride,
                                     padding=padding)[0]

            y = jax.vmap(conv_one)(x, filt)             # [B, H', W', nf]
            # flat rows are channel-major (CHW) like every image layer here
            y = jnp.moveaxis(y, -1, 1).reshape(y.shape[0], -1)
        else:
            raise ConfigError(f"unknown mixed operator {kind!r}")
        return y if out is None else out + y


@register_layer("concat2")
class Concat2Layer(MixedLayer):
    """``concat2``: like concat, but each input goes through its own
    Projection and the projection *outputs* are concatenated instead of
    summed (reference ``ConcatenateLayer2``,
    ``paddle/gserver/layers/ConcatenateLayer.cpp:99``; emitted by
    ``concat_layer`` when handed Projection inputs,
    ``trainer_config_helpers/layers.py:3309``)."""

    def param_specs(self):
        total = 0
        for i, inp in enumerate(self.conf.inputs):
            enforce(inp.proj is not None,
                    f"concat2 layer {self.conf.name!r} input {i} has no "
                    "projection")
            psize = inp.proj.resolved_output_size()
            enforce(psize > 0,
                    f"concat2 layer {self.conf.name!r} input {i}: "
                    f"{inp.proj.type} projection needs an explicit size")
            total += psize
        enforce(total == self.conf.size,
                f"concat2 layer {self.conf.name!r} size {self.conf.size} != "
                f"sum of projection outputs {total}")
        return super().param_specs()

    def forward(self, params, inputs, ctx):
        outs = []
        template = inputs[0]
        for i in range(len(inputs)):
            y, tmpl = self._project(params, inputs, i)
            if tmpl is not None:
                template = tmpl
            outs.append(y)
        out = jnp.concatenate(outs, axis=-1)
        if self.conf.with_bias:
            out = out + params[self.bias_name()]
        return self.finalize(like(template, out), ctx)


@register_layer("selective_fc")
class SelectiveFcLayer(Layer):
    def param_specs(self):
        in_size = self.model.find_size(self.conf.inputs[0].input_layer_name)
        specs = [self._weight_spec(0, (in_size, self.conf.size), initial_smart=True)]
        if self.conf.with_bias:
            specs.append(self._bias_spec((self.conf.size,)))
        return specs

    def forward(self, params, inputs, ctx):
        x = value_of(inputs[0])
        sel = value_of(inputs[1]).astype(jnp.int32) if len(inputs) > 1 else None
        out = embedding_ops.selective_fc(
            x, params[self.weight_name(0)],
            params.get(self.bias_name()) if self.conf.with_bias else None,
            sel, act=self.conf.active_type or "linear")
        return like(inputs[0], out)


@register_layer("interpolation")
class InterpolationLayer(Layer):
    def forward(self, params, inputs, ctx):
        w, x, y = (value_of(i) for i in inputs)
        return self.finalize(like(inputs[1], math_ops.interpolation(w, x, y)), ctx)


@register_layer("out_prod")
class OuterProdLayer(Layer):
    def forward(self, params, inputs, ctx):
        return self.finalize(
            like(inputs[0], math_ops.outer_prod(value_of(inputs[0]),
                                                value_of(inputs[1]))), ctx)


@register_layer("power")
class PowerLayer(Layer):
    """out = x ^ w with per-row scalar exponent w (first input)."""

    def forward(self, params, inputs, ctx):
        w = value_of(inputs[0]).reshape(-1, 1)
        x = value_of(inputs[1])
        return self.finalize(like(inputs[1], jnp.power(x, w)), ctx)


@register_layer("scaling")
class ScalingLayer(Layer):
    """Row-wise scale: weight (first input, one scalar per row/step) * x
    (second input).  Works per-timestep on sequences."""

    def forward(self, params, inputs, ctx):
        w = value_of(inputs[0])
        x = value_of(inputs[1])
        if w.ndim == x.ndim:
            pass  # [B(,T),1] broadcasts
        else:
            w = w.reshape(w.shape + (1,) * (x.ndim - w.ndim))
        return self.finalize(like(inputs[1], w * x), ctx)


@register_layer("slope_intercept")
class SlopeInterceptLayer(Layer):
    def forward(self, params, inputs, ctx):
        out = math_ops.slope_intercept(
            value_of(inputs[0]), self.conf.attrs.get("slope", 1.0),
            self.conf.attrs.get("intercept", 0.0))
        return self.finalize(like(inputs[0], out), ctx)


@register_layer("convex_comb")
class ConvexCombinationLayer(Layer):
    def forward(self, params, inputs, ctx):
        return self.finalize(
            like(inputs[1], math_ops.convex_combination(
                value_of(inputs[0]), value_of(inputs[1]))), ctx)


@register_layer("cos")
class CosSimLayer(Layer):
    def forward(self, params, inputs, ctx):
        out = math_ops.cos_sim(value_of(inputs[0]), value_of(inputs[1]),
                               scale=self.conf.attrs.get("cos_scale", 1.0))
        return like(inputs[0], out.reshape(-1, 1))


@register_layer("cos_vm")
class CosSimVecMatLayer(Layer):
    """cosine of vec [B, D] against each row of mat [B, K*D] → [B, K]."""

    def forward(self, params, inputs, ctx):
        vec = value_of(inputs[0])
        mat = value_of(inputs[1])
        b, d = vec.shape
        k = mat.shape[1] // d
        m = mat.reshape(b, k, d)
        dot = math_ops.einsum("bd,bkd->bk", vec, m)
        nv = jnp.linalg.norm(vec, axis=-1, keepdims=True)
        nm = jnp.linalg.norm(m, axis=-1)
        out = self.conf.attrs.get("cos_scale", 1.0) * dot / (nv * nm + 1e-10)
        return like(inputs[0], out)


@register_layer("sum_to_one_norm")
class SumToOneNormLayer(Layer):
    def forward(self, params, inputs, ctx):
        return map_value(math_ops.sum_to_one_norm, inputs[0])


@register_layer("row_l2_norm")
class RowL2NormLayer(Layer):
    def forward(self, params, inputs, ctx):
        return map_value(math_ops.row_l2_norm, inputs[0])


@register_layer("trans")
class TransLayer(Layer):
    def forward(self, params, inputs, ctx):
        return like(inputs[0], jnp.swapaxes(value_of(inputs[0]), -1, -2))


@register_layer("resize")
class ResizeLayer(Layer):
    def forward(self, params, inputs, ctx):
        v = value_of(inputs[0])
        return like(inputs[0], v.reshape(-1, self.conf.size))


@register_layer("clip")
class ClipLayer(Layer):
    def forward(self, params, inputs, ctx):
        return map_value(
            lambda v: jnp.clip(v, self.conf.attrs.get("min", -1.0),
                               self.conf.attrs.get("max", 1.0)), inputs[0])


@register_layer("scale_shift")
class ScaleShiftLayer(Layer):
    def param_specs(self):
        specs = [self._weight_spec(0, (1,), initial_mean=1.0, initial_std=0.0)]
        if self.conf.with_bias:
            specs.append(self._bias_spec((1,)))
        return specs

    def forward(self, params, inputs, ctx):
        out = value_of(inputs[0]) * params[self.weight_name(0)][0]
        if self.conf.with_bias:
            out = out + params[self.bias_name()][0]
        return self.finalize(like(inputs[0], out), ctx)


@register_layer("prelu")
class ParameterReluLayer(Layer):
    def param_specs(self):
        partial_sum = self.conf.attrs.get("partial_sum", 1)
        n = self.conf.size // partial_sum
        return [self._weight_spec(0, (n,), initial_mean=0.25, initial_std=0.0)]

    def forward(self, params, inputs, ctx):
        alpha = params[self.weight_name(0)]
        partial = self.conf.attrs.get("partial_sum", 1)
        v = value_of(inputs[0])
        a = jnp.repeat(alpha, partial)[: v.shape[-1]]
        return like(inputs[0], jnp.where(v >= 0, v, a * v))


@register_layer("multiplex")
class MultiplexLayer(Layer):
    def forward(self, params, inputs, ctx):
        idx = value_of(inputs[0]).reshape(-1)
        return like(inputs[1],
                    math_ops.multiplex(idx, *[value_of(x) for x in inputs[1:]]))


@register_layer("dot_prod")
class DotProdLayer(Layer):
    def forward(self, params, inputs, ctx):
        out = jnp.sum(value_of(inputs[0]) * value_of(inputs[1]), axis=-1,
                      keepdims=True)
        return like(inputs[0], out)


@register_layer("featmap_expand")
class FeatureMapExpandLayer(Layer):
    def forward(self, params, inputs, ctx):
        from ..ops.nn_ops import feature_map_expand

        return map_value(
            lambda v: feature_map_expand(
                v, self.conf.attrs["num_filters"],
                self.conf.attrs.get("as_row_vector", True)), inputs[0])


@register_layer("tensor")
class TensorLayer(Layer):
    """``TensorLayer``: out_k = x1 W_k x2^T per output unit k."""

    def param_specs(self):
        d1 = self.model.find_size(self.conf.inputs[0].input_layer_name)
        d2 = self.model.find_size(self.conf.inputs[1].input_layer_name)
        specs = [self._weight_spec(0, (self.conf.size, d1, d2), initial_smart=True)]
        if self.conf.with_bias:
            specs.append(self._bias_spec((self.conf.size,)))
        return specs

    def forward(self, params, inputs, ctx):
        x1, x2 = value_of(inputs[0]), value_of(inputs[1])
        w = params[self.weight_name(0)]
        out = math_ops.einsum("bi,kij,bj->bk", x1, w, x2)
        if self.conf.with_bias:
            out = out + params[self.bias_name()]
        return self.finalize(like(inputs[0], out), ctx)


@register_layer("nce")
class NCELayer(Layer):
    def param_specs(self):
        d = self.model.find_size(self.conf.inputs[0].input_layer_name)
        num_classes = self.conf.attrs["num_classes"]
        specs = [self._weight_spec(0, (num_classes, d), initial_smart=True)]
        if self.conf.with_bias:
            specs.append(self._bias_spec((num_classes,)))
        return specs

    def forward(self, params, inputs, ctx):
        x = value_of(inputs[0])
        labels = value_of(inputs[1]).reshape(-1)
        num_classes = self.conf.attrs["num_classes"]
        num_neg = self.conf.attrs.get("num_neg_samples", 10)
        key = ctx.layer_rng(self.name)
        sample_ids = jax.random.randint(key, (x.shape[0], num_neg), 0, num_classes)
        probs = jnp.full((x.shape[0], num_neg), 1.0 / num_classes)
        b = params.get(self.bias_name())
        if b is None:
            b = jnp.zeros(num_classes)
        cost = embedding_ops.nce_loss(
            x, labels, params[self.weight_name(0)], b, sample_ids, probs)
        return like(inputs[0], cost.reshape(-1, 1))


@register_layer("hsigmoid")
class HierarchicalSigmoidLayer(Layer):
    def param_specs(self):
        d = self.model.find_size(self.conf.inputs[0].input_layer_name)
        num_classes = self.conf.attrs["num_classes"]
        specs = [self._weight_spec(0, (num_classes - 1, d), initial_smart=True)]
        if self.conf.with_bias:
            specs.append(self._bias_spec((num_classes - 1,)))
        return specs

    def forward(self, params, inputs, ctx):
        x = value_of(inputs[0])
        labels = value_of(inputs[1]).reshape(-1)
        num_classes = self.conf.attrs["num_classes"]
        b = params.get(self.bias_name())
        if b is None:
            b = jnp.zeros(num_classes - 1)
        cost = embedding_ops.hierarchical_sigmoid(
            x, labels, params[self.weight_name(0)], b, num_classes)
        return like(inputs[0], cost.reshape(-1, 1))


@register_layer("data_norm")
class DataNormLayer(Layer):
    """z-score/min-max/decimal scaling normalization with fixed stats
    (``DataNormLayer`` — stats provided via attrs, not learned)."""

    def forward(self, params, inputs, ctx):
        strategy = self.conf.attrs.get("data_norm_strategy", "z-score")
        v = value_of(inputs[0])
        if strategy == "z-score":
            mean = jnp.asarray(self.conf.attrs.get("mean", 0.0))
            std = jnp.asarray(self.conf.attrs.get("std", 1.0))
            out = (v - mean) / jnp.maximum(std, 1e-8)
        elif strategy == "min-max":
            mn = jnp.asarray(self.conf.attrs.get("min", 0.0))
            mx = jnp.asarray(self.conf.attrs.get("max", 1.0))
            out = (v - mn) / jnp.maximum(mx - mn, 1e-8)
        else:  # decimal-scaling
            a = jnp.asarray(self.conf.attrs.get("a", 1.0))
            out = v / a
        return like(inputs[0], out)


@register_layer("print")
class PrintLayer(Layer):
    """Host-side debug print (``PrintLayer``) via jax.debug.print."""

    def forward(self, params, inputs, ctx):
        jax.debug.print(self.name + ": {}", value_of(inputs[0]))
        return inputs[0]


@register_layer("conv_shift")
class ConvShiftLayer(Layer):
    """Circular convolution of each row of a with kernel row b
    (``ConvShiftLayer.cpp``; NTM addressing): b width must be odd."""

    def forward(self, params, inputs, ctx):
        from ..ops.math_ops import conv_shift
        a = value_of(inputs[0])
        b = value_of(inputs[1])
        return self.finalize(like(inputs[0], conv_shift(a, b)), ctx)

"""Post-mortem debug dump: SIGUSR2 → metrics + flight recorder on disk.

A wedged run (deadlocked input pipeline, master stuck in backoff, a
step that never fences) usually gets SIGKILLed before anyone attaches a
debugger.  With ``--debug_dump_signal`` the process installs a SIGUSR2
handler that snapshots the full observability state of the LIVE run to
timestamped files:

    kill -USR2 <pid>
    # -> <dir>/paddle_tpu_dump_<ts>_<pid>.metrics.prom   (Prometheus text)
    # -> <dir>/paddle_tpu_dump_<ts>_<pid>.trace.json     (flight recorder,
    #                                        Chrome trace-event array)

The handler runs in the main thread (CPython delivers signals there),
does plain file IO only, and never raises — a failed dump logs and
returns, it must not take down the run it was asked to diagnose.
Opt-in by flag because library code must not steal process-wide signal
dispositions by default.
"""

from __future__ import annotations

import json
import os
import signal
import sys
import threading
import time
from typing import Optional, Tuple

from ..analysis.lockorder import named_lock
from . import trace
from .report import prometheus_dump

_installed = False
_install_lock = named_lock("observe.dump.install")


def debug_dump(out_dir: Optional[str] = None) -> Tuple[str, str]:
    """Write the dump files now; returns the (metrics, trace) paths.
    Usable directly (tests, a REPL on a live run) — the signal handler
    is just this plus plumbing.  When the training-health observatory
    has drained at least once this process, its latest structured
    report is dumped alongside as ``.health.json`` (resolved through
    ``sys.modules`` — a run that never enabled ``--health_interval``
    writes exactly the legacy two files); when this process HOSTS a
    fleet aggregator (``--fleet_port``), the cluster rollup + topology
    land as ``.fleet.json`` too."""
    from ..utils import FLAGS

    out_dir = out_dir or FLAGS.get("debug_dump_dir") or "/tmp"
    os.makedirs(out_dir, exist_ok=True)
    stem = os.path.join(
        out_dir, "paddle_tpu_dump_%s_%d" % (
            time.strftime("%Y%m%d-%H%M%S"), os.getpid()))
    prom_path = stem + ".metrics.prom"
    trace_path = stem + ".trace.json"
    with open(prom_path, "w") as f:
        f.write(prometheus_dump())
    with open(trace_path, "w") as f:
        f.write(trace.flight_recorder_json())
    hmod = sys.modules.get("paddle_tpu.observe.health")
    health_report = hmod.latest_report() if hmod is not None else None
    if health_report is not None:
        with open(stem + ".health.json", "w") as f:
            json.dump({"report": health_report,
                       "summary": hmod.status_summary()}, f, indent=1)
    # a process HOSTING the fleet aggregator dumps the cluster view
    # too: the rollup + topology of every registered peer at dump time
    # (resolved through sys.modules like health — the module is always
    # imported with the package, the gate is whether it is hosting)
    fmod = sys.modules.get("paddle_tpu.observe.fleet")
    if fmod is not None and fmod.hosting():
        with open(stem + ".fleet.json", "w") as f:
            json.dump({"healthz": fmod.rollup(),
                       "topology": fmod.topology()}, f, indent=1)
    return prom_path, trace_path


def _do_dump() -> None:
    from ..utils.logger import get_logger

    log = get_logger("observe")
    try:
        prom, tr = debug_dump()
        log.warning("SIGUSR2 debug dump: %s + %s (%d trace events)",
                    prom, tr, len(trace.events()))
    except Exception as e:   # noqa: BLE001 — a diagnostics dump must
        log.warning("SIGUSR2 debug dump FAILED: %s: %s",  # never kill
                    type(e).__name__, e)                  # the run


def _handler(signum, frame) -> None:
    # CPython runs this on the main thread, possibly INSIDE one of the
    # non-reentrant critical sections the dump must read (the trace
    # ring lock in _Span.__exit__, the registry locks in counter.inc)
    # — acquiring them here would self-deadlock the run being
    # diagnosed.  Hand the dump to a short-lived thread instead: it
    # blocks until the main thread releases the lock, the handler
    # returns immediately.
    threading.Thread(target=_do_dump, name="ptpu-debug-dump",
                     daemon=True).start()


def install_from_flags() -> bool:
    """Install the SIGUSR2 handler iff ``--debug_dump_signal`` is set.
    Idempotent; returns True when the handler is (already) installed.
    Does NOT itself enable tracing (the flag is insurance on long
    production runs and must not buy per-step fencing): the trace half
    of the dump has spans when ``--trace_jsonl`` is set or ``/trace``
    was scraped, and is an empty array otherwise.  Signals can only be
    installed from the main thread — a worker-thread entry point
    degrades gracefully."""
    global _installed
    from ..utils import FLAGS

    if not FLAGS.get("debug_dump_signal"):
        return _installed
    with _install_lock:
        if _installed:
            return True
        try:
            signal.signal(signal.SIGUSR2, _handler)
        except (ValueError, OSError, AttributeError):
            # not the main thread / platform without SIGUSR2
            from ..utils.logger import get_logger, warn_once

            warn_once("debug_dump_signal_unavailable",
                      "--debug_dump_signal: SIGUSR2 handler could not "
                      "be installed from this thread/platform",
                      logger=get_logger("observe"))
            return False
        _installed = True
    return True

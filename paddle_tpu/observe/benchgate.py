"""Perf-regression gate over the bench trajectory.

The five committed ``BENCH_r*.json`` artifacts were write-only history:
nothing compared a new run against them, so a silent 2x regression
would merge clean.  This module turns a bench run into a guarded
baseline:

- :func:`series_from_line` flattens one bench JSON line into named
  scalar **series** — the headline ``median`` (the attempts/spread
  band machinery from round 6 rides along as the tolerance input) plus
  the nested per-workload timings of the composite lanes
  (pipeline sync/prefetch ms, precision fp32/bf16 ms);
- :func:`make_baseline` renders a run into a committed baseline file:
  per series the value, the observed relative spread, a **direction**
  (``lower`` / ``higher`` is better, or ``abs`` for bounded ratios)
  and an explicit tolerance — self-describing, so the gate needs no
  out-of-band config and a human can read why a row trips;
- :func:`compare` judges a new run against the baseline band and
  :func:`render_table` prints the human diff.  ``bench.py --baseline
  FILE --check`` drives it (exit nonzero on regression,
  ``bench_regressions_total`` counter per tripped series);
  ``--write-baseline`` produces the artifact.

Stdlib-only (no jax): the gate must run in CI against replayed
artifacts (``bench.py --from_jsonl``) without a backend.
"""

from __future__ import annotations

import json
import time
from typing import Any, Dict, List, Optional, Sequence

SCHEMA = 1

#: Relative-tolerance floor for timing/throughput series.  CPU CI boxes
#: are noisy run to run (shared cores, thermal state — the round-4
#: ResNet bimodality was a 10% band on a DEDICATED chip), so the floor
#: is generous; a real regression (2x = +100%) clears it with margin.
REL_TOL_FLOOR = 0.5
#: Spread multiplier: a workload that already wobbles k% between
#: attempts gets a proportionally wider band.
SPREAD_FACTOR = 4.0
#: Absolute tolerance for bounded-ratio series (input_bound_ratio).
ABS_TOL = 0.05


def _direction(metric: str, unit: str = "") -> str:
    """``lower`` | ``higher`` | ``abs`` for a series name."""
    name = metric.lower()
    if "ratio" in name or "bound" in name:
        return "abs"
    for needle in ("ms_per_batch", "ms_per_call", "_ms", "seconds",
                   "overhead", "latency", "degradation"):
        if needle in name:
            return "lower"
    for needle in ("per_sec", "speedup", "samples", "tokens", "mfu",
                   "throughput"):
        if needle in name:
            return "higher"
    # unknown metrics: assume the headline follows its unit text
    u = unit.lower()
    if "ms/" in u or "seconds" in u or "us" in u:
        return "lower"
    return "higher"


def series_from_line(line: Dict[str, Any]) -> Dict[str, Dict[str, Any]]:
    """One bench JSON line → ``{series_key: {"value", "spread",
    "direction", "unit"}}``.  Error lines produce no series (the gate
    reports them separately)."""
    out: Dict[str, Dict[str, Any]] = {}
    metric = line.get("metric")
    if not metric or "error" in line:
        return out
    spread = float(line.get("spread", 0.0) or 0.0)
    value = line.get("median", line.get("value"))
    if value is not None:
        out[metric] = {
            "value": float(value), "spread": spread,
            "direction": _direction(metric, str(line.get("unit", ""))),
            "unit": line.get("unit", ""),
        }
    # composite lanes: nested per-workload timings are where a "2x on
    # one workload" regression actually lives (the headline of the
    # pipeline lane is a bounded ratio that would never see it).
    # Modes: pipeline sync/prefetch, precision fp32/bf16, attention
    # dense/legacy/block-skip + padded/packed + paged decode, serving
    # continuous/sequential, multichip fsdp/replicated, embedding
    # sparse (lookup kernel + sparse-exchange training, dense A/B),
    # rollout steady/swap (req/s + p99 with a hot-swap in the window).
    for row in line.get("rows", ()):
        tag = row.get("workload", "?")
        for mode in ("sync", "prefetch", "fp32", "bf16", "dense",
                     "legacy", "block_skip", "padded", "packed",
                     "decode", "continuous", "sequential",
                     "fsdp", "replicated", "sparse",
                     "steady", "swap"):
            sub = row.get(mode) or {}
            for key, unit, direction, suffix in (
                    ("ms_per_batch", "ms/batch", "lower", "_ms"),
                    ("ms_per_call", "ms/call", "lower", "_ms"),
                    # serving lane: sustained throughput gates
                    # higher-better, the p99 tail lower-better
                    ("req_per_sec", "req/s", "higher", "_req_per_sec"),
                    ("p99_ms", "ms", "lower", "_p99_ms"),
                    # multichip lane: scaling throughput gates
                    # higher-better; per-chip hbm fields are
                    # informational (not series keys)
                    ("samples_per_sec", "samples/s", "higher",
                     "_samples_per_sec"),
                    # sparse embedding lane: lookup throughput gates
                    # higher-better; exchanged_grad_bytes and call_ms
                    # are informational (not series keys)
                    ("lookups_per_sec", "lookups/s", "higher",
                     "_lookups_per_sec")):
                v = sub.get(key)
                if v is not None:
                    out[f"{metric}.{tag}.{mode}{suffix}"] = {
                        "value": float(v), "spread": spread,
                        "direction": direction, "unit": unit}
                    if suffix == "_ms":
                        break  # one _ms series per mode: a dict with
                        # both keys must not overwrite ms/batch
    return out


def _tolerance(direction: str, spread: float) -> float:
    if direction == "abs":
        return ABS_TOL
    return max(REL_TOL_FLOOR, SPREAD_FACTOR * spread)


def make_baseline(lines: Sequence[Dict[str, Any]],
                  meta: Optional[Dict[str, Any]] = None
                  ) -> Dict[str, Any]:
    """Render a bench run (its emitted JSON lines) into the committed
    baseline document.  The raw lines ride along under ``"lines"`` so
    the artifact can be replayed through the gate without re-running
    the workloads (``bench.py --from_jsonl``)."""
    series: Dict[str, Any] = {}
    for line in lines:
        for key, s in series_from_line(line).items():
            series[key] = {
                "value": s["value"],
                "spread": s["spread"],
                "direction": s["direction"],
                "tolerance": round(_tolerance(s["direction"],
                                              s["spread"]), 4),
                "unit": s["unit"],
            }
    return {
        "schema": SCHEMA,
        "created_unix": round(time.time(), 1),
        "meta": meta or {},
        "series": series,
        "lines": [dict(line) for line in lines],
    }


class GateResult:
    """Verdict of one comparison: per-series rows + the failing set."""

    def __init__(self) -> None:
        self.rows: List[Dict[str, Any]] = []
        self.regressions: List[Dict[str, Any]] = []
        self.errors: List[str] = []
        self.skipped: List[str] = []     # baseline series absent here

    @property
    def ok(self) -> bool:
        return not self.regressions and not self.errors


def compare(lines: Sequence[Dict[str, Any]],
            baseline: Dict[str, Any]) -> GateResult:
    """Judge a bench run against a baseline document.

    A series regresses when it is worse than the baseline value by more
    than the baseline's recorded tolerance (relative for timings and
    throughputs — direction-aware — absolute for bounded ratios).  A
    row that errored regresses unconditionally: a workload that stopped
    producing numbers is the worst kind of perf regression.  Baseline
    series with no counterpart in this run are *skipped* (a ``--only``
    subset run judges only what it ran).
    """
    res = GateResult()
    current: Dict[str, Dict[str, Any]] = {}
    for line in lines:
        if line.get("error"):
            res.errors.append(f"{line.get('metric', '?')}: "
                              f"{line['error']}")
            continue
        current.update(series_from_line(line))

    base_series = baseline.get("series", {})
    for key, base in sorted(base_series.items()):
        cur = current.get(key)
        if cur is None:
            res.skipped.append(key)
            continue
        direction = base.get("direction", "lower")
        tol = float(base.get("tolerance",
                             _tolerance(direction,
                                        float(base.get("spread", 0.0)))))
        bval, cval = float(base["value"]), float(cur["value"])
        if direction == "abs":
            delta = cval - bval
            worse_by = delta
            regressed = delta > tol
            ratio = None
        elif direction == "lower" and bval <= 0:
            # difference-style series (traced-minus-untraced overhead)
            # can record ~0/negative baselines where a ratio is
            # undefined or sign-flipped; judge the delta against the
            # larger magnitude so a real blow-up still trips
            scale = max(abs(bval), abs(cval), 1e-9)
            worse_by = (cval - bval) / scale
            regressed = worse_by > tol
            ratio = None
        else:
            ratio = (cval / bval) if direction == "lower" \
                else (bval / cval) if cval else float("inf")
            worse_by = ratio - 1.0
            regressed = worse_by > tol
        row = {"series": key, "baseline": bval, "current": cval,
               "direction": direction, "tolerance": tol,
               "worse_by": round(worse_by, 4),
               "ratio": round(ratio, 4) if ratio is not None else None,
               "regressed": regressed}
        res.rows.append(row)
        if regressed:
            res.regressions.append(row)
    # new series this run that the baseline has never seen: informative
    for key in sorted(set(current) - set(base_series)):
        res.rows.append({"series": key, "baseline": None,
                         "current": current[key]["value"],
                         "direction": current[key]["direction"],
                         "tolerance": None, "worse_by": None,
                         "ratio": None, "regressed": False})
    return res


def render_table(res: GateResult, baseline_path: str = "") -> str:
    """The human diff table ``--check`` prints (to stderr — stdout
    stays the machine-parsed JSONL stream)."""
    lines = [f"perf gate vs {baseline_path or 'baseline'}:"]
    lines.append(f"{'series':<58} {'base':>12} {'current':>12} "
                 f"{'worse-by':>9} {'tol':>6}  verdict")
    for r in res.rows:
        base = "—" if r["baseline"] is None else f"{r['baseline']:.4g}"
        wb = "—" if r["worse_by"] is None else f"{r['worse_by']:+.1%}" \
            if r["direction"] != "abs" else f"{r['worse_by']:+.4f}"
        tol = "—" if r["tolerance"] is None else (
            f"{r['tolerance']:.0%}" if r["direction"] != "abs"
            else f"{r['tolerance']:.3f}")
        verdict = "REGRESSED" if r["regressed"] else (
            "new" if r["baseline"] is None else "ok")
        lines.append(f"{r['series']:<58} {base:>12} "
                     f"{r['current']:>12.4g} {wb:>9} {tol:>6}  "
                     f"{verdict}")
    for key in res.skipped:
        lines.append(f"{key:<58} {'(not run this invocation)':>45}")
    for err in res.errors:
        lines.append(f"ERROR row: {err}")
    n = len(res.regressions)
    lines.append(
        f"perf gate: {'PASS' if res.ok else 'FAIL'} — "
        f"{n} regression(s), {len(res.errors)} error row(s), "
        f"{len(res.rows)} series judged, {len(res.skipped)} skipped")
    return "\n".join(lines)


def load_baseline(path: str) -> Dict[str, Any]:
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    if doc.get("schema") != SCHEMA:
        raise ValueError(
            f"baseline {path!r}: schema {doc.get('schema')!r} != "
            f"{SCHEMA} (regenerate with bench.py --write-baseline)")
    return doc


def write_baseline(path: str, lines: Sequence[Dict[str, Any]],
                   meta: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    doc = make_baseline(lines, meta)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    return doc

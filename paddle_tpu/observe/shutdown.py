"""Graceful-shutdown telemetry flush: SIGTERM → final flush → chain.

The orchestrator-kill path the chaos suite exercises is
SIGTERM-then-SIGKILL: a preempted/descheduled process gets SIGTERM and
a grace window.  Before this module the final telemetry flush relied on
``atexit`` — which only runs if the default SIGTERM disposition kills
the process *through* the interpreter's normal exit (it does not: the
default disposition terminates immediately, atexit never runs), so the
last metrics interval, the closing ``]`` of the ``--trace_jsonl``
array, and the fleet's final frame were all lost.

:func:`install_from_flags` installs a SIGTERM hook (``--sigterm_flush``,
default on, only when a telemetry surface is actually configured —
otherwise the process's signal dispositions are left untouched) that:

1. flushes the reporter's final snapshot line and pushes a final
   **going-down** fleet frame (so the aggregator's rollup records a
   clean ``down``, not a staleness ``missing``),
2. finalizes the ``--trace_jsonl`` Chrome trace array (writes ``]``),
3. then **chains**: a previously-installed Python handler is called;
   otherwise the default disposition is restored and the signal
   re-raised, so the process still dies *by SIGTERM* (exit status and
   orchestrator semantics preserved).

Deadlock discipline (the SIGUSR2 lesson, ``observe/dump.py``): the
handler body runs on the MAIN thread, possibly inside one of the very
locks the flush needs (registry lock in ``counter.inc``, ring lock in
``_Span.__exit__``).  So the handler only *starts* a short-lived
``ptpu-sigterm-flush`` thread and returns; that thread performs the
flush (blocking until the main thread releases whatever it holds) and
then re-raises SIGTERM, whose second delivery — again on the main
thread, as CPython requires for ``signal.signal`` — performs the
chaining.  Repeat SIGTERMs during the flush are coalesced.
"""

from __future__ import annotations

import os
import signal
import threading
from typing import Optional

from ..analysis.lockorder import named_lock

#: Flush-thread name (conftest thread-leak guard exemption pattern —
#: short-lived by construction, but named for auditability).
FLUSH_THREAD_NAME = "ptpu-sigterm-flush"

_lock = named_lock("observe.shutdown")
_prev_handler = None       # disposition we chain to
_installed = False
# 0 = armed, 1 = flush in flight, 2 = flushed (next delivery chains)
_phase = 0


def installed() -> bool:
    return _installed


def flush_for_shutdown() -> None:
    """The actual goodbye: final reporter flush + going-down fleet
    frame (``report.stop_global``), then finalize the trace sink
    (writes the closing ``]``).  Best-effort on every leg — a failing
    sink must not block the termination path."""
    from ..utils.logger import get_logger
    from . import trace
    from .report import stop_global as stop_reporter

    log = get_logger("observe")
    try:
        stop_reporter()          # final JSONL line + going-down frame
    except Exception as e:       # noqa: BLE001 — dying anyway; the
        log.warning("SIGTERM flush: reporter stop failed: %s: %s",
                    type(e).__name__, e)    # flush is best-effort
    try:
        trace.disable()          # join writer, close the JSON array
    except Exception as e:       # noqa: BLE001
        log.warning("SIGTERM flush: trace finalize failed: %s: %s",
                    type(e).__name__, e)


def _flush_then_reraise() -> None:
    global _phase
    from ..utils.logger import get_logger

    flush_for_shutdown()
    _phase = 2
    get_logger("observe").info(
        "SIGTERM: telemetry flushed; re-raising for the previous "
        "disposition")
    os.kill(os.getpid(), signal.SIGTERM)


def _chain(signum, frame) -> None:
    prev = _prev_handler
    if callable(prev):
        prev(signum, frame)
    elif prev is signal.SIG_IGN:
        return
    else:   # SIG_DFL (or unknowable): die by SIGTERM, exit status honest
        signal.signal(signal.SIGTERM, signal.SIG_DFL)
        os.kill(os.getpid(), signal.SIGTERM)


def _handler(signum, frame) -> None:
    global _phase
    if _phase == 2:
        _chain(signum, frame)
        return
    if _phase == 1:
        return                   # flush in flight; coalesce repeats
    _phase = 1
    threading.Thread(target=_flush_then_reraise,
                     name=FLUSH_THREAD_NAME, daemon=True).start()


def install_from_flags() -> bool:
    """Install the chaining SIGTERM hook iff ``--sigterm_flush`` (on by
    default) AND some telemetry surface is configured in this process
    (a reporter/pusher, a trace sink, or a hosted fleet aggregator) —
    a process with nothing to flush keeps its signal dispositions
    untouched.  Idempotent; main-thread only (a worker-thread entry
    point degrades gracefully, same contract as ``dump.py``)."""
    global _installed, _prev_handler, _phase
    from ..utils import FLAGS
    from . import fleet, report, trace

    if not FLAGS.get("sigterm_flush"):
        return _installed
    if report._global is None and not trace.enabled() \
            and not fleet.hosting():
        return _installed
    with _lock:
        if _installed:
            return True
        try:
            prev = signal.getsignal(signal.SIGTERM)
            signal.signal(signal.SIGTERM, _handler)
        except (ValueError, OSError, AttributeError):
            from ..utils.logger import get_logger, warn_once

            warn_once("sigterm_flush_unavailable",
                      "--sigterm_flush: SIGTERM hook could not be "
                      "installed from this thread/platform; the final "
                      "telemetry interval relies on atexit only",
                      logger=get_logger("observe"))
            return False
        _prev_handler = prev
        _phase = 0
        _installed = True
    return True


def uninstall() -> None:
    """Restore the pre-install SIGTERM disposition (tests; main-thread
    only).  No-op when never installed."""
    global _installed, _prev_handler, _phase
    with _lock:
        if not _installed:
            return
        try:
            signal.signal(signal.SIGTERM,
                          _prev_handler if _prev_handler is not None
                          else signal.SIG_DFL)
        except (ValueError, OSError) as e:
            # non-main-thread teardown: leave the hook in place
            from ..utils.logger import get_logger

            get_logger("observe").debug(
                "sigterm_flush uninstall skipped: %s", e)
        _prev_handler = None
        _phase = 0
        _installed = False

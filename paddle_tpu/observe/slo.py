"""Serving SLO engine: declarative windowed objectives + multi-window
burn rate.

An **objective** is one line of the ``--slo`` flag grammar::

    serve_ttft_seconds:p99<0.5:60s

read "the p99 of ``serve_ttft_seconds`` over the last 60 seconds stays
under 0.5" — ``metric:stat op threshold:window``, where ``stat`` is
``pNN`` (a quantile of the histogram's windowed reservoir,
:meth:`paddle_tpu.observe.metrics.Histogram.window_quantile`) or
``rate`` (events/second, :meth:`~paddle_tpu.observe.metrics.Histogram.
window_rate` — the error-rate form when failures are observed as
events), ``op`` is ``<`` or ``>``, and ``window`` takes an ``s`` or
``m`` suffix.  Several objectives join with ``,`` or ``;``.

Each objective is evaluated continuously on the reporter thread
(:mod:`paddle_tpu.observe.report`) and yields ok/breach plus a
**multi-window burn rate** — the PR-11 ``/healthz`` lesson
(standing-vs-transient) applied to SLOs:

- the **fast** burn rate reads the objective's own window: for a
  quantile objective it is the violating fraction of the windowed
  samples over the allowed fraction (``1 - q`` — the error budget), so
  burn 1.0 means the budget is being spent exactly as fast as allowed;
  for a rate objective it is the ratio to the threshold;
- the **slow** burn rate reads a :data:`SLOW_FACTOR`× confirmation
  window (clamped to the reservoir's ring span).

A **breach** requires BOTH burns ≥ 1: a single slow scrape trips the
fast window but not the slow one (transient — status stays ok, the
fast burn is still visible on the gauge); recovery clears the fast
window first (status returns to ok while the slow window drains — the
standing-clear) so a recovered server never advertises a stale breach.

Surfaces: ``slo_status{objective}`` (1 ok / 0 breach) and
``slo_burn_rate{objective}`` gauges on every evaluation, the ``/slo``
and ``/healthz`` endpoints (:mod:`paddle_tpu.observe.http`), the fleet
frame's optional ``slo`` field with the ``/fleet/healthz`` rollup
marking a breaching process degraded (:mod:`paddle_tpu.observe.fleet`),
and the ``fleet --watch`` console's SLO column.

Contract notes: stdlib-only (no jax), **telemetry never kills** — an
objective over a missing metric or an empty window is ``no_data``
(ok, burn 0), and an evaluator fault warns once and degrades to
``no_data`` instead of raising into the reporter thread.  With
``--slo`` unset no engine exists, nothing here is imported by the hot
path, and every surface above is byte-identical to the engine-less
build.
"""

from __future__ import annotations

import re
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

from ..analysis.lockorder import named_lock
from .metrics import REGISTRY, Histogram, MetricsRegistry

#: Slow confirmation window = this factor × the objective's window
#: (clamped to the metric's ring span at read time).
SLOW_FACTOR = 5.0

_OK = "ok"
_BREACH = "breach"
_NO_DATA = "no_data"

_OBJECTIVE_RE = re.compile(
    r"^(?P<metric>[A-Za-z_][A-Za-z0-9_]*)"
    r":(?P<stat>p\d{1,2}(?:\.\d+)?|rate)"
    r"(?P<op>[<>])(?P<threshold>[0-9]*\.?[0-9]+(?:[eE][+-]?[0-9]+)?)"
    r":(?P<window>[0-9]*\.?[0-9]+)(?P<unit>[sm])$")


class SloParseError(ValueError):
    """An ``--slo`` objective that does not parse."""


class Objective:
    """One parsed objective.  ``text`` is the canonical spelling — it
    labels the gauges, the fleet frames, and every report."""

    __slots__ = ("text", "metric", "stat", "q", "op", "threshold",
                 "window_s")

    def __init__(self, metric: str, stat: str, op: str,
                 threshold: float, window_s: float):
        self.metric = metric
        self.stat = stat
        self.q = None if stat == "rate" \
            else min(float(stat[1:]) / 100.0, 1.0)
        self.op = op
        self.threshold = float(threshold)
        self.window_s = float(window_s)
        win = f"{window_s:g}s"
        self.text = f"{metric}:{stat}{op}{threshold:g}:{win}"

    def __repr__(self) -> str:
        return f"Objective({self.text!r})"

    def violates(self, value: float) -> bool:
        """True when ``value`` is on the wrong side of the threshold."""
        return value >= self.threshold if self.op == "<" \
            else value <= self.threshold


def parse_objective(text: str) -> Objective:
    """``"serve_ttft_seconds:p99<0.5:60s"`` → :class:`Objective`."""
    m = _OBJECTIVE_RE.match(text.strip())
    if m is None:
        raise SloParseError(
            f"--slo objective {text!r} does not parse; expected "
            "metric:statOPthreshold:window, e.g. "
            "'serve_ttft_seconds:p99<0.5:60s' (stat pNN or rate, OP "
            "< or >, window Ns or Nm)")
    window_s = float(m.group("window"))
    if m.group("unit") == "m":
        window_s *= 60.0
    if window_s <= 0:
        raise SloParseError(f"--slo objective {text!r}: window must "
                            "be > 0")
    stat = m.group("stat")
    if stat != "rate" and not 0.0 < float(stat[1:]) <= 100.0:
        raise SloParseError(f"--slo objective {text!r}: quantile must "
                            "be in (0, 100]")
    return Objective(m.group("metric"), stat, m.group("op"),
                     float(m.group("threshold")), window_s)


def parse_objectives(spec: str) -> List[Objective]:
    """The full ``--slo`` value: objectives joined with ``,`` or ``;``
    (empty → no objectives)."""
    out = []
    for part in re.split(r"[,;]", spec or ""):
        if part.strip():
            out.append(parse_objective(part))
    return out


class SloEngine:
    """Evaluates a fixed objective list against a metrics registry.

    ``clock`` is only used to stamp evaluation time; the window math
    lives in each histogram's own (independently injectable) clock.
    Thread-safe: the reporter thread evaluates while ``/slo`` and
    ``/healthz`` handler threads read the last verdicts."""

    def __init__(self, objectives: Sequence[Union[Objective, str]],
                 registry: Optional[MetricsRegistry] = None,
                 clock: Callable[[], float] = time.monotonic,
                 slow_factor: float = SLOW_FACTOR):
        self.objectives = [o if isinstance(o, Objective)
                           else parse_objective(o) for o in objectives]
        self.registry = REGISTRY if registry is None else registry
        self.slow_factor = float(slow_factor)
        self._clock = clock
        self._lock = named_lock("observe.slo")
        self._last: List[Dict[str, Any]] = []

    # --------------------------------------------------------- verdicts
    def _burn(self, hist: Histogram, obj: Objective,
              window_s: float) -> Optional[float]:
        """Error-budget burn rate over one window (None = no data)."""
        if obj.stat == "rate":
            rate = hist.window_rate(window_s)
            if obj.op == "<":
                if obj.threshold <= 0:
                    return 0.0 if rate == 0.0 else float("inf")
                return rate / obj.threshold
            # op ">": the objective wants the rate ABOVE the floor;
            # the burn inverts so >= 1 still means "breaching"
            return obj.threshold / rate if rate > 0 else float("inf")
        samples = hist.window_samples(window_s)
        if not samples:
            return None
        bad = sum(1 for v in samples if obj.violates(v)) / len(samples)
        budget = max(1.0 - (obj.q or 1.0), 1e-9)
        return bad / budget

    def _eval_one(self, obj: Objective) -> Dict[str, Any]:
        verdict: Dict[str, Any] = {
            "objective": obj.text, "metric": obj.metric,
            "window_s": obj.window_s, "status": _NO_DATA,
            "value": None, "burn_fast": 0.0, "burn_slow": 0.0,
        }
        m = self.registry.find(obj.metric)
        if not isinstance(m, Histogram):
            return verdict
        slow_s = min(obj.window_s * self.slow_factor, m.window_span_s)
        verdict["slow_window_s"] = slow_s
        if obj.stat == "rate":
            verdict["value"] = m.window_rate(obj.window_s)
            if m.window_count(slow_s) == 0:
                return verdict
        else:
            verdict["value"] = m.window_quantile(obj.q, obj.window_s)
        fast = self._burn(m, obj, obj.window_s)
        slow = self._burn(m, obj, slow_s)
        verdict["burn_fast"] = round(fast, 4) if fast is not None else 0.0
        verdict["burn_slow"] = round(slow, 4) if slow is not None else 0.0
        if fast is None and slow is None:
            return verdict
        # standing breach needs BOTH windows burning (>= 1): the fast
        # window alerts quickly, the slow window confirms it is not a
        # transient; recovery clears fast first, so status goes back
        # to ok while the slow window drains (the standing-clear)
        breach = (fast or 0.0) >= 1.0 and (slow or 0.0) >= 1.0
        verdict["status"] = _BREACH if breach else _OK
        return verdict

    def evaluate(self) -> List[Dict[str, Any]]:
        """One evaluation pass over every objective: computes verdicts,
        publishes the ``slo_status`` / ``slo_burn_rate`` gauges, and
        retains the result for :meth:`frame_digest`.  Never raises
        (telemetry never kills): an objective whose read faults warns
        once and reports ``no_data``."""
        t0 = time.perf_counter()
        results: List[Dict[str, Any]] = []
        for obj in self.objectives:
            try:
                v = self._eval_one(obj)
            except Exception as e:  # noqa: BLE001 — degrade, never kill
                from ..utils.logger import get_logger, warn_once

                warn_once(
                    f"slo_eval_failed:{obj.text}",
                    "SLO objective %r evaluation failed (%s: %s); "
                    "reporting no_data (reported once)", obj.text,
                    type(e).__name__, e, logger=get_logger("observe"))
                v = {"objective": obj.text, "metric": obj.metric,
                     "window_s": obj.window_s, "status": _NO_DATA,
                     "value": None, "burn_fast": 0.0, "burn_slow": 0.0}
            results.append(v)
            self.registry.gauge(
                "slo_status",
                "1 while the objective holds (or has no data), 0 on "
                "a standing breach (fast AND slow burn >= 1)").set(
                0.0 if v["status"] == _BREACH else 1.0,
                objective=obj.text)
            self.registry.gauge(
                "slo_burn_rate",
                "fast-window error-budget burn rate per objective "
                "(1.0 = spending the budget exactly as fast as the "
                "objective allows)").set(
                v["burn_fast"], objective=obj.text)
        with self._lock:
            self._last = results
        self.registry.histogram(
            "slo_eval_seconds",
            "wall time of one SLO evaluation pass over every "
            "objective (runs on the reporter interval, never "
            "the request path)").observe(time.perf_counter() - t0)
        return results

    # ---------------------------------------------------------- readers
    def last(self) -> List[Dict[str, Any]]:
        """Verdicts from the most recent :meth:`evaluate` (empty before
        the first pass)."""
        with self._lock:
            return [dict(v) for v in self._last]

    def status_doc(self) -> Dict[str, Any]:
        """The ``/slo`` body: a FRESH evaluation (scrape-time truth,
        matching ``/metrics`` semantics)."""
        results = self.evaluate()
        breached = [v["objective"] for v in results
                    if v["status"] == _BREACH]
        return {"status": _BREACH if breached else _OK,
                "breached": breached, "objectives": results}

    def frame_digest(self) -> Dict[str, Any]:
        """The compact form a fleet frame carries (last verdicts, no
        re-evaluation — built on the reporter thread right after
        :meth:`evaluate` ran)."""
        results = self.last()
        breached = [v["objective"] for v in results
                    if v["status"] == _BREACH]
        return {
            "status": _BREACH if breached else _OK,
            "breached": breached,
            "objectives": {
                v["objective"]: {"status": v["status"],
                                 "burn_fast": v["burn_fast"],
                                 "burn_slow": v["burn_slow"],
                                 "value": v["value"]}
                for v in results},
        }


# ---------------------------------------------------------------- global
_engine: Optional[SloEngine] = None
_engine_lock = named_lock("observe.slo.global")


def configure_from_flags() -> Optional[SloEngine]:
    """Build the process-wide engine from ``--slo`` (idempotent; None
    with the flag unset — no engine, no gauges, every surface
    byte-identical to the engine-less build).  A malformed objective
    warns once and leaves the engine OFF: telemetry never kills the
    run it observes."""
    global _engine
    from ..utils import FLAGS

    spec = str(FLAGS.get("slo") or "")
    if not spec.strip():
        return _engine
    with _engine_lock:
        if _engine is None:
            try:
                objectives = parse_objectives(spec)
            except SloParseError as e:
                from ..utils.logger import get_logger, warn_once

                warn_once(
                    f"slo_flag_invalid:{spec}",
                    "--slo %r is not usable (%s); the SLO engine is "
                    "OFF for this run", spec, e,
                    logger=get_logger("observe"))
                return None
            if objectives:
                _engine = SloEngine(objectives)
    return _engine


def set_engine(engine: Optional[SloEngine]) -> None:
    """Install a programmatic engine (tests, notebooks)."""
    global _engine
    with _engine_lock:
        _engine = engine


def active_engine() -> Optional[SloEngine]:
    """The process-wide engine, or None when ``--slo`` never
    configured one — every surface probes this through ``sys.modules``
    so an engine-less process pays nothing."""
    return _engine


def reset() -> None:
    """Drop the process-wide engine (tests)."""
    set_engine(None)

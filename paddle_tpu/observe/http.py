"""Live observability endpoint: ``/metrics`` + ``/healthz`` +
``/trace`` + ``/roofline`` + ``/health``.

A stdlib ``http.server`` thread (name ``ptpu-metrics-http``; the
conftest thread-leak guard keys on it) behind ``--metrics_port`` makes
a live run scrapeable without the JSONL sinks:

- ``GET /metrics``  — Prometheus exposition text: the typed registry +
  the ``StatSet`` timer table (:func:`paddle_tpu.observe.prometheus_dump`);
- ``GET /healthz``  — liveness JSON (``{"status": "ok", ...}`` with pid
  and uptime), for load-balancer / k8s probes; when the training-health
  observatory is live its digest rides along (``status`` degrades to
  ``"degraded"`` on standing alerts — degraded-but-ALIVE: the code
  stays 200, a health alert must never convince an orchestrator to
  kill a recoverable run);
- ``GET /trace``    — the flight recorder as a Chrome trace-event JSON
  array, loadable directly in Perfetto — "what were the last N spans of
  this live run" without attaching a debugger;
- ``GET /roofline`` — the most recent per-region roofline/cost report
  of this process (``observe/costmodel.py``), JSON;
- ``GET /health``   — the most recent drained training-health report
  (``observe/health.py``): per-layer grad/param norms, update ratios,
  non-finite localization, recent alerts — detail beyond ``/healthz``;
- ``GET /slo``      — a FRESH evaluation of every ``--slo`` objective
  (``observe/slo.py``): ok/breach + fast/slow burn rates per
  objective (404 when no engine is configured).  A standing breach
  also rides ``/healthz`` (status degrades to ``"degraded"`` — code
  stays 200, same degraded-but-ALIVE stance as health alerts).

``/roofline``, ``/health`` and ``/slo`` follow the ``/trace`` lazy
discipline:
they read module state that only exists once the producing subsystem
ran (imports resolved at request time through ``sys.modules``), so a
``/metrics``-only run never imports — let alone pays for — either.

Zero-dependency rule: nothing here imports jax.  Starting the server
does NOT enable tracing: the first ``/trace`` request flips on
ring-only recording (``trace.ensure_ring``) — an opt-in at scrape
time, so a run that only serves ``/metrics`` never pays the tracing
fence.  With neither ``--metrics_port`` nor ``--trace_jsonl``
configured no thread starts and the hot-path instrumentation stays
no-op.

The handler never raises into the serving loop (telemetry never kills
— a scrape that fails returns 500 with the error text), binds loopback
by default (metrics are not an external API; ``--metrics_bind`` is an
explicit, loudly-warned opt-in for same-host/container scraping on a
trusted network — see :func:`resolve_bind_host`), and every request
runs on a short-lived daemon thread (``ThreadingHTTPServer``), so a
slow scraper cannot wedge the trainer.
"""

from __future__ import annotations

import json
import os
import socket
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from ..analysis.lockorder import named_lock
from . import trace
from .report import prometheus_dump

#: Serve-loop thread name (conftest thread-leak guard entry).
SERVER_THREAD_NAME = "ptpu-metrics-http"

#: Addresses that stay within the host (no warning needed).
_LOOPBACK_HOSTS = ("", "127.0.0.1", "localhost", "::1")


class _ThreadingHTTPServerV6(ThreadingHTTPServer):
    address_family = socket.AF_INET6


def make_threading_server(host: str, port: int,
                          handler) -> ThreadingHTTPServer:
    """A ``ThreadingHTTPServer`` bound to ``host:port``, picking the
    address family from the host spelling — ``ThreadingHTTPServer`` is
    AF_INET by default, so an IPv6 host (``::1``, ``::``) would always
    fail to bind and silently disable the endpoint it serves."""
    cls = _ThreadingHTTPServerV6 if ":" in host else ThreadingHTTPServer
    return cls((host, port), handler)


def resolve_bind_host(flag_name: str) -> str:
    """Resolve a bind-address flag (``metrics_bind`` /
    ``fleet_bind``): empty keeps the loopback default; anything else
    is an EXPLICIT opt-in (cross-container scraping on a trusted
    network) and logs a loud structured warning — these endpoints are
    diagnostics, not an external API (no auth, no TLS, free trace and
    metric disclosure to anyone who can connect)."""
    from ..utils import FLAGS
    from ..utils.logger import get_logger, warn_once

    host = str(FLAGS.get(flag_name)).strip()
    if host in _LOOPBACK_HOSTS:
        return host or "127.0.0.1"
    warn_once(
        f"nonloopback_bind:{flag_name}:{host}",
        "--%s=%s binds a telemetry endpoint BEYOND loopback: this is "
        "a diagnostics surface, NOT an external API — no auth, no "
        "TLS; metrics, traces and health detail are readable by "
        "anyone who can reach the port.  Keep it inside a trusted "
        "network boundary (pod/network-policy), never on a public "
        "interface", flag_name, host, logger=get_logger("observe"))
    return host


class _Handler(BaseHTTPRequestHandler):
    server_version = "paddle-tpu-observe"

    def _send(self, code: int, body: str, ctype: str) -> None:
        data = body.encode()
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self) -> None:  # noqa: N802 — BaseHTTPRequestHandler API
        path = self.path.split("?", 1)[0].rstrip("/") or "/"
        try:
            if path == "/metrics":
                self._send(200, prometheus_dump(),
                           "text/plain; version=0.0.4")
            elif path == "/healthz":
                payload = {
                    "status": "ok", "pid": os.getpid(),
                    "uptime_s": round(
                        time.monotonic() - self.server.t0, 3),
                    "trace_enabled": trace.enabled(),
                    "trace_spans_dropped": trace.dropped_count(),
                }
                # the sys.modules probe keeps the legacy-probe path
                # byte-identical when the health observatory never ran
                # this process (nothing imported, nothing computed)
                hmod = sys.modules.get("paddle_tpu.observe.health")
                if hmod is not None:
                    payload["health"] = hmod.status_summary()
                    # degraded-but-ALIVE: detail degrades, the HTTP
                    # code stays 200 — never invite a kill
                    payload["status"] = payload["health"]["status"]
                # same discipline for the SLO engine: --slo unset →
                # module never imported → byte-identical body
                smod = sys.modules.get("paddle_tpu.observe.slo")
                eng = smod.active_engine() if smod is not None else None
                if eng is not None:
                    digest = eng.frame_digest()
                    payload["slo"] = digest
                    if digest["status"] == "breach" \
                            and payload["status"] == "ok":
                        payload["status"] = "degraded"
                self._send(200, json.dumps(payload), "application/json")
            elif path == "/slo":
                smod = sys.modules.get("paddle_tpu.observe.slo")
                eng = smod.active_engine() if smod is not None else None
                if eng is None:
                    self._send(404, json.dumps(
                        {"error": "no SLO engine configured (set "
                                  "--slo 'metric:p99<0.5:60s')"}),
                        "application/json")
                else:
                    # FRESH evaluation — scrape-time truth, matching
                    # /metrics semantics (the reporter-interval cadence
                    # still drives the gauges and fleet frames)
                    self._send(200, json.dumps(eng.status_doc()),
                               "application/json")
            elif path == "/trace":
                # lazy opt-in: the FIRST /trace request enables
                # ring-only recording — fence-free (trace.fences_steps
                # stays False), so a probe of this endpoint never
                # converts the trainer's async dispatch into per-step
                # device syncs; a run only ever scraped for /metrics
                # never records at all
                trace.ensure_ring()
                self._send(200, trace.flight_recorder_json(),
                           "application/json")
            elif path == "/roofline":
                cmod = sys.modules.get("paddle_tpu.observe.costmodel")
                report = cmod.latest_report() if cmod is not None \
                    else None
                if report is None:
                    self._send(404, json.dumps(
                        {"error": "no roofline report yet (run a "
                                  "--roofline_dump pass or a bench "
                                  "lane first)"}), "application/json")
                else:
                    self._send(200, json.dumps(report),
                               "application/json")
            elif path == "/health":
                hmod = sys.modules.get("paddle_tpu.observe.health")
                report = hmod.latest_report() if hmod is not None \
                    else None
                if report is None:
                    self._send(404, json.dumps(
                        {"error": "no training-health report yet "
                                  "(enable --health_interval N)"}),
                        "application/json")
                else:
                    self._send(200, json.dumps(report),
                               "application/json")
            else:
                self._send(404, json.dumps(
                    {"error": "unknown path",
                     "paths": ["/metrics", "/healthz", "/trace",
                               "/roofline", "/health", "/slo"]}),
                    "application/json")
        except BrokenPipeError:      # scraper hung up mid-response
            pass
        except Exception as e:       # noqa: BLE001 — never kill serving
            try:
                self._send(500, f"observability handler error: {e}\n",
                           "text/plain")
            except OSError:
                pass

    def log_message(self, fmt: str, *args) -> None:
        from ..utils.logger import get_logger

        get_logger("observe.http").debug("http %s", fmt % args)


class ObservabilityServer:
    """The ``/metrics`` + ``/healthz`` + ``/trace`` + ``/roofline`` +
    ``/health`` server thread."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1"):
        self._httpd = make_threading_server(host, port, _Handler)
        self._httpd.daemon_threads = True
        self._httpd.t0 = time.monotonic()
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "ObservabilityServer":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._httpd.serve_forever,
                kwargs={"poll_interval": 0.1},
                name=SERVER_THREAD_NAME, daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        t, self._thread = self._thread, None
        if t is not None:
            self._httpd.shutdown()
            t.join(timeout=5.0)
        self._httpd.server_close()

    def __enter__(self) -> "ObservabilityServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


_global: Optional[ObservabilityServer] = None
_global_lock = named_lock("observe.http.global")


def start_from_flags() -> Optional[ObservabilityServer]:
    """Start the process-wide endpoint iff ``--metrics_port`` > 0
    (port 0 disables; use :class:`ObservabilityServer` directly for an
    ephemeral-port server in tests).  Idempotent.  A port that cannot
    be bound warns once and leaves the process running — telemetry
    never kills the run it observes."""
    global _global
    from ..utils import FLAGS
    from ..utils.logger import get_logger, warn_once

    port = int(FLAGS.get("metrics_port"))
    if port <= 0:
        return _global
    with _global_lock:
        if _global is None:
            host = resolve_bind_host("metrics_bind")
            try:
                _global = ObservabilityServer(port, host=host).start()
            except OSError as e:
                warn_once(
                    f"metrics_port_bind_failed:{port}",
                    "--metrics_port %d could not be bound (%s); the "
                    "observability endpoint is OFF for this run",
                    port, e, logger=get_logger("observe"))
                return None
            get_logger("observe").info(
                "observability endpoint on http://%s:%d "
                "(/metrics /healthz /trace /roofline /health /slo)",
                host, _global.port)
    return _global


def serving() -> bool:
    """True iff the process-wide observability endpoint is live —
    samplers that only matter when someone can scrape them (the
    trainer's pass-boundary HBM gauges) key on this together with
    ``observe.active()``."""
    return _global is not None


def stop_global() -> None:
    global _global
    with _global_lock:
        srv, _global = _global, None
    if srv is not None:
        srv.stop()

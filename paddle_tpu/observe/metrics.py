"""Typed process-wide metrics registry: Counter / Gauge / Histogram.

The reference engine's observability spine is the ``StatSet`` timer table
(``paddle/utils/Stat.h:63-242``) — wall timers only.  This module adds
the other half the subsystems built since need: monotonic event counts
(dispatch tiers, reconnects, quarantines), point-in-time gauges
(input-bound ratio, fused-pair census), and fixed-bucket latency
histograms (step/save/infer time), all exportable through one path
(:mod:`paddle_tpu.observe.report`) together with the timer table.

Design constraints, in order:

- **zero dependencies** — stdlib only, importable from the serving
  loader and the conftest without dragging in jax;
- **near-zero overhead when no sink is attached** — an increment is one
  dict lookup + a lock + a float add (~1 µs); anything that would fence
  the device or serialize the dispatch pipeline lives with the callers,
  gated on :func:`paddle_tpu.observe.report.active`;
- **thread-safe** — every metric guards its label table with its own
  lock (reader threads, the flush thread, and trainer threads race).

Labels are free-form keyword arguments; each distinct label set is an
independent sample series, Prometheus-style::

    counter("rnn_dispatch_total").inc(kind="lstm", path="fused")
"""

from __future__ import annotations

import collections
import contextlib
import math
import random
import threading
import time
from typing import Any, Callable, Dict, Iterator, List, Optional, \
    Sequence, Tuple

from ..analysis.lockorder import named_lock

LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Dict[str, Any]) -> LabelKey:
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


def format_labels(key: LabelKey) -> str:
    """``((k, v), ...)`` → ``{k="v",...}`` (empty string for no labels)."""
    if not key:
        return ""
    return "{" + ",".join(f'{k}="{v}"' for k, v in key) + "}"


class _Metric:
    kind = "abstract"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = named_lock("observe.metric")

    def describe(self) -> Dict[str, Any]:
        return {"name": self.name, "type": self.kind, "help": self.help,
                "samples": self.samples()}

    def samples(self) -> List[Dict[str, Any]]:  # pragma: no cover
        raise NotImplementedError


class Counter(_Metric):
    """Monotonically increasing count; ``inc`` of a negative amount is a
    programming error and raises."""

    kind = "counter"

    def __init__(self, name: str, help: str = ""):
        super().__init__(name, help)
        self._values: Dict[LabelKey, float] = {}

    def inc(self, amount: float = 1.0, **labels) -> None:
        amount = float(amount)   # numpy scalars would poison json.dumps
        if amount < 0:
            raise ValueError(
                f"counter {self.name!r}: negative increment {amount}")
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(_label_key(labels), 0.0)

    def total(self) -> float:
        """Sum over every label series."""
        with self._lock:
            return sum(self._values.values())

    def samples(self) -> List[Dict[str, Any]]:
        with self._lock:
            items = sorted(self._values.items())
        return [{"labels": dict(k), "value": v} for k, v in items]


class Gauge(_Metric):
    """Point-in-time value; settable, incrementable, decrementable."""

    kind = "gauge"

    def __init__(self, name: str, help: str = ""):
        super().__init__(name, help)
        self._values: Dict[LabelKey, float] = {}

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._values[_label_key(labels)] = float(value)

    def inc(self, amount: float = 1.0, **labels) -> None:
        amount = float(amount)   # numpy scalars would poison json.dumps
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels) -> None:
        self.inc(-amount, **labels)

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(_label_key(labels), 0.0)

    def samples(self) -> List[Dict[str, Any]]:
        with self._lock:
            items = sorted(self._values.items())
        return [{"labels": dict(k), "value": v} for k, v in items]


# latency buckets in seconds: 0.5 ms … 60 s, the span from a fused-kernel
# train step to a multi-GB checkpoint save
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0)


#: Default bound on raw samples a histogram series retains for the
#: exact-quantile reservoir — the retention cap that keeps a
#: million-observation training run at constant memory per series.
DEFAULT_SAMPLE_CAP = 2048

#: Windowed-reservoir geometry.  Each histogram series additionally
#: keeps a time-bucketed ring of raw samples: ``WINDOW_BUCKETS``
#: buckets of ``WINDOW_BUCKET_S`` seconds each (the ring spans
#: bucket_s × buckets seconds — 360 s at the defaults, wide enough for
#: a 60 s fast window AND its slow confirmation window,
#: :mod:`paddle_tpu.observe.slo`), at most ``WINDOW_SAMPLE_CAP`` raw
#: samples per bucket (Algorithm R within the bucket).  Memory per
#: series is therefore bounded by buckets × cap floats no matter how
#: long the process observes.
WINDOW_BUCKET_S = 5.0
WINDOW_BUCKETS = 72
WINDOW_SAMPLE_CAP = 128


class Histogram(_Metric):
    """Fixed-bucket histogram (Prometheus ``le`` convention: a bucket
    counts observations ``<= upper_bound``; ``+Inf`` is implicit).

    Beyond the buckets, each label series keeps a BOUNDED uniform
    reservoir of raw observations (Vitter's Algorithm R, cap
    ``sample_cap``, default :data:`DEFAULT_SAMPLE_CAP`, 0 disables):
    :meth:`sample_quantile` reads quantiles from it at sample
    resolution — exact while the series is under the cap, an unbiased
    uniform-subsample estimate past it — where :meth:`quantile` is
    limited to bucket-interpolation resolution.  Retention never grows
    past the cap no matter how long the run observes.

    Each series ALSO keeps a **windowed reservoir**: a time-bucketed
    ring of :data:`WINDOW_BUCKETS` buckets of ``window_bucket_s``
    seconds, each bounded at ``window_cap`` raw samples (Algorithm R
    within the bucket).  :meth:`window_quantile` /
    :meth:`window_rate` / :meth:`window_count` answer "over the last N
    seconds" — the primitive SLO verdicts, burn-rate alerts, and
    canary comparisons need, which the LIFETIME reservoir cannot
    (a recovered server's lifetime p99 advertises the bad minute
    forever).  The observe-path cost is one clock read plus a ring
    append under the same lock; the merge/sort work happens only when
    a window is actually read, so a process that never reads a window
    pays nothing beyond that.  ``clock`` is injectable (monotonic
    seconds) so expiry is unit-testable with a fake clock."""

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 buckets: Optional[Sequence[float]] = None,
                 sample_cap: Optional[int] = None,
                 window_bucket_s: Optional[float] = None,
                 window_buckets: Optional[int] = None,
                 window_cap: Optional[int] = None,
                 clock: Optional[Callable[[], float]] = None):
        super().__init__(name, help)
        bs = tuple(sorted(buckets if buckets is not None
                          else DEFAULT_BUCKETS))
        if not bs:
            raise ValueError(f"histogram {self.name!r}: needs >= 1 bucket")
        self.buckets = bs
        self.sample_cap = DEFAULT_SAMPLE_CAP if sample_cap is None \
            else max(0, int(sample_cap))
        self.window_bucket_s = float(WINDOW_BUCKET_S if window_bucket_s
                                     is None else window_bucket_s)
        if self.window_bucket_s <= 0:
            raise ValueError(f"histogram {self.name!r}: window_bucket_s "
                             "must be > 0")
        self.window_buckets = max(1, int(WINDOW_BUCKETS if window_buckets
                                         is None else window_buckets))
        self.window_cap = max(0, int(WINDOW_SAMPLE_CAP if window_cap
                                     is None else window_cap))
        self._now = time.monotonic if clock is None else clock
        # reservoir replacement draws need no crypto strength; a
        # name-derived seed keeps runs reproducible
        self._rng = random.Random(name)
        # per label set: [per-bucket counts + overflow, sum, count,
        #                 bounded sample reservoir, window ring] where
        # the ring is a bounded deque of [bucket_id, count, sum,
        # bounded samples] time buckets
        self._series: Dict[LabelKey, List[Any]] = {}

    @property
    def window_span_s(self) -> float:
        """Widest answerable window: ring buckets × bucket width.
        Wider queries clamp to it."""
        return self.window_bucket_s * self.window_buckets

    def observe(self, value: float, **labels) -> None:
        value = float(value)     # numpy scalars would poison json.dumps
        key = _label_key(labels)
        now = self._now() if self.window_cap else 0.0
        with self._lock:
            s = self._series.get(key)
            if s is None:
                s = self._series[key] = [
                    [0] * (len(self.buckets) + 1), 0.0, 0, [],
                    collections.deque(maxlen=self.window_buckets)]
            counts = s[0]
            for i, ub in enumerate(self.buckets):
                if value <= ub:
                    counts[i] += 1
                    break
            else:
                counts[-1] += 1
            s[1] += value
            s[2] += 1
            if self.sample_cap:
                res = s[3]
                if len(res) < self.sample_cap:
                    res.append(value)
                else:
                    # Algorithm R: keep each of the n observations so
                    # far with equal probability cap/n
                    j = self._rng.randrange(s[2])
                    if j < self.sample_cap:
                        res[j] = value
            if self.window_cap:
                bid = int(now // self.window_bucket_s)
                ring = s[4]
                b = ring[-1] if ring else None
                if b is None or b[0] != bid:
                    b = [bid, 0, 0.0, []]
                    ring.append(b)   # maxlen evicts the oldest bucket
                b[1] += 1
                b[2] += value
                ws = b[3]
                if len(ws) < self.window_cap:
                    ws.append(value)
                else:
                    j = self._rng.randrange(b[1])
                    if j < self.window_cap:
                        ws[j] = value

    @contextlib.contextmanager
    def time(self, **labels) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.observe(time.perf_counter() - t0, **labels)

    def count(self, **labels) -> int:
        with self._lock:
            s = self._series.get(_label_key(labels))
            return s[2] if s else 0

    #: Derived quantiles exported with every histogram (p50/p95/p99) —
    #: step-latency SLOs become checkable straight off ``/metrics`` /
    #: the JSONL sink, no Prometheus server required.
    EXPORT_QUANTILES: Tuple[float, ...] = (0.5, 0.95, 0.99)

    def quantile(self, q: float, **labels) -> Optional[float]:
        """Estimated ``q``-quantile (0 < q <= 1) from the fixed buckets,
        Prometheus ``histogram_quantile`` style: linear interpolation
        inside the bucket the rank falls in.  Observations past the last
        finite bound clamp to it (the +Inf bucket has no width to
        interpolate over).  None with no observations."""
        with self._lock:
            s = self._series.get(_label_key(labels))
            if s is None or s[2] == 0:
                return None
            counts, n = list(s[0]), s[2]
        rank = q * n
        acc, lo = 0.0, 0.0
        for i, ub in enumerate(self.buckets):
            prev = acc
            acc += counts[i]
            if acc >= rank:
                if counts[i] == 0:        # rank == prev on an empty bucket
                    return lo
                frac = min(max((rank - prev) / counts[i], 0.0), 1.0)
                return lo + (ub - lo) * frac
            lo = ub
        return self.buckets[-1]

    def quantiles(self, **labels) -> Dict[str, float]:
        """``{"p50": ..., "p95": ..., "p99": ...}`` (empty when no
        observations) — the derived series :meth:`samples` and the
        Prometheus dump export."""
        out: Dict[str, float] = {}
        for q in self.EXPORT_QUANTILES:
            v = self.quantile(q, **labels)
            if v is not None:
                out[f"p{int(q * 100)}"] = v
        return out

    def sample_quantile(self, q: float, **labels) -> Optional[float]:
        """``q``-quantile from the bounded raw-sample reservoir: exact
        while the series has observed <= ``sample_cap`` values, an
        unbiased uniform-subsample estimate beyond (linear
        interpolation between order statistics).  None with no retained
        samples (empty series or ``sample_cap=0``) — callers fall back
        to the bucket-resolution :meth:`quantile`."""
        with self._lock:
            s = self._series.get(_label_key(labels))
            res = list(s[3]) if s else []
        if not res:
            return None
        res.sort()
        pos = min(max(q, 0.0), 1.0) * (len(res) - 1)
        lo = int(pos)
        hi = min(lo + 1, len(res) - 1)
        return res[lo] + (res[hi] - res[lo]) * (pos - lo)

    def retained_samples(self, **labels) -> int:
        """Raw observations currently held in the reservoir for this
        series — bounded by ``sample_cap`` by construction."""
        with self._lock:
            s = self._series.get(_label_key(labels))
            return len(s[3]) if s else 0

    # --------------------------------------------------------- windows
    def _window_cut(self, window_s: float) -> Tuple[float, float]:
        """(clamped window, cutoff time): a bucket whose interval ends
        at or before the cutoff holds no sample younger than
        ``window_s`` and is expired for this read."""
        window_s = min(max(float(window_s), self.window_bucket_s),
                       self.window_span_s)
        return window_s, self._now() - window_s

    def _window_ring(self, window_s: float, **labels
                     ) -> Tuple[float, List[List[Any]]]:
        """Lock-consistent copy of the ring buckets still inside the
        window (newest data only; bucket granularity)."""
        window_s, cutoff = self._window_cut(window_s)
        with self._lock:
            s = self._series.get(_label_key(labels))
            ring = [[b[0], b[1], b[2], list(b[3])] for b in s[4]] \
                if s else []
        live = [b for b in ring
                if (b[0] + 1) * self.window_bucket_s > cutoff]
        return window_s, live

    def window_count(self, window_s: float, **labels) -> int:
        """Observations recorded in the last ``window_s`` seconds
        (bucket granularity — a window narrower than one ring bucket
        widens to it, one wider than the ring span clamps to it)."""
        _, live = self._window_ring(window_s, **labels)
        return sum(b[1] for b in live)

    def window_rate(self, window_s: float, **labels) -> float:
        """Observations per second over the last ``window_s`` seconds
        (the error-rate reader when failures are observed as events)."""
        window_s, live = self._window_ring(window_s, **labels)
        return sum(b[1] for b in live) / window_s

    def window_sum(self, window_s: float, **labels) -> float:
        """Sum of observed values over the last ``window_s`` seconds."""
        _, live = self._window_ring(window_s, **labels)
        return sum(b[2] for b in live)

    def window_samples(self, window_s: float, **labels) -> List[float]:
        """The raw samples retained for the last ``window_s`` seconds
        (unsorted; at most ``window_cap`` per ring bucket).  The SLO
        engine's burn-rate reader: the violating fraction of these IS
        the fraction of the error budget being burned."""
        _, live = self._window_ring(window_s, **labels)
        return [v for b in live for v in b[3]]

    def window_quantile(self, q: float, window_s: float,
                        **labels) -> Optional[float]:
        """``q``-quantile over the last ``window_s`` seconds, from the
        windowed reservoir: exact while the in-window buckets are under
        their per-bucket cap, an unbiased uniform-subsample estimate
        beyond (linear interpolation between order statistics, the
        :meth:`sample_quantile` convention).  None with no in-window
        samples — a recovered series goes back to None/ok instead of
        advertising a stale bad quantile forever."""
        res = self.window_samples(window_s, **labels)
        if not res:
            return None
        res.sort()
        pos = min(max(q, 0.0), 1.0) * (len(res) - 1)
        lo = int(pos)
        hi = min(lo + 1, len(res) - 1)
        return res[lo] + (res[hi] - res[lo]) * (pos - lo)

    def window_retained(self, **labels) -> int:
        """Raw samples currently held across the whole ring for this
        series — bounded by buckets × window_cap by construction (the
        cross-window monotone memory bound)."""
        with self._lock:
            s = self._series.get(_label_key(labels))
            return sum(len(b[3]) for b in s[4]) if s else 0

    def sum(self, **labels) -> float:
        with self._lock:
            s = self._series.get(_label_key(labels))
            return s[1] if s else 0.0

    def cumulative_buckets(self, **labels) -> List[Tuple[float, int]]:
        """``[(le, cumulative_count), ...]`` ending with ``(inf, count)``."""
        with self._lock:
            s = self._series.get(_label_key(labels))
            counts = list(s[0]) if s else [0] * (len(self.buckets) + 1)
        out, acc = [], 0
        for ub, c in zip(self.buckets + (math.inf,), counts):
            acc += c
            out.append((ub, acc))
        return out

    def samples(self) -> List[Dict[str, Any]]:
        with self._lock:
            items = [(k, list(s[0]), s[1], s[2])
                     for k, s in sorted(self._series.items())]
        out = []
        for key, counts, total, n in items:
            acc, buckets = 0, []
            for ub, c in zip(self.buckets + (math.inf,), counts):
                acc += c
                buckets.append(["+Inf" if ub == math.inf else ub, acc])
            out.append({"labels": dict(key), "count": n,
                        "sum": total, "buckets": buckets,
                        "quantiles": self.quantiles(**dict(key))})
        return out


class MetricsRegistry:
    """Get-or-create home for every metric in the process.

    Re-requesting a name returns the existing instance; re-requesting it
    as a different type raises — a name means one thing process-wide.
    """

    def __init__(self) -> None:
        self._lock = named_lock("observe.registry")
        self._metrics: Dict[str, _Metric] = {}

    def _get(self, cls, name: str, help: str, **kw) -> Any:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, help, **kw)
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {m.kind}, "
                    f"requested as {cls.kind}")
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets: Optional[Sequence[float]] = None,
                  sample_cap: Optional[int] = None,
                  **window_kw) -> Histogram:
        return self._get(Histogram, name, help, buckets=buckets,
                         sample_cap=sample_cap, **window_kw)

    def find(self, name: str) -> Optional[_Metric]:
        """The registered metric of that name, or None — readers that
        must not CREATE a series (the SLO evaluator, the fleet frame's
        windowed-TTFT stamp) probe with this instead of the
        get-or-create accessors."""
        with self._lock:
            return self._metrics.get(name)

    def metrics(self) -> List[_Metric]:
        with self._lock:
            return [self._metrics[n] for n in sorted(self._metrics)]

    def snapshot(self) -> List[Dict[str, Any]]:
        """Self-describing dump of every metric (the JSONL line body)."""
        return [m.describe() for m in self.metrics()]

    def flat(self, kinds: Sequence[str] = ("counter", "gauge")
             ) -> Dict[str, float]:
        """``{'name{k="v"}': value}`` for scalar metric kinds — the
        compact form bench lines and delta assertions consume."""
        out: Dict[str, float] = {}
        for m in self.metrics():
            if m.kind not in kinds:
                continue
            for s in m.samples():
                out[m.name + format_labels(_label_key(s["labels"]))] = \
                    s["value"]
        return out

    def prometheus_text(self) -> str:
        """Prometheus exposition-format dump of the registry."""
        lines: List[str] = []
        for m in self.metrics():
            if m.help:
                lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            qlines: List[str] = []
            for s in m.samples():
                key = _label_key(s["labels"])
                if m.kind == "histogram":
                    for le, acc in zip([b[0] for b in s["buckets"]],
                                       [b[1] for b in s["buckets"]]):
                        lk = _label_key({**s["labels"], "le": le})
                        lines.append(
                            f"{m.name}_bucket{format_labels(lk)} {acc}")
                    lines.append(f"{m.name}_sum{format_labels(key)} "
                                 f"{s['sum']}")
                    lines.append(f"{m.name}_count{format_labels(key)} "
                                 f"{s['count']}")
                    # derived p50/p95/p99 as a sibling gauge family
                    # (summary-style quantile label): SLOs readable off
                    # one scrape, no PromQL histogram_quantile needed
                    for tag, v in s.get("quantiles", {}).items():
                        lk = _label_key({**s["labels"],
                                         "quantile": f"0.{tag[1:]}"})
                        qlines.append(
                            f"{m.name}_q{format_labels(lk)} {v}")
                else:
                    lines.append(
                        f"{m.name}{format_labels(key)} {s['value']}")
            if qlines:
                lines.append(f"# TYPE {m.name}_q gauge")
                lines.extend(qlines)
        return "\n".join(lines) + ("\n" if lines else "")

    def reset(self) -> None:
        """Drop every metric (tests; a live process never resets)."""
        with self._lock:
            self._metrics.clear()


#: The process-wide registry every subsystem instruments against.
REGISTRY = MetricsRegistry()


# The module-level get-or-create shims forward their caller's name
# verbatim — THEY are not registration sites, their callers are
# (PT-METRIC judges the literal-ness of the name where it originates).
def counter(name: str, help: str = "") -> Counter:
    # ptpu: lint-ok[PT-METRIC] forwarding shim; callers are the sites
    return REGISTRY.counter(name, help)


def gauge(name: str, help: str = "") -> Gauge:
    # ptpu: lint-ok[PT-METRIC] forwarding shim; callers are the sites
    return REGISTRY.gauge(name, help)


def histogram(name: str, help: str = "",
              buckets: Optional[Sequence[float]] = None,
              sample_cap: Optional[int] = None, **window_kw) -> Histogram:
    # ptpu: lint-ok[PT-METRIC] forwarding shim; callers are the sites
    return REGISTRY.histogram(name, help, buckets=buckets,
                              sample_cap=sample_cap, **window_kw)

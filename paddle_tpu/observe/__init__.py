"""paddle_tpu.observe — the unified telemetry layer.

Typed metrics (Counter / Gauge / Histogram) in a process-wide registry,
exported together with the ``StatSet`` wall-timer table through one
reporter: a JSONL sink (``--metrics_jsonl PATH``, one self-describing
line per flush interval) and an on-demand Prometheus text dump.

Instrumented surfaces (all against :data:`REGISTRY`):

- trainer: step latency split host-feed vs device-blocked, samples/sec,
  jit recompiles (``paddle_tpu/trainer/trainer.py``);
- data path: input wait (reader or prefetch queue) + feed-convert time
  → input-bound ratio; async-pipeline queue depth, prefetch hit/stall
  census, worker convert time, cloud read-ahead depth/chunks
  (``paddle_tpu/data/pipeline.py``, ``distributed/master.py``);
- dispatch tiers: RNN fused_blocked/fused/scan with fallback reasons,
  conv+BN fused/chain/unfused (``ops/recurrent_ops.py``,
  ``ops/nn_ops.py``), build-time fused-pair census
  (``layers/network.py``);
- fault tolerance: master reconnect/backoff/replay, checkpoint
  save/verify latency + quarantines, elastic skipped-save/election
  releases (``distributed/``, ``trainer/checkpoint.py``);
- serving: request count + inference latency (``serving/loader.py``);
- training health: per-layer grad/param norms, update ratios,
  non-finite localization and detector alerts, drained from the
  on-device accumulators every ``--health_interval`` steps
  (``observe/health.py``, ``trainer/trainer.py``);
- the fleet plane: cross-process push aggregation — every process
  with ``--fleet_addr`` ships its snapshot + recent spans + health
  digest to an aggregator any process hosts with ``--fleet_port``
  (cluster health rollup, merged Prometheus, ONE merged Perfetto
  timeline; ``observe/fleet.py``), with a chaining SIGTERM hook so
  the final interval survives an orchestrator kill
  (``observe/shutdown.py``).

Overhead contract: with no sink attached every instrument is a dict
lookup + lock + add; anything more expensive (step fencing) is gated on
:func:`active`.
"""

from .metrics import (  # noqa: F401
    DEFAULT_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    REGISTRY,
    counter,
    format_labels,
    gauge,
    histogram,
)
from .report import (  # noqa: F401
    MetricsReporter,
    active,
    attach,
    prometheus_dump,
)
from .report import start_from_flags as _start_reporter_from_flags
from .report import stop_global as _stop_reporter_global
from . import benchgate, dump, fleet, http, memory, shutdown, trace  # noqa: F401
# costmodel and health are NOT imported eagerly: their entry points
# touch jax (lazily), and keeping them explicit `from
# paddle_tpu.observe import costmodel` / `... import health` imports
# preserves this package's import-time zero-dep rule — AND lets the
# HTTP endpoint / healthz probe resolve them through sys.modules so a
# process that never trained pays nothing for either surface.


def start_from_flags():
    """One call a long-running entry point makes (``Trainer.train``,
    ``bench.main``, the CLI): start every flag-configured observability
    surface — the ``--metrics_jsonl`` reporter (with the
    ``--fleet_addr`` push client folded in), ``--trace_jsonl`` span
    sink, the ``--metrics_port`` HTTP endpoint, the ``--fleet_port``
    aggregator, the ``--debug_dump_signal`` SIGUSR2 handler, and the
    graceful-shutdown SIGTERM flush hook (installed only once some
    surface above actually got configured).  Each piece is
    individually idempotent and a no-op when its flag is unset, so
    with nothing configured this is a few dict lookups and no thread
    starts."""
    reporter = _start_reporter_from_flags()
    trace.start_from_flags()
    http.start_from_flags()
    fleet.start_from_flags()
    dump.install_from_flags()
    shutdown.install_from_flags()
    return reporter


def stop_global():
    """Stop every process-wide observability surface (reporter + HTTP
    endpoint + fleet aggregator + trace sink + SIGTERM hook) — the
    mirror of :func:`start_from_flags`."""
    _stop_reporter_global()
    http.stop_global()
    fleet.stop_global()
    trace.disable()
    shutdown.uninstall()


__all__ = [
    "DEFAULT_BUCKETS", "Counter", "Gauge", "Histogram",
    "MetricsRegistry", "REGISTRY", "counter", "gauge", "histogram",
    "format_labels", "MetricsReporter", "active", "attach",
    "prometheus_dump", "start_from_flags", "stop_global",
    "trace", "http", "dump", "memory", "benchgate", "fleet",
    "shutdown",
]

"""Device (HBM) memory accounting: gauges + category attribution.

The second leg of the performance observatory: until now the framework
had NO device-memory telemetry at all — an OOM was the first and only
signal.  This module samples

- ``hbm_in_use_bytes`` / ``hbm_peak_bytes`` — from the backend's
  ``device.memory_stats()`` when it reports (TPU/GPU runtimes), else
  from the sum of live committed arrays (``jax.live_arrays()``; the
  CPU backend reports no allocator stats, so the peak is tracked as a
  running max across samples — honest about being sample-resolution);
- ``hbm_category_bytes{category=...}`` — attribution of the in-use
  bytes to the trainer's known pytrees by **buffer identity**: params,
  opt_state, buffers (batch-norm stats), loss_scale, data (the feed),
  and ``other`` for everything unclaimed (mostly activations held by
  in-flight dispatch and donated-buffer slack).  Category figures are
  **per-chip** (:func:`per_chip_bytes`): a leaf sharded n ways over
  the mesh counts one shard, so the FSDP params/opt_state win is read
  directly off the gauge; replicated/single-chip leaves read as their
  full ``nbytes``, unchanged.

Sampling discipline (the 26 µs/step no-sink contract): nothing here
runs per step.  The trainer samples at pass boundaries — and only when
someone is listening (a metrics sink is attached or the ``/metrics``
endpoint is live); ``bench.py`` stamps every JSON line through
:func:`sample`.  jax is imported lazily so importing
:mod:`paddle_tpu.observe` stays backend-free.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from .metrics import gauge

#: Running peak for backends without allocator stats (CPU): max of the
#: in-use figure across samples taken this process.
_live_peak = 0


def device_stats(device=None) -> Optional[Dict[str, Any]]:
    """The backend allocator's ``memory_stats()`` for ``device`` (the
    first device by default); None when the backend doesn't report
    (CPU) or no backend is initialized."""
    try:
        if device is None:
            import jax
            device = jax.devices()[0]
        return device.memory_stats()
    except Exception:  # noqa: BLE001 — telemetry never kills the host
        return None


def tree_bytes(tree) -> int:
    """Total committed bytes of a pytree's array leaves (0 for None)."""
    if tree is None:
        return 0
    import jax

    return sum(int(getattr(leaf, "nbytes", 0) or 0)
               for leaf in jax.tree_util.tree_leaves(tree))


def per_chip_bytes(leaf) -> int:
    """Bytes of ``leaf`` resident on ONE chip.

    A sharded ``jax.Array``'s ``nbytes`` is the GLOBAL logical size —
    useless for judging per-chip HBM headroom, which is what caps
    model size.  This reads the sharding's per-device shard shape
    instead: a replicated leaf costs its full size per chip, an
    FSDP-sharded one costs ``nbytes / n_shards``.  Host numpy leaves
    and scalars fall back to ``nbytes``."""
    nb = int(getattr(leaf, "nbytes", 0) or 0)
    sh = getattr(leaf, "sharding", None)
    if not nb or sh is None:
        return nb
    try:
        shard_shape = sh.shard_shape(leaf.shape)
        n = 1
        for d in shard_shape:
            n *= int(d)
        return n * int(leaf.dtype.itemsize)
    except Exception:  # noqa: BLE001 — telemetry never kills the host
        return nb


def _category_trees(trainer, feed=None) -> Dict[str, Any]:
    cats: Dict[str, Any] = {}
    if trainer is not None:
        cats["params"] = getattr(trainer, "params", None)
        cats["opt_state"] = getattr(trainer, "opt_state", None)
        cats["buffers"] = getattr(trainer, "buffers", None)
        ls = getattr(trainer, "_ls_state", None)
        if ls is not None:
            cats["loss_scale"] = ls
    if feed is not None:
        cats["data"] = feed
    return cats


def account(trainer=None, feed=None,
            device=None) -> Dict[str, Any]:
    """One memory accounting snapshot.

    Returns ``{"in_use_bytes", "peak_bytes", "source", "categories":
    {name: bytes}, "attributed_frac"}``.  Categories are attributed by
    buffer identity against the live-array set, so a leaf that is BOTH
    in ``trainer.params`` and alive is counted once under ``params``
    and never under ``other``.

    Category bytes are **per-chip** (:func:`per_chip_bytes`): under
    FSDP a parameter sharded 8 ways contributes 1/8 of its global
    size, which is exactly the HBM-headroom question the gauges
    answer; on a single chip or for replicated leaves the figure
    equals ``nbytes``, so the legacy reading is unchanged.
    """
    global _live_peak
    import jax

    cats = _category_trees(trainer, feed)
    cat_ids: Dict[int, str] = {}
    cat_bytes: Dict[str, int] = {}
    for name, tree in cats.items():
        n = 0
        if tree is not None:
            for leaf in jax.tree_util.tree_leaves(tree):
                nb = per_chip_bytes(leaf)
                if nb and id(leaf) not in cat_ids:
                    cat_ids[id(leaf)] = name
                    n += nb
        cat_bytes[name] = n

    stats = device_stats(device)
    if stats and stats.get("bytes_in_use") is not None:
        in_use = int(stats["bytes_in_use"])
        peak = int(stats.get("peak_bytes_in_use", in_use))
        source = "device"
        other = max(in_use - sum(cat_bytes.values()), 0)
    else:
        live = 0
        other = 0
        try:
            arrays = jax.live_arrays()
        except Exception:  # noqa: BLE001 — older jax / odd backend
            arrays = []
        for arr in arrays:
            nb = int(getattr(arr, "nbytes", 0) or 0)
            live += nb
            if id(arr) not in cat_ids:
                other += nb
        in_use = live
        _live_peak = max(_live_peak, live)
        peak = _live_peak
        source = "live_arrays"
    cat_bytes["other"] = other
    attributed = sum(v for k, v in cat_bytes.items() if k != "other")
    return {
        "in_use_bytes": in_use,
        "peak_bytes": peak,
        "source": source,
        "categories": cat_bytes,
        "attributed_frac": round(attributed / in_use, 4) if in_use
        else 0.0,
    }


def shard_categories(trainer=None, feed=None) -> Dict[str, Dict[str, int]]:
    """Per-SHARD category attribution: category → {device id (str) →
    bytes resident on that device}.

    The per-device refinement of :func:`account`'s per-chip figures —
    on a row-sharded 10⁷-row embedding table each ``data``-axis shard
    carries ``V/n`` rows, and this is where an imbalance (a replicated
    stray slot, an indivisible-dim degrade) becomes visible per chip.
    Replicated leaves contribute their full size to EVERY device they
    live on, sharded leaves one shard each.  One series per (category,
    device) — a label-explosion family by design; consoles summarize
    it top-k (``fleet --watch``) rather than one line per series."""
    out: Dict[str, Dict[str, int]] = {}
    for name, tree in _category_trees(trainer, feed).items():
        if tree is None:
            continue
        per_dev: Dict[str, int] = {}
        import jax
        for leaf in jax.tree_util.tree_leaves(tree):
            sh = getattr(leaf, "sharding", None)
            if sh is None or not getattr(leaf, "nbytes", 0):
                continue
            nb = per_chip_bytes(leaf)
            try:
                devices = sorted(sh.device_set, key=lambda d: d.id)
            except Exception:  # noqa: BLE001 — telemetry never kills
                continue
            for d in devices:
                key = str(d.id)
                per_dev[key] = per_dev.get(key, 0) + nb
        if per_dev:
            out[name] = per_dev
    return out


def sample(trainer=None, feed=None, device=None) -> Dict[str, Any]:
    """Take one accounting snapshot AND publish it as gauges — the
    ``/metrics`` surface (``hbm_in_use_bytes``, ``hbm_peak_bytes``,
    ``hbm_category_bytes{category=...}``, and the per-device
    ``hbm_shard_bytes{category,shard}`` family).  Returns the
    snapshot (with the per-shard breakdown under ``"shards"``)."""
    snap = account(trainer, feed, device)
    shards = shard_categories(trainer, feed)
    snap["shards"] = shards
    if shards:
        sg = gauge("hbm_shard_bytes",
                   "bytes of each accounting category resident on each "
                   "device (sharded leaves count one shard per device, "
                   "replicated leaves their full size on every device) "
                   "— a per-(category,shard) label-explosion family; "
                   "consoles render it as a top-k summary")
        for cname, per_dev in shards.items():
            for dev_id, nbytes in per_dev.items():
                sg.set(nbytes, category=cname, shard=dev_id)
    gauge("hbm_in_use_bytes",
          "device memory currently in use (allocator stats when the "
          "backend reports them, else total live committed arrays)"
          ).set(snap["in_use_bytes"])
    gauge("hbm_peak_bytes",
          "peak device memory (allocator peak_bytes_in_use; running "
          "max of samples on stat-less backends)").set(snap["peak_bytes"])
    cat = gauge("hbm_category_bytes",
                "PER-CHIP bytes attributed to the trainer's known "
                "pytrees by buffer identity (sharded leaves count "
                "their one-device shard — the FSDP win reads "
                "directly); 'other' = unclaimed (activations in "
                "flight, allocator slack)")
    for name, nbytes in snap["categories"].items():
        cat.set(nbytes, category=name)
    return snap


def reset_peak() -> None:
    """Drop the running live-array peak (tests)."""
    global _live_peak
    _live_peak = 0

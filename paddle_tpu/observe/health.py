"""Training-health observatory: on-device per-layer telemetry + detectors.

The performance observatory (``costmodel``/``memory``/``benchgate``)
answers "how fast"; this module answers "is the model healthy".  Two
halves:

- **Device side** (``--health_interval N > 0``): the trainer fuses a
  health-aux path into the jitted train step — per-layer gradient
  norms, parameter norms, update norms (for the ‖Δw‖/‖w‖ ratio) and
  non-finite counts, computed in ONE pass over the grad pytree and
  keyed to the SAME layer names the roofline attribution uses
  (:func:`layer_param_map` groups parameters by owning layer exactly
  like ``costmodel._known_regions`` keys regions).  The per-step
  results accumulate in a small :class:`HealthState` pytree threaded
  through the step (the ``LossScaleState`` pattern), so the hot loop
  never syncs; the trainer drains it every N steps and at pass
  boundaries — the drain's small D2H fetch is the ONLY fence the
  feature buys, amortized over the interval.  With the flag at its
  default 0 the step is built without any aux outputs: byte-for-byte
  the legacy program, zero extra HBM traffic, no fencing (the
  ``observe.active()`` / ``trace.fences_steps()`` discipline).

- **Host side**: :class:`HealthMonitor` turns the drained stream into
  verdicts — first-non-finite localization (which layer's grad went
  inf/nan first, with loss-scale skip steps under ``--precision=bf16``
  classified as *benign* and never alerted), loss-spike and plateau
  detection over a rolling median/MAD window, and dead-/exploding-layer
  flags from the update ratio.  Each detector emits a warn-once log
  line, a ``health_alerts_total{kind,layer}`` increment, and a
  structured entry served by ``/health`` (and summarized as
  degraded-but-alive detail on ``/healthz``).

Zero-dependency rule: module import touches stdlib only (the HTTP
endpoint imports this lazily at scrape time); jax enters function
scope only, inside the step-builder helpers the trainer calls.
"""

from __future__ import annotations

import math
import time
from collections import deque
from typing import Any, Dict, List, NamedTuple, Optional, Sequence, Tuple

from ..analysis.lockorder import named_lock
from .metrics import counter, gauge, histogram

#: Region name for parameters no layer claims — matches the roofline
#: attribution's fallback bucket so the two surfaces stay joinable.
UNATTRIBUTED = "_unattributed"

#: ``first_nonfinite`` sentinel: the layer never went non-finite.
NEVER = -1

#: Loss histogram buckets: losses live on a log scale, not a latency
#: scale — 1e-4 … 1e4 in decades plus the DEFAULT_BUCKETS-style tail.
LOSS_BUCKETS = (1e-4, 1e-3, 1e-2, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
                25.0, 100.0, 1e3, 1e4)


# --------------------------------------------------------- layer keying
def layer_param_map(network) -> List[Tuple[str, List[str]]]:
    """``[(layer_name, [param names])...]`` for every parameter-owning
    layer, keyed exactly like the roofline regions: top-level layers by
    name, recurrent-group step layers as ``"<layer>.<group>"``
    (``costmodel._known_regions``).  A parameter two layers declare
    (explicit sharing) belongs to its first declarer; parameters in
    ``network.param_specs`` that no layer claims land in
    :data:`UNATTRIBUTED`."""
    owned: Dict[str, List[str]] = {}
    order: List[str] = []
    seen: set = set()

    def claim(layer_key: str, layer) -> None:
        try:
            specs = layer.param_specs()
        except Exception:  # noqa: BLE001 — telemetry never kills
            return
        for spec in specs:
            if spec.name in seen or spec.name not in network.param_specs:
                continue
            seen.add(spec.name)
            if layer_key not in owned:
                owned[layer_key] = []
                order.append(layer_key)
            owned[layer_key].append(spec.name)

    for name, layer in network.layers.items():
        claim(name, layer)
    for gname, grp in getattr(network, "groups", {}).items():
        for name, layer in getattr(grp, "layers", {}).items():
            claim(f"{name}.{gname}", layer)
    unclaimed = [n for n in sorted(network.param_specs) if n not in seen]
    if unclaimed:
        owned[UNATTRIBUTED] = unclaimed
        order.append(UNATTRIBUTED)
    return [(k, owned[k]) for k in order]


# ------------------------------------------------------ device-side aux
class HealthState(NamedTuple):
    """Per-layer device accumulators threaded through the train step
    (all arrays of length L = number of parameter-owning layers).
    ``steps`` counts steps since the last drain; norms hold the LAST
    step's values (gauges are point-in-time), the non-finite fields
    accumulate so a between-drain incident is never missed."""
    steps: Any                    # i32 []
    grad_sq: Any                  # f32 [L]  ‖g‖² per layer, last step
    param_sq: Any                 # f32 [L]  ‖w‖² per layer, last step
    update_sq: Any                # f32 [L]  ‖Δw‖² per layer, last step
    nonfinite_steps: Any          # i32 [L]  steps with inf/nan grads,
    #                                        update APPLIED (pathological)
    benign_nonfinite_steps: Any   # i32 [L]  steps with inf/nan grads,
    #                                        update SKIPPED (loss scale)
    first_nonfinite: Any          # i32 [L]  step index of first inf/nan
    #                                        since drain; NEVER = none


def init_state(num_layers: int) -> HealthState:
    """Fresh zeroed accumulator (host constants; placed on first
    dispatch).  Every field gets its OWN buffer — the state is donated
    into the train step, and donating one deduped zeros array twice is
    a runtime error (the trainer's ``_dealias`` rule)."""
    import jax.numpy as jnp

    def z(dtype, fill=0):
        return jnp.full((num_layers,), fill, dtype)

    return HealthState(
        steps=jnp.zeros((), jnp.int32),
        grad_sq=z(jnp.float32), param_sq=z(jnp.float32),
        update_sq=z(jnp.float32),
        nonfinite_steps=z(jnp.int32),
        benign_nonfinite_steps=z(jnp.int32),
        first_nonfinite=z(jnp.int32, NEVER))


def layer_stats(groups: Sequence[Sequence[str]], grads, params,
                new_params, nonfinite_counts=None):
    """(grad_sq[L], param_sq[L], update_sq[L], nonfinite[L]) in one
    traversal of the grad pytree.  ``groups`` is the static per-layer
    parameter-name grouping from :func:`layer_param_map`; everything
    here is jittable and reduction-only (no MXU ops), accumulated in
    fp32 regardless of the compute policy.  ``nonfinite_counts`` lets
    the bf16 step hand over the per-leaf counts its loss-scale skip
    decision already computed (``loss_scale.leaf_nonfinite_counts``) so
    one isfinite sweep serves both consumers."""
    import jax.numpy as jnp

    gsq, psq, usq, nf = [], [], [], []
    for names in groups:
        g_acc = jnp.zeros((), jnp.float32)
        p_acc = jnp.zeros((), jnp.float32)
        u_acc = jnp.zeros((), jnp.float32)
        n_acc = jnp.zeros((), jnp.int32)
        for n in names:
            g = grads[n].astype(jnp.float32)
            w = params[n].astype(jnp.float32)
            d = new_params[n].astype(jnp.float32) - w
            g_acc = g_acc + jnp.sum(g * g)
            p_acc = p_acc + jnp.sum(w * w)
            u_acc = u_acc + jnp.sum(d * d)
            if nonfinite_counts is not None:
                n_acc = n_acc + nonfinite_counts[n]
            else:
                n_acc = n_acc + jnp.sum(
                    (~jnp.isfinite(g)).astype(jnp.int32))
        gsq.append(g_acc)
        psq.append(p_acc)
        usq.append(u_acc)
        nf.append(n_acc)
    return (jnp.stack(gsq), jnp.stack(psq), jnp.stack(usq),
            jnp.stack(nf))


def accumulate(state: HealthState, stats, applied) -> HealthState:
    """Fold one step's ``layer_stats`` into the accumulator (branchless,
    jit-safe).  ``applied`` is a scalar bool: whether the optimizer
    update was applied (False on a loss-scale skip step — those
    non-finites count as *benign*)."""
    import jax.numpy as jnp

    grad_sq, param_sq, update_sq, nonfinite = stats
    had_nf = nonfinite > 0
    applied = jnp.asarray(applied)
    patho = jnp.logical_and(had_nf, applied).astype(jnp.int32)
    benign = jnp.logical_and(had_nf,
                             jnp.logical_not(applied)).astype(jnp.int32)
    return HealthState(
        steps=state.steps + 1,
        grad_sq=grad_sq, param_sq=param_sq, update_sq=update_sq,
        nonfinite_steps=state.nonfinite_steps + patho,
        benign_nonfinite_steps=state.benign_nonfinite_steps + benign,
        first_nonfinite=jnp.where(
            jnp.logical_and(state.first_nonfinite == NEVER, had_nf),
            state.steps, state.first_nonfinite))


# ----------------------------------------------------------- host side
def _finite_or_none(v: float) -> Optional[float]:
    return v if math.isfinite(v) else None


class HealthMonitor:
    """Rolling host-side detectors over drained :class:`HealthState`
    reports.  One instance per trainer; thread-safe (the drain runs on
    the training thread, ``/health`` reads from scraper threads)."""

    def __init__(self, layers: Sequence[str],
                 window: int = 32, spike_mad: float = 8.0,
                 plateau_rtol: float = 1e-4,
                 dead_ratio: float = 1e-10,
                 explode_ratio: float = 0.5,
                 patience: int = 2):
        self.layers = list(layers)
        self.window = max(4, int(window))
        self.spike_mad = float(spike_mad)
        self.plateau_rtol = float(plateau_rtol)
        self.dead_ratio = float(dead_ratio)
        self.explode_ratio = float(explode_ratio)
        self.patience = max(1, int(patience))
        self._losses: deque = deque(maxlen=self.window)
        self._dead_streak: Dict[str, int] = {}
        self._explode_streak: Dict[str, int] = {}
        self._fired: set = set()
        # conditions that held on the LAST drain — the "standing
        # alerts" set /healthz degrades on; rebuilt every observe() so
        # a recovered run goes back to "ok" (the historical _alerts
        # log keeps the incident for /health forensics)
        self._active: set = set()
        self._alerts: deque = deque(maxlen=64)
        self._lock = named_lock("observe.health")
        self.drains = 0

    @classmethod
    def from_flags(cls, layers: Sequence[str]) -> "HealthMonitor":
        from ..utils import FLAGS

        return cls(layers,
                   window=FLAGS.get("health_window"),
                   spike_mad=FLAGS.get("health_spike_mad"),
                   plateau_rtol=FLAGS.get("health_plateau_rtol"),
                   dead_ratio=FLAGS.get("health_dead_ratio"),
                   explode_ratio=FLAGS.get("health_explode_ratio"),
                   patience=FLAGS.get("health_patience"))

    # ------------------------------------------------------- detectors
    def _fire(self, kind: str, layer: str, detail: str,
              alerts: List[Dict[str, Any]]) -> None:
        """Warn-once per (kind, layer): the log line and the structured
        entry fire on the first occurrence; the counter counts every
        drain that re-observes the condition (alert pressure is a
        signal too)."""
        counter(
            "health_alerts_total",
            "training-health detector verdicts by kind "
            "(nonfinite | loss_spike | loss_plateau | dead_layer | "
            "exploding_layer) and layer").inc(kind=kind, layer=layer)
        key = (kind, layer)
        if key in self._fired:
            return
        self._fired.add(key)
        entry = {"kind": kind, "layer": layer, "detail": detail,
                 "ts": round(time.time(), 3)}
        self._alerts.append(entry)
        alerts.append(entry)
        from ..utils.logger import get_logger, warn_once

        warn_once(f"health:{kind}:{layer}",
                  "training-health alert [%s] layer=%s: %s",
                  kind, layer, detail, logger=get_logger("observe"))

    def _robust_window(self) -> Tuple[Optional[float], Optional[float]]:
        """(median, MAD) of the loss window (None, None when too few
        samples for a robust verdict)."""
        vals = sorted(self._losses)
        n = len(vals)
        if n < 4:
            return None, None
        med = (vals[n // 2] if n % 2
               else 0.5 * (vals[n // 2 - 1] + vals[n // 2]))
        dev = sorted(abs(v - med) for v in vals)
        mad = (dev[n // 2] if n % 2
               else 0.5 * (dev[n // 2 - 1] + dev[n // 2]))
        return med, mad

    def observe(self, report: Dict[str, Any],
                loss: Optional[float]) -> List[Dict[str, Any]]:
        """Run every detector over one drained report; returns the
        alerts NEWLY fired by this drain (the structured entries)."""
        alerts: List[Dict[str, Any]] = []
        active: set = set()
        with self._lock:
            self.drains += 1
            # --- non-finite localization: pathological only.  Benign
            # loss-scale skips are already counted by
            # loss_scale_skipped_steps_total and must not alert.
            patho = [(l, r) for l, r in report["layers"].items()
                     if r["nonfinite_steps"] > 0]
            if patho:
                firsts = [r["first_nonfinite"] for _, r in patho
                          if r["first_nonfinite"] != NEVER]
                first_step = min(firsts) if firsts else NEVER
                culprits = [l for l, r in patho
                            if r["first_nonfinite"] == first_step]
                for l in culprits or [l for l, _ in patho]:
                    active.add(("nonfinite", l))
                    self._fire(
                        "nonfinite", l,
                        f"gradients went inf/nan at step "
                        f"{report['base_step'] + max(first_step, 0)} "
                        "with the update APPLIED (no loss-scale "
                        "skip protected it)", alerts)
            # --- loss spike / plateau over the rolling robust window
            if loss is not None and math.isfinite(loss):
                med, mad = self._robust_window()
                if med is not None:
                    # sigma floor: a perfectly flat window has MAD 0,
                    # and the classic spike — constant loss, then a
                    # jump — must still trip the detector
                    sigma = max(1.4826 * (mad or 0.0),
                                self.plateau_rtol
                                * max(abs(med), 1e-12))
                    if loss > med + self.spike_mad * sigma:
                        active.add(("loss_spike", "_model"))
                        self._fire(
                            "loss_spike", "_model",
                            f"loss {loss:.6g} above rolling median "
                            f"{med:.6g} + {self.spike_mad:.3g} robust "
                            f"sigma ({sigma:.3g})", alerts)
                    elif (len(self._losses) == self.window
                          and max(self._losses) - min(self._losses)
                          <= self.plateau_rtol * max(abs(med), 1e-12)
                          and abs(loss - med)
                          <= self.plateau_rtol * max(abs(med), 1e-12)):
                        active.add(("loss_plateau", "_model"))
                        self._fire(
                            "loss_plateau", "_model",
                            f"loss flat within rtol "
                            f"{self.plateau_rtol:.1g} of {med:.6g} "
                            f"over the last {self.window} drains",
                            alerts)
                self._losses.append(loss)
            # --- dead / exploding layers from the update ratio
            for l, r in report["layers"].items():
                ratio = r["update_ratio"]
                grad = r["grad_norm"]
                if ratio is None or grad is None:
                    # a drain without a usable reading (non-finite
                    # norms) breaks the "N CONSECUTIVE drains" streaks
                    # — the non-finite detectors own this state
                    self._dead_streak[l] = 0
                    self._explode_streak[l] = 0
                    continue
                dead = (grad == 0.0 or ratio <= self.dead_ratio)
                self._dead_streak[l] = self._dead_streak.get(l, 0) + 1 \
                    if dead else 0
                if self._dead_streak[l] >= self.patience:
                    active.add(("dead_layer", l))
                    self._fire(
                        "dead_layer", l,
                        f"update ratio {ratio:.3g} <= "
                        f"{self.dead_ratio:.1g} for "
                        f"{self._dead_streak[l]} consecutive drains "
                        "(no learning signal reaches this layer)",
                        alerts)
                explode = ratio > self.explode_ratio
                self._explode_streak[l] = \
                    self._explode_streak.get(l, 0) + 1 if explode else 0
                if self._explode_streak[l] >= self.patience:
                    active.add(("exploding_layer", l))
                    self._fire(
                        "exploding_layer", l,
                        f"update ratio {ratio:.3g} > "
                        f"{self.explode_ratio:.3g} for "
                        f"{self._explode_streak[l]} consecutive drains "
                        "(step size is rewriting the layer)", alerts)
            self._active = active
        return alerts

    def recent_alerts(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._alerts)

    def active_conditions(self) -> List[Tuple[str, str]]:
        """(kind, layer) conditions that held on the LAST drain — the
        "standing alerts" /healthz degrades on.  Empty once the run
        recovers (streaks reset, no pathological non-finites), even
        though the historical :meth:`recent_alerts` log keeps the
        incident for forensics."""
        with self._lock:
            return sorted(self._active)


# ------------------------------------------------------ trainer session
class HealthSession:
    """Everything the trainer holds for an enabled health path: the
    static layer grouping (captured at step-build time), the device
    accumulator, the drain cadence, and the monitor."""

    def __init__(self, network, interval: int,
                 monitor: Optional[HealthMonitor] = None):
        self.interval = max(1, int(interval))
        self.pairs = layer_param_map(network)
        self.layers = [k for k, _ in self.pairs]
        self.groups = [names for _, names in self.pairs]
        self.monitor = monitor or HealthMonitor.from_flags(self.layers)
        self.state: Optional[HealthState] = None
        self._since_drain = 0
        self._base_step = 0

    def ensure_state(self, place=None) -> HealthState:
        """Init (and optionally place/replicate) the device accumulator
        — called from the trainer's first-step state placement."""
        if self.state is None:
            self.state = init_state(len(self.layers))
            if place is not None:
                self.state = place(self.state)
        return self.state

    def stats_fn(self):
        """The traced per-step aux: ``(grads, params, new_params) ->
        stats`` over this session's static layer grouping."""
        groups = self.groups

        def fn(grads, params, new_params, nonfinite_counts=None):
            return layer_stats(groups, grads, params, new_params,
                               nonfinite_counts)

        return fn

    def step_done(self) -> bool:
        """Tick the host-side step mirror; True when a drain is due."""
        self._since_drain += 1
        return self._since_drain >= self.interval

    def pending(self) -> bool:
        return self.state is not None and self._since_drain > 0

    # ---------------------------------------------------------- drain
    def drain(self, loss: Optional[float] = None,
              place=None) -> Optional[Dict[str, Any]]:
        """Fetch the device accumulator (the amortized fence), publish
        gauges/counters, run the detectors, reset the accumulator, and
        stash the structured report for ``/health``.  Returns the
        report (None when nothing accumulated)."""
        if self.state is None or self._since_drain == 0:
            return None
        import jax

        # ONE batched D2H over the whole state — per-field serial
        # fetches would pay a host round trip each (the
        # utils/profiler.py parameter_stats lesson)
        st = jax.device_get(self.state)
        steps = int(st.steps)
        if steps == 0:
            self._since_drain = 0
            return None
        grad_sq = [float(v) for v in st.grad_sq]
        param_sq = [float(v) for v in st.param_sq]
        update_sq = [float(v) for v in st.update_sq]
        nf = [int(v) for v in st.nonfinite_steps]
        benign = [int(v) for v in st.benign_nonfinite_steps]
        first = [int(v) for v in st.first_nonfinite]
        layers: Dict[str, Dict[str, Any]] = {}
        g_gauge = gauge(
            "health_grad_norm",
            "per-layer L2 gradient norm at the last drained step "
            "(--health_interval; layer names match the roofline "
            "attribution regions)")
        p_gauge = gauge(
            "health_param_norm",
            "per-layer L2 parameter norm at the last drained step")
        u_gauge = gauge(
            "health_update_ratio",
            "per-layer update ratio (L2 ||delta w|| / ||w||) at the "
            "last drained step — the learning-rate health signal")
        u_hist = histogram(
            "health_update_ratio_hist",
            "distribution of drained per-layer update ratios",
            buckets=(1e-8, 1e-7, 1e-6, 1e-5, 1e-4, 1e-3, 1e-2, 0.1,
                     0.5, 1.0))
        nf_ctr = counter(
            "health_nonfinite_steps_total",
            "steps whose per-layer gradients contained inf/nan, by "
            "layer; benign=true = the update was skipped by dynamic "
            "loss scaling (no alert), benign=false = the update "
            "applied (pathological, alerts)")
        # the norm gauges keep their last FINITE reading (a NaN value
        # would poison the strict-JSON metrics sink) — this 0/1 flag is
        # the live divergence state a dashboard overlays on them
        nf_flag = gauge(
            "health_layer_nonfinite",
            "1 while the layer's gradient norm at the last drain was "
            "inf/nan (its health_grad_norm gauge then holds the last "
            "finite reading), else 0")
        for i, name in enumerate(self.layers):
            gn = math.sqrt(grad_sq[i]) if grad_sq[i] >= 0 else \
                float("nan")
            pn = math.sqrt(param_sq[i]) if param_sq[i] >= 0 else \
                float("nan")
            un = math.sqrt(update_sq[i]) if update_sq[i] >= 0 else \
                float("nan")
            ratio = un / pn if pn and math.isfinite(un) \
                and math.isfinite(pn) else (0.0 if math.isfinite(un)
                                            else float("nan"))
            layers[name] = {
                "grad_norm": _finite_or_none(gn),
                "param_norm": _finite_or_none(pn),
                "update_norm": _finite_or_none(un),
                "update_ratio": _finite_or_none(ratio),
                "nonfinite_steps": nf[i],
                "benign_nonfinite_steps": benign[i],
                "first_nonfinite": first[i],
            }
            nf_flag.set(0.0 if math.isfinite(gn) else 1.0, layer=name)
            if math.isfinite(gn):
                g_gauge.set(gn, layer=name)
            if math.isfinite(pn):
                p_gauge.set(pn, layer=name)
            if math.isfinite(ratio):
                u_gauge.set(ratio, layer=name)
                u_hist.observe(ratio)
            if nf[i]:
                nf_ctr.inc(nf[i], layer=name, benign="false")
            if benign[i]:
                nf_ctr.inc(benign[i], layer=name, benign="true")
        counter("health_drains_total",
                "health-accumulator drains (every --health_interval "
                "steps and at pass boundaries)").inc()
        if loss is not None and math.isfinite(loss):
            histogram("health_loss",
                      "training loss at each health drain",
                      buckets=LOSS_BUCKETS).observe(loss)
        report = {
            "ts": round(time.time(), 3),
            "steps": steps,
            "base_step": self._base_step,
            "interval": self.interval,
            "loss": _finite_or_none(loss) if loss is not None else None,
            "layers": layers,
        }
        report["alerts"] = self.monitor.observe(report, report["loss"])
        # the structured alerts above are warn-once NEW firings; the
        # /health body must also show an ONGOING incident one drain
        # later, so the standing conditions and the recent log ride
        # along (the README "recent alerts" contract)
        report["active"] = [{"kind": k, "layer": l}
                            for k, l in self.monitor.active_conditions()]
        report["recent_alerts"] = self.monitor.recent_alerts()[-5:]
        self._base_step += steps
        self._since_drain = 0
        self.state = init_state(len(self.layers))
        if place is not None:
            self.state = place(self.state)
        publish_report(report, self.monitor)
        return report

    def span_summary(self, report: Dict[str, Any]) -> Dict[str, Any]:
        """Compact drain summary for ``train_step`` span attributes."""
        norms = [(r["grad_norm"], l) for l, r in report["layers"].items()
                 if r["grad_norm"] is not None]
        out: Dict[str, Any] = {"health_drained_steps": report["steps"]}
        if norms:
            mx = max(norms)
            out["health_grad_norm_max"] = round(mx[0], 6)
            out["health_grad_norm_max_layer"] = mx[1]
        if report["alerts"]:
            out["health_alerts"] = ",".join(
                f"{a['kind']}:{a['layer']}" for a in report["alerts"])
        return out


# --------------------------------------------------- process-wide view
_latest_lock = named_lock("observe.health.latest")
_latest: Optional[Dict[str, Any]] = None
_latest_monitor: Optional[HealthMonitor] = None


def publish_report(report: Dict[str, Any],
                   monitor: Optional[HealthMonitor] = None) -> None:
    """Stash the most recent drained report (plus its monitor) for the
    ``/health`` endpoint and the ``/healthz`` degraded summary."""
    global _latest, _latest_monitor
    with _latest_lock:
        _latest = report
        if monitor is not None:
            _latest_monitor = monitor


def latest_report() -> Optional[Dict[str, Any]]:
    with _latest_lock:
        return _latest


def status_summary() -> Dict[str, Any]:
    """Small health digest for ``/healthz``: alive processes stay 200
    — alerts degrade the *detail*, never the liveness verdict.
    ``status`` keys on the conditions STANDING at the last drain, so a
    run that recovered from a transient incident reports ``ok`` again
    (the incident stays visible in ``last_alerts``)."""
    with _latest_lock:
        report, monitor = _latest, _latest_monitor
    alerts = monitor.recent_alerts() if monitor is not None else []
    active = monitor.active_conditions() if monitor is not None else []
    return {
        "status": "degraded" if active else "ok",
        "active": [{"kind": k, "layer": l} for k, l in active],
        "alerts_total": len(alerts),
        "last_alerts": alerts[-5:],
        "last_drain_ts": report["ts"] if report else None,
        "drained_steps": report["base_step"] + report["steps"]
        if report else 0,
    }


def reset() -> None:
    """Drop the process-wide latest report/monitor (tests)."""
    global _latest, _latest_monitor
    with _latest_lock:
        _latest = None
        _latest_monitor = None

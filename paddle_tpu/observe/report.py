"""Metrics export: JSONL sink + Prometheus text dump.

One export path for BOTH telemetry families: the typed registry
(:mod:`paddle_tpu.observe.metrics`) and the ``StatSet`` wall-timer table
(:mod:`paddle_tpu.utils.stat` — the reference's ``Stat.h`` RAII timers).
A :class:`MetricsReporter` snapshots them together:

- **JSONL** (``--metrics_jsonl PATH``): one self-describing line per
  flush interval — ``{"ts", "seq", "flags", "metrics": [...],
  "timers": [...]}`` — append-only, so a crash loses at most the last
  interval and any log shipper can tail it;
- **Prometheus text** (:meth:`MetricsReporter.prometheus_text`): the
  standard exposition format, rendered on demand (wire it behind any
  HTTP handler; no server is bundled — zero-dependency rule).

:func:`start_from_flags` is the one call subsystem entry points make
(trainer, bench, CLI): idempotent, starts the global background reporter
iff ``--metrics_jsonl`` is set.  :func:`active` tells instrumentation
whether a sink is attached — callers use it to gate work that is NOT
near-zero-cost, e.g. the trainer's ``block_until_ready`` step fencing
that the host/device time split needs.
"""

from __future__ import annotations

import atexit
import json
import threading
import time
from typing import Any, Dict, List, Optional

from ..analysis.lockorder import named_lock
from .metrics import REGISTRY, MetricsRegistry


def _timer_snapshot(stat) -> List[Dict[str, Any]]:
    """StatSet → list of per-timer dicts (lock-consistent reads)."""
    if stat is None:
        return []
    snap = stat.snapshot()
    return [snap[name] for name in sorted(snap)]


class MetricsReporter:
    """Periodic snapshot writer over (registry, stat-timer) state.

    With ``fleet_addr`` set the reporter additionally drives a
    :class:`paddle_tpu.observe.fleet.FleetPusher` from the same
    background thread: each interval pushes one self-describing frame
    (metrics + recent spans + health digest) to the aggregator, and
    :meth:`stop` sends a final going-down frame.  The pusher degrades
    independently of the JSONL sink (a dead aggregator never wedges
    the trainer, a dead disk never stops the push) and adds NO thread
    beyond the reporter's own."""

    def __init__(self, path: Optional[str] = None,
                 interval_s: float = 10.0,
                 registry: Optional[MetricsRegistry] = None,
                 stat: Any = "global",
                 fleet_addr: Optional[str] = None):
        if stat == "global":
            from ..utils.stat import global_stat
            stat = global_stat
        self.path = path
        self.interval_s = interval_s
        self.registry = REGISTRY if registry is None else registry
        self.stat = stat
        self.fleet = None
        if fleet_addr:
            from .fleet import FleetPusher

            try:
                self.fleet = FleetPusher(
                    fleet_addr, interval_s=interval_s,
                    registry=self.registry, stat=self.stat,
                    jsonl_degraded=lambda: self.degraded
                    and bool(self.path))
            except ValueError as e:
                # telemetry never kills: a typo'd --fleet_addr warns
                # (same contract as a typo'd --metrics_jsonl path) and
                # the run proceeds without a push client
                from ..utils.logger import get_logger, warn_once

                warn_once(
                    f"fleet_addr_invalid:{fleet_addr}",
                    "--fleet_addr %r is not usable (%s); the fleet "
                    "push client is OFF for this run", fleet_addr, e,
                    logger=get_logger("observe"))
        # a sink that cannot be written is DEGRADED: snapshots are being
        # dropped, so active() must stop claiming someone is listening —
        # otherwise the trainer keeps paying block_until_ready step
        # fencing for telemetry that never lands.  A later successful
        # flush (path fixed, disk freed) clears the state.
        self.degraded = False
        self._seq = 0
        self._lock = named_lock("observe.reporter")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------ snapshot
    def snapshot_line(self) -> Dict[str, Any]:
        """One self-describing export record (the JSONL line body)."""
        line = {
            "ts": round(time.time(), 3),
            "seq": self._seq,
            "metrics": self.registry.snapshot(),
            "timers": _timer_snapshot(self.stat),
        }
        return line

    def flush(self) -> Optional[Dict[str, Any]]:
        """Append one snapshot line to the sink; returns the record
        (None when no path is configured)."""
        if not self.path:
            return None
        with self._lock:
            line = self.snapshot_line()
            self._seq += 1
            try:
                with open(self.path, "a") as f:
                    f.write(json.dumps(line) + "\n")
            except Exception as e:   # noqa: BLE001 — mark + re-raise:
                self.degraded = True  # the loop warns-once, direct
                self._warn_flush_failure(e)  # callers see the error
                raise
            self.degraded = False
        return line

    # ---------------------------------------------------------- prometheus
    def prometheus_text(self) -> str:
        """Registry metrics + timer table in exposition format.  Timers
        render as a summary-style family (``_count``/``_sum`` plus
        ``_max``/``_min`` gauges) so one scrape covers both worlds."""
        out = [self.registry.prometheus_text()]
        timers = _timer_snapshot(self.stat)
        if timers:
            out.append("# HELP paddle_tpu_timer_seconds named wall "
                       "timers (StatSet)\n")
            out.append("# TYPE paddle_tpu_timer_seconds summary\n")
            for t in timers:
                lbl = '{name="%s"}' % t["name"]
                out.append(
                    f"paddle_tpu_timer_seconds_count{lbl} {t['count']}\n"
                    f"paddle_tpu_timer_seconds_sum{lbl} {t['total']}\n"
                    f"paddle_tpu_timer_seconds_max{lbl} {t['max']}\n"
                    f"paddle_tpu_timer_seconds_min{lbl} {t['min']}\n")
        return "".join(out)

    # ----------------------------------------------------------- lifecycle
    def start(self) -> "MetricsReporter":
        """Start the background flush thread (daemon; one per reporter)."""
        if self._thread is not None:
            return self
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.interval_s):
                self._evaluate_slo()
                try:
                    self.flush()
                except Exception as e:  # noqa: BLE001 — telemetry never
                    # kills (or silently abandons) the process it
                    # observes: an unwritable sink or a non-JSON value
                    # is reported once, then the loop keeps retrying
                    self._warn_flush_failure(e)
                if self.fleet is not None:
                    # never raises (degrade/backoff inside); honors the
                    # pusher's own backoff window across intervals
                    self.fleet.maybe_push()

        self._thread = threading.Thread(
            target=loop, name="ptpu-metrics-reporter", daemon=True)
        self._thread.start()
        return self

    def _evaluate_slo(self) -> None:
        """One SLO evaluation pass BEFORE the flush, so the verdicts
        (and the ``slo_status``/``slo_burn_rate`` gauges) ride this
        interval's JSONL line and fleet frame.  ``sys.modules`` probe:
        an engine-less process (``--slo`` unset) pays one dict lookup
        and nothing else."""
        import sys

        smod = sys.modules.get("paddle_tpu.observe.slo")
        eng = smod.active_engine() if smod is not None else None
        if eng is not None:
            eng.evaluate()  # never raises (telemetry never kills)

    def _warn_flush_failure(self, e: Exception) -> None:
        from ..utils.logger import get_logger, warn_once

        warn_once(
            f"metrics_flush_failed:{self.path}",
            "metrics flush to %r failed (%s: %s); telemetry for this "
            "sink is being DROPPED — fix the path/payload (reported "
            "once)", self.path, type(e).__name__, e,
            logger=get_logger("observe"))

    def stop(self) -> None:
        """Stop the flush thread and write one final snapshot; with a
        fleet pusher attached, also push the final going-down frame so
        the aggregator's rollup records a CLEAN shutdown (vs a
        SIGKILL, which goes 'missing' via staleness)."""
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)
        try:
            self.flush()
        except Exception as e:  # noqa: BLE001 — see loop()
            self._warn_flush_failure(e)
        if self.fleet is not None:
            # direct push (not maybe_push): the goodbye frame ignores
            # the backoff window — it is the last chance to say so
            self.fleet.push(going_down=True)


# --------------------------------------------------------------- global
_global: Optional[MetricsReporter] = None
_global_lock = named_lock("observe.reporter.global")


def start_from_flags() -> Optional[MetricsReporter]:
    """Start the process-wide reporter from ``--metrics_jsonl`` /
    ``--fleet_addr`` / ``--metrics_interval_s`` / ``--slo``.
    Idempotent; returns the reporter (None when no sink or SLO engine
    is configured — no thread starts, no work happens).  ``--slo``
    alone starts the reporter too: the engine needs the interval
    thread to evaluate on even when nothing is exported.  Every
    long-running entry point calls this once (``Trainer.train``,
    ``bench.main``, the CLI)."""
    global _global
    from ..utils import FLAGS

    path = FLAGS.get("metrics_jsonl")
    fleet_addr = FLAGS.get("fleet_addr")
    slo_spec = str(FLAGS.get("slo") or "").strip()
    if not path and not fleet_addr and not slo_spec:
        return _global
    if slo_spec:
        # import (not sys.modules probe): --slo set IS the opt-in that
        # brings the engine into the process; every later surface
        # probes sys.modules and now finds it
        from . import slo as _slo

        _slo.configure_from_flags()
    with _global_lock:
        if _global is None:
            _global = MetricsReporter(
                path=path or None,
                interval_s=FLAGS.get("metrics_interval_s"),
                fleet_addr=fleet_addr or None)
            _global.start()
            atexit.register(stop_global)
            # probe the sinks NOW: a typo'd path (or a dead
            # aggregator) warns at startup, not after a multi-hour run
            # produced zero telemetry — and the first fleet push IS
            # the registration, so /fleet/topology shows this process
            # immediately instead of one interval late
            if path:
                try:
                    _global.flush()
                except Exception as e:  # noqa: BLE001
                    _global._warn_flush_failure(e)
            if _global.fleet is not None:
                _global.fleet.maybe_push()
    return _global


def attach(path: str, interval_s: float = 10.0,
           registry: Optional[MetricsRegistry] = None,
           stat: Any = "global") -> MetricsReporter:
    """Programmatic sink attach (tests, notebooks): replaces the global
    reporter."""
    global _global
    with _global_lock:
        if _global is not None:
            _global.stop()
        _global = MetricsReporter(path, interval_s, registry, stat)
        _global.start()
    return _global


def stop_global() -> None:
    global _global
    with _global_lock:
        r, _global = _global, None
    if r is not None:
        r.stop()


def active() -> bool:
    """True iff a sink is attached AND delivering — instrumentation
    whose cost is NOT negligible (device fencing for the host/device
    split) keys on this, so telemetry is effectively free when nobody
    is listening.  The fleet push client counts as a sink: a trainer
    started with only ``--fleet_addr`` IS being listened to, and the
    fenced headline metrics (samples/sec, the time split) are exactly
    what the aggregator's watch console renders.  A degraded sink
    (every flush/push failing — bad path, full disk, dead aggregator)
    reports False: nobody IS listening, so the hot loop must not keep
    paying for snapshots that are being dropped."""
    r = _global
    if r is None:
        return False
    if r.path and not r.degraded:
        return True
    return r.fleet is not None and not r.fleet.degraded


def prometheus_dump() -> str:
    """On-demand Prometheus text over the default registry + timers
    (works with or without a running reporter)."""
    # benign racy read: writes are _global_lock-guarded; a scrape
    # racing stop_global reads the old reporter or a fresh throwaway
    # ptpu: lint-ok[PT-RACE] atomic reference read, writes lock-guarded
    r = _global or MetricsReporter()
    return r.prometheus_text()

"""Per-region roofline/MFU attribution over compiled XLA executables.

``bench.py`` has carried a whole-step ``hbm_gb_per_step`` scalar since
round 7 and a hand-computed MFU per workload since round 1 — one number
per step, no way to see *which* layer is the bottleneck.  This module is
the attribution half of the performance observatory:

- :func:`analyze_trainer_step` lowers the trainer's compiled train step,
  parses the **optimized HLO text** (``Compiled.as_text()``) and breaks
  FLOPs / HBM bytes down **per fused region**, keyed back to network
  layer names through the ``jax.named_scope`` each layer executes under
  (``layers/network.py`` threads the layer name into XLA's ``op_name``
  metadata; autodiff wraps it as ``jvp(name)`` / ``transpose(jvp(name))``
  so forward and backward cost of one layer land in one region);
- each region gets a **roofline verdict** — compute- vs memory-bound
  against the detected chip peaks (:func:`detect_peaks`), with
  arithmetic intensity and a peak-bound time estimate;
- :func:`mfu` / :func:`step_mfu` are the ONE model-level MFU
  implementation every bench row stamps (replacing the per-workload
  hand formulas): measured-step FLOPs over ``time x peak x chips``.

Counting conventions (deliberately XLA-compatible so the per-region
costs reconcile against ``Compiled.cost_analysis()``):

- every computation is counted ONCE (``total_flops`` matches XLA's
  ``flops``, which does NOT multiply a ``while`` body by its trip
  count); the *executed* cost — what the roofline and MFU use — is the
  trip-count-amortized ``flops_per_step`` (trip counts recovered from
  the loop-condition ``compare(lt, constant)`` pattern ``lax.scan``
  emits);
- transcendentals (tanh/exp/...) are tracked separately (``trans``),
  again matching XLA's split, but count as work for roofline/MFU;
- HBM bytes are charged at **kernel granularity**: instructions inside
  a fusion/called computation touch VMEM/registers, not HBM, so only
  top-level (entry / loop-body) instructions and fusion/call sites
  contribute operand+result bytes — the same model behind the round-7
  fused-kernel traffic arithmetic;
- ``custom-call`` regions (the Pallas kernels) are **opaque**: XLA
  reports zero FLOPs for them and so does this parser (bytes are still
  charged from the call-site shapes).  A step containing opaque regions
  reports them, and :func:`step_mfu` falls back to the caller's
  analytic FLOP count so the MFU stays honest instead of silently
  reading near-zero.

jax is imported lazily (function scope) — the parser itself is pure
text and testable without a backend; the zero-dependency rule of
:mod:`paddle_tpu.observe` holds for module import.
"""

from __future__ import annotations

import json
import re
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

# ------------------------------------------------------------- shapes
_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
    "c64": 8, "c128": 16, "s4": 1, "u4": 1, "f8e4m3fn": 1, "f8e5m2": 1,
}

_SHAPE_RE = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\]")


def _shape_stats(text: str) -> Tuple[int, int]:
    """(total bytes, total elements) over every array shape token in
    ``text`` — tuples contribute the sum of their elements."""
    bytes_, elems = 0, 0
    for dtype, dims in _SHAPE_RE.findall(text):
        size = _DTYPE_BYTES.get(dtype)
        if size is None:
            continue                      # token/opaque types
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        elems += n
        bytes_ += n * size
    return bytes_, elems


def _split_top_level(s: str) -> List[str]:
    """Split an operand list on top-level commas (brackets tracked)."""
    out, depth, cur = [], 0, []
    for ch in s:
        if ch in "([{":
            depth += 1
        elif ch in ")]}":
            depth -= 1
        if ch == "," and depth == 0:
            out.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    if cur:
        out.append("".join(cur))
    return out


# -------------------------------------------------------------- parser
# A computation header is "<name> (params) -> result {": the name is
# followed directly by its parameter list (instructions read "<name> =
# ..."), and tuple-typed parameters nest parens — (p: (s32[], f32[8]))
# — so the params cannot be regexed away; matching up to the first "("
# and requiring the "-> ... {" tail is enough to tell headers apart.
_COMP_NAME_RE = re.compile(r"^%?([\w.\-]+)\s*\(")


def _comp_header(line: str) -> Optional[Tuple[str, bool]]:
    """(name, is_entry) when ``line`` is a computation header."""
    s = line.strip()
    if not s.endswith("{") or "->" not in s:
        return None
    is_entry = s.startswith("ENTRY ")
    if is_entry:
        s = s[len("ENTRY "):].lstrip()
    m = _COMP_NAME_RE.match(s)
    return (m.group(1), is_entry) if m else None
_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?([\w.\-]+)\s*=\s*(.+?)\s+([\w\-]+)\(")
_OP_NAME_RE = re.compile(r'op_name="([^"]*)"')
_ATTR_RES = {
    "calls": re.compile(r"calls=%?([\w.\-]+)"),
    "to_apply": re.compile(r"to_apply=%?([\w.\-]+)"),
    "body": re.compile(r"body=%?([\w.\-]+)"),
    "condition": re.compile(r"condition=%?([\w.\-]+)"),
    "lhs_contracting": re.compile(r"lhs_contracting_dims=\{([0-9,]*)\}"),
    "rhs_contracting": re.compile(r"rhs_contracting_dims=\{([0-9,]*)\}"),
    "lhs_batch": re.compile(r"lhs_batch_dims=\{([0-9,]*)\}"),
    "rhs_batch": re.compile(r"rhs_batch_dims=\{([0-9,]*)\}"),
    "custom_call_target": re.compile(r'custom_call_target="([^"]*)"'),
    "feature_group_count": re.compile(r"feature_group_count=(\d+)"),
    "dim_labels": re.compile(r"dim_labels=(\S+?)(?:,|\s|$)"),
    "branches": re.compile(r"branch_computations=\{([^}]*)\}"),
}

#: Opcodes that move/alias data without arithmetic (FLOPs 0 — matches
#: XLA's convention closely enough for the reconciliation tolerance).
_ZERO_FLOP = frozenset((
    "parameter", "constant", "get-tuple-element", "tuple", "bitcast",
    "copy", "copy-start", "copy-done", "reshape", "transpose",
    "broadcast", "slice", "dynamic-slice", "dynamic-update-slice",
    "concatenate", "pad", "reverse", "iota", "convert", "gather",
    "after-all", "optimization-barrier", "partition-id", "replica-id",
    "rng-bit-generator", "rng", "infeed", "outfeed", "domain",
    "custom-call", "call", "fusion", "while", "conditional",
    "all-gather", "all-reduce", "reduce-scatter", "collective-permute",
    "send", "recv", "bitcast-convert", "real", "imag", "sort",
))

#: Transcendental opcodes — XLA counts these in its separate
#: ``transcendentals`` bucket, not ``flops``.
_TRANS_OPS = frozenset((
    "tanh", "exp", "expm1", "log", "log1p", "logistic", "sqrt", "rsqrt",
    "cbrt", "sine", "cosine", "tan", "atan2", "power", "erf",
))


class _Instr:
    __slots__ = ("name", "opcode", "result", "operands", "line",
                 "op_name", "attrs")

    def __init__(self, name, opcode, result, operands, line, op_name,
                 attrs):
        self.name = name
        self.opcode = opcode
        self.result = result          # result shape text
        self.operands = operands      # operand list text (inside parens)
        self.line = line              # full line (attribute regexes)
        self.op_name = op_name
        self.attrs = attrs            # parsed attribute dict


class _Computation:
    __slots__ = ("name", "is_entry", "instrs")

    def __init__(self, name: str, is_entry: bool):
        self.name = name
        self.is_entry = is_entry
        self.instrs: List[_Instr] = []


def _operand_segment(line: str) -> str:
    """Text inside the instruction's top-level operand parens."""
    i = line.find("(")
    if i < 0:
        return ""
    depth = 0
    for j in range(i, len(line)):
        if line[j] == "(":
            depth += 1
        elif line[j] == ")":
            depth -= 1
            if depth == 0:
                return line[i + 1:j]
    return line[i + 1:]


def parse_hlo(text: str) -> Dict[str, _Computation]:
    """Optimized HLO module text → ``{computation name: _Computation}``
    (the entry computation has ``is_entry`` set)."""
    comps: Dict[str, _Computation] = {}
    cur: Optional[_Computation] = None
    for raw in text.splitlines():
        line = raw.rstrip()
        if cur is None:
            head = _comp_header(line)
            if head is not None:
                cur = _Computation(*head)
                comps[cur.name] = cur
            continue
        if line.strip() == "}":
            cur = None
            continue
        m = _INSTR_RE.match(line)
        if not m:
            continue
        name, result, opcode = m.group(1), m.group(2), m.group(3)
        attrs: Dict[str, Any] = {}
        for key, rx in _ATTR_RES.items():
            am = rx.search(line)
            if am:
                attrs[key] = am.group(1)
        opn = _OP_NAME_RE.search(line)
        cur.instrs.append(_Instr(
            name, opcode, result, _operand_segment(line), line,
            opn.group(1) if opn else "", attrs))
    return comps


# --------------------------------------------------------- cost of one
def _dims_prod(shape_text: str, dims: Sequence[int]) -> int:
    m = _SHAPE_RE.search(shape_text)
    if not m:
        return 1
    sizes = [int(d) for d in m.group(2).split(",") if d]
    out = 1
    for d in dims:
        if 0 <= d < len(sizes):
            out *= sizes[d]
    return out


def _parse_int_list(s: str) -> List[int]:
    return [int(x) for x in s.split(",") if x.strip()]


def _instr_flops(instr: _Instr) -> Tuple[float, float]:
    """(flops, transcendentals) of one instruction, XLA-style."""
    out_bytes, out_elems = _shape_stats(instr.result)
    op = instr.opcode
    if op == "dot":
        operands = _split_top_level(instr.operands)
        if len(operands) < 2:
            return 0.0, 0.0
        lhs, rhs = operands[0], operands[1]
        _, lhs_elems = _shape_stats(lhs)
        rcd = _parse_int_list(instr.attrs.get("rhs_contracting", ""))
        rbd = _parse_int_list(instr.attrs.get("rhs_batch", ""))
        _, rhs_elems = _shape_stats(rhs)
        shared = _dims_prod(rhs, rcd) * _dims_prod(rhs, rbd)
        return 2.0 * lhs_elems * (rhs_elems / max(shared, 1)), 0.0
    if op == "convolution":
        operands = _split_top_level(instr.operands)
        if len(operands) < 2:
            return 0.0, 0.0
        rhs = operands[1]
        _, k_elems = _shape_stats(rhs)
        # dim_labels like b01f_01io->b01f: 'o' indexes output features
        labels = instr.attrs.get("dim_labels", "")
        kernel_labels = labels.split("_")[1].split("-")[0] \
            if "_" in labels else ""
        o_dim = kernel_labels.find("o")
        m = _SHAPE_RE.search(rhs)
        o = 1
        if m and o_dim >= 0:
            sizes = [int(d) for d in m.group(2).split(",") if d]
            if o_dim < len(sizes):
                o = sizes[o_dim]
        groups = int(instr.attrs.get("feature_group_count", 1) or 1)
        taps = k_elems / max(o, 1) / max(groups, 1)
        return 2.0 * out_elems * taps, 0.0
    if op in _TRANS_OPS:
        return 0.0, float(out_elems)
    if op in _ZERO_FLOP:
        return 0.0, 0.0
    if op in ("reduce", "reduce-window", "select-and-scatter", "scatter",
              "map"):
        _, in_elems = _shape_stats(instr.operands)
        return float(max(in_elems, out_elems)), 0.0
    # default: one op per output element (add/mul/select/compare/...)
    return float(out_elems), 0.0


def _while_trip_count(instr: _Instr,
                      comps: Dict[str, _Computation]) -> int:
    """Recover a static trip count from the ``lax.scan`` loop shape:
    the condition computation's ROOT is ``compare(counter, constant)``
    direction=LT and the bound constant is defined in the condition.
    Returns 1 when the pattern doesn't match (honest under-estimate)."""
    cond = comps.get(instr.attrs.get("condition", ""))
    if cond is None:
        return 1
    root = cond.instrs[-1] if cond.instrs else None
    if root is None or root.opcode != "compare" \
            or "direction=LT" not in root.line:
        return 1
    consts = {}
    for i in cond.instrs:
        if i.opcode == "constant":
            m = re.search(r"constant\((-?\d+)\)", i.line)
            if m:
                consts[i.name] = int(m.group(1))
    for name in re.findall(r"%([\w.\-]+)", root.operands):
        if name in consts and consts[name] > 0:
            return consts[name]
    return 1


# ------------------------------------------------------------- regions
_WRAP_RE = re.compile(r"([^()]+)\((.*)\)$")


def _region_of(op_name: str, known: frozenset) -> Tuple[str, bool]:
    """(region, is_backward) for an ``op_name`` metadata path: the
    innermost path component whose unwrapped token (``transpose(jvp(x))``
    → ``x``) is a known region name; backward iff an autodiff
    ``transpose(...)`` wrapper encloses it."""
    region, bwd = "_unattributed", False
    for comp in op_name.split("/"):
        tokens = []
        cur = comp
        while True:
            m = _WRAP_RE.match(cur)
            if not m:
                tokens.append(cur)
                break
            tokens.append(m.group(1))
            cur = m.group(2)
        hit = None
        for t in tokens:
            if t in known:
                hit = t
        if hit is not None:
            region = hit
            bwd = "transpose" in tokens[:-1]
    return region, bwd


_OPERAND_NAME_RE = re.compile(r"%([\w.\-]+)")


def _streaming_discount(instr: _Instr,
                        comps: Dict[str, _Computation]) -> float:
    """HBM bytes to discount for slice-granularity access patterns —
    the shapes every ``lax.scan`` body reads/writes its buffers
    through.  XLA aliases a ``dynamic-update-slice`` result to the
    updated operand and streams only the slice, and a
    ``dynamic-slice`` reads only the slice, so charging whole buffers
    as read+written per trip overstates a 100-trip scan's traffic
    ~buffer/slice-fold (XLA's own ``bytes accessed`` counts slices).
    Covers the bare opcodes and fusions that consume a parameter via
    ``dynamic-slice`` or root in a ``dynamic-update-slice``."""
    op = instr.opcode
    if op == "dynamic-update-slice":
        res_bytes, _ = _shape_stats(instr.result)
        ops = _split_top_level(instr.operands)
        upd = _shape_stats(ops[1])[0] if len(ops) > 1 else 0
        return max(2.0 * (res_bytes - upd), 0.0)
    if op == "dynamic-slice":
        ops = _split_top_level(instr.operands)
        src = _shape_stats(ops[0])[0] if ops else 0
        res_bytes, _ = _shape_stats(instr.result)
        return max(float(src - res_bytes), 0.0)
    if op != "fusion":
        return 0.0
    callee = comps.get(instr.attrs.get("calls")
                       or instr.attrs.get("to_apply", ""))
    if callee is None:
        return 0.0
    discount = 0.0
    params: Dict[str, int] = {}
    for i in callee.instrs:
        if i.opcode == "parameter":
            params[i.name] = _shape_stats(i.result)[0]
    for i in callee.instrs:
        if i.opcode == "dynamic-slice":
            ops = _split_top_level(i.operands)
            m = _OPERAND_NAME_RE.search(ops[0]) if ops else None
            if m and m.group(1) in params:
                discount += max(params.pop(m.group(1))
                                - _shape_stats(i.result)[0], 0)
    if callee.instrs and callee.instrs[-1].opcode \
            == "dynamic-update-slice":
        res_bytes, _ = _shape_stats(instr.result)
        ops = _split_top_level(callee.instrs[-1].operands)
        upd = _shape_stats(ops[1])[0] if len(ops) > 1 else 0
        discount += max(2.0 * (res_bytes - upd), 0.0)
    return discount


def attribute(text: str, known: Iterable[str] = ()) -> Dict[str, Any]:
    """Parse + attribute one optimized HLO module.

    Returns ``{"regions": {name: {...}}, "total_flops",
    "total_trans", "total_bytes", "flops_per_step", "bytes_per_step",
    "opaque_calls": [target names], "while_trips": {instr: n}}`` —
    totals follow the XLA count-each-computation-once convention,
    ``*_per_step`` amortize loop bodies by their recovered trip count.
    """
    comps = parse_hlo(text)
    known = frozenset(known)
    entry = next((c for c in comps.values() if c.is_entry), None)
    if entry is None:
        return {"regions": {}, "total_flops": 0.0, "total_trans": 0.0,
                "total_bytes": 0.0, "flops_per_step": 0.0,
                "bytes_per_step": 0.0, "opaque_calls": [],
                "while_trips": {}}

    # computation roles + executed-count multipliers, propagated from
    # the entry (HLO computations cannot recurse, so this terminates):
    # kernel-level computations (entry, while body/cond, conditional
    # branches) charge HBM bytes; fusion/to_apply callees do not.
    mult: Dict[str, float] = {c: 0.0 for c in comps}
    kernel_level = {entry.name}
    mult[entry.name] = 1.0
    # region fallback per computation: a loop body's carry plumbing
    # (copies, slices, tuple shuffles) carries no layer op_name of its
    # own, but the `while` that runs it usually does — an lstm layer's
    # scan overhead should land in THAT layer's region, not in
    # _unattributed
    comp_fallback: Dict[str, str] = {entry.name: "_unattributed"}
    # second-chance fallback: XLA's loop-optimization passes (double
    # buffering, "wide" region cloning) synthesize `while` instructions
    # with NO op_name of their own, so the site tells us nothing — but
    # the body's surviving instructions still carry their scopes.  Each
    # computation votes with its resolvable op_names; a callee reached
    # through an unattributed site inherits its own majority region
    # (the paged decode kernel's per-page DMA loop is the motivating
    # case: 512 trips of pool-carry copies must land in attn_decode,
    # not smear the report with phantom _unattributed terabytes).
    dominant: Dict[str, str] = {}
    for comp in comps.values():
        votes: Dict[str, float] = {}
        for instr in comp.instrs:
            region, _ = _region_of(instr.op_name, known)
            if region != "_unattributed":
                votes[region] = votes.get(region, 0.0) + 1.0
        if votes:
            dominant[comp.name] = max(votes, key=lambda k: votes[k])
    while_trips: Dict[str, int] = {}
    stack = [entry.name]
    seen_edges = set()
    while stack:
        cname = stack.pop()
        comp = comps.get(cname)
        if comp is None:
            continue
        for instr in comp.instrs:
            callees: List[Tuple[str, float, bool]] = []
            if instr.opcode == "while":
                trips = _while_trip_count(instr, comps)
                while_trips[instr.name] = trips
                for key in ("body", "condition"):
                    tgt = instr.attrs.get(key)
                    if tgt:
                        callees.append((tgt, float(trips), True))
            elif instr.opcode == "conditional":
                for tgt in re.findall(r"%([\w.\-]+)",
                                      instr.attrs.get("branches", "")):
                    callees.append((tgt, 1.0, True))
            else:
                for key in ("calls", "to_apply"):
                    tgt = instr.attrs.get(key)
                    if tgt:
                        callees.append((tgt, 1.0, False))
            site_region, _ = _region_of(instr.op_name, known)
            if site_region == "_unattributed":
                site_region = comp_fallback.get(cname, "_unattributed")
            for tgt, factor, kernel in callees:
                if tgt not in comps:
                    continue
                if kernel:
                    kernel_level.add(tgt)
                comp_fallback.setdefault(
                    tgt, site_region if site_region != "_unattributed"
                    else dominant.get(tgt, "_unattributed"))
                edge = (cname, tgt)
                mult[tgt] = mult.get(tgt, 0.0) \
                    + mult.get(cname, 1.0) * factor
                if edge not in seen_edges:
                    seen_edges.add(edge)
                    stack.append(tgt)

    regions: Dict[str, Dict[str, float]] = {}
    totals = {"flops": 0.0, "trans": 0.0, "bytes": 0.0}
    per_step = {"flops": 0.0, "bytes": 0.0}
    opaque: List[str] = []

    def bucket(name: str) -> Dict[str, float]:
        r = regions.get(name)
        if r is None:
            r = regions[name] = {
                "flops": 0.0, "trans": 0.0, "bytes": 0.0,
                "flops_once": 0.0, "bytes_once": 0.0,
                "bwd_flops": 0.0, "instrs": 0.0, "opaque": 0.0}
        return r

    for comp in comps.values():
        m = mult.get(comp.name, 0.0)
        if m == 0.0 and not comp.is_entry:
            continue                      # dead computation
        charge_bytes = comp.name in kernel_level
        for instr in comp.instrs:
            flops, trans = _instr_flops(instr)
            # control-flow sites (while/conditional/call) charge no
            # bytes of their own: their callees are kernel-level and
            # already charged, so the carried-tuple operands here would
            # double-count
            if charge_bytes and instr.opcode not in (
                    "parameter", "constant", "get-tuple-element",
                    "tuple", "bitcast", "while", "conditional", "call"):
                op_bytes, _ = _shape_stats(instr.operands)
                res_bytes, _ = _shape_stats(instr.result)
                ibytes = max(float(op_bytes + res_bytes)
                             - _streaming_discount(instr, comps),
                             0.0)
            else:
                ibytes = 0.0
            region, bwd = _region_of(instr.op_name, known)
            if region == "_unattributed":
                region = comp_fallback.get(comp.name, "_unattributed")
            r = bucket(region)
            r["flops_once"] += flops
            r["bytes_once"] += ibytes
            r["flops"] += flops * m
            r["trans"] += trans * m
            r["bytes"] += ibytes * m
            r["instrs"] += 1
            if bwd:
                r["bwd_flops"] += flops * m
            if instr.opcode == "custom-call":
                r["opaque"] += 1
                opaque.append(instr.attrs.get("custom_call_target", "?"))
            totals["flops"] += flops
            totals["trans"] += trans
            totals["bytes"] += ibytes
            per_step["flops"] += (flops + trans) * m
            per_step["bytes"] += ibytes * m

    return {"regions": regions,
            "total_flops": totals["flops"],
            "total_trans": totals["trans"],
            "total_bytes": totals["bytes"],
            "flops_per_step": per_step["flops"],
            "bytes_per_step": per_step["bytes"],
            "opaque_calls": opaque,
            "while_trips": while_trips}


# ------------------------------------------------------------- roofline
#: device_kind (prefix, lower-cased) → (peak FLOP/s dense bf16-class,
#: HBM bandwidth B/s).  Published chip specs; unknown kinds fall back
#: to the CPU row so the verdicts stay defined everywhere.
_PEAKS_BY_KIND = (
    ("tpu v6", (918e12, 1640e9)),
    ("tpu v5p", (459e12, 2765e9)),
    ("tpu v5e", (197e12, 819e9)),
    ("tpu v5", (197e12, 819e9)),
    ("tpu v4", (275e12, 1228e9)),
    ("tpu v3", (123e12, 900e9)),
    ("tpu v2", (46e12, 700e9)),
    # host CPU: order-of-magnitude figures for a modern many-core box —
    # the verdicts (and the CPU-small baseline lane) only need the
    # ridge point to sit between elementwise (<1 flop/byte) and matmul
    # (tens of flops/byte) intensity
    ("cpu", (2e11, 4e10)),
)


def detect_peaks(device=None) -> Dict[str, Any]:
    """{"flops": peak FLOP/s, "bw": HBM B/s, "ridge": flops/byte,
    "source": device kind} for the attached accelerator.  The
    ``--roofline_peak_flops`` / ``--roofline_peak_gbps`` flags override
    detection (0 = auto)."""
    from ..utils import FLAGS

    kind = "cpu"
    try:
        if device is None:
            import jax
            device = jax.devices()[0]
        kind = str(device.device_kind).lower()
    except Exception as e:  # noqa: BLE001 — peaks resolve backend-less
        from ..utils.logger import get_logger

        get_logger("observe").debug(
            "device-kind detection failed (%s); using CPU peaks", e)
    flops, bw = _PEAKS_BY_KIND[-1][1]
    source = "cpu-default"
    for prefix, peaks in _PEAKS_BY_KIND:
        if kind.startswith(prefix):
            flops, bw = peaks
            source = prefix
            break
    try:
        if float(FLAGS.get("roofline_peak_flops")) > 0:
            flops = float(FLAGS.get("roofline_peak_flops"))
            source = "flag"
        if float(FLAGS.get("roofline_peak_gbps")) > 0:
            bw = float(FLAGS.get("roofline_peak_gbps")) * 1e9
            source = "flag"
    except KeyError:       # flags module not fully initialized (tests)
        pass
    return {"flops": flops, "bw": bw, "ridge": flops / bw,
            "source": source, "device_kind": kind}


def roofline(flops: float, bytes_: float,
             peaks: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Roofline verdict for one region: arithmetic intensity vs the
    ridge point, plus the peak-bound time estimate."""
    peaks = peaks or detect_peaks()
    intensity = flops / max(bytes_, 1.0)
    t_compute = flops / peaks["flops"]
    t_memory = bytes_ / peaks["bw"]
    return {
        "intensity": intensity,
        "bound": "compute" if intensity >= peaks["ridge"] else "memory",
        "time_est_s": max(t_compute, t_memory),
    }


def mfu(flops_per_step: float, seconds_per_step: float,
        devices: int = 1,
        peaks: Optional[Dict[str, Any]] = None) -> float:
    """Model FLOP utilization: executed FLOPs per step over
    ``time x peak x chips`` — THE shared implementation every bench row
    stamps (replaces the per-workload hand arithmetic)."""
    peaks = peaks or detect_peaks()
    denom = max(seconds_per_step, 1e-12) * peaks["flops"] \
        * max(devices, 1)
    return flops_per_step / denom


# ----------------------------------------------------- trainer analysis
def _step_args(trainer, feed):
    """The train step's argument tuple, exactly as ``train_one_batch``
    dispatches it (loss-scale state appended under --precision=bf16,
    the health accumulator appended under --health_interval > 0)."""
    import jax
    import jax.numpy as jnp

    sfeed = trainer._shard_feed(feed)
    return (trainer.params, trainer.opt_state, trainer.buffers, sfeed,
            jax.random.PRNGKey(0), jnp.zeros((), jnp.float32)) \
        + trainer._step_extras()


def _known_regions(network) -> frozenset:
    names = set(network.layers)
    # recurrent-group step layers scope as "<layer>.<group>" (see
    # layers/recurrent_group.py — "@" doesn't survive XLA's op_name
    # sanitizer)
    for gname, grp in getattr(network, "groups", {}).items():
        names.update(f"{n}.{gname}" for n in grp.layers)
    names.add("optimizer")
    # the --health_interval aux path scopes as its own region so its
    # (small) reduction cost is attributed, not smeared over layers
    names.add("health")
    return frozenset(names)


_ANALYSIS_CACHE: Dict[str, Dict[str, Any]] = {}

#: Version stamped on every report this module emits.  v1 = the PR-10
#: unversioned dump; v2 adds ``schema`` + optional ``mfu_est`` and is
#: the first version ``attribution_diff`` treats as its own.  Bump on
#: any region-row field change so two dumps are comparable by machine.
SCHEMA_VERSION = 2

# most recent report produced in this process — the /roofline endpoint
# body (observe/http.py reads it lazily at scrape time)
_latest_report: Optional[Dict[str, Any]] = None


def latest_report() -> Optional[Dict[str, Any]]:
    """The most recent :func:`analyze_trainer_step` report (None before
    the first analysis)."""
    return _latest_report


def analyze_trainer_step(trainer, feed, top: int = 12,
                         peaks: Optional[Dict[str, Any]] = None,
                         cache_key: Optional[str] = None
                         ) -> Optional[Dict[str, Any]]:
    """Attributed cost report of ONE compiled train step.

    Lowers the trainer's jitted step for ``feed`` (hits the jit/persistent
    compile cache — the step was already compiled by the run that wants
    the report), reconciles the parsed per-region costs against XLA's
    ``cost_analysis()`` totals, and renders the per-region roofline.
    Returns None when anything in the stack declines (missing cost
    analysis, exotic backend) — the report is an artifact field, never
    a crash.  ``cache_key`` memoizes per workload: the report is a
    property of the lowering, identical across timing attempts.
    """
    global _latest_report
    if cache_key is not None and cache_key in _ANALYSIS_CACHE:
        _latest_report = _ANALYSIS_CACHE[cache_key]
        return _latest_report
    try:
        # build+compile the step only if the trainer has never stepped:
        # at a pass boundary (--roofline_dump) the step exists, and
        # running a real batch here would advance params/opt state
        # outside the training loop — observability must not train
        if getattr(trainer, "_train_step", None) is None:
            trainer.train_one_batch(feed)
        compiled = trainer._train_step.lower(
            *_step_args(trainer, feed)).compile()
        return _report_from_compiled(
            compiled, _known_regions(trainer.network), top, peaks,
            cache_key)
    except Exception as e:   # noqa: BLE001 — best-effort artifact field
        from ..utils.logger import get_logger, warn_once

        warn_once("costmodel_analyze_failed",
                  "train-step cost attribution unavailable (%s: %s)",
                  type(e).__name__, e, logger=get_logger("observe"))
        return None


def analyze_fn(fn, args: Sequence[Any], known: Iterable[str] = (),
               top: int = 12, peaks: Optional[Dict[str, Any]] = None,
               cache_key: Optional[str] = None
               ) -> Optional[Dict[str, Any]]:
    """Attributed cost report of an arbitrary jitted callable — the
    trainer-free sibling of :func:`analyze_trainer_step` (same report
    dict, same schema), for inference paths like the serving decode
    step where there is no trainer to lower.  ``fn`` is jitted if it
    is not already; ``known`` are the ``jax.named_scope`` names to
    resolve regions against.  Returns None when the stack declines —
    a report is an artifact field, never a crash."""
    global _latest_report
    if cache_key is not None and cache_key in _ANALYSIS_CACHE:
        _latest_report = _ANALYSIS_CACHE[cache_key]
        return _latest_report
    try:
        import jax

        jfn = fn if hasattr(fn, "lower") else jax.jit(fn)
        compiled = jfn.lower(*args).compile()
        return _report_from_compiled(compiled, frozenset(known), top,
                                     peaks, cache_key)
    except Exception as e:   # noqa: BLE001 — best-effort artifact field
        from ..utils.logger import get_logger, warn_once

        warn_once("costmodel_analyze_fn_failed",
                  "fn cost attribution unavailable (%s: %s)",
                  type(e).__name__, e, logger=get_logger("observe"))
        return None


def _report_from_compiled(compiled, known: frozenset, top: int,
                          peaks: Optional[Dict[str, Any]],
                          cache_key: Optional[str]) -> Dict[str, Any]:
    """Shared back half of :func:`analyze_trainer_step` /
    :func:`analyze_fn`: optimized-HLO attribution reconciled against
    ``cost_analysis()``, rendered as the versioned per-region roofline
    report."""
    global _latest_report
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    ca = ca or {}
    report = attribute(compiled.as_text(), known)

    peaks = peaks or detect_peaks()
    xla_flops = float(ca.get("flops", 0.0) or 0.0)
    xla_bytes = float(ca.get("bytes accessed", 0.0) or 0.0)
    rows = []
    for name, r in report["regions"].items():
        work = r["flops"] + r["trans"]
        verdict = roofline(work, r["bytes"], peaks)
        rows.append({
            "region": name,
            "flops": round(work, 1),
            "bytes": round(r["bytes"], 1),
            "bwd_frac": round(r["bwd_flops"] / work, 3) if work else 0.0,
            "opaque": int(r["opaque"]),
            "intensity": round(verdict["intensity"], 4),
            "bound": verdict["bound"],
            # time_est_s keeps full precision until the shares are
            # derived — tiny/CPU regions sit at 1e-8 s, where a fixed
            # decimal rounding collapses every share to zero
            "time_est_s": verdict["time_est_s"],
        })
    rows.sort(key=lambda r: r["time_est_s"], reverse=True)
    total_time_est = sum(r["time_est_s"] for r in rows) or 1.0
    for r in rows:
        r["share"] = round(r["time_est_s"] / total_time_est, 3)
        r["time_est_s"] = float(f"{r['time_est_s']:.4g}")
    out = {
        "schema": SCHEMA_VERSION,
        "regions": rows[:top],
        "regions_elided": max(len(rows) - top, 0),
        "flops_per_step": report["flops_per_step"],
        "bytes_per_step": report["bytes_per_step"],
        "parsed_flops": report["total_flops"],
        "parsed_trans": report["total_trans"],
        "parsed_bytes": report["total_bytes"],
        "xla_flops": xla_flops,
        "xla_bytes": xla_bytes,
        "flop_agreement": round(report["total_flops"] / xla_flops, 3)
        if xla_flops else None,
        "opaque_custom_calls": sorted(set(report["opaque_calls"])),
        "while_trips": report["while_trips"],
        "peaks": {"flops": peaks["flops"], "bw": peaks["bw"],
                  "ridge": round(peaks["ridge"], 2),
                  "source": peaks["source"]},
    }
    if cache_key is not None:
        _ANALYSIS_CACHE[cache_key] = out
    _latest_report = out
    return out


def step_mfu(trainer, feed, seconds_per_step: float,
             devices: int = 1, fallback_flops: Optional[float] = None,
             cache_key: Optional[str] = None) -> Dict[str, Any]:
    """Shared MFU stamp for a measured step: executed FLOPs from
    :func:`analyze_trainer_step` (memoized via ``cache_key``) over
    ``time x peak x chips``.  When the step contains opaque custom
    calls (Pallas kernels — zero parsed FLOPs), the caller's analytic
    ``fallback_flops`` takes over if it is larger, and the stamp says
    which source produced the number."""
    report = analyze_trainer_step(trainer, feed, cache_key=cache_key)
    peaks = detect_peaks()
    flops = report["flops_per_step"] if report else 0.0
    source = "costmodel"
    if fallback_flops and (report is None
                           or (report["opaque_custom_calls"]
                               and fallback_flops > flops)):
        flops = float(fallback_flops)
        source = "analytic-fallback"
    return {"mfu_est": round(mfu(flops, seconds_per_step, devices,
                                 peaks), 3),
            "mfu_source": source,
            "flops_per_step": round(flops, 1)}


def clear_cache() -> None:
    """Drop memoized per-workload reports (tests; bench lanes that
    rebuild a workload under different flags)."""
    _ANALYSIS_CACHE.clear()


def render_table(report: Dict[str, Any]) -> str:
    """Human-readable per-region roofline table (PERF_NOTES material)."""
    lines = [f"{'region':<28} {'GFLOPs':>10} {'MB':>10} {'int.':>8} "
             f"{'bound':>8} {'t_est_ms':>9} {'share':>6} {'bwd%':>5}"]
    for r in report.get("regions", []):
        lines.append(
            f"{r['region']:<28} {r['flops'] / 1e9:>10.3f} "
            f"{r['bytes'] / 1e6:>10.2f} {r['intensity']:>8.2f} "
            f"{r['bound']:>8} {r['time_est_s'] * 1e3:>9.3f} "
            f"{r['share']:>6.1%} {r['bwd_frac']:>5.0%}")
    p = report.get("peaks", {})
    lines.append(
        f"peaks: {p.get('flops', 0) / 1e12:.1f} TFLOP/s, "
        f"{p.get('bw', 0) / 1e9:.0f} GB/s (ridge "
        f"{p.get('ridge', 0):.1f} flop/B, source {p.get('source')}); "
        f"flop agreement vs XLA: {report.get('flop_agreement')}")
    return "\n".join(lines)


def dump_report(report: Dict[str, Any], path: str) -> None:
    """Write a cost report as JSON (the ``--roofline_dump`` artifact)."""
    report.setdefault("schema", SCHEMA_VERSION)
    with open(path, "w") as f:
        json.dump(report, f, indent=1)
        f.write("\n")


# ----------------------------------------------------- attribution diff
def load_report(path: str) -> Dict[str, Any]:
    """Read a ``--roofline_dump`` artifact; unversioned (pre-v2) dumps
    are stamped ``schema: 1`` so the diff can say what it compared."""
    with open(path) as f:
        report = json.load(f)
    if not isinstance(report, dict) or "regions" not in report:
        raise ValueError(
            f"{path!r} is not a roofline/cost report (no 'regions')")
    report.setdefault("schema", 1)
    return report


def _region_rows(report: Dict[str, Any]) -> Dict[str, Dict[str, Any]]:
    return {r["region"]: r for r in report.get("regions") or []}


def _frac(old: float, new: float) -> Optional[float]:
    """(new - old) / |old| — None when the base is zero (a fraction of
    nothing is noise, the absolute delta field still tells the story)."""
    if not old:
        return None
    return round((new - old) / abs(old), 4)


def _match_renames(removed: Dict[str, Dict[str, Any]],
                   added: Dict[str, Dict[str, Any]],
                   rtol: float = 0.02) -> Dict[str, str]:
    """``{added name: removed name}`` for region pairs whose FLOPs AND
    bytes agree within ``rtol`` — a layer rename (or a named_scope
    re-label) rather than a genuine add+remove.  A pair is claimed
    only when the match is unique in BOTH directions: an added region
    with two removal candidates, or a removed region two added regions
    could stand in for, stays an honest add/remove — a wrong rename
    claim is worse than no claim."""
    hits: Dict[str, List[str]] = {}      # added -> matching removed
    claims: Dict[str, List[str]] = {}    # removed -> claiming added
    for aname, arow in added.items():
        for rname, rrow in removed.items():
            fo, fn = rrow.get("flops", 0.0), arow.get("flops", 0.0)
            bo, bn = rrow.get("bytes", 0.0), arow.get("bytes", 0.0)
            if abs(fn - fo) <= rtol * max(abs(fo), 1.0) \
                    and abs(bn - bo) <= rtol * max(abs(bo), 1.0):
                hits.setdefault(aname, []).append(rname)
                claims.setdefault(rname, []).append(aname)
    return {aname: rnames[0] for aname, rnames in hits.items()
            if len(rnames) == 1 and len(claims[rnames[0]]) == 1}


#: Per-region numeric fields the diff reports (field, fraction-worthy).
_DIFF_FIELDS = ("flops", "bytes", "intensity", "time_est_s", "share",
                "bwd_frac")


def attribution_diff(old: Dict[str, Any], new: Dict[str, Any],
                     tolerance: float = 0.05) -> Dict[str, Any]:
    """Machine-readable per-region delta between two roofline reports
    — the ``bench.py --attribution_diff OLD NEW`` payload, closing the
    loop on attribution-driven kernel work: a PR's before/after claim
    is verified by machine, not prose.

    Region rows carry ``status`` (``common | added | removed |
    renamed``), per-field ``*_old / *_new / *_delta / *_delta_frac``,
    and the roofline ``bound`` verdict transition.  ``regressions``
    lists common/renamed regions whose HBM ``bytes`` or ``time_est_s``
    grew beyond ``tolerance`` (fractional) plus total
    flops/bytes-per-step growth; ``ok`` is False iff any exist —
    ``--check`` gates on it."""
    o_rows, n_rows = _region_rows(old), _region_rows(new)
    removed = {k: v for k, v in o_rows.items() if k not in n_rows}
    added = {k: v for k, v in n_rows.items() if k not in o_rows}
    renames = _match_renames(removed, added)

    regions: List[Dict[str, Any]] = []
    regressions: List[Dict[str, Any]] = []
    improvements: List[Dict[str, Any]] = []

    def diff_row(name: str, orow: Dict[str, Any], nrow: Dict[str, Any],
                 status: str, renamed_from: Optional[str] = None
                 ) -> Dict[str, Any]:
        row: Dict[str, Any] = {"region": name, "status": status}
        if renamed_from:
            row["renamed_from"] = renamed_from
        for f in _DIFF_FIELDS:
            ov = float(orow.get(f, 0.0) or 0.0)
            nv = float(nrow.get(f, 0.0) or 0.0)
            row[f + "_old"] = ov
            row[f + "_new"] = nv
            row[f + "_delta"] = round(nv - ov, 6)
            row[f + "_delta_frac"] = _frac(ov, nv)
        row["bound_old"] = orow.get("bound")
        row["bound_new"] = nrow.get("bound")
        row["bound_changed"] = row["bound_old"] != row["bound_new"]
        for f in ("bytes", "time_est_s"):
            frac = row[f + "_delta_frac"]
            if frac is None:
                continue
            entry = {"region": name, "field": f,
                     "old": row[f + "_old"], "new": row[f + "_new"],
                     "delta_frac": frac}
            if frac > tolerance:
                regressions.append(entry)
            elif frac < -tolerance:
                improvements.append(entry)
        return row

    for name in sorted(set(o_rows) & set(n_rows)):
        regions.append(diff_row(name, o_rows[name], n_rows[name],
                                "common"))
    for aname, rname in sorted(renames.items()):
        regions.append(diff_row(aname, o_rows[rname], n_rows[aname],
                                "renamed", renamed_from=rname))
    zero = {f: 0.0 for f in _DIFF_FIELDS}
    for name in sorted(added):
        if name in renames:
            continue
        regions.append(diff_row(name, zero, n_rows[name], "added"))
    for name in sorted(removed):
        if name in renames.values():
            continue
        regions.append(diff_row(name, o_rows[name], zero, "removed"))

    totals: Dict[str, Any] = {}
    for f in ("flops_per_step", "bytes_per_step"):
        ov = float(old.get(f, 0.0) or 0.0)
        nv = float(new.get(f, 0.0) or 0.0)
        totals[f + "_old"] = ov
        totals[f + "_new"] = nv
        totals[f + "_delta_frac"] = _frac(ov, nv)
        frac = totals[f + "_delta_frac"]
        if frac is not None and frac > tolerance:
            regressions.append({"region": "_total", "field": f,
                                "old": ov, "new": nv,
                                "delta_frac": frac})
        elif frac is not None and frac < -tolerance:
            improvements.append({"region": "_total", "field": f,
                                 "old": ov, "new": nv,
                                 "delta_frac": frac})
    for f in ("mfu_est",):
        if old.get(f) is not None or new.get(f) is not None:
            totals[f + "_old"] = old.get(f)
            totals[f + "_new"] = new.get(f)
            if old.get(f) and new.get(f):
                totals[f + "_delta_frac"] = _frac(float(old[f]),
                                                  float(new[f]))

    return {
        "kind": "attribution_diff",
        "schema": {"old": old.get("schema", 1),
                   "new": new.get("schema", 1),
                   "diff": SCHEMA_VERSION},
        "tolerance": tolerance,
        "regions": regions,
        "totals": totals,
        "added": sorted(n for n in added if n not in renames),
        "removed": sorted(r for r in removed
                          if r not in renames.values()),
        "renamed": {a: r for a, r in sorted(renames.items())},
        "regressions": regressions,
        "improvements": improvements,
        "ok": not regressions,
    }


def render_diff_table(diff: Dict[str, Any]) -> str:
    """Human-readable attribution diff (stderr companion of the JSON
    payload; PERF_NOTES material)."""
    lines = [f"{'region':<28} {'status':>8} {'GFLOPs Δ%':>10} "
             f"{'HBM Δ%':>8} {'t_est Δ%':>9} {'bound':>18}"]

    def pct(v: Optional[float]) -> str:
        return f"{v * 100:+.1f}%" if v is not None else "n/a"

    for r in diff.get("regions", []):
        bound = (r.get("bound_old") or "?")
        if r.get("bound_changed"):
            bound = f"{bound}->{r.get('bound_new') or '?'}"
        name = r["region"]
        if r.get("renamed_from"):
            name = f"{r['renamed_from']}->{name}"
        lines.append(
            f"{name:<28} {r['status']:>8} "
            f"{pct(r.get('flops_delta_frac')):>10} "
            f"{pct(r.get('bytes_delta_frac')):>8} "
            f"{pct(r.get('time_est_s_delta_frac')):>9} {bound:>18}")
    t = diff.get("totals", {})
    lines.append(
        "totals: flops/step "
        f"{pct(t.get('flops_per_step_delta_frac'))}, bytes/step "
        f"{pct(t.get('bytes_per_step_delta_frac'))}; "
        f"{len(diff.get('regressions', []))} regression(s), "
        f"{len(diff.get('improvements', []))} improvement(s), "
        f"ok={diff.get('ok')}")
    return "\n".join(lines)

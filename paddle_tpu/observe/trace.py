"""Span-based distributed tracing: flight recorder + Chrome trace JSONL.

The metrics registry (:mod:`paddle_tpu.observe.metrics`) answers "how
much / how often"; this module answers "where did *this* step / request
/ lease spend its time".  A :func:`span` context manager produces
timeline spans with trace-id / span-id / parent-id, recorded into

- a bounded thread-safe **ring buffer** (the flight recorder — the last
  N spans of a live run, dumped on demand through ``/trace`` or the
  SIGUSR2 debug dump), and
- optionally a ``--trace_jsonl PATH`` sink: **Chrome trace-event JSON**
  (``ph:"X"`` complete events, one lane per thread) written by a
  background ``ptpu-trace-writer`` thread — the file loads directly in
  Perfetto / ``chrome://tracing`` and parses with ``json.load``.

Trace context propagates three ways:

- **nesting** — thread-local: a span opened inside another becomes its
  child (same trace id, ``parent_id`` set);
- **across threads** — :func:`current_context` / :func:`context_scope`
  hand the active context to worker threads (the async input pipeline
  and the cloud read-ahead fetcher do this), so reader/convert/place
  spans land in the trace of the pass that consumes them;
- **across processes** — :func:`parent_header` renders the active
  context as an opaque ``<trace_id>/<span_id>`` token the master RPC
  protocol carries (``CTX`` framing, ``distributed/master.py`` +
  ``native/master/master.cc``); the server echoes it with its own
  pid + handling time and the client records that as a server-side
  span via :func:`record_span` — one trace across the RPC boundary.

Device-timeline correlation: while a ``jax.profiler`` window is open
(``utils/profiler.trace`` tick-counts it), every span additionally
enters a ``jax.profiler.TraceAnnotation`` so host spans line up with
XLA ops in the TensorBoard/xprof timeline.  jax is never imported from
here (zero-dependency rule — the serving loader and conftest import
this module standalone); the annotation hook goes through
``sys.modules`` and only fires when the profiler module is already
live.

Overhead contract (PR-5 rules): with tracing disabled — no
``--trace_jsonl``, no ``--metrics_port``, no programmatic
:func:`enable` — :func:`span` returns a shared no-op context manager
(one function call + a None check, well under 1 µs), NOTHING is written
to the ring buffer, and no writer thread exists.  Telemetry never kills
the process it observes: an unwritable sink degrades to ring-only
recording with a warn-once.
"""

from __future__ import annotations

import atexit
import collections
import contextlib
import json
import os
import queue
import random
import sys
import threading
import time
from typing import Any, Dict, Iterator, List, NamedTuple, Optional

from ..analysis.lockorder import named_lock

DEFAULT_RING_SIZE = 4096

#: Thread name of the JSONL writer; the conftest thread-leak guard
#: keys on it (same contract as the pipeline's ``ptpu-io-*`` workers).
WRITER_THREAD_NAME = "ptpu-trace-writer"

# perf_counter is the span clock (monotonic, ns resolution); this offset
# maps it onto the epoch once so trace timestamps are wall-clock µs and
# multiple processes' traces can be merged on one timeline.
_EPOCH_OFFSET_S = time.time() - time.perf_counter()

_ids = random.Random()          # span/trace ids need no crypto strength
_ids_lock = named_lock("trace.ids")

_tls = threading.local()        # .ctx: the active SpanContext (or None)


class SpanContext(NamedTuple):
    """The propagatable identity of an active span."""
    trace_id: str
    span_id: str


def _new_id() -> str:
    with _ids_lock:
        return "%016x" % _ids.getrandbits(64)


def now_us() -> float:
    """Wall-clock microseconds on the span clock (epoch-aligned)."""
    return (time.perf_counter() + _EPOCH_OFFSET_S) * 1e6


# ------------------------------------------------------------- context
def current_context() -> Optional[SpanContext]:
    """The innermost open span's context on THIS thread (None outside
    any span).  Cheap enough to call unconditionally."""
    return getattr(_tls, "ctx", None)


@contextlib.contextmanager
def context_scope(ctx: Optional[SpanContext]) -> Iterator[None]:
    """Run a block under ``ctx`` — how worker threads adopt the trace of
    the pass/step that spawned them (thread-locals don't inherit)."""
    prev = getattr(_tls, "ctx", None)
    _tls.ctx = ctx
    try:
        yield
    finally:
        _tls.ctx = prev


def parent_header() -> str:
    """Active context as the opaque wire token (``trace_id/span_id``;
    empty string outside any span).  Tab/newline-free by construction,
    so it rides the master line protocol unescaped."""
    ctx = getattr(_tls, "ctx", None)
    return f"{ctx.trace_id}/{ctx.span_id}" if ctx is not None else ""


def parse_header(header: str) -> Optional[SpanContext]:
    """Inverse of :func:`parent_header`; None on anything malformed (a
    peer speaking a different dialect must not kill telemetry)."""
    if not header or "/" not in header:
        return None
    trace_id, _, span_id = header.partition("/")
    if not trace_id or not span_id:
        return None
    return SpanContext(trace_id, span_id)


# ------------------------------------------------------------ recorder
class _Recorder:
    """Ring buffer + optional JSONL writer behind one record() call."""

    def __init__(self, jsonl_path: Optional[str],
                 ring_size: int = DEFAULT_RING_SIZE, fences: bool = True):
        self.ring: "collections.deque" = collections.deque(
            maxlen=max(1, int(ring_size)))
        self._ring_lock = named_lock("trace.ring")
        self.jsonl_path = jsonl_path or None
        # an explicit sink always wants the honest (fenced) timeline;
        # scrape-originated ring-only recording opts out (see
        # fences_steps)
        self.fences = bool(fences) or self.jsonl_path is not None
        self.dropped = 0
        self._q: Optional["queue.Queue"] = None
        self._writer: Optional[threading.Thread] = None
        self._file = None
        if self.jsonl_path:
            try:
                self._file = open(self.jsonl_path, "w")
                self._file.write("[")
            except OSError as e:
                self._warn_sink(e)
            else:
                self._q = queue.Queue(maxsize=8192)
                self._writer = threading.Thread(
                    target=self._writer_loop, name=WRITER_THREAD_NAME,
                    daemon=True)
                self._writer.start()

    def _warn_sink(self, e: Exception) -> None:
        from ..utils.logger import get_logger, warn_once

        f, self._file = self._file, None
        if f is not None:
            try:
                f.close()
            except OSError:
                pass
        warn_once(
            f"trace_sink_failed:{self.jsonl_path}",
            "trace sink %r failed (%s: %s); spans keep landing in the "
            "flight recorder but the JSONL stream is DROPPED (reported "
            "once)", self.jsonl_path, type(e).__name__, e,
            logger=get_logger("observe"))

    def record(self, event: Dict[str, Any]) -> None:
        with self._ring_lock:
            self.ring.append(event)
        if self._q is not None:
            try:
                self._q.put_nowait(event)
            except queue.Full:      # writer can't keep up: shed, count
                with self._ring_lock:
                    self.dropped += 1
                    first = self.dropped == 1
                if first:   # a silently-truncated timeline lies: say so
                    from ..utils.logger import get_logger, warn_once

                    warn_once(
                        f"trace_spans_dropped:{self.jsonl_path}",
                        "trace writer can't keep up with span volume; "
                        "spans are being DROPPED from the %r stream "
                        "(the flight recorder still has them; dropped "
                        "count on /healthz)", self.jsonl_path,
                        logger=get_logger("observe"))

    def events(self) -> List[Dict[str, Any]]:
        with self._ring_lock:
            return list(self.ring)

    # writer thread: drains the queue into the trace-event JSON array.
    _STOP = object()

    def _writer_loop(self) -> None:
        first = True
        while True:
            item = self._q.get()
            if item is self._STOP:
                break
            if self._file is None:
                continue            # sink already degraded: drain only
            try:
                self._file.write(("\n" if first else ",\n")
                                 + json.dumps(item))
                first = False
            except (OSError, TypeError, ValueError) as e:
                self._warn_sink(e)

    def close(self) -> None:
        if self._writer is not None:
            self._q.put(self._STOP)
            self._writer.join(timeout=5.0)
            self._writer = None
        if self._file is not None:
            try:
                # terminate the array so json.load accepts the file
                # (Perfetto tolerates a missing "]" after a crash; a
                # clean stop writes a strictly valid document)
                self._file.write("\n]\n")
                self._file.close()
            except OSError:
                pass
            self._file = None


_recorder: Optional[_Recorder] = None
_state_lock = named_lock("trace.state")
_atexit_installed = False


def enabled() -> bool:
    """True iff spans are being recorded — the hot-path gate."""
    # benign racy read on the span hot path: every write is
    # _state_lock-guarded; a stale recorder finishes one span into the
    # old ring harmlessly — taking the lock here would price every span
    # ptpu: lint-ok[PT-RACE] atomic reference read, writes lock-guarded
    return _recorder is not None


def fences_steps() -> bool:
    """True iff tracing asked for the trainer's per-step fence: an
    EXPLICIT opt-in — ``--trace_jsonl`` or a programmatic
    :func:`enable`.  Ring-only recording lazily enabled by a ``/trace``
    scrape (:func:`ensure_ring`) stays fence-free, so an accidental
    probe of the endpoint can never convert a production run's async
    dispatch into a per-step device sync; its spans carry dispatch-time
    durations, honest about what they measured."""
    rec = _recorder
    return rec is not None and rec.fences


def dropped_count() -> int:
    """Spans shed from the JSONL stream because the writer couldn't
    keep up (the flight recorder keeps them); surfaced on /healthz."""
    rec = _recorder
    return rec.dropped if rec is not None else 0


def enable(jsonl_path: Optional[str] = None,
           ring_size: int = DEFAULT_RING_SIZE,
           fences: bool = True) -> None:
    """Turn tracing on: flight recorder always, JSONL stream when
    ``jsonl_path`` is given.  Idempotent re-enable replaces the sink.
    ``fences=False`` (the ``/trace`` scrape path) records ring-only
    without asking the trainer for its per-step fence."""
    global _recorder, _atexit_installed
    with _state_lock:
        old, _recorder = _recorder, _Recorder(jsonl_path, ring_size,
                                              fences=fences)
        if not _atexit_installed:
            atexit.register(disable)
            _atexit_installed = True
    if old is not None:
        old.close()


def disable() -> None:
    """Stop recording, join the writer, and finalize the JSONL file
    (writes the closing ``]``).  Idempotent; spans still open keep a
    reference to the old recorder and finish harmlessly into it."""
    global _recorder
    with _state_lock:
        rec, _recorder = _recorder, None
    if rec is not None:
        rec.close()


def start_from_flags() -> bool:
    """Enable tracing iff ``--trace_jsonl`` is set (the HTTP endpoint
    enables ring-only recording lazily, on its first ``/trace``
    request — see :mod:`paddle_tpu.observe.http`).  Idempotent:
    re-calls with an unchanged flag don't restart the sink mid-run."""
    from ..utils import FLAGS

    path = FLAGS.get("trace_jsonl")
    if not path:
        return enabled()
    if _recorder is not None and _recorder.jsonl_path == path:
        return True
    enable(jsonl_path=path, ring_size=FLAGS.get("trace_ring_size"))
    return True


def ensure_ring(ring_size: Optional[int] = None) -> None:
    """Enable ring-only, fence-free recording if tracing is fully off
    — the lazy opt-in behind the HTTP endpoint's first ``/trace``
    request, so a run scraped only for ``/metrics`` never starts
    recording, and even a ``/trace`` scrape never buys the trainer's
    per-step fence (:func:`fences_steps` stays False); a live recorder
    — with or without a sink — is kept."""
    if _recorder is None:
        from ..utils import FLAGS

        enable(ring_size=FLAGS.get("trace_ring_size")
               if ring_size is None else ring_size, fences=False)


# ------------------------------------------------------------- spans
class _NullSpan:
    """Shared do-nothing span: the disabled-mode fast path."""
    __slots__ = ()
    context = None

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False


_NULL_SPAN = _NullSpan()


def _xprof_annotation(name: str):
    """A jax.profiler.TraceAnnotation for ``name`` iff an xprof window
    is open right now — resolved through sys.modules so this module
    never imports jax (and pays nothing when the profiler is idle)."""
    prof = sys.modules.get("paddle_tpu.utils.profiler")
    if prof is None or not prof.trace_active():
        return None
    try:
        return prof.annotate(name)
    except Exception:   # noqa: BLE001 — telemetry never kills the host
        return None


class _Span:
    __slots__ = ("_rec", "name", "attrs", "context", "parent_id",
                 "_t0", "_prev", "_annot")

    def __init__(self, rec: _Recorder, name: str,
                 remote_parent: Optional[SpanContext],
                 attrs: Dict[str, Any]):
        self._rec = rec
        self.name = name
        self.attrs = attrs
        parent = remote_parent if remote_parent is not None \
            else getattr(_tls, "ctx", None)
        if parent is not None:
            self.context = SpanContext(parent.trace_id, _new_id())
            self.parent_id = parent.span_id
        else:
            self.context = SpanContext(_new_id(), _new_id())
            self.parent_id = None

    def __enter__(self) -> "_Span":
        self._prev = getattr(_tls, "ctx", None)
        _tls.ctx = self.context
        annot = _xprof_annotation(self.name)
        if annot is not None:
            # the profiler window can close between the trace_active()
            # check and this enter — a raise here would skip the with
            # body AND leak _tls.ctx (no __exit__ runs)
            try:
                annot.__enter__()
            except Exception:   # noqa: BLE001 — telemetry never kills
                annot = None
        self._annot = annot
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        t1 = time.perf_counter()
        if self._annot is not None:
            try:
                self._annot.__exit__(exc_type, exc, tb)
            except Exception as e:  # noqa: BLE001 — telemetry never
                # kills: the xprof window can close mid-span
                from ..utils.logger import get_logger
                get_logger("observe").debug(
                    "xprof annotation exit failed (window closed "
                    "mid-span?): %s: %s", type(e).__name__, e)
        _tls.ctx = self._prev
        args = {"trace_id": self.context.trace_id,
                "span_id": self.context.span_id}
        if self.parent_id is not None:
            args["parent_id"] = self.parent_id
        if exc_type is not None:
            args["error"] = exc_type.__name__
        for k, v in self.attrs.items():
            args[k] = v if isinstance(v, (int, float, bool)) else str(v)
        self._rec.record({
            "name": self.name, "ph": "X", "cat": "ptpu",
            "ts": round((self._t0 + _EPOCH_OFFSET_S) * 1e6, 3),
            "dur": round((t1 - self._t0) * 1e6, 3),
            "pid": os.getpid(), "tid": threading.get_native_id(),
            "args": args})
        return False


def span(name: str, remote_parent: Optional[SpanContext] = None,
         **attrs):
    """Open a timeline span: ``with trace.span("feed", step=i): ...``.

    Disabled mode returns a shared no-op (the <50 µs/step contract);
    enabled mode records one ``ph:"X"`` complete event on exit, parented
    under the innermost open span of this thread — or under
    ``remote_parent`` when an RPC peer handed its context over."""
    rec = _recorder
    if rec is None:
        return _NULL_SPAN
    return _Span(rec, name, remote_parent, attrs)


def record_span(name: str, ts_us: float, dur_us: float, trace_id: str,
                parent_id: Optional[str] = None,
                pid: Optional[int] = None, tid: Optional[int] = None,
                **attrs) -> Optional[str]:
    """Record a span observed OUTSIDE this thread's clock — e.g. the
    master's server-side handling time echoed back over the RPC.  The
    caller supplies absolute µs timestamps; returns the new span id
    (None when tracing is disabled)."""
    rec = _recorder
    if rec is None:
        return None
    span_id = _new_id()
    args: Dict[str, Any] = {"trace_id": trace_id, "span_id": span_id}
    if parent_id:
        args["parent_id"] = parent_id
    for k, v in attrs.items():
        args[k] = v if isinstance(v, (int, float, bool)) else str(v)
    rec.record({
        "name": name, "ph": "X", "cat": "ptpu",
        "ts": round(float(ts_us), 3), "dur": round(float(dur_us), 3),
        "pid": os.getpid() if pid is None else int(pid),
        "tid": threading.get_native_id() if tid is None else int(tid),
        "args": args})
    return span_id


# ----------------------------------------------------- flight recorder
def events() -> List[Dict[str, Any]]:
    """Current flight-recorder contents (oldest first; [] when off)."""
    rec = _recorder
    return rec.events() if rec is not None else []


def flight_recorder_json() -> str:
    """Flight recorder as a Chrome trace-event JSON array — the
    ``/trace`` endpoint body and the SIGUSR2 dump payload; loadable
    as-is in Perfetto."""
    return json.dumps(events())

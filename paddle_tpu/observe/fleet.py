"""Fleet observatory: the cross-process telemetry plane.

Rounds 13–16 gave every *single* paddle_tpu process superb
self-observation (``/metrics``, ``/healthz``, ``/trace``, JSONL sinks,
CTX-framed cross-process trace propagation) — but a real run is a
*cluster*: master + N elastic trainers + a serving loader, each its own
pane of glass.  This module is the plane that merges them:

- **Aggregator** (:class:`FleetAggregator`, ``--fleet_port``): a
  stdlib-only HTTP service any process can host — same
  ``ThreadingHTTPServer`` discipline as :mod:`paddle_tpu.observe.http`
  (daemon handler threads, telemetry-never-kills, loopback bind unless
  explicitly opted out).  Endpoints:

  - ``POST /fleet/push``   — frame intake (see below);
  - ``GET /fleet/metrics`` — every registered process's metric families
    merged into ONE Prometheus exposition, each sample labeled with the
    pushing process's ``role`` / ``pid`` / ``node`` / ``proc`` identity;
  - ``GET /fleet/healthz`` — the cluster rollup: per-process
    ok / degraded / **missing** / down, with staleness detection — a
    process that has not pushed for ``--fleet_stale_factor`` × its own
    advertised interval flips to ``missing``; a restarted process
    (same logical id, new pid) flips it back;
  - ``GET /fleet/trace``   — spans from ALL processes merged by their
    already-propagated trace ids into ONE Chrome trace-event document
    with per-process lanes (``process_name`` metadata events) —
    loadable directly in Perfetto;
  - ``GET /fleet/topology``— who is registered: role, pid, node,
    uptime, frames received, last push.

- **Push client** (:class:`FleetPusher`, ``--fleet_addr host:port``):
  folded into :class:`paddle_tpu.observe.report.MetricsReporter` — on
  the reporter interval each process pushes ONE self-describing frame:
  its metrics snapshot, the flight-recorder spans recorded since the
  last acknowledged push, and a health digest.  Registration is
  implicit in every frame (role / pid / node / logical id), so a
  restarted process re-registers by simply pushing again.

- **Live console**: ``python -m paddle_tpu.observe.fleet --watch
  host:port`` renders per-process step/s, input-bound ratio, HBM peak,
  health status and last-seen age from a running aggregator;
  ``python -m paddle_tpu.observe.fleet --fleet_port N`` hosts a
  standalone aggregator.

Failure semantics are the PR-4 contract, verbatim: **telemetry never
kills** — a dead/unreachable aggregator marks the push sink degraded
(warn-once) and backs off exponentially with per-client jitter, the
trainer never notices; a peer speaking a different dialect (bare-ERR
body, version-skew ``schema`` rejection) degrades the sink exactly like
a failing JSONL flush; a later successful push clears the state.  With
``--fleet_addr`` unset nothing here runs: no thread, no socket, no
write (the reporter doesn't even start unless a JSONL sink is also
configured).

Zero-dependency rule: nothing in this module imports jax — the frame
payload is the same self-describing JSON the ``--metrics_jsonl`` sink
writes, and the aggregator renders merged Prometheus text from those
snapshots without ever touching live metric objects.
"""

from __future__ import annotations

import argparse
import collections
import http.client
import json
import os
import random
import signal
import socket
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..analysis.lockorder import named_lock
from . import trace
from .metrics import REGISTRY, _label_key, format_labels

#: Frame/protocol schema this build speaks.  An aggregator rejects
#: frames from a NEWER schema with a structured 400 (the pusher
#: degrades, the run continues); older frames are accepted as-is.
FLEET_SCHEMA = 1

#: Aggregator serve-loop thread name (conftest thread-leak guard entry).
AGGREGATOR_THREAD_NAME = "ptpu-fleet-http"

#: Spans a single frame may carry; older unsent spans beyond this are
#: acknowledged as dropped on the frame itself (``spans_dropped``) —
#: a slow interval must not grow frames without bound.
MAX_SPANS_PER_FRAME = 1000

_DOWN = "down"
_MISSING = "missing"
_DEGRADED = "degraded"
_OK = "ok"

# ------------------------------------------------------------ identity
# Role/logical-name a subsystem claims for this process.  Flags give
# the defaults; the elastic trainer (trainer_id), the serving loader
# and bench override programmatically.  The pusher reads this at frame
# build time, so an identity set after the reporter started still
# lands on the next frame.
_identity_lock = named_lock("observe.fleet.identity")
_identity: Dict[str, str] = {}


def set_identity(role: Optional[str] = None,
                 name: Optional[str] = None,
                 node: Optional[str] = None) -> None:
    """Claim this process's fleet identity (role ∈ trainer |
    master-client | serving | bench by convention; free-form).  Unset
    fields keep their flag/derived defaults."""
    with _identity_lock:
        if role:
            _identity["role"] = str(role)
        if name:
            _identity["name"] = str(name)
        if node:
            _identity["node"] = str(node)


def reset_identity() -> None:
    """Drop programmatic identity overrides (tests)."""
    with _identity_lock:
        _identity.clear()
    with _serving_lock:
        _serving_info.clear()


# Serving-plane info a replica publishes alongside its identity: model
# version (artifact digest + export time) and rollout state.  Rides
# every frame as the optional "serving" field (additive — schema 1
# aggregators that predate it simply ignore the key).
_serving_lock = named_lock("observe.fleet.serving")
_serving_info: Dict[str, Any] = {}


def set_serving_info(version: Optional[str] = None,
                     state: Optional[str] = None,
                     exported_at: Optional[float] = None,
                     error: Optional[str] = None) -> None:
    """Publish this process's served-model version + rollout state
    (``serving/server.py`` calls this at start and at every swap /
    rollback); lands on the next pushed frame."""
    with _serving_lock:
        if version is not None:
            _serving_info["model_version"] = str(version)
        _serving_info["rollout_state"] = str(state or "serving")
        _serving_info["exported_at"] = exported_at
        _serving_info["swap_error"] = error


def serving_info() -> Dict[str, Any]:
    """This process's published serving info (``{}`` when it never
    loaded a model — trainers and exporters push no serving field)."""
    with _serving_lock:
        return dict(_serving_info)


def identity() -> Dict[str, str]:
    """The resolved (role, name, node) triple this process pushes as.
    ``name`` is the *logical* id staleness tracking keys on: stable
    across restarts when set (``--fleet_id`` / trainer_id), else
    derived from role+node+pid (a restart then registers as a new
    process and the old entry ages out as ``missing``)."""
    from ..utils import FLAGS

    with _identity_lock:
        ident = dict(_identity)
    role = ident.get("role") or str(FLAGS.get("fleet_role")) or "trainer"
    node = ident.get("node") or socket.gethostname()
    name = ident.get("name") or str(FLAGS.get("fleet_id")) \
        or f"{role}@{node}:{os.getpid()}"
    return {"role": role, "name": name, "node": node}


def local_health_digest() -> Dict[str, Any]:
    """This process's own health summary — the ``/healthz`` body logic,
    reused as the frame's ``health`` field (training-health observatory
    resolved through ``sys.modules`` so a run that never enabled it
    pays nothing)."""
    digest: Dict[str, Any] = {"status": _OK,
                              "trace_enabled": trace.enabled()}
    hmod = sys.modules.get("paddle_tpu.observe.health")
    if hmod is not None:
        digest["health"] = hmod.status_summary()
        digest["status"] = digest["health"]["status"]
    return digest


# --------------------------------------------------------------- state
class FleetFrameError(ValueError):
    """A push body that is not a fleet frame at all."""


class FleetSchemaError(ValueError):
    """A frame from a NEWER protocol than this aggregator speaks."""


class FleetState:
    """The aggregator's model of the cluster — pure bookkeeping, no IO.

    Injectable ``clock`` (monotonic seconds) so staleness math is unit-
    testable with a fake clock, no sleeps.  Thread-safe: handler
    threads ingest concurrently with rollup/metrics scrapes."""

    def __init__(self, stale_factor: Optional[float] = None,
                 ring_size: Optional[int] = None,
                 clock: Callable[[], float] = time.monotonic):
        from ..utils import FLAGS

        self.stale_factor = float(FLAGS.get("fleet_stale_factor")
                                  if stale_factor is None else stale_factor)
        self.ring_size = int(FLAGS.get("fleet_ring_size")
                             if ring_size is None else ring_size)
        self._clock = clock
        self._lock = named_lock("observe.fleet.state")
        self._procs: Dict[str, Dict[str, Any]] = {}
        self._spans: Dict[str, "collections.deque"] = {}

    # ------------------------------------------------------------ intake
    @staticmethod
    def _span_key(e: Dict[str, Any]) -> Tuple:
        args = e.get("args") or {}
        sid = args.get("span_id")
        if sid:
            return (e.get("pid"), sid)
        return (e.get("pid"), e.get("tid"), e.get("ts"), e.get("dur"),
                e.get("name"))

    def ingest(self, frame: Dict[str, Any]) -> Dict[str, Any]:
        """Fold one pushed frame in; returns the ack body.  Raises
        :class:`FleetFrameError` / :class:`FleetSchemaError` on a body
        that must be refused (the HTTP layer maps them to 400)."""
        if not isinstance(frame, dict) or "schema" not in frame:
            raise FleetFrameError("not a fleet frame (no schema field)")
        try:
            schema = int(frame["schema"])
        except (TypeError, ValueError):
            raise FleetFrameError("non-integer schema field")
        if schema > FLEET_SCHEMA:
            raise FleetSchemaError(
                f"frame schema {schema} is newer than this aggregator "
                f"(speaks <= {FLEET_SCHEMA}); upgrade the aggregator")
        pid = int(frame.get("pid") or 0)
        role = str(frame.get("role") or "proc")
        name = str(frame.get("name") or f"{role}:{pid}")
        now = self._clock()
        spans = frame.get("spans") or []
        with self._lock:
            prev = self._procs.get(name)
            restarted = prev is not None and prev["pid"] != pid
            entry = {
                "role": role, "pid": pid,
                "node": str(frame.get("node") or "?"),
                "name": name,
                "interval_s": float(frame.get("interval_s") or 10.0),
                "seq": int(frame.get("seq") or 0),
                "uptime_s": float(frame.get("uptime_s") or 0.0),
                "going_down": bool(frame.get("going_down")),
                "health": frame.get("health")
                if isinstance(frame.get("health"), dict) else {},
                "metrics": frame.get("metrics")
                if isinstance(frame.get("metrics"), list) else [],
                "timers": frame.get("timers")
                if isinstance(frame.get("timers"), list) else [],
                "last_push": now,
                "first_seen": now if (prev is None or restarted)
                else prev["first_seen"],
                "frames": 1 if (prev is None or restarted)
                else prev["frames"] + 1,
                "restarts": (prev.get("restarts", 0) + 1)
                if restarted else (prev or {}).get("restarts", 0),
                "spans_dropped": int(frame.get("spans_dropped") or 0)
                + (0 if (prev is None or restarted)
                   else prev.get("spans_dropped", 0)),
                "serving": frame.get("serving")
                if isinstance(frame.get("serving"), dict) else {},
                "slo": frame.get("slo")
                if isinstance(frame.get("slo"), dict) else {},
            }
            self._procs[name] = entry
            # a restart KEEPS the predecessor incarnation's spans (the
            # ring bounds them): "what was trainer-0 doing before it
            # died" is exactly what the merged timeline is for, and
            # span pids are real so the lanes stay distinct
            dq = self._spans.get(name)
            if dq is None:
                dq = self._spans[name] = collections.deque(
                    maxlen=max(1, self.ring_size))
            if spans:
                known = {self._span_key(e) for e in dq}
                for e in spans:
                    if not isinstance(e, dict):
                        continue
                    k = self._span_key(e)
                    if k not in known:
                        known.add(k)
                        dq.append(e)
            n_procs = len(self._procs)
        # aggregator's own telemetry — OUTSIDE the state lock (lock
        # hygiene: never nest observe.metric under observe.fleet.state)
        from .metrics import counter, gauge

        counter("fleet_frames_total",
                "fleet frames ingested by the hosted aggregator").inc(
            role=role)
        gauge("fleet_procs",
              "processes currently registered with the hosted "
              "aggregator").set(n_procs)
        return {"ok": True, "schema": FLEET_SCHEMA, "procs": n_procs,
                "name": name}

    # ----------------------------------------------------------- rollup
    def _proc_status(self, e: Dict[str, Any], now: float) -> str:
        if e["going_down"]:
            return _DOWN
        age = now - e["last_push"]
        if age > self.stale_factor * max(e["interval_s"], 1e-3):
            return _MISSING
        status = str(e["health"].get("status", _OK))
        if status not in (_OK, _DEGRADED):
            return _DEGRADED
        # a standing SLO breach marks the process degraded — the
        # breached objective is named in the rollup entry
        if status == _OK and (e.get("slo") or {}).get("status") == "breach":
            return _DEGRADED
        return status

    def rollup(self) -> Dict[str, Any]:
        """The ``/fleet/healthz`` body: per-process status + cluster
        verdict.  ``missing`` dominates ``degraded`` dominates ``ok``;
        a clean ``down`` (final going-down frame received) is reported
        but does not degrade the cluster — a SIGKILLed process never
        says goodbye, which is exactly how the two cases differ."""
        now = self._clock()
        with self._lock:
            items = [(name, dict(e)) for name, e in self._procs.items()]
        procs: Dict[str, Any] = {}
        counts = {_OK: 0, _DEGRADED: 0, _MISSING: 0, _DOWN: 0}
        for name, e in sorted(items):
            st = self._proc_status(e, now)
            counts[st] += 1
            procs[name] = {
                "role": e["role"], "pid": e["pid"], "node": e["node"],
                "status": st,
                "last_push_age_s": round(now - e["last_push"], 3),
                "interval_s": e["interval_s"],
                "stale_after_s": round(
                    self.stale_factor * max(e["interval_s"], 1e-3), 3),
                "seq": e["seq"], "uptime_s": round(e["uptime_s"], 3),
                "restarts": e["restarts"],
            }
            slo = e.get("slo") or {}
            if slo:
                procs[name]["slo"] = str(slo.get("status", "?"))
                if slo.get("breached"):
                    # name WHICH objective degraded this process
                    procs[name]["slo_breached"] = list(slo["breached"])
        if counts[_MISSING]:
            status = _MISSING
        elif counts[_DEGRADED]:
            status = _DEGRADED
        elif procs:
            status = _OK
        else:
            status = "empty"
        return {"status": status, "pid": os.getpid(),
                "schema": FLEET_SCHEMA,
                "stale_factor": self.stale_factor,
                "counts": counts, "procs": procs}

    def topology(self) -> Dict[str, Any]:
        """The ``/fleet/topology`` body: who is registered, since when,
        last push."""
        now = self._clock()
        with self._lock:
            items = [(name, dict(e)) for name, e in self._procs.items()]
            span_counts = {name: len(dq)
                           for name, dq in self._spans.items()}
        procs = {}
        for name, e in sorted(items):
            procs[name] = {
                "role": e["role"], "pid": e["pid"], "node": e["node"],
                "registered_age_s": round(now - e["first_seen"], 3),
                "last_push_age_s": round(now - e["last_push"], 3),
                "uptime_s": round(e["uptime_s"], 3),
                "frames": e["frames"], "seq": e["seq"],
                "restarts": e["restarts"],
                "spans_held": span_counts.get(name, 0),
                "spans_dropped": e["spans_dropped"],
                "going_down": e["going_down"],
                # the process's own LAST-PUSHED health verdict —
                # distinct from the rollup's liveness status (a
                # missing process keeps its last-known health here)
                "health": str(e["health"].get("status", "?")),
            }
            serving = e.get("serving") or {}
            if serving:
                # the rollout plane: artifact digest + export time +
                # swap state, straight off the replica's frames — what
                # the rolling coordinator and --watch version column read
                procs[name]["model_version"] = serving.get(
                    "model_version", "?")
                procs[name]["rollout_state"] = serving.get(
                    "rollout_state", "?")
                procs[name]["model_exported_at"] = serving.get(
                    "exported_at")
                if serving.get("swap_error"):
                    procs[name]["swap_error"] = serving["swap_error"]
                # windowed serving signals (PR 20): what the canary
                # bake compares across replicas
                if serving.get("ttft_p99_s") is not None:
                    procs[name]["ttft_p99_s"] = serving["ttft_p99_s"]
                if serving.get("error_rate_s") is not None:
                    procs[name]["error_rate_s"] = serving["error_rate_s"]
            slo = e.get("slo") or {}
            if slo:
                procs[name]["slo"] = str(slo.get("status", "?"))
                if slo.get("breached"):
                    procs[name]["slo_breached"] = list(slo["breached"])
        return {"schema": FLEET_SCHEMA, "pid": os.getpid(),
                "procs": procs}

    # ---------------------------------------------------------- metrics
    def merged_prometheus(self) -> str:
        """Every process's snapshot rendered as ONE Prometheus
        exposition, samples labeled ``role``/``pid``/``node``/``proc``.
        Families keep their original names; the TYPE/HELP header is
        emitted once per family (first pusher's description wins; a
        name that arrives as a different type from another process is
        skipped with a comment — a name means one thing fleet-wide,
        same rule as the in-process registry)."""
        with self._lock:
            items = [(name, dict(e)) for name, e in
                     sorted(self._procs.items())]
        fams: Dict[str, Dict[str, Any]] = {}
        skipped: List[str] = []
        for name, e in items:
            extra = {"role": e["role"], "pid": e["pid"],
                     "node": e["node"], "proc": name}
            for m in e["metrics"]:
                if not isinstance(m, dict) or "name" not in m:
                    continue
                fam = fams.setdefault(
                    m["name"], {"type": m.get("type", "gauge"),
                                "help": m.get("help", ""),
                                "lines": [], "qlines": []})
                if fam["type"] != m.get("type", "gauge"):
                    skipped.append(f"{m['name']} from {name}: type "
                                   f"{m.get('type')} != {fam['type']}")
                    continue
                self._render_family(fam, m, extra)
        out: List[str] = []
        for fname in sorted(fams):
            fam = fams[fname]
            if fam["help"]:
                out.append(f"# HELP {fname} {fam['help']}")
            out.append(f"# TYPE {fname} {fam['type']}")
            out.extend(fam["lines"])
            if fam["qlines"]:
                out.append(f"# TYPE {fname}_q gauge")
                out.extend(fam["qlines"])
        for s in skipped:
            out.append(f"# fleet: skipped conflicting family {s}")
        return "\n".join(out) + ("\n" if out else "")

    @staticmethod
    def _render_family(fam: Dict[str, Any], m: Dict[str, Any],
                       extra: Dict[str, Any]) -> None:
        name = m["name"]
        for s in m.get("samples", []):
            if not isinstance(s, dict):
                continue
            labels = {**(s.get("labels") or {}), **extra}
            key = _label_key(labels)
            if fam["type"] == "histogram":
                for le, acc in s.get("buckets", []):
                    lk = _label_key({**labels, "le": le})
                    fam["lines"].append(
                        f"{name}_bucket{format_labels(lk)} {acc}")
                fam["lines"].append(
                    f"{name}_sum{format_labels(key)} {s.get('sum', 0.0)}")
                fam["lines"].append(
                    f"{name}_count{format_labels(key)} "
                    f"{s.get('count', 0)}")
                for tag, v in (s.get("quantiles") or {}).items():
                    lk = _label_key({**labels,
                                     "quantile": f"0.{tag[1:]}"})
                    fam["qlines"].append(
                        f"{name}_q{format_labels(lk)} {v}")
            else:
                fam["lines"].append(
                    f"{name}{format_labels(key)} {s.get('value', 0.0)}")

    # ------------------------------------------------------------ trace
    def merged_trace_events(self) -> List[Dict[str, Any]]:
        """Spans from every process on ONE timeline: per-process
        ``process_name`` metadata lanes first, then all recorded spans
        ordered by wall-clock ``ts`` — trace ids were already
        propagated at record time (CTX frames, context_scope), so a
        cross-process flow lines up without any join logic here."""
        with self._lock:
            procs = [(name, dict(e))
                     for name, e in sorted(self._procs.items())]
            spans = [e for dq in self._spans.values() for e in dq]
        out: List[Dict[str, Any]] = []
        for name, e in procs:
            out.append({
                "name": "process_name", "ph": "M", "cat": "__metadata",
                "pid": e["pid"], "tid": 0, "ts": 0, "dur": 0,
                "args": {"name": f"{e['role']} {name}@{e['node']}"}})
        out.extend(sorted(
            spans, key=lambda ev: (ev.get("ts") or 0,
                                   ev.get("pid") or 0)))
        return out

    def merged_trace_json(self) -> str:
        return json.dumps(self.merged_trace_events())

    # ------------------------------------------------------------ watch
    @staticmethod
    def _snapshot_value(metrics: List[Dict[str, Any]], name: str,
                        agg: str = "sum") -> Optional[float]:
        for m in metrics:
            if m.get("name") != name:
                continue
            vals = [s.get("value") for s in m.get("samples", [])
                    if isinstance(s, dict)
                    and isinstance(s.get("value"), (int, float))]
            if not vals:
                return None
            return float(sum(vals) if agg == "sum" else max(vals))
        return None

    def watch_rows(self) -> List[Dict[str, Any]]:
        """Per-process headline numbers for the live console."""
        now = self._clock()
        with self._lock:
            items = [(name, dict(e)) for name, e in
                     sorted(self._procs.items())]
        rows = []
        for name, e in items:
            metrics = e["metrics"]
            rows.append({
                "proc": name, "role": e["role"], "pid": e["pid"],
                "node": e["node"],
                "status": self._proc_status(e, now),
                "last_seen_s": round(now - e["last_push"], 1),
                "steps_per_s": self._snapshot_value(
                    metrics, "train_samples_per_sec"),
                "input_bound": self._snapshot_value(
                    metrics, "input_bound_ratio", agg="max"),
                "hbm_peak_bytes": self._snapshot_value(
                    metrics, "hbm_peak_bytes", agg="max"),
                "health": str(e["health"].get("status", "?")),
                "version": (e.get("serving") or {}).get("model_version"),
                "rollout": (e.get("serving") or {}).get("rollout_state"),
                "ttft_p99_s": (e.get("serving") or {}).get("ttft_p99_s"),
                "slo": (e.get("slo") or {}).get("status"),
            })
        return rows

    def reset(self) -> None:
        with self._lock:
            self._procs.clear()
            self._spans.clear()


# ---------------------------------------------------------- aggregator
class _FleetHandler(BaseHTTPRequestHandler):
    server_version = "paddle-tpu-fleet"

    def _send(self, code: int, body: str, ctype: str) -> None:
        data = body.encode()
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _send_json(self, code: int, payload: Dict[str, Any]) -> None:
        self._send(code, json.dumps(payload), "application/json")

    def do_GET(self) -> None:  # noqa: N802 — BaseHTTPRequestHandler API
        path = self.path.split("?", 1)[0].rstrip("/") or "/"
        state: FleetState = self.server.state
        try:
            if path == "/fleet/metrics":
                self._send(200, state.merged_prometheus(),
                           "text/plain; version=0.0.4")
            elif path == "/fleet/healthz":
                self._send_json(200, state.rollup())
            elif path == "/fleet/trace":
                self._send(200, state.merged_trace_json(),
                           "application/json")
            elif path == "/fleet/topology":
                self._send_json(200, state.topology())
            else:
                self._send_json(404, {
                    "error": "unknown path",
                    "paths": ["/fleet/metrics", "/fleet/healthz",
                              "/fleet/trace", "/fleet/topology",
                              "POST /fleet/push"]})
        except BrokenPipeError:      # scraper hung up mid-response
            pass
        except Exception as e:       # noqa: BLE001 — never kill serving
            try:
                self._send(500, f"fleet handler error: {e}\n",
                           "text/plain")
            except OSError:
                pass

    def do_POST(self) -> None:  # noqa: N802 — BaseHTTPRequestHandler API
        path = self.path.split("?", 1)[0].rstrip("/")
        state: FleetState = self.server.state
        try:
            if path != "/fleet/push":
                self._send_json(404, {"error": "unknown path",
                                      "paths": ["POST /fleet/push"]})
                return
            length = int(self.headers.get("Content-Length") or 0)
            raw = self.rfile.read(length) if length else b""
            try:
                frame = json.loads(raw.decode("utf-8", "replace"))
            except ValueError:
                self._send_json(400, {"error": "push body is not JSON",
                                      "schema": FLEET_SCHEMA})
                return
            try:
                ack = state.ingest(frame)
            except FleetSchemaError as e:
                self._send_json(400, {"error": str(e),
                                      "schema": FLEET_SCHEMA})
                return
            except FleetFrameError as e:
                self._send_json(400, {"error": str(e),
                                      "schema": FLEET_SCHEMA})
                return
            self._send_json(200, ack)
        except BrokenPipeError:
            pass
        except Exception as e:       # noqa: BLE001 — never kill serving
            try:
                self._send(500, f"fleet handler error: {e}\n",
                           "text/plain")
            except OSError:
                pass

    def log_message(self, fmt: str, *args) -> None:
        from ..utils.logger import get_logger

        get_logger("observe.fleet").debug("http %s", fmt % args)


class FleetAggregator:
    """The hosted aggregator: :class:`FleetState` behind a
    ``ThreadingHTTPServer`` (thread name ``ptpu-fleet-http``)."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 state: Optional[FleetState] = None):
        from .http import make_threading_server

        self.state = state if state is not None else FleetState()
        self._httpd = make_threading_server(host, port, _FleetHandler)
        self._httpd.daemon_threads = True
        self._httpd.state = self.state
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    @property
    def addr(self) -> str:
        """A CONNECTABLE host:port for this aggregator — the bind host,
        except the wildcard binds (empty / 0.0.0.0 / ::), which are
        reachable locally via loopback."""
        host = self.host
        if host in ("", "0.0.0.0", "::"):
            host = "127.0.0.1"
        return f"{host}:{self.port}"

    def start(self) -> "FleetAggregator":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._httpd.serve_forever,
                kwargs={"poll_interval": 0.1},
                name=AGGREGATOR_THREAD_NAME, daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        t, self._thread = self._thread, None
        if t is not None:
            self._httpd.shutdown()
            t.join(timeout=5.0)
        self._httpd.server_close()

    def __enter__(self) -> "FleetAggregator":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


_global: Optional[FleetAggregator] = None
_global_lock = named_lock("observe.fleet.global")


def start_from_flags() -> Optional[FleetAggregator]:
    """Host the process-wide aggregator iff ``--fleet_port`` > 0.
    Idempotent; an unbindable port warns once and leaves the process
    running — telemetry never kills the run it observes."""
    global _global
    from ..utils import FLAGS
    from ..utils.logger import get_logger, warn_once
    from .http import resolve_bind_host

    port = int(FLAGS.get("fleet_port"))
    if port <= 0:
        return _global
    with _global_lock:
        if _global is None:
            host = resolve_bind_host("fleet_bind")
            try:
                _global = FleetAggregator(port, host=host).start()
            except OSError as e:
                warn_once(
                    f"fleet_port_bind_failed:{port}",
                    "--fleet_port %d could not be bound (%s); the "
                    "fleet aggregator is OFF for this run", port, e,
                    logger=get_logger("observe"))
                return None
            get_logger("observe").info(
                "fleet aggregator on http://%s:%d (/fleet/metrics "
                "/fleet/healthz /fleet/trace /fleet/topology)",
                host, _global.port)
    return _global


def hosting() -> bool:
    """True iff this process hosts the process-wide aggregator — the
    SIGUSR2 debug dump keys its ``.fleet.json`` artifact on this."""
    return _global is not None


def topology() -> Optional[Dict[str, Any]]:
    agg = _global
    return agg.state.topology() if agg is not None else None


def rollup() -> Optional[Dict[str, Any]]:
    agg = _global
    return agg.state.rollup() if agg is not None else None


def stop_global() -> None:
    global _global
    with _global_lock:
        agg, _global = _global, None
    if agg is not None:
        agg.stop()


# -------------------------------------------------------------- pusher
class FleetPusher:
    """The push half: builds and POSTs one frame per reporter interval.

    Owned by :class:`paddle_tpu.observe.report.MetricsReporter` and
    driven from ITS background thread — the pusher starts no thread of
    its own and never touches the train step.  Failure semantics are
    the PR-4 retry/backoff/degrade contract (see module docstring)."""

    def __init__(self, addr: str, interval_s: float = 10.0,
                 registry=None, stat: Any = None,
                 timeout_s: Optional[float] = None,
                 jsonl_degraded: Optional[Callable[[], bool]] = None,
                 clock: Callable[[], float] = time.monotonic):
        from ..utils import FLAGS

        host, _, port_s = addr.rpartition(":")
        try:
            self.host, self.port = host or "127.0.0.1", int(port_s)
        except ValueError:
            raise ValueError(
                f"--fleet_addr {addr!r}: expected host:port")
        self.addr = addr
        self.interval_s = float(interval_s)
        self.registry = REGISTRY if registry is None else registry
        self.stat = stat
        self.timeout_s = float(FLAGS.get("fleet_push_timeout_s")
                               if timeout_s is None else timeout_s)
        self._jsonl_degraded = jsonl_degraded
        self._clock = clock
        self.degraded = False
        self.failures = 0            # consecutive
        self._skip_until = 0.0
        self._seq = 0
        self._t0 = clock()
        self._last_span_ts = 0.0
        self._pending_span_ts = 0.0
        # per-client jitter nonce: a fleet of trainers restarting in
        # lockstep must not retry the aggregator in lockstep (the PR-4
        # reconnect-stampede lesson)
        self._jitter = random.Random(f"{addr}:{os.getpid()}")
        self._lock = named_lock("observe.fleet.pusher")

    # ------------------------------------------------------------ frame
    @staticmethod
    def _span_end(e: Dict[str, Any]) -> float:
        return (e.get("ts") or 0) + (e.get("dur") or 0)

    def _new_spans(self) -> Tuple[List[Dict[str, Any]], float, int]:
        """Flight-recorder events recorded since the last acknowledged
        push: (events, candidate high-water mark, dropped count).  The
        mark is the END time (ts + dur) — spans are recorded at exit
        with ts = their START, so filtering on start would silently
        drop any long span straddling a push boundary (a 0.5 s
        master_rpc starting before a short span that already shipped);
        boundary-equal resends are harmless, the aggregator dedups by
        span id."""
        evs = [e for e in trace.events()
               if self._span_end(e) > self._last_span_ts]
        dropped = 0
        if len(evs) > MAX_SPANS_PER_FRAME:
            dropped = len(evs) - MAX_SPANS_PER_FRAME
            evs = evs[-MAX_SPANS_PER_FRAME:]
        high = max((self._span_end(e) for e in evs),
                   default=self._last_span_ts)
        return evs, high, dropped

    def build_frame(self, going_down: bool = False) -> Dict[str, Any]:
        ident = identity()
        spans, self._pending_span_ts, dropped = self._new_spans()
        timers: List[Dict[str, Any]] = []
        if self.stat is not None:
            snap = self.stat.snapshot()
            timers = [snap[n] for n in sorted(snap)]
        digest = local_health_digest()
        if self._jsonl_degraded is not None and self._jsonl_degraded():
            digest["status"] = _DEGRADED
            digest["jsonl_sink"] = _DEGRADED
        frame = {
            "schema": FLEET_SCHEMA, "kind": "fleet-frame",
            "role": ident["role"], "name": ident["name"],
            "node": ident["node"], "pid": os.getpid(),
            "seq": self._seq, "ts": round(time.time(), 3),
            "uptime_s": round(self._clock() - self._t0, 3),
            "interval_s": self.interval_s,
            "going_down": bool(going_down),
            "health": digest,
            "metrics": self.registry.snapshot(),
            "timers": timers,
            "spans": spans,
        }
        if dropped:
            frame["spans_dropped"] = dropped
        serving = serving_info()
        if serving:
            # additive, optional: only processes that loaded a serving
            # model carry it, and older aggregators ignore the key
            frame["serving"] = serving
            # windowed serving signals ride the frame so the canary
            # bake can compare replicas fleet-side (sys.modules read:
            # the registry was imported long before any pusher exists)
            hist = self.registry.find("serve_ttft_seconds")
            if hist is not None and hasattr(hist, "window_quantile"):
                p99 = hist.window_quantile(0.99, 60.0)
                if p99 is not None:
                    serving["ttft_p99_s"] = round(p99, 6)
            errs = self.registry.find("serve_request_failures")
            if errs is not None and hasattr(errs, "window_rate"):
                serving["error_rate_s"] = round(
                    errs.window_rate(60.0), 6)
        # SLO verdicts (additive, optional — same discipline); the
        # reporter evaluated right before this push, so last() is fresh
        smod = sys.modules.get("paddle_tpu.observe.slo")
        eng = smod.active_engine() if smod is not None else None
        if eng is not None:
            frame["slo"] = eng.frame_digest()
        return frame

    # ------------------------------------------------------------- push
    def maybe_push(self) -> Optional[bool]:
        """Interval-driven push honoring the backoff window: returns
        None while backing off, else the push outcome."""
        if self._clock() < self._skip_until:
            return None
        return self.push()

    def push(self, going_down: bool = False) -> bool:
        """Build + POST one frame.  Never raises; a failure (network,
        HTTP != 200, bare-ERR body, version skew) degrades the sink
        with warn-once and schedules backoff; success clears the
        degraded state and advances the span high-water mark."""
        from .metrics import counter, histogram

        t0 = time.perf_counter()
        with self._lock:
            try:
                frame = self.build_frame(going_down=going_down)
                ack = self._post(frame)
            except Exception as e:   # noqa: BLE001 — telemetry never
                self._note_failure(e)        # kills the process it
                counter("fleet_pushes_total",     # observes
                        "fleet frames pushed, by result").inc(
                    result="error")
                return False
            self._seq += 1
            self._last_span_ts = self._pending_span_ts
            recovered, self.degraded, self.failures = \
                self.degraded, False, 0
            self._skip_until = 0.0
        counter("fleet_pushes_total",
                "fleet frames pushed, by result").inc(result="ok")
        histogram("fleet_push_seconds",
                  "one fleet frame build + POST round trip (runs on "
                  "the reporter thread, never the step path)").observe(
            time.perf_counter() - t0)
        if recovered:
            from ..utils.logger import get_logger, reset_warn_once

            get_logger("observe").info(
                "fleet push to %s recovered after degradation",
                self.addr)
            reset_warn_once(f"fleet_push_failed:{self.addr}")
        return True

    def _post(self, frame: Dict[str, Any]) -> Dict[str, Any]:
        body = json.dumps(frame)
        conn = http.client.HTTPConnection(self.host, self.port,
                                          timeout=self.timeout_s)
        try:
            conn.request("POST", "/fleet/push", body=body,
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            data = resp.read()
        finally:
            try:
                conn.close()
            except OSError as e:
                from ..utils.logger import get_logger
                get_logger("observe").debug(
                    "fleet push connection close failed: %s", e)
        try:
            ack = json.loads(data.decode("utf-8", "replace"))
        except ValueError:
            # a bare-ERR (or any non-JSON) body: a peer speaking a
            # different dialect — degrade exactly like a failing flush
            raise OSError(
                f"aggregator answered non-JSON ({resp.status}): "
                f"{data[:80]!r}")
        if resp.status != 200 or not isinstance(ack, dict) \
                or ack.get("ok") is not True:
            err = ack.get("error") if isinstance(ack, dict) else ack
            raise OSError(
                f"aggregator refused frame (HTTP {resp.status}): {err}")
        if int(ack.get("schema") or 0) > FLEET_SCHEMA:
            raise OSError(
                f"aggregator speaks schema {ack.get('schema')} > "
                f"{FLEET_SCHEMA} (version skew)")
        return ack

    def _note_failure(self, e: Exception) -> None:
        from ..utils.logger import get_logger, warn_once

        self.degraded = True
        self.failures += 1
        backoff = min(self.interval_s * (2.0 ** (self.failures - 1)),
                      max(60.0, 8.0 * self.interval_s))
        backoff *= 1.0 + 0.25 * self._jitter.random()
        self._skip_until = self._clock() + backoff
        warn_once(
            f"fleet_push_failed:{self.addr}",
            "fleet push to %s failed (%s: %s); the push sink is "
            "DEGRADED — frames are being dropped, retrying with "
            "backoff (reported once)", self.addr, type(e).__name__, e,
            logger=get_logger("observe"))


# ------------------------------------------------------- watch console
def _http_get(addr: str, path: str, timeout_s: float = 5.0) -> bytes:
    host, _, port_s = addr.rpartition(":")
    conn = http.client.HTTPConnection(host or "127.0.0.1", int(port_s),
                                      timeout=timeout_s)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        data = resp.read()
        if resp.status != 200:
            raise OSError(f"GET {path}: HTTP {resp.status}")
        return data
    finally:
        conn.close()


def _fmt_bytes(v: Optional[float]) -> str:
    if v is None:
        return "-"
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if abs(v) < 1024.0:
            return f"{v:.1f}{unit}"
        v /= 1024.0
    return f"{v:.1f}PB"


def _parse_prom_labels(label_str: str) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for part in label_str.strip("{}").split(","):
        k, eq, v = part.partition("=")
        if eq:
            out[k.strip()] = v.strip().strip('"')
    return out


def summarize_label_families(prom: str, threshold: int = 8,
                             top_k: int = 3) -> List[str]:
    """Label-explosion guard for the watch console: a gauge family
    with ``threshold``-or-more labelled series — the per-(category,
    shard) ``hbm_shard_bytes`` family on a wide mesh is the canonical
    case — renders as ONE summary line (series count, total, top-k
    series by value) instead of one console line per series.  Families
    below the threshold are left to their usual columns."""
    fams: Dict[str, List[Any]] = {}
    for line in prom.splitlines():
        if line.startswith("#") or "{" not in line:
            continue
        fam = line.split("{", 1)[0]
        labels, _, value = line.rpartition("} ")
        try:
            v = float(value)
        except ValueError:
            continue
        fams.setdefault(fam, []).append(
            (_parse_prom_labels(labels.split("{", 1)[-1]), v))
    out: List[str] = []
    for fam in sorted(fams):
        series = fams[fam]
        if len(series) < threshold:
            continue
        fmt = _fmt_bytes if fam.endswith("_bytes") \
            else (lambda v: f"{v:g}")
        top = sorted(series, key=lambda s: -s[1])[:top_k]
        cells = []
        for labels, v in top:
            key = ",".join(f"{k}={labels[k]}" for k in sorted(labels)
                           if k != "proc")
            cells.append(f"{key}={fmt(v)}")
        total = sum(v for _, v in series)
        out.append(f"{fam}  {len(series)} series  total={fmt(total)}"
                   f"  top: " + "  ".join(cells))
    return out


def render_watch(rollup_doc: Dict[str, Any],
                 rows: List[Dict[str, Any]],
                 family_summaries: Optional[List[str]] = None) -> str:
    """The live-console frame: one aligned row per process, plus a
    top-k summary line per label-explosion gauge family (see
    :func:`summarize_label_families`)."""
    hdr = (f"fleet: {rollup_doc['status']}  "
           + "  ".join(f"{k}={v}" for k, v in
                       sorted(rollup_doc.get("counts", {}).items())
                       if v))
    cols = ["proc", "role", "pid", "status", "step/s", "input_bound",
            "hbm_peak", "health", "version", "p99_ttft", "slo",
            "last_seen"]
    table: List[List[str]] = [cols]
    for r in rows:
        version = r.get("version")
        rollout = r.get("rollout")
        # digest-prefix + swap state: "1a2b3c4d5e6f" while serving,
        # "1a2b…(swapping)" mid-rollout — a rolling rollout is visible
        # as the column changing row by row
        if version is None:
            vcell = "-"
        else:
            vcell = str(version)[:12]
            if rollout and rollout != "serving":
                vcell = f"{vcell[:6]}…({rollout})"
        p99 = r.get("ttft_p99_s")
        table.append([
            str(r["proc"]), str(r["role"]), str(r["pid"]),
            str(r["status"]),
            "-" if r["steps_per_s"] is None
            else f"{r['steps_per_s']:.1f}",
            "-" if r["input_bound"] is None
            else f"{r['input_bound']:.3f}",
            _fmt_bytes(r["hbm_peak_bytes"]),
            str(r["health"]), vcell,
            "-" if p99 is None else f"{p99 * 1e3:.0f}ms",
            str(r.get("slo") or "-"),
            f"{r['last_seen_s']:.1f}s",
        ])
    widths = [max(len(row[i]) for row in table)
              for i in range(len(cols))]
    lines = [hdr, ""]
    for row in table:
        lines.append("  ".join(c.ljust(w) for c, w in
                               zip(row, widths)).rstrip())
    if family_summaries:
        lines.append("")
        lines.append("label-wide families (one line per family, "
                     "top series by value):")
        lines.extend(f"  {s}" for s in family_summaries)
    return "\n".join(lines)


def watch_once(addr: str) -> str:
    """One console frame from a remote aggregator (fetch + render)."""
    roll = json.loads(_http_get(addr, "/fleet/healthz"))
    topo = json.loads(_http_get(addr, "/fleet/topology"))
    # re-derive watch rows from the remote documents: the remote holds
    # the snapshots, so headline numbers ride a dedicated scrape of
    # /fleet/metrics only when needed — topology + rollup are enough
    # for the table's identity/status columns
    rows = []
    for name, p in sorted(topo.get("procs", {}).items()):
        r = roll.get("procs", {}).get(name, {})
        rows.append({
            "proc": name, "role": p["role"], "pid": p["pid"],
            "node": p["node"], "status": r.get("status", "?"),
            "last_seen_s": p["last_push_age_s"],
            "steps_per_s": None, "input_bound": None,
            "hbm_peak_bytes": None,
            # liveness (rollup) and the pushed health digest are
            # DIFFERENT columns: a missing process still shows its
            # last-known health
            "health": p.get("health", "?"),
            "version": p.get("model_version"),
            "rollout": p.get("rollout_state"),
            "ttft_p99_s": p.get("ttft_p99_s"),
            "slo": p.get("slo"),
        })
    # headline metrics come from the merged exposition
    summaries: List[str] = []
    try:
        prom = _http_get(addr, "/fleet/metrics").decode()
        _fill_headline_from_prometheus(prom, rows)
        summaries = summarize_label_families(prom)
    except OSError:
        pass
    return render_watch(roll, rows, family_summaries=summaries)


def _fill_headline_from_prometheus(prom: str,
                                   rows: List[Dict[str, Any]]) -> None:
    """Scrape per-proc headline gauges back out of the merged text."""
    want = {"train_samples_per_sec": "steps_per_s",
            "input_bound_ratio": "input_bound",
            "hbm_peak_bytes": "hbm_peak_bytes"}
    by_proc = {r["proc"]: r for r in rows}
    for line in prom.splitlines():
        if line.startswith("#") or "{" not in line:
            continue
        fam = line.split("{", 1)[0]
        field = want.get(fam)
        if field is None:
            continue
        labels, _, value = line.rpartition("} ")
        proc = None
        for part in labels.split("{", 1)[-1].split(","):
            if part.startswith('proc="'):
                proc = part[len('proc="'):].rstrip('"')
        row = by_proc.get(proc)
        if row is None:
            continue
        try:
            row[field] = float(value)
        except ValueError:
            continue


def watch_loop(addr: str, interval_s: float = 2.0,
               once: bool = False, out=None) -> int:
    """The ``--watch`` console: redraw every ``interval_s`` until
    interrupted (or a single frame with ``once``)."""
    out = sys.stdout if out is None else out
    while True:
        try:
            frame = watch_once(addr)
        except (OSError, ValueError) as e:
            frame = f"fleet: aggregator at {addr} unreachable ({e})"
        print(frame, file=out, flush=True)
        if once:
            return 0
        try:
            time.sleep(interval_s)
        except KeyboardInterrupt:
            return 0
        print("", file=out)


def main(argv: Optional[List[str]] = None) -> int:
    """``python -m paddle_tpu.observe.fleet``: host a standalone
    aggregator (``--fleet_port``) or watch a running one
    (``--watch host:port``)."""
    from ..utils import FLAGS

    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.observe.fleet",
        description="fleet observatory: host or watch an aggregator")
    ap.add_argument("--watch", metavar="HOST:PORT",
                    help="render the live per-process console from a "
                         "running aggregator")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="console refresh period (seconds)")
    ap.add_argument("--once", action="store_true",
                    help="render one console frame and exit")
    ap.add_argument("--fleet_port", type=int, default=None,
                    help="host a standalone aggregator on this port")
    ap.add_argument("--fleet_bind", default=None,
                    help="aggregator bind address (default loopback; "
                         "non-loopback is an explicit opt-in and warns "
                         "— fleet telemetry is not an external API)")
    args = ap.parse_args(argv)
    if args.watch:
        return watch_loop(args.watch, interval_s=args.interval,
                          once=args.once)
    if args.fleet_port is None:
        ap.error("one of --watch HOST:PORT or --fleet_port N required")
    FLAGS.set("fleet_port", args.fleet_port)
    if args.fleet_bind is not None:
        FLAGS.set("fleet_bind", args.fleet_bind)
    agg = start_from_flags()
    if agg is None:
        return 1
    print(f"fleet aggregator on :{agg.port} (/fleet/metrics "
          "/fleet/healthz /fleet/trace /fleet/topology)", flush=True)
    stop: List[int] = []
    try:
        signal.signal(signal.SIGTERM, lambda *_: stop.append(1))
        signal.signal(signal.SIGINT, lambda *_: stop.append(1))
    except ValueError as e:   # non-main thread (embedding): poll-only
        from ..utils.logger import get_logger
        get_logger("observe").debug(
            "fleet main: signal handlers unavailable: %s", e)
    while not stop:
        time.sleep(0.2)
    stop_global()
    return 0


if __name__ == "__main__":
    # `python -m paddle_tpu.observe.fleet` runs a runpy COPY of this
    # module while the package's eager import holds the canonical one
    # — delegate so --fleet_port hosting lands in the state every
    # other surface (dump.py, hosting()) actually reads.
    from paddle_tpu.observe import fleet as _canonical

    sys.exit(_canonical.main())

"""``paddle`` CLI — the ``paddle train`` driver
(``paddle/trainer/TrainerMain.cpp:32`` + ``paddle/scripts/submit_local.sh.in``).

Jobs: train / test / time / checkgrad (``--job=``, ``Trainer.cpp:299``,
``TrainerBenchmark.cpp``), plus ``version``.  Config files use the v1
protocol (see :mod:`paddle_tpu.config.config_parser`).

Usage:
    python -m paddle_tpu train --config=conf.py --job=time \
        --config_args batch_size=64 --num_passes=2 --save_dir=./out
"""

from __future__ import annotations

import argparse
import importlib
import json
import os
import sys
import time
from typing import Any, Dict, List, Optional

from .utils import FLAGS, get_logger

log = get_logger("cli")


def _build_reader(ds, opt, test: bool = False):
    """Data source spec → batched reader (PyDataProvider2 protocol)."""
    from .data.reader import batch as batch_reader

    file_list: List[str] = []
    lst = ds.test_list if test and ds.test_list else ds.train_list
    if lst and os.path.exists(lst):
        with open(lst) as f:
            file_list = [ln.strip() for ln in f if ln.strip()]
    mod = importlib.import_module(ds.module)
    provider = getattr(mod, ds.obj)
    reader = provider.reader(*file_list, **ds.args)
    return batch_reader(reader, opt.batch_size), provider


def _feeder_for(provider, model):
    from .data.feeder import DataFeeder

    # init_hook providers fill settings.input_types when reader() is built
    types = provider.input_types or \
        getattr(provider.settings, "input_types", None)
    if isinstance(types, dict):
        pairs = list(types.items())
    else:
        data_layers = [l for l in model.layers if l.type == "data"]
        pairs = [(dl.name, t) for dl, t in zip(data_layers, types)]
    return DataFeeder(pairs)


def cmd_train(args) -> int:
    from .config.config_parser import parse_config
    from .distributed.launch import cluster_env, initialize_cluster
    from .layers.network import NeuralNetwork
    from .parallel.local_sgd import make_trainer

    if cluster_env() or args.distributed:
        # PADDLE_COORDINATOR set, or --distributed for TPU-pod
        # auto-detection
        env = cluster_env()
        if env and env["coordinator_address"] and not (
                env["num_processes"] and env["process_id"] is not None):
            log.error("PADDLE_COORDINATOR requires PADDLE_NUM_NODES "
                      "and PADDLE_NODE_ID")
            return 2
        initialize_cluster()

    model, opt, ds = parse_config(args.config, args.config_args)
    log.info("config parsed: %d layers, batch_size=%d, method=%s",
             len(model.layers), opt.batch_size, opt.learning_method)
    # provider modules live next to the config file
    cfg_dir = os.path.dirname(os.path.abspath(args.config))
    if cfg_dir not in sys.path:
        sys.path.insert(0, cfg_dir)
    net = NeuralNetwork(model)
    # honors OptimizationConfig.local_sgd_steps (async/local-SGD mode)
    trainer = make_trainer(net, opt)
    # restore parameters BEFORE any job runs (test must see them)
    if args.init_model_path:
        trainer.load(args.init_model_path)
    if args.save_dir:
        FLAGS.set("save_dir", args.save_dir)
        trainer.resume(args.save_dir)
    reader, provider = _build_reader(ds, opt, test=(args.job == "test"))
    feeder = _feeder_for(provider, model)

    if args.job == "time":
        metrics = trainer.time_job(reader, feeder,
                                   batches=args.test_period or 20)
        print(json.dumps({"job": "time", **{k: round(v, 3)
                                            for k, v in metrics.items()}}))
        return 0
    if args.job == "checkgrad":
        batch = next(iter(reader()))
        diffs = trainer.check_gradients(feeder.convert(batch))
        bad = {k: v for k, v in diffs.items() if v > 1e-2}
        print(json.dumps({"job": "checkgrad", "checked": len(diffs),
                          "failed": len(bad)}))
        return 1 if bad else 0
    if args.job == "test":
        metrics = trainer.test(reader, feeder)
        print(json.dumps({"job": "test", **metrics}))
        return 0

    trainer.train(reader, num_passes=args.num_passes, feeder=feeder)
    if args.save_dir:
        trainer.save(args.save_dir, args.num_passes - 1)
    return 0


def cmd_merge_model(args) -> int:
    """``paddle_merge_model`` (``paddle/trainer/MergeModel.cpp``): config
    + trained parameters → ONE self-contained model file."""
    from .config.config_parser import parse_config
    from .trainer import interop

    model, _opt, _ds = parse_config(args.config_file, args.config_args)
    model = interop.with_full_param_specs(model)
    params = interop.checkpoint_to_params(args.model_dir)
    if not params:  # reference raw-buffer pass-%05d layout
        params = interop.load_reference_model_dir(args.model_dir, model)
    missing = [p.name for p in model.parameters if p.name not in params]
    if missing:
        log.error("model_dir %s lacks parameters: %s", args.model_dir,
                  missing)
        return 1
    interop.merge_model(model, params, args.model_file)
    print(json.dumps({"job": "merge_model", "out": args.model_file,
                      "parameters": len(model.parameters)}))
    return 0


def cmd_dump_config(args) -> int:
    """``dump_config``/``show_pb`` equivalent
    (``python/paddle/utils/dump_config.py``): print the parsed model
    config (``--whole`` adds optimization + data config)."""
    from .config.config_parser import parse_config

    model, opt, ds = parse_config(args.config, args.config_args)
    if args.whole:
        import dataclasses
        payload = {"model": json.loads(model.to_json()),
                   "opt": dataclasses.asdict(opt),
                   "data": dataclasses.asdict(ds) if ds else None}
        print(json.dumps(payload, indent=1))
    else:
        print(model.to_json())
    return 0


def cmd_diagram(args) -> int:
    """``make_model_diagram.py`` equivalent: config → graphviz DOT."""
    from .config.config_parser import parse_config
    from .utils.model_diagram import model_to_dot

    model, _, _ = parse_config(args.config, args.config_args)
    print(model_to_dot(model))
    return 0


def cmd_master(args) -> int:
    """Standalone data-task master (the reference's standalone
    coordinator binaries: ``paddle pserver`` / ``go/cmd/master``) — serve
    the C++ task-lease service over TCP for remote trainers."""
    import signal

    from .data import recordio as rio
    from .distributed import Master

    m = Master(timeout_s=args.task_timeout, failure_max=args.failure_max,
               snapshot_path=args.snapshot or "")
    if args.dataset:
        payloads = rio.chunk_payloads(args.dataset) if args.chunked \
            else rio.expand_paths(args.dataset)
        m.set_dataset(payloads)
        print(f"dataset: {len(payloads)} task(s)")
    port = m.serve(args.port, bind_any=not args.local_only)
    print(f"master serving on :{port}", flush=True)
    stop = []
    signal.signal(signal.SIGTERM, lambda *_: stop.append(1))
    signal.signal(signal.SIGINT, lambda *_: stop.append(1))
    import time

    last_snap = time.time()
    while not stop:
        time.sleep(0.5)
        if args.snapshot and time.time() - last_snap >= args.snapshot_period:
            m.snapshot()
            last_snap = time.time()
    if args.snapshot:
        m.snapshot()
    return 0


def cmd_version(_args) -> int:
    import jax

    from . import __version__
    print(f"paddle_tpu {__version__} (jax {jax.__version__}, "
          f"backend {jax.default_backend()})")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(prog="paddle",
                                     description=__doc__.splitlines()[0])
    sub = parser.add_subparsers(dest="command", required=True)

    tp = sub.add_parser("train", help="train/test/time/checkgrad a config")
    tp.add_argument("--config", required=True)
    tp.add_argument("--job", default="train",
                    choices=["train", "test", "time", "checkgrad"])
    tp.add_argument("--config_args", default="")
    tp.add_argument("--num_passes", type=int, default=1)
    tp.add_argument("--save_dir", default="")
    tp.add_argument("--init_model_path", default="")
    tp.add_argument("--test_period", type=int, default=0)
    tp.add_argument("--distributed", action="store_true",
                    help="join/auto-detect a multi-host cluster "
                         "(jax.distributed)")
    tp.add_argument("--mesh_shape", default="",
                    help="e.g. data=4,model=2 (replaces --trainer_count)")
    tp.add_argument("--precision", default=None,
                    choices=["fp32", "bf16"],
                    help="training precision policy: bf16 = fp32 "
                         "master weights + bf16 compute + dynamic "
                         "loss scaling (default fp32)")
    tp.add_argument("--use_bf16", type=int, default=None)
    tp.add_argument("--bf16_activations", type=int, default=None)
    tp.add_argument("--log_level", default="",
                    help="framework log level "
                         "(debug|info|warning|error|fatal)")
    tp.add_argument("--metrics_jsonl", default="",
                    help="telemetry sink: append one metrics+timers "
                         "snapshot line here every "
                         "--metrics_interval_s seconds")
    tp.add_argument("--metrics_interval_s", type=float, default=None)
    tp.add_argument("--trace_jsonl", default="",
                    help="span-trace sink: stream every span (step "
                         "phases, pipeline workers, master RPCs, "
                         "checkpoints) here as Chrome trace-event "
                         "JSON, loadable in Perfetto")
    tp.add_argument("--metrics_port", type=int, default=None,
                    help="serve /metrics + /healthz + /trace on this "
                         "loopback port during the run (0 = off)")
    tp.add_argument("--metrics_bind", default=None,
                    help="bind address for --metrics_port (default "
                         "loopback; non-loopback is an explicit, "
                         "loudly-warned opt-in — the endpoint is "
                         "diagnostics, not an external API)")
    tp.add_argument("--fleet_addr", default=None,
                    help="push one telemetry frame (metrics + recent "
                         "spans + health digest) per interval to the "
                         "fleet aggregator at host:port "
                         "(observe/fleet.py); a dead aggregator "
                         "degrades the push sink, never the run")
    tp.add_argument("--fleet_port", type=int, default=None,
                    help="host the fleet aggregator in this process: "
                         "/fleet/metrics /fleet/healthz /fleet/trace "
                         "/fleet/topology + POST /fleet/push "
                         "(0 = off)")
    tp.add_argument("--fleet_id", default=None,
                    help="logical fleet identity (e.g. trainer-0): "
                         "stable across restarts so the cluster "
                         "rollup recovers when this process comes "
                         "back")
    tp.add_argument("--debug_dump_signal", action="store_true",
                    help="SIGUSR2 dumps metrics + flight-recorder "
                         "trace of the live run to --debug_dump_dir")
    tp.add_argument("--health_interval", type=int, default=None,
                    help="training-health telemetry: drain per-layer "
                         "grad/param/update-ratio accumulators and run "
                         "the divergence/non-finite detectors every N "
                         "steps (served on /metrics, /health and "
                         "/healthz; 0 = off, the byte-for-byte legacy "
                         "step)")
    tp.set_defaults(fn=cmd_train)

    mp = sub.add_parser(
        "merge_model",
        help="fuse config + trained parameters into one model file")
    mp.add_argument("--model_dir", required=True,
                    help="pass-%%05d checkpoint dir (ours or reference "
                         "raw-buffer layout)")
    mp.add_argument("--config_file", required=True)
    mp.add_argument("--model_file", required=True,
                    help="output merged model path")
    mp.add_argument("--config_args", default="")
    mp.set_defaults(fn=cmd_merge_model)

    dp = sub.add_parser("dump_config",
                        help="parse a config file and print the model IR")
    dp.add_argument("config")
    dp.add_argument("config_args", nargs="?", default="")
    dp.add_argument("--whole", action="store_true",
                    help="include optimization + data config")
    dp.set_defaults(fn=cmd_dump_config)

    gp = sub.add_parser("diagram",
                        help="emit a graphviz DOT diagram of a config")
    gp.add_argument("config")
    gp.add_argument("config_args", nargs="?", default="")
    gp.set_defaults(fn=cmd_diagram)

    sp = sub.add_parser(
        "master",
        help="serve the standalone data-task master (pserver-era "
             "coordinator)")
    sp.add_argument("--port", type=int, default=0,
                    help="TCP port (0 = ephemeral, printed on start)")
    sp.add_argument("--dataset", nargs="*", default=[],
                    help="task payloads: file paths / globs")
    sp.add_argument("--chunked", action="store_true",
                    help="expand recordio files into per-chunk tasks")
    sp.add_argument("--task_timeout", type=float, default=60.0)
    sp.add_argument("--failure_max", type=int, default=3)
    sp.add_argument("--snapshot", default="",
                    help="snapshot/recover state file (written every "
                         "--snapshot_period seconds and on shutdown)")
    sp.add_argument("--snapshot_period", type=float, default=30.0)
    sp.add_argument("--local_only", action="store_true",
                    help="bind loopback instead of all interfaces")
    sp.add_argument("--fleet_port", type=int, default=None,
                    help="also host the fleet telemetry aggregator on "
                         "this port (observe/fleet.py) — the natural "
                         "home: trainers already know the master's "
                         "address (0 = off)")
    sp.add_argument("--fleet_bind", default=None,
                    help="aggregator bind address (default loopback; "
                         "non-loopback warns — not an external API)")
    sp.set_defaults(fn=cmd_master)

    vp = sub.add_parser("version", help="print build info")
    vp.set_defaults(fn=cmd_version)

    args = parser.parse_args(argv)
    if getattr(args, "mesh_shape", ""):
        FLAGS.set("mesh_shape", args.mesh_shape)
    if getattr(args, "precision", None) is not None:
        FLAGS.set("precision", args.precision)
    if getattr(args, "use_bf16", None) is not None:
        FLAGS.set("use_bf16", bool(args.use_bf16))
    if getattr(args, "bf16_activations", None) is not None:
        FLAGS.set("bf16_activations", bool(args.bf16_activations))
    if getattr(args, "log_level", "") or FLAGS.get("log_level"):
        from .utils import set_log_level
        if getattr(args, "log_level", ""):
            FLAGS.set("log_level", args.log_level)
        set_log_level(FLAGS.get("log_level"))
    if getattr(args, "metrics_jsonl", ""):
        FLAGS.set("metrics_jsonl", args.metrics_jsonl)
    if getattr(args, "metrics_interval_s", None) is not None:
        FLAGS.set("metrics_interval_s", args.metrics_interval_s)
    if getattr(args, "trace_jsonl", ""):
        FLAGS.set("trace_jsonl", args.trace_jsonl)
    if getattr(args, "metrics_port", None) is not None:
        FLAGS.set("metrics_port", args.metrics_port)
    if getattr(args, "metrics_bind", None) is not None:
        FLAGS.set("metrics_bind", args.metrics_bind)
    if getattr(args, "fleet_addr", None) is not None:
        FLAGS.set("fleet_addr", args.fleet_addr)
    if getattr(args, "fleet_port", None) is not None:
        FLAGS.set("fleet_port", args.fleet_port)
    if getattr(args, "fleet_id", None) is not None:
        FLAGS.set("fleet_id", args.fleet_id)
    if getattr(args, "fleet_bind", None) is not None:
        FLAGS.set("fleet_bind", args.fleet_bind)
    if getattr(args, "debug_dump_signal", False):
        FLAGS.set("debug_dump_signal", True)
    if getattr(args, "health_interval", None) is not None:
        FLAGS.set("health_interval", args.health_interval)
    # umbrella: --metrics_jsonl reporter, --trace_jsonl span sink,
    # --metrics_port endpoint, --debug_dump_signal handler — each a
    # no-op when its flag is unset (no thread starts)
    from . import observe
    observe.start_from_flags()
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())

"""Text model builders.

``lstm_text_classifier`` mirrors the reference's RNN benchmark config
(``benchmark/paddle/rnn/rnn.py``: data → embedding(128) → N × simple_lstm
→ last_seq → fc softmax → classification_cost) — the workload behind the
LSTM rows of ``benchmark/README.md:117-160``.
"""

from __future__ import annotations

from ..config import dsl
from ..config.model_config import ModelConfig
from ..data.feeder import integer_value, integer_value_sequence
from ..v2.networks import simple_lstm


def transformer_classifier_cost(vocab_size: int, model_dim: int = 128,
                                num_heads: int = 4, num_layers: int = 2,
                                ffn_dim: int = 512, num_classes: int = 2,
                                max_len: int = 2048,
                                causal: bool = False,
                                packed: bool = False,
                                block_q: int = 512,
                                block_k: int = 512,
                                data_name: str = "data"):
    """Build the transformer classifier cost INSIDE an open
    ``config_scope`` — shared by :func:`transformer_text_classifier`
    and ``demo/transformer/train.py`` so model zoo and demo can't
    drift."""
    net = dsl.data(data_name, integer_value_sequence(vocab_size))
    net = dsl.embedding(net, size=model_dim)
    net = dsl.position_embedding(net, max_len=max_len)
    for i in range(num_layers):
        att = dsl.scaled_dot_product_attention(
            dsl.layer_norm(net, name=f"ln{i}a"), size=model_dim,
            num_heads=num_heads, causal=causal, packed=packed,
            block_q=block_q, block_k=block_k,
            name=f"attn{i}", bias_attr=True)
        net = dsl.addto([net, att], name=f"res{i}a")
        ffn = dsl.fc(dsl.layer_norm(net, name=f"ln{i}f"),
                     size=ffn_dim, act=dsl.Activation("relu"),
                     name=f"ffn{i}_in")
        ffn = dsl.fc(ffn, size=model_dim, name=f"ffn{i}_out")
        net = dsl.addto([net, ffn], name=f"res{i}f")
    net = dsl.layer_norm(net, name="ln_final")
    net = dsl.pooling_layer(net, pooling_type=dsl.AvgPooling())
    net = dsl.fc(net, size=num_classes,
                 act=dsl.Activation("softmax"), name="cls")
    lab = dsl.data("label", integer_value(num_classes))
    return dsl.classification_cost(net, lab)


def transformer_text_classifier(vocab_size: int = 30000,
                                model_dim: int = 128, num_heads: int = 4,
                                num_layers: int = 2, ffn_dim: int = 512,
                                num_classes: int = 2,
                                max_len: int = 2048,
                                causal: bool = False,
                                packed: bool = False,
                                block_q: int = 512,
                                block_k: int = 512) -> ModelConfig:
    """Pre-LN transformer encoder classifier over the flash-attention
    layer: embedding + position table → N × (LN → multi-head attention →
    residual; LN → ffn → residual) → final LN → masked mean pool → fc
    softmax → classification_cost.  The attention core is the Pallas
    kernel (``ops/pallas_attention.py``) — this model is its product
    surface, the way the reference's RNN benchmark fronts ``hl_lstm``.
    """
    with dsl.config_scope():
        return dsl.topology(transformer_classifier_cost(
            vocab_size, model_dim, num_heads, num_layers, ffn_dim,
            num_classes, max_len, causal, packed,
            block_q=block_q, block_k=block_k))


def lstm_text_classifier(vocab_size: int = 30000, embed_dim: int = 128,
                         hidden_size: int = 512, lstm_num: int = 2,
                         num_classes: int = 2) -> ModelConfig:
    """Build the benchmark LSTM text classifier as a ModelConfig."""
    with dsl.config_scope():
        net = dsl.data("data", integer_value_sequence(vocab_size))
        net = dsl.embedding(net, size=embed_dim)
        for i in range(lstm_num):
            net = simple_lstm(net, size=hidden_size, name=f"lstm{i}")
        net = dsl.last_seq(net)
        net = dsl.fc(net, size=num_classes, act=dsl.Activation("softmax"))
        lab = dsl.data("label", integer_value(num_classes))
        cost = dsl.classification_cost(net, lab)
        return dsl.topology(cost)

"""Text model builders.

``lstm_text_classifier`` mirrors the reference's RNN benchmark config
(``benchmark/paddle/rnn/rnn.py``: data → embedding(128) → N × simple_lstm
→ last_seq → fc softmax → classification_cost) — the workload behind the
LSTM rows of ``benchmark/README.md:117-160``.
"""

from __future__ import annotations

from ..config import dsl
from ..config.model_config import ModelConfig
from ..data.feeder import integer_value, integer_value_sequence
from ..v2.networks import simple_lstm


def lstm_text_classifier(vocab_size: int = 30000, embed_dim: int = 128,
                         hidden_size: int = 512, lstm_num: int = 2,
                         num_classes: int = 2) -> ModelConfig:
    """Build the benchmark LSTM text classifier as a ModelConfig."""
    with dsl.config_scope():
        net = dsl.data("data", integer_value_sequence(vocab_size))
        net = dsl.embedding(net, size=embed_dim)
        for i in range(lstm_num):
            net = simple_lstm(net, size=hidden_size, name=f"lstm{i}")
        net = dsl.last_seq(net)
        net = dsl.fc(net, size=num_classes, act=dsl.Activation("softmax"))
        lab = dsl.data("label", integer_value(num_classes))
        cost = dsl.classification_cost(net, lab)
        return dsl.topology(cost)

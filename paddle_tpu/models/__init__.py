"""Model zoo: benchmark/demo network builders (reference ``benchmark/paddle``
configs and ``v1_api_demo/model_zoo`` re-expressed with the TPU-native DSL)."""

from .text import lstm_text_classifier, transformer_text_classifier  # noqa: F401

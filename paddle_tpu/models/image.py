"""Image model builders — the reference's benchmark + model-zoo networks.

Workloads from ``benchmark/paddle/image/{alexnet,googlenet,vgg,
smallnet_mnist_cifar}.py`` and ResNet from ``v1_api_demo/model_zoo/resnet``
/ ``test_image_classification_train.py`` (resnet_cifar10), rebuilt on the
TPU-native DSL.  All return a ``(prob_layer, cost_layer)`` pair given the
data/label layers so callers choose training or inference topologies.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from ..config import dsl
from ..config.dsl import (AvgPooling, ExtraAttr, LinearActivation,
                          MaxPooling, ReluActivation, SoftmaxActivation)


def _conv(net, fs, nf, stride=1, pad=None, channels=None, act=None,
          groups=1):
    return dsl.img_conv(net, filter_size=fs, num_filters=nf, stride=stride,
                        padding=fs // 2 if pad is None else pad,
                        num_channels=channels, groups=groups,
                        act=act or ReluActivation())


def _pool(net, size=3, stride=2, pad=0, avg=False):
    return dsl.img_pool(net, pool_size=size, stride=stride, padding=pad,
                        pool_type=AvgPooling() if avg else MaxPooling())


# ------------------------------------------------------------------ nets
def smallnet_mnist_cifar(img, num_classes: int = 10):
    """``smallnet_mnist_cifar.py`` (CIFAR quick): 3 conv + 2 fc."""
    net = _conv(img, 5, 32, 1, 2, channels=3)
    net = _pool(net, 3, 2, 1)
    net = _conv(net, 5, 32, 1, 2)
    net = _pool(net, 3, 2, 1, avg=True)
    net = _conv(net, 3, 64, 1, 1)
    net = _pool(net, 3, 2, 1, avg=True)
    net = dsl.fc(net, size=64, act=ReluActivation())
    return dsl.fc(net, size=num_classes, act=SoftmaxActivation())


def alexnet(img, num_classes: int = 1000):
    """``alexnet.py``: 5 conv (+LRN on 1-2) + 3 fc w/ dropout."""
    net = _conv(img, 11, 96, 4, 1, channels=3)
    net = dsl.img_cmrnorm(net, size=5, scale=0.0001, power=0.75)
    net = _pool(net)
    net = _conv(net, 5, 256, 1, 2)
    net = dsl.img_cmrnorm(net, size=5, scale=0.0001, power=0.75)
    net = _pool(net)
    net = _conv(net, 3, 384, 1, 1)
    net = _conv(net, 3, 384, 1, 1)
    net = _conv(net, 3, 256, 1, 1)
    net = _pool(net)
    net = dsl.fc(net, size=4096, act=ReluActivation(),
                 layer_attr=ExtraAttr(drop_rate=0.5))
    net = dsl.fc(net, size=4096, act=ReluActivation(),
                 layer_attr=ExtraAttr(drop_rate=0.5))
    return dsl.fc(net, size=num_classes, act=SoftmaxActivation())


def vgg(img, depth: int = 19, num_classes: int = 1000):
    """``vgg.py``: VGG-16/19 as conv groups + 2×4096 fc."""
    assert depth in (16, 19)
    reps = 3 if depth == 16 else 4
    from ..v2.networks import img_conv_group

    net = img_conv_group(img, conv_num_filter=[64, 64],
                         conv_filter_size=3, conv_act=ReluActivation(),
                         pool_size=2, pool_stride=2, num_channels=3)
    net = img_conv_group(net, conv_num_filter=[128, 128],
                         conv_filter_size=3, conv_act=ReluActivation(),
                         pool_size=2, pool_stride=2)
    for nf in (256, 512, 512):
        net = img_conv_group(net, conv_num_filter=[nf] * reps,
                             conv_filter_size=3,
                             conv_act=ReluActivation(), pool_size=2,
                             pool_stride=2)
    net = dsl.fc(net, size=4096, act=ReluActivation(),
                 layer_attr=ExtraAttr(drop_rate=0.5))
    net = dsl.fc(net, size=4096, act=ReluActivation(),
                 layer_attr=ExtraAttr(drop_rate=0.5))
    return dsl.fc(net, size=num_classes, act=SoftmaxActivation())


def _inception(name, input, channels, f1, f3r, f3, f5r, f5, proj):
    """GoogleNet inception module (``googlenet.py`` inception2)."""
    cov1 = _conv(input, 1, f1, 1, 0, channels=channels)
    cov3r = _conv(input, 1, f3r, 1, 0, channels=channels)
    cov3 = _conv(cov3r, 3, f3, 1, 1)
    cov5r = _conv(input, 1, f5r, 1, 0, channels=channels)
    cov5 = _conv(cov5r, 5, f5, 1, 2)
    pool = _pool(input, 3, 1, 1)
    covprj = _conv(pool, 1, proj, 1, 0)
    out = dsl.concat([cov1, cov3, cov5, covprj], name=f"{name}_concat")
    out.channels = f1 + f3 + f5 + proj
    out.img_size = cov1.img_size
    out.img_size_y = cov1.img_size_y
    out.size = out.channels * out.img_size * out.img_size_y
    return out


def googlenet(img, num_classes: int = 1000):
    """``googlenet.py``: stem + 9 inception modules + avg pool."""
    net = _conv(img, 7, 64, 2, 3, channels=3)
    net = _pool(net, 3, 2, 1)
    net = _conv(net, 1, 64, 1, 0)
    net = _conv(net, 3, 192, 1, 1)
    net = _pool(net, 3, 2, 1)
    net = _inception("i3a", net, 192, 64, 96, 128, 16, 32, 32)
    net = _inception("i3b", net, 256, 128, 128, 192, 32, 96, 64)
    net = _pool(net, 3, 2, 1)
    net = _inception("i4a", net, 480, 192, 96, 208, 16, 48, 64)
    net = _inception("i4b", net, 512, 160, 112, 224, 24, 64, 64)
    net = _inception("i4c", net, 512, 128, 128, 256, 24, 64, 64)
    net = _inception("i4d", net, 512, 112, 144, 288, 32, 64, 64)
    net = _inception("i4e", net, 528, 256, 160, 320, 32, 128, 128)
    net = _pool(net, 3, 2, 1)
    net = _inception("i5a", net, 832, 256, 160, 320, 32, 128, 128)
    net = _inception("i5b", net, 832, 384, 192, 384, 48, 128, 128)
    net = _pool(net, 7, 1, 0, avg=True)
    net = dsl.dropout(net, dropout_rate=0.4)
    return dsl.fc(net, size=num_classes, act=SoftmaxActivation())


def _bn_conv(net, fs, nf, stride=1, pad=None, channels=None,
             act=None, linear=False):
    c = _conv(net, fs, nf, stride, pad, channels=channels,
              act=LinearActivation())
    return dsl.batch_norm(c, act=LinearActivation() if linear
                          else (act or ReluActivation()))


def _shortcut(net, out_ch, stride):
    if getattr(net, "channels", None) != out_ch or stride != 1:
        return _bn_conv(net, 1, out_ch, stride, 0, linear=True)
    return net


def _residual(short, main):
    out = dsl.addto([short, main], act=ReluActivation())
    out.channels = main.channels
    out.img_size = main.img_size
    out.img_size_y = main.img_size_y
    return out


def _basic_block(net, ch, stride):
    short = _shortcut(net, ch, stride)
    c1 = _bn_conv(net, 3, ch, stride, 1)
    c2 = _bn_conv(c1, 3, ch, 1, 1, linear=True)
    return _residual(short, c2)


def _bottleneck(net, ch, stride):
    short = _shortcut(net, ch * 4, stride)
    c1 = _bn_conv(net, 1, ch, stride, 0)
    c2 = _bn_conv(c1, 3, ch, 1, 1)
    c3 = _bn_conv(c2, 1, ch * 4, 1, 0, linear=True)
    return _residual(short, c3)


def resnet_cifar10(img, depth: int = 32, num_classes: int = 10):
    """``test_image_classification_train.py:13`` resnet_cifar10:
    6n+2 layers of basic blocks over 16/32/64 channels."""
    assert (depth - 2) % 6 == 0
    n = (depth - 2) // 6
    net = _bn_conv(img, 3, 16, 1, 1, channels=3)
    for ch, first_stride in ((16, 1), (32, 2), (64, 2)):
        for i in range(n):
            net = _basic_block(net, ch, first_stride if i == 0 else 1)
    net = _pool(net, 8, 1, 0, avg=True)
    return dsl.fc(net, size=num_classes, act=SoftmaxActivation())


def resnet(img, depth: int = 50, num_classes: int = 1000):
    """``model_zoo/resnet``: ImageNet ResNet-50/101/152 (bottlenecks)."""
    cfg = {50: (3, 4, 6, 3), 101: (3, 4, 23, 3), 152: (3, 8, 36, 3)}[depth]
    net = _bn_conv(img, 7, 64, 2, 3, channels=3)
    net = _pool(net, 3, 2, 1)
    for stage, blocks in enumerate(cfg):
        ch = 64 * (2 ** stage)
        for i in range(blocks):
            stride = 2 if stage > 0 and i == 0 else 1
            net = _bottleneck(net, ch, stride)
    net = _pool(net, 7, 1, 0, avg=True)
    return dsl.fc(net, size=num_classes, act=SoftmaxActivation())

"""Parameter updater hooks — the static pruning hook.

Reference: ``paddle/parameter/ParameterUpdaterHook.cpp:39``
(``StaticPruningHook``, Han et al. magnitude pruning).  Semantics kept
exactly: at init a mask keeping the largest ``(1 - sparsity_ratio)``
fraction of |w| is generated from the initial (or loaded) parameter
value and applied to the value; every update then masks the gradient, so
pruned weights stay zero for the whole run.

TPU-first: the mask is a device-resident array captured by the jitted
train step; grad masking fuses into the update kernel (one extra
multiply, no host round-trips — the reference re-reads the mask vector
on every ``update()`` call from the updater thread).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np


def static_pruning_mask(value: jax.Array,
                        sparsity_ratio: float) -> jax.Array:
    """Mask keeping exactly ``int(size * (1 - ratio))`` largest-|w|
    entries — truncating, like the reference's ``size_t nonZeroNum``
    multiply (``StaticPruningHook::generateMask``: partial_sort
    descending by fabs, top ``nonZeroNum`` set to 1)."""
    flat = jnp.abs(value).ravel()
    size = flat.shape[0]
    keep = int(size * (1.0 - sparsity_ratio))
    mask = jnp.zeros((size,), value.dtype)
    if keep > 0:
        idx = jnp.argsort(-flat)[:keep]
        mask = mask.at[idx].set(1)
    return mask.reshape(value.shape)


def build_prune_masks(param_specs: Dict[str, Any],
                      params: Dict[str, jax.Array]
                      ) -> Optional[Dict[str, jax.Array]]:
    """Masks for every parameter whose spec carries a pruning hook;
    None when no parameter is hooked."""
    masks: Dict[str, jax.Array] = {}
    for name, spec in param_specs.items():
        for hook in getattr(spec, "update_hooks", []) or []:
            if hook.get("type") == "pruning" and name in params:
                ratio = hook.get("sparsity_ratio")
                masks[name] = static_pruning_mask(
                    params[name], 0.6 if ratio is None else float(ratio))
    return masks or None


def apply_prune_init(params: Dict[str, jax.Array],
                     masks: Optional[Dict[str, jax.Array]]
                     ) -> Dict[str, jax.Array]:
    """``StaticPruningHook::init``: value ·= mask."""
    if not masks:
        return params
    return {n: (p * masks[n] if n in masks else p)
            for n, p in params.items()}


def apply_prune_grads(grads: Dict[str, jax.Array],
                      masks: Optional[Dict[str, jax.Array]]
                      ) -> Dict[str, jax.Array]:
    """``StaticPruningHook::update``: grad ·= mask (inside the jitted
    step; the masks are closed-over device constants)."""
    if not masks:
        return grads
    return {n: (g * masks[n] if n in masks else g)
            for n, g in grads.items()}

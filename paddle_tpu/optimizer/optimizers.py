"""First-order optimizers as pure jitted update rules.

Reference: ``paddle/parameter/FirstOrderOptimizer.h:24-335`` (SGD, momentum,
Adagrad, AdaDelta, RMSProp, DecayedAdagrad, Adam, Adamax), the optimizer math
kernels (``paddle/math/TrainingAlgorithmOp.h:67-122``), regularizers
(``Regularizer.h``), gradient clipping (``trainer_config_helpers/
optimizers.py`` gradient_clipping_threshold), and parameter averaging
(``AverageOptimizer.h``).

Design: an :class:`Optimizer` holds static hyperparameters; ``init(params)``
builds a state pytree and ``apply(params, grads, state, lr)`` returns
``(new_params, new_state)`` — a pure function that runs **inside** the jitted
train step (and therefore inside ``shard_map``, where each replica applies
identical updates after the gradient all-reduce).  This replaces the whole
``ParameterUpdater`` class family for the local path.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..utils import Registry

OPTIMIZERS: Registry = Registry("optimizer")

PyTree = Any


def tree_map(fn, *trees):
    return jax.tree_util.tree_map(fn, *trees)


def _mask_rows(mask, p_old, p_new, slot_old, slot_new):
    """Keep updated values only on touched rows; revert the rest (value
    and any param-shaped slot leaf — scalar/step slots pass through)."""
    m = mask.reshape((-1,) + (1,) * (p_old.ndim - 1))
    p = jnp.where(m, p_new, p_old)
    slot = tuple(
        jnp.where(m, sn, so) if getattr(so, "shape", None) == p_old.shape
        else sn
        for so, sn in zip(slot_old, slot_new))
    return p, slot


@dataclasses.dataclass
class Optimizer:
    """Base class; subclasses define per-leaf slot init and update math."""

    learning_rate: float = 0.01
    # L2 ("decay_rate" in ParameterConfig) applied as grad += decay * param,
    # matching OptimizerWithRegularizer semantics for dense params.
    weight_decay: float = 0.0
    l1_decay: float = 0.0
    gradient_clipping_threshold: float = 0.0

    def init(self, params: PyTree) -> list:
        """Slot list aligned with the flattened parameter leaves."""
        leaves = jax.tree_util.tree_leaves(params)
        return [self._init_slot(p) for p in leaves]

    def _init_slot(self, p):
        return ()

    def _update(self, p, g, slot, lr, count):
        raise NotImplementedError

    def apply(self, params: PyTree, grads: PyTree, state: PyTree,
              lr: Optional[jax.Array] = None,
              lr_scales: Optional[PyTree] = None,
              sparse_masks: Optional[PyTree] = None
              ) -> Tuple[PyTree, PyTree]:
        lr = jnp.asarray(self.learning_rate if lr is None else lr, jnp.float32)
        count, slots = state
        count = count + 1
        p_leaves, treedef = jax.tree_util.tree_flatten(params)
        g_leaves = treedef.flatten_up_to(grads)
        if lr_scales is None:
            scale_leaves = [None] * len(p_leaves)
        else:
            scale_leaves = treedef.flatten_up_to(lr_scales)
        if sparse_masks is None:
            mask_leaves = [None] * len(p_leaves)
        else:
            mask_leaves = treedef.flatten_up_to(sparse_masks)
        if self.gradient_clipping_threshold > 0:
            # reference clips per-parameter elementwise by threshold
            t = self.gradient_clipping_threshold
            g_leaves = [jnp.clip(g, -t, t) for g in g_leaves]
        if self.weight_decay:
            g_leaves = [g + self.weight_decay * p
                        for g, p in zip(g_leaves, p_leaves)]
        new_p, new_slots = [], []
        for p, g, slot, sc, mask in zip(p_leaves, g_leaves, slots,
                                        scale_leaves, mask_leaves):
            eff_lr = lr if sc is None else lr * sc
            np_, ns = self._update(p, g, slot, eff_lr, count)
            if self.l1_decay:
                shrink = eff_lr * self.l1_decay
                np_ = jnp.sign(np_) * jnp.maximum(jnp.abs(np_) - shrink, 0.0)
            if mask is not None:
                # lazy row-sparse semantics (SparseRowMatrix contract):
                # untouched rows keep value AND slots bit-identical
                np_, ns = _mask_rows(mask, p, np_, slot, ns)
            new_p.append(np_)
            new_slots.append(ns)
        return treedef.unflatten(new_p), (count, new_slots)

    def apply_rows(self, table: jax.Array, rows: jax.Array,
                   row_grads: jax.Array, state: Tuple[jax.Array, tuple],
                   lr: Optional[jax.Array] = None
                   ) -> Tuple[jax.Array, Tuple[jax.Array, tuple]]:
        """Fixed-capacity row-sparse update (O(K), table never dense in
        the gradient): gather the touched rows of the parameter and its
        slots, run the per-row optimizer math, scatter back.  Correct for
        every optimizer in the registry — their update rules are
        elementwise, so a row block updates independently.  ``rows`` may
        contain -1 padding (those slots are dropped).  SelectedRows
        optimizer-kernel equivalent (``math/selected_rows_functor.cc``).

        ``state = (count, slot_tuple)`` for THIS parameter — like
        ``apply``'s state but with a single slot entry; thread the
        returned state into the next step (Adam/Adamax bias correction
        depends on the advancing count).  Initialize with
        ``(jnp.zeros((), jnp.int32), opt.init({"t": table})[0])``.
        """
        from ..parallel.sparse import row_gather, row_scatter_set

        lr = jnp.asarray(self.learning_rate if lr is None else lr,
                         jnp.float32)
        count, slot = state
        count = count + 1
        p_rows = row_gather(table, rows)
        g = row_grads
        if self.gradient_clipping_threshold > 0:
            t = self.gradient_clipping_threshold
            g = jnp.clip(g, -t, t)
        if self.weight_decay:
            g = g + self.weight_decay * p_rows
        slot_rows = tuple(row_gather(s, rows) if s.shape == table.shape
                          else s for s in slot)
        np_, ns = self._update(p_rows, g, slot_rows, lr, count)
        if self.l1_decay:
            shrink = lr * self.l1_decay
            np_ = jnp.sign(np_) * jnp.maximum(jnp.abs(np_) - shrink, 0.0)
        new_table = row_scatter_set(table, rows, np_)
        new_slot = tuple(
            row_scatter_set(s, rows, n) if s.shape == table.shape else n
            for s, n in zip(slot, ns))
        return new_table, (count, new_slot)

    def init_state(self, params: PyTree) -> Tuple[jax.Array, list]:
        return (jnp.zeros((), jnp.int32), self.init(params))


@OPTIMIZERS.register("sgd")
@dataclasses.dataclass
class SGD(Optimizer):
    """Plain SGD (``SgdOptimizer``)."""

    def _update(self, p, g, slot, lr, count):
        return (p - lr * g).astype(p.dtype), slot


@OPTIMIZERS.register("momentum")
@dataclasses.dataclass
class Momentum(Optimizer):
    """Momentum SGD (``sgdUpdate`` in TrainingAlgorithmOp.h):
    v = mom*v - lr*g ; p += v."""

    momentum: float = 0.9

    def _init_slot(self, p):
        return (jnp.zeros_like(p),)

    def _update(self, p, g, slot, lr, count):
        (v,) = slot
        v = self.momentum * v - lr * g
        return (p + v).astype(p.dtype), (v,)


@OPTIMIZERS.register("adagrad")
@dataclasses.dataclass
class Adagrad(Optimizer):
    """``AdagradOptimizer``: accum += g^2; p -= lr*g/(sqrt(accum)+eps)."""

    epsilon: float = 1e-6

    def _init_slot(self, p):
        return (jnp.zeros_like(p, dtype=jnp.float32),)

    def _update(self, p, g, slot, lr, count):
        (acc,) = slot
        acc = acc + jnp.square(g)
        step = lr * g / (jnp.sqrt(acc) + self.epsilon)
        return (p - step).astype(p.dtype), (acc,)


@OPTIMIZERS.register("adadelta")
@dataclasses.dataclass
class AdaDelta(Optimizer):
    """``AdaDeltaOptimizer`` (rou/epsilon as in adadeltaApply)."""

    rho: float = 0.95
    epsilon: float = 1e-6

    def _init_slot(self, p):
        z = jnp.zeros_like(p, dtype=jnp.float32)
        return (z, z)

    def _update(self, p, g, slot, lr, count):
        eg2, edx2 = slot
        eg2 = self.rho * eg2 + (1 - self.rho) * jnp.square(g)
        dx = jnp.sqrt((edx2 + self.epsilon) / (eg2 + self.epsilon)) * g
        edx2 = self.rho * edx2 + (1 - self.rho) * jnp.square(dx)
        return (p - lr * dx).astype(p.dtype), (eg2, edx2)


@OPTIMIZERS.register("rmsprop")
@dataclasses.dataclass
class RMSProp(Optimizer):
    """``RMSPropOptimizer`` — the centered variant the reference implements
    (keeps E[g] as well as E[g^2]; rmspropApply in TrainingAlgorithmOp)."""

    rho: float = 0.95
    epsilon: float = 1e-6

    def _init_slot(self, p):
        z = jnp.zeros_like(p, dtype=jnp.float32)
        return (z, z)

    def _update(self, p, g, slot, lr, count):
        eg2, eg = slot
        eg2 = self.rho * eg2 + (1 - self.rho) * jnp.square(g)
        eg = self.rho * eg + (1 - self.rho) * g
        step = lr * g / jnp.sqrt(eg2 - jnp.square(eg) + self.epsilon)
        return (p - step).astype(p.dtype), (eg2, eg)


@OPTIMIZERS.register("decayed_adagrad")
@dataclasses.dataclass
class DecayedAdagrad(Optimizer):
    """``DecayedAdagradOptimizer``: like RMSProp without centering."""

    rho: float = 0.95
    epsilon: float = 1e-6

    def _init_slot(self, p):
        return (jnp.zeros_like(p, dtype=jnp.float32),)

    def _update(self, p, g, slot, lr, count):
        (acc,) = slot
        acc = self.rho * acc + (1 - self.rho) * jnp.square(g)
        step = lr * g / jnp.sqrt(acc + self.epsilon)
        return (p - step).astype(p.dtype), (acc,)


@OPTIMIZERS.register("adam")
@dataclasses.dataclass
class Adam(Optimizer):
    """``AdamOptimizer`` (adamApply): bias-corrected moments."""

    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def _init_slot(self, p):
        z = jnp.zeros_like(p, dtype=jnp.float32)
        return (z, z)

    def _update(self, p, g, slot, lr, count):
        m, v = slot
        g32 = g.astype(jnp.float32)
        m = self.beta1 * m + (1 - self.beta1) * g32
        v = self.beta2 * v + (1 - self.beta2) * jnp.square(g32)
        t = count.astype(jnp.float32)
        mhat = m / (1 - jnp.power(self.beta1, t))
        vhat = v / (1 - jnp.power(self.beta2, t))
        step = lr * mhat / (jnp.sqrt(vhat) + self.epsilon)
        return (p - step).astype(p.dtype), (m, v)


@OPTIMIZERS.register("adamax")
@dataclasses.dataclass
class Adamax(Optimizer):
    """``AdamaxOptimizer`` (adamaxApply): infinity-norm second moment."""

    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def _init_slot(self, p):
        z = jnp.zeros_like(p, dtype=jnp.float32)
        return (z, z)

    def _update(self, p, g, slot, lr, count):
        m, u = slot
        g32 = g.astype(jnp.float32)
        m = self.beta1 * m + (1 - self.beta1) * g32
        u = jnp.maximum(self.beta2 * u, jnp.abs(g32))
        t = count.astype(jnp.float32)
        step = lr / (1 - jnp.power(self.beta1, t)) * m / (u + self.epsilon)
        return (p - step).astype(p.dtype), (m, u)


@OPTIMIZERS.register("proximal_gd")
@dataclasses.dataclass
class ProximalGD(Optimizer):
    """``proximal_gd_op``: SGD + proximal L1/L2 shrinkage."""

    l1: float = 0.0
    l2: float = 0.0

    def _update(self, p, g, slot, lr, count):
        prox = p - lr * g
        if self.l1:
            prox = jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - lr * self.l1, 0.0)
        return (prox / (1.0 + lr * self.l2)).astype(p.dtype), slot


@OPTIMIZERS.register("proximal_adagrad")
@dataclasses.dataclass
class ProximalAdagrad(Optimizer):
    """``proximal_adagrad_op``."""

    l1: float = 0.0
    l2: float = 0.0
    epsilon: float = 1e-6

    def _init_slot(self, p):
        return (jnp.zeros_like(p, dtype=jnp.float32),)

    def _update(self, p, g, slot, lr, count):
        (acc,) = slot
        acc = acc + jnp.square(g)
        eff = lr / (jnp.sqrt(acc) + self.epsilon)
        prox = p - eff * g
        if self.l1:
            prox = jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - eff * self.l1, 0.0)
        return (prox / (1.0 + eff * self.l2)).astype(p.dtype), (acc,)


@dataclasses.dataclass
class ModelAverage:
    """Parameter averaging over a sliding window
    (``AverageOptimizer.h`` / v2 ``ModelAverage``).

    Keeps a running sum; ``average(state)`` yields eval-time params.
    average_window is the fraction of recent updates to average over
    (reference semantics: window grows up to max_average_window).
    """

    average_window: float = 0.5
    max_average_window: int = 10000

    def init(self, params):
        return {
            "sum": tree_map(lambda p: p.astype(jnp.float32), params),
            "count": jnp.ones((), jnp.float32),
        }

    def accumulate(self, state, params):
        # restart window when it exceeds max
        count = state["count"] + 1
        reset = count > self.max_average_window
        new_sum = tree_map(
            lambda s, p: jnp.where(reset, p.astype(jnp.float32),
                                   s + p.astype(jnp.float32)),
            state["sum"], params)
        return {"sum": new_sum, "count": jnp.where(reset, 1.0, count)}

    def average(self, state):
        return tree_map(lambda s: s / state["count"], state["sum"])


def create_optimizer(name: str, **kwargs) -> Optimizer:
    return OPTIMIZERS.create(name, **kwargs)

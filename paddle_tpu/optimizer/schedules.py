"""Learning-rate schedules.

Reference: ``paddle/parameter/LearningRateScheduler.cpp:50-172`` — schedules
are keyed by the number of **samples processed** (pass_manual by pass id).
All are pure functions of (base_lr, progress) so they trace into jit.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax.numpy as jnp

from ..utils import ConfigError, Registry

SCHEDULES: Registry = Registry("lr schedule")


def _reg(name):
    def deco(fn):
        SCHEDULES.register_value(name, fn)
        return fn

    return deco


@_reg("constant")
def constant(base_lr, num_samples, a=0.0, b=0.0):
    return jnp.asarray(base_lr, jnp.float32)


@_reg("poly")
def poly(base_lr, num_samples, a=1.0, b=0.0):
    """lr * (1 + a*n)^(-b)  (reference 'poly': a=gamma, b=power)."""
    return base_lr * jnp.power(1.0 + a * num_samples, -b)


@_reg("caffe_poly")
def caffe_poly(base_lr, num_samples, a=1.0, b=0.0):
    """lr * (1 - n/a)^b  (a=max steps, b=power)."""
    return base_lr * jnp.power(1.0 - num_samples / a, b)


@_reg("exp")
def exp(base_lr, num_samples, a=0.5, b=1.0):
    """lr * a^(n/b)."""
    return base_lr * jnp.power(a, num_samples / b)


@_reg("discexp")
def discexp(base_lr, num_samples, a=0.5, b=1.0):
    """lr * a^floor(n/b)."""
    return base_lr * jnp.power(a, jnp.floor(num_samples / b))


@_reg("linear")
def linear(base_lr, num_samples, a=0.0, b=0.0):
    """max(lr - a*n, b)."""
    return jnp.maximum(base_lr - a * num_samples, b)


def parse_manual_spec(spec: str) -> Tuple[Sequence[float], Sequence[float]]:
    """Parse 'seg0:lr0,seg1:lr1,...' (learning_rate_args for manual modes)."""
    bounds, rates = [], []
    for part in spec.split(","):
        if not part.strip():
            continue
        seg, lr = part.split(":")
        bounds.append(float(seg))
        rates.append(float(lr))
    return bounds, rates


def manual(base_lr, progress, spec: str):
    bounds, rates = parse_manual_spec(spec)
    lr = jnp.asarray(rates[-1], jnp.float32) * base_lr
    for bound, rate in zip(reversed(bounds[:-1]), reversed(rates[:-1])):
        lr = jnp.where(progress < bound, rate * base_lr, lr)
    # first segment
    lr = jnp.where(progress < bounds[0], rates[0] * base_lr, lr)
    return lr


SCHEDULES.register_value("manual", manual)
SCHEDULES.register_value("pass_manual", manual)


def make_schedule(name: str = "constant", base_lr: float = 0.01,
                  decay_a: float = 0.0, decay_b: float = 0.0,
                  args: str = ""):
    """Build lr(num_samples_or_pass) from config fields
    (learning_rate_schedule / learning_rate_decay_a/_b / learning_rate_args)."""
    name = name or "constant"
    if name not in SCHEDULES:
        raise ConfigError(f"unknown learning_rate_schedule {name!r}")
    fn = SCHEDULES.get(name)
    if name in ("manual", "pass_manual"):
        return lambda progress: fn(base_lr, progress, args)
    kw = {}
    if decay_a:
        kw["a"] = decay_a
    if decay_b:
        kw["b"] = decay_b
    return lambda progress: fn(base_lr, progress, **kw)

"""Dynamic loss scaling for the ``--precision=bf16`` training policy.

The classic mixed-precision recipe (Micikevicius et al., "Mixed
Precision Training"): multiply the loss by a scale before the backward
pass, divide the gradients by it in fp32 afterwards, and adapt the scale
from observed overflows — grow 2× after every ``growth_interval``
overflow-free steps, halve (floor 1.0) and SKIP the update when any
gradient is non-finite, leaving parameters and optimizer state
bit-identical.

bf16 shares fp32's 8-bit exponent, so unlike fp16 it cannot underflow a
gradient the scale would have saved — here the machinery is primarily
the *skipped-step safety net* (a single inf/nan batch never poisons the
master weights) and the observability hook (``loss_scale`` gauge,
``loss_scale_skipped_steps_total``).  The math is kept as pure jittable
functions over a small state tuple so the trainer threads it through the
compiled train step and unit tests hit it directly.

State layout (a NamedTuple of device scalars):
    scale          f32 — current multiplier
    growth_count   i32 — overflow-free steps since the last change
    skipped_total  i32 — lifetime skipped steps (device-side so the hot
                         loop never syncs; the trainer drains the delta
                         into the observe counter at pass boundaries)
"""

from __future__ import annotations

from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp

from ..utils import FLAGS

GROWTH_FACTOR = 2.0
BACKOFF_FACTOR = 0.5
MIN_SCALE = 1.0
# Growth ceiling: without it a long clean run doubles the scale until
# the f32 scale itself overflows to inf, after which every step skips
# and backoff (inf*0.5 = inf) can never recover — a silent permanent
# stall.  2^24 leaves ample headroom over any useful scale.
MAX_SCALE = float(2 ** 24)


class LossScaleState(NamedTuple):
    scale: jax.Array
    growth_count: jax.Array
    skipped_total: jax.Array


def init_state(init_scale: float = None) -> LossScaleState:
    """Fresh state from ``--loss_scale_init`` (or an explicit value —
    checkpoint resume passes the persisted scale back in)."""
    if init_scale is None:
        init_scale = FLAGS.loss_scale_init
    return LossScaleState(
        scale=jnp.asarray(float(init_scale), jnp.float32),
        growth_count=jnp.zeros((), jnp.int32),
        skipped_total=jnp.zeros((), jnp.int32))


def all_finite(grads: Any) -> jax.Array:
    """Scalar bool: every float leaf of the gradient pytree is finite."""
    leaves = [g for g in jax.tree_util.tree_leaves(grads)
              if jnp.issubdtype(jnp.result_type(g), jnp.floating)]
    finite = jnp.asarray(True)
    for g in leaves:
        finite = jnp.logical_and(finite, jnp.all(jnp.isfinite(g)))
    return finite


def leaf_nonfinite_counts(grads: Any) -> Any:
    """Per-leaf inf/nan element counts (i32 scalars; non-float leaves
    count 0), same pytree structure as ``grads``.  The skip-step
    disambiguation primitive: the training-health aux path
    (``observe/health.py``) aggregates these per layer while
    :func:`all_finite_from_counts` derives the skip decision from the
    SAME pass — one ``isfinite`` sweep serves both, and a non-finite
    the loss scaler skipped is distinguishable from one it let through.
    """
    def count(g):
        if not jnp.issubdtype(jnp.result_type(g), jnp.floating):
            return jnp.zeros((), jnp.int32)
        return jnp.sum((~jnp.isfinite(g)).astype(jnp.int32))

    return jax.tree_util.tree_map(count, grads)


def all_finite_from_counts(counts: Any) -> jax.Array:
    """Scalar bool from :func:`leaf_nonfinite_counts` output —
    equivalent to :func:`all_finite` without a second isfinite pass."""
    total = jnp.zeros((), jnp.int32)
    for c in jax.tree_util.tree_leaves(counts):
        total = total + c
    return total == 0


def unscale(grads: Any, scale: jax.Array) -> Any:
    """Gradients / scale, accumulated in fp32 (master-grad dtype)."""
    inv = (1.0 / scale).astype(jnp.float32)
    return jax.tree_util.tree_map(
        lambda g: g.astype(jnp.float32) * inv
        if jnp.issubdtype(jnp.result_type(g), jnp.floating) else g,
        grads)


def update(state: LossScaleState, finite: jax.Array,
           growth_interval: int = None) -> LossScaleState:
    """Post-step scale adaptation (branchless, jit-safe)."""
    if growth_interval is None:
        growth_interval = FLAGS.loss_scale_growth_interval
    count = state.growth_count + 1
    # growth_interval is a Python flag value, baked as a trace-time
    # constant on purpose (one compiled step per configured interval)
    grow = count >= jnp.asarray(
        int(growth_interval),  # ptpu: lint-ok[PT-TRACE] static flag
        jnp.int32)
    grown_scale = jnp.where(grow, jnp.minimum(state.scale * GROWTH_FACTOR,
                                              MAX_SCALE),
                            state.scale)
    backed_off = jnp.maximum(state.scale * BACKOFF_FACTOR, MIN_SCALE)
    return LossScaleState(
        scale=jnp.where(finite, grown_scale, backed_off),
        growth_count=jnp.where(finite, jnp.where(grow, 0, count), 0)
        .astype(jnp.int32),
        skipped_total=state.skipped_total
        + (1 - finite.astype(jnp.int32)))


def select(finite: jax.Array, updated: Any, previous: Any) -> Any:
    """``updated`` when the step was finite, else ``previous`` —
    elementwise select keeps the skipped step's state bit-identical."""
    return jax.tree_util.tree_map(
        lambda n, o: jnp.where(finite, n, o), updated, previous)

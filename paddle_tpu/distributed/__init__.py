"""Distributed runtime: the gen-2 (Go master/pserver) equivalents.

- :mod:`master`: C++ data-task service (leases, failure re-queue,
  snapshot/recover, save-model election, PING liveness) over ctypes,
  plus a reconnecting TCP client (backoff + request replay,
  ``--master_retry_max``) — replaces ``go/master`` + etcd.
- :mod:`elastic`: preemption-tolerant checkpointed training loop —
  replaces the stateless-trainer + checkpointing pserver story
  (``doc/design/cluster_train/README.md``); recovery paths are verified
  by fault injection (``paddle_tpu/testing/fault.py``,
  ``tests/test_chaos.py``).

The parameter-server *gradient* path has no equivalent by design: gradient
exchange is ICI all-reduce inside the jitted train step (SURVEY §2.5 →
TPU mapping, BASELINE north star).
"""

from .master import Master, MasterClient, master_reader  # noqa: F401
from .elastic import ElasticTrainer  # noqa: F401

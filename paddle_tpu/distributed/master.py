"""Python bindings for the C++ master service (``native/master/master.cc``).

Two transports, mirroring the reference's two paths:
- in-process via ctypes (like ``go/master/c/client.go`` cgo exports used
  through ``python/paddle/v2/master/client.py``),
- TCP line protocol for multi-process trainers (replaces Go RPC + etcd
  discovery — address is passed explicitly, no external coordinator).
"""

from __future__ import annotations

import ctypes
import itertools
import os
import queue
import random
import socket
import subprocess
import threading
import time
import zlib
from typing import List, Optional, Sequence, Tuple

from ..analysis.lockorder import named_lock
from ..observe import counter, gauge, trace
from ..utils import FLAGS, PaddleTpuError, enforce, get_logger

log = get_logger("master")

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_NATIVE = os.path.join(_REPO, "native")
_SO = os.path.join(_NATIVE, "build", "libptpu_master.so")
_CC = os.path.join(_NATIVE, "master", "master.cc")

_lib = None


def _needs_build() -> bool:
    if not os.path.exists(_SO):
        return True
    try:  # stale .so from an older source tree: rebuild
        return os.path.getmtime(_SO) < os.path.getmtime(_CC)
    except OSError:
        return False


def _load_lib() -> ctypes.CDLL:
    global _lib
    if _lib is not None:
        return _lib
    if _needs_build():
        log.info("building native master library…")
        subprocess.run(["make", "-C", _NATIVE], check=True,
                       capture_output=True)
    lib = ctypes.CDLL(_SO)
    lib.ptpu_master_create.restype = ctypes.c_void_p
    lib.ptpu_master_create.argtypes = [ctypes.c_double, ctypes.c_int,
                                       ctypes.c_char_p]
    lib.ptpu_master_destroy.argtypes = [ctypes.c_void_p]
    lib.ptpu_master_set_dataset.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_char_p), ctypes.c_int]
    lib.ptpu_master_get_task.restype = ctypes.c_int
    lib.ptpu_master_get_task.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
        ctypes.POINTER(ctypes.c_int)]
    lib.ptpu_master_task_finished.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.ptpu_master_task_failed.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.ptpu_master_reset_epoch.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.ptpu_master_epoch.restype = ctypes.c_int
    lib.ptpu_master_epoch.argtypes = [ctypes.c_void_p]
    lib.ptpu_master_request_save_model.restype = ctypes.c_int
    lib.ptpu_master_request_save_model.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_double]
    lib.ptpu_master_counts.argtypes = [ctypes.c_void_p] + \
        [ctypes.POINTER(ctypes.c_int)] * 4
    lib.ptpu_master_snapshot.argtypes = [ctypes.c_void_p]
    lib.ptpu_master_serve.restype = ctypes.c_int
    lib.ptpu_master_serve.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                      ctypes.c_int]
    _lib = lib
    return lib


class Master:
    """In-process master (``go/master/service.go`` Service equivalent)."""

    def __init__(self, timeout_s: float = 60.0, failure_max: int = 3,
                 snapshot_path: str = ""):
        self._lib = _load_lib()
        self._h = self._lib.ptpu_master_create(
            timeout_s, failure_max,
            snapshot_path.encode() if snapshot_path else None)

    def set_dataset(self, payloads: Sequence[str]) -> None:
        arr = (ctypes.c_char_p * len(payloads))(
            *[p.encode() for p in payloads])
        self._lib.ptpu_master_set_dataset(self._h, arr, len(payloads))

    def get_task(self) -> Tuple[int, Optional[str]]:
        """Returns (rc, payload): rc 0 granted / 1 wait / -1 epoch done."""
        buf = ctypes.create_string_buffer(1 << 16)
        tid = ctypes.c_int(-1)
        rc = self._lib.ptpu_master_get_task(self._h, buf, len(buf),
                                            ctypes.byref(tid))
        if rc == 0:
            return tid.value, buf.value.decode()
        return rc, None

    def task_finished(self, task_id: int) -> None:
        self._lib.ptpu_master_task_finished(self._h, task_id)

    def task_failed(self, task_id: int) -> None:
        self._lib.ptpu_master_task_failed(self._h, task_id)

    def reset_epoch(self, target_epoch: int = -1) -> None:
        """Request the start of ``target_epoch`` (pass-number handshake:
        a trainer that finished pass P asks for P+1); peers' duplicate
        requests for an already-performed reset are no-ops. ``-1`` is
        the legacy argless reset."""
        self._lib.ptpu_master_reset_epoch(self._h, target_epoch)

    def current_epoch(self) -> int:
        """Epoch counter — read on (re)connect to offset local pass
        counters against a long-lived or snapshot-recovered master."""
        return self._lib.ptpu_master_epoch(self._h)

    def request_save_model(self, trainer_id: str,
                           interval_s: float = 60.0) -> bool:
        return bool(self._lib.ptpu_master_request_save_model(
            self._h, trainer_id.encode(), interval_s))

    def counts(self) -> dict:
        vals = [ctypes.c_int() for _ in range(4)]
        self._lib.ptpu_master_counts(self._h, *[ctypes.byref(v)
                                                for v in vals])
        return dict(zip(("todo", "pending", "done", "failed"),
                        (v.value for v in vals)))

    def snapshot(self) -> None:
        self._lib.ptpu_master_snapshot(self._h)

    def serve(self, port: int = 0, bind_any: bool = False) -> int:
        """Start the TCP server (loopback by default; ``bind_any``
        listens on all interfaces for multi-host trainers); returns the
        bound port."""
        p = self._lib.ptpu_master_serve(self._h, port, int(bind_any))
        enforce(p > 0, "master serve failed")
        return p

    def __del__(self):
        try:
            if getattr(self, "_h", None):
                self._lib.ptpu_master_destroy(self._h)
                self._h = None
        # modules/loggers may already be torn down under us
        except Exception:   # ptpu: lint-ok[PT-RESOURCE] __del__ teardown
            pass


def _escape_payload(s: str) -> str:
    """%-escape control bytes that would break the line/tab framing
    (mirrors ``EscapePayload`` in ``native/master/master.cc``)."""
    out = []
    for ch in s:
        if ch in "%\n\r\t\x1f":
            out.append("%%%02X" % ord(ch))
        else:
            out.append(ch)
    return "".join(out)


_HEX = set("0123456789abcdefABCDEF")

# distinct jitter streams for clients created in the same process
_client_nonce = itertools.count()


def _unescape_payload(s: str) -> str:
    out = []
    i = 0
    while i < len(s):
        # decode only well-formed %XX; a literal '%' from a pre-escaping
        # master passes through untouched
        if s[i] == "%" and i + 3 <= len(s) and s[i + 1] in _HEX \
                and s[i + 2] in _HEX:
            out.append(chr(int(s[i + 1:i + 3], 16)))
            i += 3
        else:
            out.append(s[i])
            i += 1
    return "".join(out)


class MasterClient:
    """TCP client speaking the master's line protocol (remote trainers).

    Connection loss mid-call is survived, not fatal: ``_call`` re-dials
    with exponential backoff + jitter and replays the request up to
    ``retry_max`` times (default ``--master_retry_max``).  Replay is safe
    for every op in the protocol: a GET whose response was lost leaves a
    granted-but-unheard lease that times out server-side and re-queues
    (at-least-once); SET is first-wins; FIN/FAIL on an unknown lease and
    duplicate RESET/SAVE are no-ops.  ``retry_max=0`` restores the
    legacy fail-fast contract — the first drop raises
    ``PaddleTpuError("master connection closed")``.
    """

    def __init__(self, addr: str, timeout: float = 30.0,
                 retry_max: Optional[int] = None,
                 retry_base_s: float = 0.05, retry_cap_s: float = 2.0):
        host, port = addr.rsplit(":", 1)
        self._addr = (host, int(port))
        self._timeout = timeout
        self._retry_max = (FLAGS.master_retry_max if retry_max is None
                           else retry_max)
        self._retry_base_s = retry_base_s
        self._retry_cap_s = retry_cap_s
        # jitter spread: every client of one master must NOT share a
        # backoff sequence or a master restart gets a reconnect stampede
        # in lockstep — mix a per-process/per-client nonce into the seed
        # (chaos tests stay deterministic via call-count triggers, not
        # jitter values)
        self._rng = random.Random(
            zlib.crc32(addr.encode()) ^ (os.getpid() << 16)
            ^ next(_client_nonce))
        self._buf = b""
        self._closed = False
        # trace-context framing capability: assumed until a master
        # answers a CTX frame with a bare ERR (pre-CTX binary) — then
        # this client stops framing so tracing never breaks the RPCs
        # it is meant to observe
        self._ctx_frames = True
        # the initial dial keeps today's fail-fast semantics: a wrong
        # address should error immediately, not burn a retry budget
        self._sock: Optional[socket.socket] = socket.create_connection(
            self._addr, timeout=timeout)

    def _drop_sock(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
        self._buf = b""  # a partial response from the dead socket is junk

    def _call(self, line: str, retry_override: Optional[int] = None) -> str:
        if self._closed:
            raise PaddleTpuError("master client is closed")
        retry_max = (self._retry_max if retry_override is None
                     else retry_override)
        attempt = 0
        op = line.split("\t", 1)[0]
        # one span covers the whole call incl. reconnect+replay; when
        # tracing is on the request rides a CTX frame so the master's
        # own handling comes back as a server-side span in this trace
        with trace.span("master_rpc", op=op):
            while True:
                try:
                    if self._sock is None:
                        self._sock = socket.create_connection(
                            self._addr, timeout=self._timeout)
                        self._buf = b""
                    wire = line
                    framed = False
                    if trace.enabled() and self._ctx_frames:
                        hdr = trace.parent_header()
                        if hdr:
                            wire = f"CTX\t{hdr}\t{line}"
                            framed = True
                    t_send = trace.now_us()
                    self._sock.sendall(wire.encode() + b"\n")
                    while b"\n" not in self._buf:
                        chunk = self._sock.recv(4096)
                        if not chunk:
                            raise ConnectionResetError(
                                "master closed the connection")
                        self._buf += chunk
                    resp, self._buf = self._buf.split(b"\n", 1)
                    if attempt:   # request survived via reconnect+replay
                        counter("master_replays",
                                "master RPCs completed on a replay after "
                                "reconnect").inc()
                    resp_s = resp.decode()
                    if framed and resp_s.startswith("ERR"):
                        # a pre-CTX master parsed "CTX" as the op and
                        # errored without touching state: stop framing
                        # and replay this request bare (one extra round
                        # trip, once per client)
                        from ..utils.logger import warn_once
                        self._ctx_frames = False
                        warn_once(
                            f"master_no_ctx:{self._addr}",
                            "master %s:%d predates trace-context "
                            "framing; tracing continues client-side "
                            "only (no server-side spans)", *self._addr,
                            logger=log)
                        continue
                    if resp_s.startswith("CTX\t"):
                        resp_s = self._absorb_ctx_echo(
                            resp_s, t_send, trace.now_us(), op)
                    return resp_s
                except OSError as e:  # incl. ConnectionError, timeout
                    self._drop_sock()
                    if attempt >= retry_max:
                        counter("master_giveups",
                                "master RPCs that exhausted the "
                                "reconnect budget and raised").inc()
                        raise PaddleTpuError(
                            "master connection closed") from e
                    delay = min(self._retry_cap_s,
                                self._retry_base_s * (2 ** attempt))
                    delay *= 0.5 + self._rng.random()  # jitter [0.5,1.5)
                    attempt += 1
                    counter("master_reconnects",
                            "master connection losses answered with a "
                            "re-dial (per retry attempt)").inc()
                    counter("master_backoff_seconds",
                            "total backoff slept before master re-dials"
                            ).inc(delay)
                    log.warning(
                        "master call %s failed (%s: %s); reconnect "
                        "attempt %d/%d in %.2fs", op,
                        type(e).__name__, e, attempt, retry_max, delay)
                    with trace.span("master_backoff", op=op,
                                    attempt=attempt):
                        time.sleep(delay)

    @staticmethod
    def _absorb_ctx_echo(resp: str, t_send_us: float, t_recv_us: float,
                         op: str) -> str:
        """Unwrap a ``CTX\\t<opaque>\\t<pid>\\t<us>\\t<response>`` echo
        and record the master's handling as a server-side span of the
        echoed context (clock skew sidestepped: the span is placed at
        the midpoint of the client-observed round trip, its duration is
        the server-measured one).  Anything malformed passes through
        untouched — trace framing must never corrupt the protocol."""
        try:
            _, hdr, pid_s, us_s, rest = resp.split("\t", 4)
            dur_us = float(us_s)
            server_pid = int(pid_s)
        except ValueError:
            return resp
        ctx = trace.parse_header(hdr)
        if ctx is not None:
            slack = max(0.0, (t_recv_us - t_send_us) - dur_us)
            trace.record_span(
                "master.handle", t_send_us + slack / 2.0, dur_us,
                ctx.trace_id, parent_id=ctx.span_id, pid=server_pid,
                tid=server_pid, op=op)
        return rest

    def ping(self) -> bool:
        """Cheap liveness probe (PING op; no master state touched).

        A probe must answer fast, not block through the full reconnect
        budget: at most one re-dial (to shed a dead cached socket), so
        a down master yields False in ~one connect timeout.
        """
        try:
            return self._call("PING",
                              retry_override=min(self._retry_max, 1)) \
                == "PONG"
        except PaddleTpuError:
            return False

    def set_dataset(self, payloads: Sequence[str]) -> None:
        self._call("SET\t" + "\x1f".join(_escape_payload(p)
                                         for p in payloads))

    def get_task(self) -> Tuple[int, Optional[str]]:
        resp = self._call("GET")
        if resp.startswith("OK\t"):
            _, tid, payload = resp.split("\t", 2)
            return int(tid), _unescape_payload(payload)
        return (1, None) if resp == "WAIT" else (-1, None)

    def task_finished(self, task_id: int) -> None:
        self._call(f"FIN\t{task_id}")

    def task_failed(self, task_id: int) -> None:
        self._call(f"FAIL\t{task_id}")

    def reset_epoch(self, target_epoch: int = -1) -> None:
        self._call("RESET" if target_epoch < 0 else f"RESET\t{target_epoch}")

    def current_epoch(self) -> int:
        resp = self._call("EPOCH")
        try:
            return int(resp)
        except ValueError:
            return 0  # pre-EPOCH master binary: degrade to legacy base

    def request_save_model(self, trainer_id: str,
                           interval_s: float = 60.0) -> bool:
        return self._call(f"SAVE\t{trainer_id}\t{interval_s}") == "1"

    def counts(self) -> dict:
        vals = [int(x) for x in self._call("COUNTS").split("\t")]
        return dict(zip(("todo", "pending", "done", "failed"), vals))

    def close(self) -> None:
        """Idempotent: safe to call any number of times."""
        self._closed = True
        self._drop_sock()

    def __enter__(self) -> "MasterClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def master_reader(client, load_fn, wait_sleep: float = 0.05,
                  close_client: bool = True, read_ahead: int = 0):
    """Reader pulling task payloads from a master and yielding samples —
    the ``cloud_reader`` equivalent (``python/paddle/v2/reader/creator.py:91``).

    ``load_fn(payload) -> iterable of samples``; a task is marked finished
    only after its samples were fully consumed, failed if ``load_fn``
    raises — so a dead trainer's lease times out and the shard is re-done
    elsewhere (fault tolerance, ``go/master/service.go:313``).

    When the generator is torn down *abandoned* — ``close()``d or
    garbage-collected mid-pass (GeneratorExit) — the in-flight lease is
    FAILed (immediate re-queue; peers must not WAIT out a dead lease's
    full timeout) and the client's ``close()`` is called when it has
    one, so a dropped reader never leaks its master socket.  Normal
    exhaustion and escaping load faults leave the client open: the
    returned reader is re-invocable (one call per pass) and
    poison-shard retry loops re-enter it.  Pass ``close_client=False``
    for a shared client whose lifecycle is managed elsewhere (e.g.
    ``cloud_reader``'s multi-pass wrapper — the lease FAIL on
    abandonment still happens).

    ``read_ahead > 0`` overlaps the NEXT chunk's lease + ``load_fn``
    fetch with consumption of the current one: a background thread
    leases tasks and materializes their samples into a queue at most
    ``read_ahead`` chunks deep (see :func:`_readahead_reader`).  The
    lease contract is unchanged — FIN only after the chunk's samples
    were all consumed, FAIL on a load fault or on abandonment, for
    every chunk the prefetcher holds (queued, in flight, or being
    consumed).  The client survives master reconnects mid-prefetch
    exactly as in the synchronous path (``_call`` replays).
    """

    if read_ahead > 0:
        return _readahead_reader(client, load_fn, wait_sleep,
                                 close_client, read_ahead)

    def reader():
        open_tid = None                    # leased, not yet FIN/FAILed
        try:
            while True:
                tid, payload = client.get_task()
                if payload is None:
                    if tid == 1:           # all leased elsewhere: wait
                        time.sleep(wait_sleep)
                        continue
                    break                   # epoch done
                open_tid = tid
                try:
                    for sample in load_fn(payload):
                        yield sample
                except Exception:
                    open_tid = None
                    client.task_failed(tid)
                    raise
                open_tid = None
                client.task_finished(tid)
        except GeneratorExit:
            try:
                if open_tid is not None:   # re-queue the abandoned shard
                    client.task_failed(open_tid)
            except Exception as e:  # noqa: BLE001 — teardown best-effort
                log.debug("abandoned-shard FAIL for task %s lost: %s: %s",
                          open_tid, type(e).__name__, e)
            if close_client:
                close = getattr(client, "close", None)
                if close is not None:
                    close()
            raise

    return reader


def _readahead_reader(client, load_fn, wait_sleep: float,
                      close_client: bool, depth: int):
    """``master_reader`` with chunk read-ahead: a background thread
    leases the next task and materializes its samples while the trainer
    consumes the current chunk, so shard fetch (network/disk IO in
    ``load_fn``) overlaps training instead of stalling each chunk
    boundary.

    Lease lifecycle is identical to the synchronous path, just tracked
    for every chunk the prefetcher holds: FIN after the chunk's last
    sample was consumed; FAIL on a load fault (which then re-raises in
    the consumer, so retry loops re-enter the reader) and on
    abandonment — a torn-down generator FAILs the chunk being consumed
    AND every prefetched-but-unconsumed chunk, so peers re-lease them
    immediately instead of waiting out the server-side timeout.  All
    client calls (two threads share one socket) are serialized under a
    lock; master reconnects inside ``_call`` replay as usual, so the
    prefetcher rides through connection drops.

    Note: a prefetched chunk's lease ages while it waits in the queue —
    keep ``read_ahead × chunk-train-time`` well under the master's
    lease ``timeout_s`` or leases re-queue spuriously (at-least-once
    still holds; samples may train twice).
    """
    from ..data.pipeline import IO_THREAD_PREFIX
    from ..data.reader import _put_until

    _End = object()
    depth_gauge = gauge(
        "cloud_readahead_depth",
        "prefetched chunks waiting in the cloud reader's read-ahead "
        "queue")
    chunk_counter = counter(
        "cloud_readahead_chunks_total",
        "chunks fetched by the cloud reader's read-ahead thread")

    def reader():
        out_q: "queue.Queue" = queue.Queue(maxsize=depth)
        stop = threading.Event()
        error: List[BaseException] = []
        call_lock = named_lock("master.readahead.call")  # one socket, two threads
        tids_lock = named_lock("master.readahead.tids")
        open_tids: set = set()         # leased, not yet FIN/FAILed
        # the fetcher adopts the consuming pass's trace context so its
        # lease RPCs + chunk loads land in that trace, not a fresh one
        trace_ctx = trace.current_context()

        def _put(item) -> bool:
            return _put_until(out_q, item, stop)

        def fetcher():
            with trace.context_scope(trace_ctx):
                _fetch_loop()

        def _fetch_loop():
            try:
                while not stop.is_set():
                    with call_lock:
                        tid, payload = client.get_task()
                    if payload is None:
                        if tid == 1:           # all leased elsewhere
                            time.sleep(wait_sleep)
                            continue
                        break                  # epoch done
                    with tids_lock:
                        open_tids.add(tid)
                    try:
                        with trace.span("master_load_chunk", task=tid):
                            samples = list(load_fn(payload))
                    except Exception as exc:   # shard fault: re-queue,
                        with tids_lock:        # then re-raise consumer-
                            open_tids.discard(tid)  # side
                        with call_lock:
                            client.task_failed(tid)
                        error.append(exc)
                        break
                    chunk_counter.inc()
                    if not _put((tid, samples)):
                        return                 # consumer gone
                    depth_gauge.set(out_q.qsize())
            except BaseException as exc:  # noqa: BLE001 — incl. RPC
                error.append(exc)         # giveups: consumer re-raises
            finally:
                _put(_End)

        t = threading.Thread(target=fetcher, daemon=True,
                             name=IO_THREAD_PREFIX + "cloud-readahead")
        t.start()
        abandoned = False
        try:
            while True:
                item = out_q.get()
                if item is _End:
                    if error:
                        raise error[0]
                    return
                tid, samples = item
                depth_gauge.set(out_q.qsize())
                for sample in samples:
                    yield sample
                with call_lock:
                    client.task_finished(tid)  # fully consumed
                with tids_lock:
                    open_tids.discard(tid)
        except GeneratorExit:
            abandoned = True
            raise
        finally:
            stop.set()
            t.join(timeout=5.0)
            with tids_lock:
                leftovers = sorted(open_tids)
                open_tids.clear()
            for tid in leftovers:   # re-queue: consumed-not-FINed chunk
                try:                # + every prefetched-unconsumed one
                    with call_lock:
                        client.task_failed(tid)
                except Exception as e:  # noqa: BLE001 — best-effort
                    log.debug("read-ahead FAIL for task %s lost during "
                              "teardown: %s: %s", tid,
                              type(e).__name__, e)
            if abandoned and close_client:
                close = getattr(client, "close", None)
                if close is not None:
                    close()

    return reader

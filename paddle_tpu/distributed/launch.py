"""Multi-host cluster launch — the ``cluster_train`` equivalent.

Reference: ``paddle/scripts/cluster_train/paddle.py`` (fabric/ssh
process launcher + pserver endpoint lists) and
``cluster_train_v2/openmpi`` — infrastructure whose only job is to start
N trainer processes that find each other.  TPU-native replacement:
``jax.distributed.initialize`` — every host runs the SAME program, the
coordinator handles rendezvous, and the global device mesh spans all
hosts; gradient exchange stays inside the jitted step (ICI within a
slice, DCN across slices), no pserver endpoints to wire.

Usage (same command on every host):

    PADDLE_COORDINATOR=host0:1234 PADDLE_NUM_NODES=4 PADDLE_NODE_ID=$i \\
        python -m paddle_tpu train --config ... --mesh_shape data=32

or programmatically ``initialize_cluster(...)`` before any jax call.
On Cloud TPU pods the three env vars are unnecessary —
``jax.distributed.initialize()`` auto-detects the pod topology.
"""

from __future__ import annotations

import os
from typing import Dict, Optional

from ..utils import get_logger

log = get_logger("launch")

ENV_COORDINATOR = "PADDLE_COORDINATOR"
ENV_NUM_NODES = "PADDLE_NUM_NODES"
ENV_NODE_ID = "PADDLE_NODE_ID"


def cluster_env() -> Optional[Dict[str, str]]:
    """The launch-relevant environment, or None when single-host."""
    if ENV_COORDINATOR not in os.environ:
        return None
    return {
        "coordinator_address": os.environ[ENV_COORDINATOR],
        "num_processes": os.environ.get(ENV_NUM_NODES),
        "process_id": os.environ.get(ENV_NODE_ID),
    }


def initialize_cluster(coordinator_address: Optional[str] = None,
                       num_processes: Optional[int] = None,
                       process_id: Optional[int] = None) -> bool:
    """Join (or auto-detect) the multi-host cluster.  Returns True when
    a multi-process runtime was initialized.  Must run before the first
    jax device use.  Arguments default to the ``PADDLE_*`` env vars; on
    TPU pods everything can be auto-detected by jax."""
    import jax

    env = cluster_env() or {}
    coordinator_address = coordinator_address or \
        env.get("coordinator_address")
    if num_processes is None and env.get("num_processes"):
        num_processes = int(env["num_processes"])
    if process_id is None and env.get("process_id"):
        process_id = int(env["process_id"])
    if coordinator_address is None and num_processes is None:
        try:  # TPU pod auto-detection
            jax.distributed.initialize()
        except Exception:
            return False
        ok = jax.process_count() > 1
        if ok:
            log.info("cluster: auto-detected %d processes",
                     jax.process_count())
        return ok
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
    log.info("cluster: process %d/%d via %s (%d global devices)",
             jax.process_index(), jax.process_count(),
             coordinator_address, jax.device_count())
    return True


def global_mesh(axes: Dict[str, int]):
    """Build a mesh over ALL processes' devices (the multi-host
    ``--mesh_shape``); axis sizes must multiply to the global device
    count."""
    import numpy as np

    import jax
    from jax.sharding import Mesh

    devices = np.array(jax.devices())
    names = tuple(axes)
    sizes = tuple(axes.values())
    if int(np.prod(sizes)) != devices.size:
        raise ValueError(f"mesh {axes} needs {np.prod(sizes)} devices, "
                         f"cluster has {devices.size}")
    return Mesh(devices.reshape(sizes), names)

"""Elastic, preemption-tolerant training loop.

Re-expresses the gen-2 fault-tolerance story
(``doc/design/cluster_train/README.md``): trainers are stateless — data
progress lives in the master's leased task queue (snapshot/recover),
model+optimizer state lives in periodic checkpoints (the Go pserver's
``parameterCheckpoint``, ``go/pserver/service.go:146``).  Kill any trainer
at any point; a restart recovers the latest checkpoint and the master
re-leases unfinished shards.
"""

from __future__ import annotations

import os
import time
from typing import Callable, List, Optional

from ..observe import counter, fleet
from ..trainer.trainer import Trainer
from ..utils import get_logger

log = get_logger("elastic")


class ElasticTrainer:
    """Wraps a :class:`Trainer` with master-driven data tasks and
    periodic checkpoints; safe to kill+restart at any batch."""

    def __init__(self, trainer: Trainer, client, load_fn: Callable,
                 save_dir: str, trainer_id: str = "trainer-0",
                 checkpoint_every_s: float = 60.0,
                 ckpt_fail_max: int = 3):
        self.trainer = trainer
        self.client = client
        self.load_fn = load_fn
        self.save_dir = save_dir
        self.trainer_id = trainer_id
        self.checkpoint_every_s = checkpoint_every_s
        self.ckpt_fail_max = ckpt_fail_max
        self._last_ckpt = 0.0
        self._ckpt_failures = 0  # consecutive save failures
        # fleet identity: the trainer_id IS the stable logical id —
        # a SIGKILLed-and-restarted trainer re-registers under the
        # same key, so the /fleet/healthz rollup flips its 'missing'
        # entry back to ok instead of mourning a ghost pid forever
        fleet.set_identity(role="trainer", name=trainer_id)

    def resume(self) -> bool:
        """Load the newest *valid* checkpoint if one exists (corrupt
        dirs are skipped + quarantined by ``Trainer.resume``)."""
        ok = self.trainer.resume(self.save_dir)
        if ok:
            log.info("resumed from checkpoint in %s "
                     "(samples_seen=%d)", self.save_dir,
                     self.trainer.samples_seen)
        return ok

    def _maybe_checkpoint(self, epoch: int, force: bool = False) -> None:
        now = time.monotonic()
        if not force and now - self._last_ckpt < self.checkpoint_every_s:
            return
        # save-model election: exactly one trainer checkpoints per window
        if not self.client.request_save_model(self.trainer_id,
                                              self.checkpoint_every_s):
            return
        try:
            self.trainer.save(self.save_dir, epoch)
        except OSError as e:
            # a transient disk fault (ENOSPC, EACCES, ...) must not kill
            # the training loop: skip this window, retry at the next one.
            # Only an epoch-end force save escalates, and only once the
            # disk has been bad for ckpt_fail_max consecutive attempts —
            # at that point progress durability is genuinely gone.
            self._ckpt_failures += 1
            counter("elastic_skipped_saves",
                    "checkpoint windows skipped after a failed save "
                    "(disk fault survived)").inc()
            try:
                # release the won election (interval < 0) so a healthy
                # peer can checkpoint this window instead of the fleet
                # silently losing it to our broken disk
                self.client.request_save_model(self.trainer_id, -1.0)
                counter("elastic_election_releases",
                        "save-model elections released to a peer after "
                        "a local save failure").inc()
            except Exception as rel_e:  # noqa: BLE001 — best-effort
                log.debug("save-model election release failed: %s: %s",
                          type(rel_e).__name__, rel_e)
            log.warning(
                "checkpoint save failed: epoch=%d force=%s dir=%s "
                "consecutive=%d/%d error=%s: %s — skipping this window",
                epoch, force, self.save_dir, self._ckpt_failures,
                self.ckpt_fail_max, type(e).__name__, e)
            if force and self._ckpt_failures >= self.ckpt_fail_max:
                raise
            self._last_ckpt = now  # full window before the next attempt
            return
        self._ckpt_failures = 0
        self._last_ckpt = now

    def _train_batch(self, feeder, samples, epoch: int,
                     event_handler: Optional[Callable]) -> None:
        feed = feeder.convert(samples) if feeder else samples
        loss = self.trainer.train_one_batch(feed)
        self._maybe_checkpoint(epoch)
        if event_handler is not None:
            event_handler(epoch, loss)

    def train(self, feeder, batch_size: int, num_epochs: int = 1,
              event_handler: Optional[Callable] = None) -> None:
        self.resume()
        # pass-number handshake, offset by the master's epoch so a
        # restarted trainer's resets keep advancing against a recovered
        # or long-lived master instead of no-opping (zero-sample passes)
        epoch_base = self.client.current_epoch()
        for epoch in range(num_epochs):
            self._train_one_epoch(feeder, batch_size, epoch, event_handler)
            self._maybe_checkpoint(epoch, force=True)
            self.client.reset_epoch(epoch_base + epoch + 1)
            log.info("epoch %d complete: %s", epoch, self.client.counts())

    def _train_one_epoch(self, feeder, batch_size: int, epoch: int,
                         event_handler: Optional[Callable]) -> None:
        """Lease tasks and train them, marking a task FINished only once
        every one of its samples has actually gone through a training
        step.  Any exception — in ``load_fn`` *or* on the consumer side
        (feeder/``train_one_batch``) — FAILs the leased tasks whose
        samples were in flight, so the master re-queues them instead of
        waiting out the lease; one bad shard must not kill the trainer
        (``go/master/service.go:313`` failure-tolerance contract).
        Samples buffered from earlier tasks are tracked per-task (at-least
        -once: a task is re-leased unless fully trained)."""
        buf: List[tuple] = []          # (task_id, sample) carried remainder
        open_tasks: List[int] = []     # leased ids not yet FIN/FAILed

        def _finish_drained() -> None:
            # a leased task is complete once it's fully loaded (always
            # true here — tasks enter open_tasks after their load loop
            # ends) and none of its samples await training; covers
            # zero-sample shards too
            remaining = {t for t, _ in buf}
            for t in list(open_tasks):
                if t not in remaining:
                    open_tasks.remove(t)
                    self.client.task_finished(t)

        def _train_buffered(flush_tail: bool) -> None:
            while len(buf) >= batch_size:
                chunk, rest = buf[:batch_size], buf[batch_size:]
                self._train_batch(feeder, [s for _, s in chunk],
                                  epoch, event_handler)
                buf[:] = rest
                _finish_drained()
            if flush_tail and buf:
                self._train_batch(feeder, [s for _, s in buf],
                                  epoch, event_handler)
                buf.clear()
            _finish_drained()

        def _fail_in_flight(e: Exception, what: str) -> None:
            for t in open_tasks:        # in-flight tasks → re-queue now
                self.client.task_failed(t)
            open_tasks.clear()
            buf.clear()
            log.warning("%s failed (%s: %s); continuing", what,
                        type(e).__name__, e)

        while True:
            tid, payload = self.client.get_task()
            if payload is None:
                # WAIT means every remaining task is leased elsewhere —
                # or by US (sub-batch remainders); flush so our own
                # leases can finish, else the epoch deadlocks on them
                try:
                    _train_buffered(flush_tail=True)
                except Exception as e:  # noqa: BLE001 — shard fault
                    _fail_in_flight(e, "tail batch")
                if tid == 1:
                    time.sleep(0.05)
                    continue
                break                   # epoch drained
            open_tasks.append(tid)
            try:
                for sample in self.load_fn(payload):
                    buf.append((tid, sample))
                _train_buffered(flush_tail=False)
            except Exception as e:      # noqa: BLE001 — shard fault
                _fail_in_flight(e, "shard")

"""Elastic, preemption-tolerant training loop.

Re-expresses the gen-2 fault-tolerance story
(``doc/design/cluster_train/README.md``): trainers are stateless — data
progress lives in the master's leased task queue (snapshot/recover),
model+optimizer state lives in periodic checkpoints (the Go pserver's
``parameterCheckpoint``, ``go/pserver/service.go:146``).  Kill any trainer
at any point; a restart recovers the latest checkpoint and the master
re-leases unfinished shards.
"""

from __future__ import annotations

import os
import time
from typing import Callable, Optional

from ..trainer.trainer import Trainer
from ..utils import get_logger
from .master import Master, MasterClient, master_reader

log = get_logger("elastic")


class ElasticTrainer:
    """Wraps a :class:`Trainer` with master-driven data tasks and
    periodic checkpoints; safe to kill+restart at any batch."""

    def __init__(self, trainer: Trainer, client, load_fn: Callable,
                 save_dir: str, trainer_id: str = "trainer-0",
                 checkpoint_every_s: float = 60.0):
        self.trainer = trainer
        self.client = client
        self.load_fn = load_fn
        self.save_dir = save_dir
        self.trainer_id = trainer_id
        self.checkpoint_every_s = checkpoint_every_s
        self._last_ckpt = 0.0

    def resume(self) -> bool:
        """Load the latest checkpoint if one exists."""
        ok = self.trainer.resume(self.save_dir)
        if ok:
            log.info("resumed from checkpoint in %s "
                     "(samples_seen=%d)", self.save_dir,
                     self.trainer.samples_seen)
        return ok

    def _maybe_checkpoint(self, epoch: int, force: bool = False) -> None:
        now = time.monotonic()
        if not force and now - self._last_ckpt < self.checkpoint_every_s:
            return
        # save-model election: exactly one trainer checkpoints per window
        if self.client.request_save_model(self.trainer_id,
                                          self.checkpoint_every_s):
            self.trainer.save(self.save_dir, epoch)
            self._last_ckpt = now

    def train(self, feeder, batch_size: int, num_epochs: int = 1,
              event_handler: Optional[Callable] = None) -> None:
        from ..data.reader import batch as batch_reader

        self.resume()
        for epoch in range(num_epochs):
            # a failing shard is marked failed (master re-queues it until
            # failure_max) and we keep consuming — one bad shard must not
            # kill the trainer (go/master failure-tolerance contract)
            while True:
                reader = batch_reader(
                    master_reader(self.client, self.load_fn), batch_size)
                try:
                    for samples in reader():
                        feed = feeder.convert(samples) if feeder \
                            else samples
                        loss = self.trainer.train_one_batch(feed)
                        self._maybe_checkpoint(epoch)
                        if event_handler is not None:
                            event_handler(epoch, loss)
                    break  # drained cleanly
                except Exception as e:     # noqa: BLE001 — shard fault
                    log.warning("shard failed (%s: %s); continuing",
                                type(e).__name__, e)
            self._maybe_checkpoint(epoch, force=True)
            self.client.reset_epoch()
            log.info("epoch %d complete: %s", epoch, self.client.counts())

"""Dataset download + md5 cache.

Reference: ``python/paddle/v2/dataset/common.py:33-98`` — corpora are
fetched once into ``~/.cache/paddle/dataset/<module>/`` and verified by
md5; every loader goes through :func:`download` so a warm cache never
touches the network.  This port keeps the exact cache layout (a cache
populated by the reference is picked up as-is) and uses urllib (stdlib)
instead of ``requests``.

Sandboxed/zero-egress environments: set ``PADDLE_TPU_NO_DOWNLOAD=1`` to
fail fast without a connection attempt; loaders in
:mod:`paddle_tpu.data.datasets` catch :class:`DownloadError` and fall
back to their synthetic surrogates.
"""

from __future__ import annotations

import hashlib
import os
import shutil
import urllib.request

from ..utils import get_logger

log = get_logger("dataset")

DATA_HOME = os.path.expanduser(
    os.environ.get("PADDLE_TPU_DATASET_CACHE", "~/.cache/paddle/dataset"))


class DownloadError(RuntimeError):
    pass


def md5file(fname: str) -> str:
    hash_md5 = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(4096), b""):
            hash_md5.update(chunk)
    return hash_md5.hexdigest()


def cached_path(url: str, module_name: str) -> str:
    return os.path.join(DATA_HOME, module_name, url.split("/")[-1])


def download(url: str, module_name: str, md5sum: str,
             retry_limit: int = 3) -> str:
    """Return the local path of ``url``, downloading + md5-verifying into
    the cache if needed (``common.py:62`` semantics, including the retry
    loop)."""
    filename = cached_path(url, module_name)
    os.makedirs(os.path.dirname(filename), exist_ok=True)
    retry = 0
    while not (os.path.exists(filename) and md5file(filename) == md5sum):
        if os.environ.get("PADDLE_TPU_NO_DOWNLOAD"):
            raise DownloadError(
                f"{filename} not cached and downloads are disabled "
                "(PADDLE_TPU_NO_DOWNLOAD)")
        if retry >= retry_limit:
            raise DownloadError(
                f"cannot download {url} within {retry_limit} retries")
        retry += 1
        log.info("cache miss for %s, downloading %s (try %d)",
                 filename, url, retry)
        tmp = filename + ".part"
        try:
            with urllib.request.urlopen(url, timeout=60) as r, \
                    open(tmp, "wb") as f:
                shutil.copyfileobj(r, f)
            os.replace(tmp, filename)
        except OSError as e:
            if os.path.exists(tmp):
                os.remove(tmp)
            raise DownloadError(f"download of {url} failed: {e}") from e
    return filename


def split(reader, line_count: int, suffix: str = "%05d.pickle",
          dumper=None) -> int:
    """Split a reader's samples into fixed-size pickle shard files
    (``v2/dataset/common.py:121``); returns the number of files
    written."""
    import pickle

    dumper = dumper or (lambda obj, f: pickle.dump(obj, f))
    lines, indx_f = [], 0
    for sample in reader():
        lines.append(sample)
        if len(lines) == line_count:
            with open(suffix % indx_f, "wb") as f:
                dumper(lines, f)
            lines, indx_f = [], indx_f + 1
    if lines:
        with open(suffix % indx_f, "wb") as f:
            dumper(lines, f)
        indx_f += 1
    return indx_f


def cluster_files_reader(files_pattern: str, trainer_count: int,
                         trainer_id: int, loader=None):
    """Reader over the shard files produced by :func:`split`, taking
    every ``trainer_count``-th file starting at ``trainer_id``
    (``v2/dataset/common.py:158``)."""
    import glob
    import pickle

    loader = loader or pickle.load

    def reader():
        file_list = sorted(glob.glob(files_pattern))
        for idx, fn in enumerate(file_list):
            if idx % trainer_count != trainer_id:
                continue
            with open(fn, "rb") as f:
                for sample in loader(f):
                    yield sample

    return reader


def convert(output_path: str, reader, line_count: int,
            name_prefix: str, shuffle_seed: int = 0) -> list:
    """Convert a reader's samples to chunked recordio shard files
    (``v2/dataset/common.py:194``); returns the shard paths.  Samples
    are pickled per the reference convention; each shard shuffles its
    buffer before writing."""
    import pickle
    import random

    from . import recordio as rio

    rand = random.Random(shuffle_seed)
    paths, lines, indx_f = [], [], 0

    def write_shard(idx, buf):
        rand.shuffle(buf)
        path = os.path.join(output_path, "%s-%05d" % (name_prefix, idx))
        with rio.Writer(path) as w:
            for sample in buf:
                w.write(pickle.dumps(sample))
        paths.append(path)

    for sample in reader():
        lines.append(sample)
        if len(lines) == line_count:
            write_shard(indx_f, lines)
            lines, indx_f = [], indx_f + 1
    if lines:
        write_shard(indx_f, lines)
    return paths

"""Dataset download + md5 cache.

Reference: ``python/paddle/v2/dataset/common.py:33-98`` — corpora are
fetched once into ``~/.cache/paddle/dataset/<module>/`` and verified by
md5; every loader goes through :func:`download` so a warm cache never
touches the network.  This port keeps the exact cache layout (a cache
populated by the reference is picked up as-is) and uses urllib (stdlib)
instead of ``requests``.

Sandboxed/zero-egress environments: set ``PADDLE_TPU_NO_DOWNLOAD=1`` to
fail fast without a connection attempt; loaders in
:mod:`paddle_tpu.data.datasets` catch :class:`DownloadError` and fall
back to their synthetic surrogates.
"""

from __future__ import annotations

import hashlib
import os
import shutil
import urllib.request

from ..utils import get_logger

log = get_logger("dataset")

DATA_HOME = os.path.expanduser(
    os.environ.get("PADDLE_TPU_DATASET_CACHE", "~/.cache/paddle/dataset"))


class DownloadError(RuntimeError):
    pass


def md5file(fname: str) -> str:
    hash_md5 = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(4096), b""):
            hash_md5.update(chunk)
    return hash_md5.hexdigest()


def cached_path(url: str, module_name: str) -> str:
    return os.path.join(DATA_HOME, module_name, url.split("/")[-1])


def download(url: str, module_name: str, md5sum: str,
             retry_limit: int = 3) -> str:
    """Return the local path of ``url``, downloading + md5-verifying into
    the cache if needed (``common.py:62`` semantics, including the retry
    loop)."""
    filename = cached_path(url, module_name)
    os.makedirs(os.path.dirname(filename), exist_ok=True)
    retry = 0
    while not (os.path.exists(filename) and md5file(filename) == md5sum):
        if os.environ.get("PADDLE_TPU_NO_DOWNLOAD"):
            raise DownloadError(
                f"{filename} not cached and downloads are disabled "
                "(PADDLE_TPU_NO_DOWNLOAD)")
        if retry >= retry_limit:
            raise DownloadError(
                f"cannot download {url} within {retry_limit} retries")
        retry += 1
        log.info("cache miss for %s, downloading %s (try %d)",
                 filename, url, retry)
        tmp = filename + ".part"
        try:
            with urllib.request.urlopen(url, timeout=60) as r, \
                    open(tmp, "wb") as f:
                shutil.copyfileobj(r, f)
            os.replace(tmp, filename)
        except OSError as e:
            if os.path.exists(tmp):
                os.remove(tmp)
            raise DownloadError(f"download of {url} failed: {e}") from e
    return filename

"""Dataset download + md5 cache.

Reference: ``python/paddle/v2/dataset/common.py:33-98`` — corpora are
fetched once into ``~/.cache/paddle/dataset/<module>/`` and verified by
md5; every loader goes through :func:`download` so a warm cache never
touches the network.  This port keeps the exact cache layout (a cache
populated by the reference is picked up as-is) and uses urllib (stdlib)
instead of ``requests``.

Sandboxed/zero-egress environments: set ``PADDLE_TPU_NO_DOWNLOAD=1`` to
fail fast without a connection attempt; loaders in
:mod:`paddle_tpu.data.datasets` catch :class:`DownloadError` and fall
back to their synthetic surrogates.
"""

from __future__ import annotations

import hashlib
import os
import random
import shutil
import time
import urllib.request

from ..utils import get_logger

log = get_logger("dataset")

DATA_HOME = os.path.expanduser(
    os.environ.get("PADDLE_TPU_DATASET_CACHE", "~/.cache/paddle/dataset"))


class DownloadError(RuntimeError):
    pass


def md5file(fname: str) -> str:
    hash_md5 = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(4096), b""):
            hash_md5.update(chunk)
    return hash_md5.hexdigest()


def cached_path(url: str, module_name: str) -> str:
    return os.path.join(DATA_HOME, module_name, url.split("/")[-1])


def download(url: str, module_name: str, md5sum: str,
             retry_limit: int = 3, backoff_base_s: float = 0.5) -> str:
    """Return the local path of ``url``, downloading + md5-verifying into
    the cache if needed (``common.py:62`` semantics, including the retry
    loop).

    A transient ``OSError`` (connection reset, timeout, DNS blip, 5xx)
    consumes one retry and backs off exponentially with jitter;
    :class:`DownloadError` is raised only once ``retry_limit`` attempts
    are exhausted.  A permanent HTTP client error (4xx — the URL is
    wrong, not the network) fails fast without burning retries.
    """
    filename = cached_path(url, module_name)
    os.makedirs(os.path.dirname(filename), exist_ok=True)
    retry = 0
    last_err = None
    while not (os.path.exists(filename) and md5file(filename) == md5sum):
        if os.environ.get("PADDLE_TPU_NO_DOWNLOAD"):
            raise DownloadError(
                f"{filename} not cached and downloads are disabled "
                "(PADDLE_TPU_NO_DOWNLOAD)")
        if retry >= retry_limit:
            detail = f" (last error: {last_err})" if last_err else ""
            raise DownloadError(
                f"cannot download {url} within {retry_limit} "
                f"retries{detail}")
        retry += 1
        log.info("cache miss for %s, downloading %s (try %d)",
                 filename, url, retry)
        tmp = filename + ".part"
        try:
            with urllib.request.urlopen(url, timeout=60) as r, \
                    open(tmp, "wb") as f:
                shutil.copyfileobj(r, f)
            os.replace(tmp, filename)
        except OSError as e:
            last_err = e
            if os.path.exists(tmp):
                os.remove(tmp)
            code = getattr(e, "code", None)  # urllib HTTPError status
            # 408 (request timeout) and 429 (rate limited) are transient
            # despite being 4xx — they are exactly what backoff is for
            if code is not None and 400 <= code < 500 \
                    and code not in (408, 429):
                raise DownloadError(
                    f"download of {url} failed permanently "
                    f"(HTTP {code}): {e}") from e
            if retry >= retry_limit:
                continue  # the loop head raises with this error attached
            delay = backoff_base_s * (2 ** (retry - 1))
            delay *= 0.5 + random.random()  # jitter: [0.5, 1.5)x
            log.warning("download of %s failed (%s: %s); retry %d/%d "
                        "in %.1fs", url, type(e).__name__, e, retry,
                        retry_limit, delay)
            time.sleep(delay)
    return filename


def split(reader, line_count: int, suffix: str = "%05d.pickle",
          dumper=None) -> int:
    """Split a reader's samples into fixed-size pickle shard files
    (``v2/dataset/common.py:121``); returns the number of files
    written."""
    import pickle

    dumper = dumper or (lambda obj, f: pickle.dump(obj, f))
    lines, indx_f = [], 0
    for sample in reader():
        lines.append(sample)
        if len(lines) == line_count:
            with open(suffix % indx_f, "wb") as f:
                dumper(lines, f)
            lines, indx_f = [], indx_f + 1
    if lines:
        with open(suffix % indx_f, "wb") as f:
            dumper(lines, f)
        indx_f += 1
    return indx_f


def cluster_files_reader(files_pattern: str, trainer_count: int,
                         trainer_id: int, loader=None):
    """Reader over the shard files produced by :func:`split`, taking
    every ``trainer_count``-th file starting at ``trainer_id``
    (``v2/dataset/common.py:158``)."""
    import glob
    import pickle

    loader = loader or pickle.load

    def reader():
        file_list = sorted(glob.glob(files_pattern))
        for idx, fn in enumerate(file_list):
            if idx % trainer_count != trainer_id:
                continue
            with open(fn, "rb") as f:
                for sample in loader(f):
                    yield sample

    return reader


def convert(output_path: str, reader, line_count: int,
            name_prefix: str, shuffle_seed: int = 0) -> list:
    """Convert a reader's samples to chunked recordio shard files
    (``v2/dataset/common.py:194``); returns the shard paths.  Samples
    are pickled per the reference convention; each shard shuffles its
    buffer before writing."""
    import pickle
    import random

    from . import recordio as rio

    rand = random.Random(shuffle_seed)
    paths, lines, indx_f = [], [], 0

    def write_shard(idx, buf):
        rand.shuffle(buf)
        path = os.path.join(output_path, "%s-%05d" % (name_prefix, idx))
        with rio.Writer(path) as w:
            for sample in buf:
                w.write(pickle.dumps(sample))
        paths.append(path)

    for sample in reader():
        lines.append(sample)
        if len(lines) == line_count:
            write_shard(indx_f, lines)
            lines, indx_f = [], indx_f + 1
    if lines:
        write_shard(indx_f, lines)
    return paths

"""Chunked record files — the RecordIO capability, redesigned.

The reference's master leases dataset *chunks* to trainers
(``go/master/service.go:56-75`` ``Chunk``/``Task`` over
``github.com/PaddlePaddle/recordio`` files; trainers stream records via
``python/paddle/v2/reader/creator.py:60`` ``recordio`` and ``:91``
``cloud_reader``).  That library is external to the reference tree, so
this is a from-scratch format with the same capabilities:

- append-only **writer** batching records into chunks (optionally
  gzip-compressed, crc32-checked);
- a **chunk index** built by scanning headers only (no record decode) so
  a coordinator can partition work by chunk, like ``recordio.LoadIndex``
  (``service.go:253``);
- **readers** for a whole file/glob or one chunk at a byte offset (the
  unit the master hands out).

Layout per chunk::

    magic 'PTRC' | u32 num_records | u32 body_len | u32 crc32(body) |
    u8 compressor (0 none, 1 gzip) | body
    body = repeat(u32 record_len | record_bytes)

All integers little-endian.
"""

from __future__ import annotations

import glob as _glob
import gzip
import os
import struct
import zlib
from typing import Iterator, List, Optional, Sequence, Tuple, Union

from ..utils import PaddleTpuError, enforce

MAGIC = b"PTRC"
_HEADER = struct.Struct("<4sIIIB")
NO_COMPRESS, GZIP = 0, 1


class Writer:
    """Append records (bytes) into chunked files.

    >>> with Writer("part-00000.recordio") as w:
    ...     w.write(b"sample")
    """

    def __init__(self, path: str, max_records_per_chunk: int = 1000,
                 compressor: int = NO_COMPRESS):
        enforce(compressor in (NO_COMPRESS, GZIP),
                f"unknown compressor {compressor}")
        self._f = open(path, "wb")
        self._max = max_records_per_chunk
        self._compressor = compressor
        self._pending: List[bytes] = []

    def write(self, record: bytes) -> None:
        if isinstance(record, str):
            record = record.encode("utf-8")
        self._pending.append(bytes(record))
        if len(self._pending) >= self._max:
            self.flush()

    def flush(self) -> None:
        if not self._pending:
            return
        body = b"".join(struct.pack("<I", len(r)) + r
                        for r in self._pending)
        if self._compressor == GZIP:
            body = gzip.compress(body)
        self._f.write(_HEADER.pack(MAGIC, len(self._pending), len(body),
                                   zlib.crc32(body) & 0xFFFFFFFF,
                                   self._compressor))
        self._f.write(body)
        self._pending = []

    def close(self) -> None:
        if not self._f.closed:
            self.flush()
            self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def load_index(path: str) -> List[Tuple[int, int]]:
    """Scan chunk headers only; returns ``[(byte_offset, num_records)]``
    — the partitioning unit for master data tasks."""
    index = []
    size = os.path.getsize(path)
    with open(path, "rb") as f:
        off = 0
        while off < size:
            head = f.read(_HEADER.size)
            if len(head) < _HEADER.size:
                raise PaddleTpuError(f"{path}: truncated chunk header "
                                     f"at offset {off}")
            magic, n, body_len, _crc, _comp = _HEADER.unpack(head)
            if magic != MAGIC:
                raise PaddleTpuError(f"{path}: bad chunk magic at "
                                     f"offset {off}")
            index.append((off, n))
            off += _HEADER.size + body_len
            f.seek(off)
    return index


def read_chunk(path: str, offset: int) -> List[bytes]:
    """Decode the records of the single chunk at ``offset``."""
    with open(path, "rb") as f:
        f.seek(offset)
        head = f.read(_HEADER.size)
        enforce(len(head) == _HEADER.size, f"{path}: truncated chunk")
        magic, n, body_len, crc, comp = _HEADER.unpack(head)
        enforce(magic == MAGIC, f"{path}: bad chunk magic @{offset}")
        body = f.read(body_len)
    enforce(len(body) == body_len, f"{path}: truncated chunk body")
    if zlib.crc32(body) & 0xFFFFFFFF != crc:
        raise PaddleTpuError(f"{path}: chunk crc mismatch @{offset}")
    if comp == GZIP:
        body = gzip.decompress(body)
    records, off = [], 0
    for _ in range(n):
        (rlen,) = struct.unpack_from("<I", body, off)
        off += 4
        records.append(body[off:off + rlen])
        off += rlen
    return records


def expand_paths(paths: Union[str, Sequence[str]]) -> List[str]:
    """Reference path convention: comma-separated string or list, glob
    patterns supported (``creator.py:62``)."""
    if isinstance(paths, str):
        paths = paths.split(",")
    out: List[str] = []
    for p in paths:
        matches = sorted(_glob.glob(p))
        out.extend(matches if matches else [p])
    return out


def reader(paths: Union[str, Sequence[str]]) -> Iterator[bytes]:
    """Stream raw records across files/globs in order."""
    for path in expand_paths(paths):
        for off, _n in load_index(path):
            yield from read_chunk(path, off)


def chunk_payloads(paths: Union[str, Sequence[str]]) -> List[str]:
    """Master task payloads addressing individual chunks
    (``"path\\toffset"`` — the format :func:`paddle_tpu.data.reader.
    cloud_reader`'s ``load_chunk`` parses)."""
    return [f"{p}\t{off}" for p in expand_paths(paths)
            for off, _n in load_index(p)]

"""PyDataProvider2-compatible ``@provider`` decorator.

Reference: ``python/paddle/trainer/PyDataProvider2.py:365`` — users declare a
generator of samples with input types; the C++ engine
(``PyDataProvider2.cpp:195``) pulls from it with pooling/shuffling.  Here the
decorator just wraps the generator into the reader protocol plus metadata;
the trainer's feeder consumes it directly (no embedded-interpreter hop).
"""

from __future__ import annotations

import functools
import random
from typing import Any, Callable, Optional, Sequence

from .feeder import InputType
from ..compat import CacheType  # noqa: F401  (PyDataProvider2 name)


class Settings:
    """Provider settings object handed to ``init_hook`` / the generator.

    The reference's init hooks set either ``settings.input_types`` or the
    older alias ``settings.slots`` (``python/paddle/trainer/
    PyDataProvider2.py``, used by ``benchmark/paddle/image/provider.py:18``)
    — keep both names pointing at the same list.
    """

    def __init__(self, input_types=None):
        self.input_types = input_types

    @property
    def slots(self):
        return self.input_types

    @slots.setter
    def slots(self, value):
        self.input_types = value


class ProviderWrapper:
    def __init__(self, generator: Callable, input_types, cache: bool,
                 should_shuffle: bool, pool_size: int,
                 init_hook: Optional[Callable]):
        self.generator = generator
        self.input_types = input_types
        self.cache = cache
        self.should_shuffle = should_shuffle
        self.pool_size = pool_size
        self.init_hook = init_hook
        self._cached = None
        self.settings = Settings(input_types)

    def reader(self, *file_list, **kwargs):
        """Build a reader over the provider's generator."""
        if self.init_hook:
            self.init_hook(self.settings, file_list=file_list, **kwargs)

        def read():
            if self.cache and self._cached is not None:
                data = self._cached
            else:
                data = []
                files = file_list or [None]
                for fname in files:
                    for sample in self.generator(self.settings, fname):
                        if self.cache:
                            data.append(sample)
                        else:
                            yield sample
                if self.cache:
                    self._cached = data
                else:
                    return
            if self.should_shuffle:
                data = list(data)
                random.shuffle(data)
            yield from data

        if self.should_shuffle and not self.cache:
            from .reader import shuffle

            return shuffle(read, max(self.pool_size, 1) or 1000)
        return read


def provider(input_types=None, cache=False, should_shuffle=True,
             pool_size=1000, min_pool_size=-1, calc_batch_size=None,
             init_hook=None, **_ignored):
    """``@provider(input_types=[...])`` decorator (PyDataProvider2 API)."""

    def deco(fn):
        wrapper = ProviderWrapper(fn, input_types, cache, should_shuffle,
                                  pool_size, init_hook)
        functools.update_wrapper(wrapper, fn, updated=[])
        return wrapper

    return deco

from . import datasets, pipeline, reader, recordio
from .feeder import (
    DataFeeder,
    InputType,
    dense_vector,
    dense_vector_sequence,
    dense_vector_sub_sequence,
    integer_value,
    integer_value_sequence,
    integer_value_sub_sequence,
    sparse_binary_vector,
    sparse_binary_vector_sequence,
    sparse_float_vector,
    sparse_float_vector_sequence,
)
from .pipeline import AsyncPipeline, prefetch_reader
from .provider import provider

__all__ = [
    "AsyncPipeline",
    "DataFeeder",
    "InputType",
    "datasets",
    "dense_vector",
    "dense_vector_sequence",
    "dense_vector_sub_sequence",
    "integer_value",
    "integer_value_sequence",
    "integer_value_sub_sequence",
    "pipeline",
    "prefetch_reader",
    "provider",
    "reader",
    "sparse_binary_vector",
    "sparse_binary_vector_sequence",
    "sparse_float_vector",
    "sparse_float_vector_sequence",
]

"""Bounded asynchronous input pipeline: reader → convert → device.

The synchronous train loop serializes three kinds of host work in front
of every device step — pulling the next minibatch from the reader
(IO), ``DataFeeder.convert`` (the numpy densify/pad hot path), and the
host→device transfer — so every millisecond of them is a millisecond
the TPU starves.  :class:`AsyncPipeline` overlaps all three with the
running step: N worker threads share the pass's reader iterator,
convert and device-place batches off the critical path, and feed a
depth-bounded queue of *already-on-device* feed dicts; the consumer
(``Trainer.train``'s loop) only ever blocks when the queue is empty.
This is the host-input-vs-device-step overlap that Wang et al.
(arXiv:1907.10701) identify as the #1 TPU utilization lever, and the
equivalent of the reference's double-buffer ``DataProvider`` queue
(``DataProvider.h:360``) generalized to a worker pool.

Contract (what the tests pin):

- **order determinism** — batches come out in exactly the reader's
  order regardless of worker count, so a fixed-seed run's loss
  trajectory is byte-identical to the synchronous path's;
- **bounded** — at most ``depth`` batches are in flight between the
  reader and the consumer (reader IO, conversion, and the ready queue
  all count against the bound), so prefetch never balloons host/device
  memory;
- **exceptions propagate** — a fault in the reader or in a worker's
  convert re-raises in the consumer at the position it occurred, after
  every earlier batch was delivered;
- **clean shutdown** — ``close()`` (idempotent; also run when a
  consumer abandons iteration) stops and joins every worker and closes
  the source iterator, so an abandoned generator chain (``buffered``,
  ``master_reader`` leases) still runs its teardown.

Telemetry (``paddle_tpu/observe``): ``pipeline_queue_depth`` gauge
(ready batches), ``pipeline_prefetch_hits_total`` /
``pipeline_prefetch_stalls_total`` counters (was the next batch ready
when the consumer asked?), and the ``pipeline_worker_convert_seconds``
histogram (per-batch convert+place time on the worker threads).

Tracing (:mod:`paddle_tpu.observe.trace`): worker threads adopt the
trace context active when the pipeline was constructed (the trainer's
``train_pass`` span), so each ``pipeline_read`` (source pull — reader
IO, master lease RPCs) and ``pipeline_convert`` (convert + H2D place,
indexed by batch) span lands in the consuming pass's trace, one lane
per worker thread in Perfetto.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Iterable, Iterator, Optional

from .. import observe
from ..analysis.lockorder import named_condition, named_lock
from ..observe import trace
from ..utils import get_logger

log = get_logger("pipeline")

#: Thread-name prefix shared by every IO/pipeline worker thread in the
#: framework (pipeline workers, buffered/xmap reader threads, the cloud
#: read-ahead thread).  The conftest thread-leak guard keys on it.
IO_THREAD_PREFIX = "ptpu-io-"

_POLL_S = 0.05          # stop-flag poll period for blocking queue ops
_JOIN_TIMEOUT_S = 5.0   # per-thread join budget on close()


class PipelineClosed(RuntimeError):
    """Raised when the consumer keeps iterating a closed pipeline."""


class AsyncPipeline:
    """One pass's async prefetcher over an iterable of raw minibatches.

    :param batches: iterable (typically a reader generator) of raw
        minibatches for this pass.  Consumed by the worker threads,
        serialized under a lock — the iterator itself need not be
        thread-safe.
    :param convert_fn: per-batch host conversion (``feeder.convert``);
        runs on a worker thread.  None = batches are already feed dicts.
    :param place_fn: device placement for a converted feed
        (``Trainer._place_feed``); runs on the same worker thread so the
        H2D copy overlaps the running step.  None = no placement.
    :param depth: max batches in flight between reader and consumer.
    :param workers: reader/convert worker threads (clamped to
        ``[1, depth]`` — more workers than queue slots would only starve).

    Iterating the pipeline yields converted+placed feeds in reader
    order; breaking out of the loop (or an exception crossing it) closes
    it.  ``close()`` may also be called explicitly and is idempotent.
    """

    def __init__(self, batches: Iterable[Any],
                 convert_fn: Optional[Callable[[Any], Any]] = None,
                 place_fn: Optional[Callable[[Any], Any]] = None,
                 depth: int = 2, workers: int = 2,
                 name: str = "pipeline"):
        if depth < 1:
            raise ValueError(f"AsyncPipeline: depth must be >= 1, "
                             f"got {depth} (0 means: don't build one)")
        self._src = iter(batches)
        self._convert = convert_fn
        self._place = place_fn
        self.depth = depth
        self.workers = max(1, min(int(workers), depth))
        self.name = name

        # worker threads adopt the CREATING thread's trace context
        # (thread-locals don't inherit), so reader/convert/place spans
        # land in the trace of the pass that consumes them
        self._trace_ctx = trace.current_context()

        self._src_lock = named_lock("pipeline.source")  # serializes next(_src)
        self._cond = named_condition("pipeline.queue")  # guards the state below
        self._ready: dict = {}              # index -> (feed, exc|None)
        self._seq = 0                       # next index to read from src
        self._next_out = 0                  # next index the consumer wants
        self._end_at: Optional[int] = None  # src exhausted/faulted here
        self._closed = False
        # at most `depth` batches between src and consumer: a worker
        # must hold a credit to pull a batch; the consumer returns it
        self._credits = threading.Semaphore(depth)

        self._depth_gauge = observe.gauge(
            "pipeline_queue_depth",
            "converted+placed batches ready in the async input "
            "pipeline's reorder queue")
        self._hits = observe.counter(
            "pipeline_prefetch_hits_total",
            "consumer asked for a batch and it was already prefetched")
        self._stalls = observe.counter(
            "pipeline_prefetch_stalls_total",
            "consumer asked for a batch and had to wait on the "
            "pipeline (input-bound step)")
        self._convert_hist = observe.histogram(
            "pipeline_worker_convert_seconds",
            "per-batch convert+device-place time on pipeline worker "
            "threads (overlapped with the running step)")

        self._threads = [
            threading.Thread(target=self._worker, daemon=True,
                             name=f"{IO_THREAD_PREFIX}{name}-w{i}")
            for i in range(self.workers)]
        for t in self._threads:
            t.start()

    # ------------------------------------------------------------ workers
    def _pull(self):
        """Pull (index, raw_batch) from the source, or None when the
        pipeline should wind down.  Serialized; also records source
        exhaustion/faults so peers stop pulling."""
        with self._src_lock:
            with self._cond:
                if self._closed or self._end_at is not None:
                    return None
                i = self._seq
            try:
                with trace.span("pipeline_read"):
                    raw = next(self._src)
            except StopIteration:
                with self._cond:
                    if self._end_at is None:
                        self._end_at = i
                    self._cond.notify_all()
                return None
            except BaseException as exc:  # reader fault: deliver at i
                with self._cond:
                    self._ready[i] = (None, exc)
                    self._end_at = i + 1
                    self._cond.notify_all()
                return None
            with self._cond:
                self._seq = i + 1
            return i, raw

    def _worker(self) -> None:
        with trace.context_scope(self._trace_ctx):
            self._worker_loop()

    def _worker_loop(self) -> None:
        while True:
            # a credit bounds in-flight batches; poll so close() is
            # never stuck behind a full queue
            if not self._credits.acquire(timeout=_POLL_S):
                with self._cond:
                    if self._closed:
                        return
                continue
            item = self._pull()
            if item is None:
                self._credits.release()
                return
            i, raw = item
            t0 = time.perf_counter()
            try:
                with trace.span("pipeline_convert", index=i):
                    feed = self._convert(raw) if self._convert else raw
                    if self._place is not None:
                        feed = self._place(feed)
                out = (feed, None)
            except BaseException as exc:  # convert fault: deliver at i
                out = (None, exc)
            self._convert_hist.observe(time.perf_counter() - t0)
            with self._cond:
                if self._closed:
                    return
                self._ready[i] = out
                self._depth_gauge.set(len(self._ready))
                self._cond.notify_all()

    # ----------------------------------------------------------- consumer
    def __iter__(self) -> Iterator[Any]:
        try:
            while True:
                try:
                    yield self.get()
                except StopIteration:
                    return
        finally:
            self.close()

    def get(self) -> Any:
        """Next feed in reader order; raises StopIteration at the end,
        re-raises reader/convert faults at their position."""
        with self._cond:
            i = self._next_out
            waited = False
            while i not in self._ready:
                if self._end_at is not None and i >= self._end_at:
                    raise StopIteration
                if self._closed:
                    raise PipelineClosed(
                        f"pipeline {self.name!r} is closed")
                waited = True
                self._cond.wait(_POLL_S)
            # hit/stall census only counts delivered batches (the
            # end-of-pass probe that raises StopIteration is not a stall)
            (self._stalls if waited else self._hits).inc()
            feed, exc = self._ready.pop(i)
            self._next_out = i + 1
            self._depth_gauge.set(len(self._ready))
        self._credits.release()
        if exc is not None:
            raise exc
        return feed

    # ----------------------------------------------------------- teardown
    def close(self) -> None:
        """Stop and join every worker, then close the source iterator
        (propagating GeneratorExit through reader generator chains so
        e.g. an in-flight master lease is FAILed).  Idempotent."""
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._ready.clear()   # buffered batches die with the pass
            self._depth_gauge.set(0)
            self._cond.notify_all()
        for t in self._threads:
            t.join(timeout=_JOIN_TIMEOUT_S)
            if t.is_alive():  # pragma: no cover — indicates a stuck src
                log.warning("pipeline %r worker %s did not stop within "
                            "%.0fs", self.name, t.name, _JOIN_TIMEOUT_S)
        close = getattr(self._src, "close", None)
        if close is not None:
            try:
                close()
            except Exception as e:  # noqa: BLE001 — teardown best-effort
                log.debug("pipeline %r source close failed during "
                          "teardown: %s: %s", self.name,
                          type(e).__name__, e)

    def __enter__(self) -> "AsyncPipeline":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def prefetch_reader(reader: Callable[[], Iterable[Any]],
                    convert_fn: Optional[Callable[[Any], Any]] = None,
                    place_fn: Optional[Callable[[Any], Any]] = None,
                    depth: int = 2, workers: int = 2,
                    name: str = "pipeline") -> Callable[[], Iterator[Any]]:
    """Wrap a reader (zero-arg callable returning an iterable) so each
    invocation runs through a fresh :class:`AsyncPipeline` — the reader
    -protocol face of the pipeline for code that composes readers rather
    than driving the trainer loop."""

    def prefetched() -> Iterator[Any]:
        # generator function: the pipeline (and its worker threads) is
        # only constructed when iteration actually starts, so a dropped
        # never-started invocation leaks nothing
        pipe = AsyncPipeline(reader(), convert_fn=convert_fn,
                             place_fn=place_fn, depth=depth,
                             workers=workers, name=name)
        try:
            yield from pipe
        finally:
            pipe.close()

    return prefetched

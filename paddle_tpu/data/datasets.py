"""Bundled datasets.

Port of ``python/paddle/v2/dataset`` (mnist, cifar, imdb, imikolov,
uci_housing, movielens, conll05, wmt14 — auto-downloading corpora cached
under ``~/.cache/paddle/dataset``).  This environment has **zero egress**, so
each dataset loads from the same cache layout if present and otherwise falls
back to a deterministic synthetic surrogate with identical shapes/vocab
sizes — keeping every demo/benchmark runnable and CI hermetic (the bundled
``mnist_bin_part``-style fixture trick, ``paddle/trainer/tests``).
"""

from __future__ import annotations

import gzip
import os
import pickle
import re
import string
import struct
import tarfile
from typing import Callable, Dict, Iterator, Optional, Tuple

import numpy as np

from .download import DownloadError, download
from ..utils import get_logger

log = get_logger("dataset")

# Official corpus URLs + md5s (python/paddle/v2/dataset/*.py constants)
MNIST_URL_PREFIX = "http://yann.lecun.com/exdb/mnist/"
MNIST_MD5 = {
    "train-images-idx3-ubyte.gz": "f68b3c2dcbeaaa9fbdd348bbdeb94873",
    "train-labels-idx1-ubyte.gz": "d53e105ee54ea40749a09fcbcd1e9432",
    "t10k-images-idx3-ubyte.gz": "9fb629c4189551a2d022fa330f9573f3",
    "t10k-labels-idx1-ubyte.gz": "ec29112dd5afa0611ce80d1b7f02629c",
}
CIFAR10_URL = "https://www.cs.toronto.edu/~kriz/cifar-10-python.tar.gz"
CIFAR10_MD5 = "c58f30108f718f92721af3b95e74349a"
IMDB_URL = "http://ai.stanford.edu/%7Eamaas/data/sentiment/aclImdb_v1.tar.gz"
IMDB_MD5 = "7c2ac02c03563afcf9b574c7e56c153a"
UCI_HOUSING_URL = ("https://archive.ics.uci.edu/ml/machine-learning-"
                   "databases/housing/housing.data")
UCI_HOUSING_MD5 = "d4accdce7a25600298819f8e28e8d593"
WMT14_TRAIN_URL = ("http://paddlepaddle.cdn.bcebos.com/demo/"
                   "wmt_shrinked_data/wmt14.tgz")
WMT14_TRAIN_MD5 = "0791583d57d5beb693b9414c5b36798c"


_download_failed: set = set()


def _try_download(url: str, module: str, md5: str) -> Optional[str]:
    """Cached-or-downloaded path, or None (loaders then fall back to
    their synthetic surrogate).  A failed URL is not retried within the
    process — readers re-run every pass."""
    if url in _download_failed:
        return None
    try:
        return download(url, module, md5)
    except DownloadError as e:
        log.warning("%s unavailable (%s); using synthetic surrogate",
                    module, e)
        _download_failed.add(url)
        return None

CACHE_ROOT = os.path.expanduser(
    os.environ.get("PADDLE_TPU_DATASET_CACHE", "~/.cache/paddle/dataset"))


def _cache_path(*parts: str) -> str:
    return os.path.join(CACHE_ROOT, *parts)


# --------------------------------------------------------------------- mnist

def _synthetic_images(n: int, side: int, classes: int, seed: int,
                      proto_seed: int = 1234):
    """Deterministic class-conditional blobs — learnable but non-trivial.
    Prototypes come from ``proto_seed`` (shared by train/test splits so the
    test set measures generalization); only the sample draw uses ``seed``."""
    protos = np.random.RandomState(proto_seed).randn(
        classes, side * side).astype(np.float32)
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, classes, n)
    noise = rng.randn(n, side * side).astype(np.float32) * 0.7
    imgs = np.clip(protos[labels] * 0.8 + noise, -1, 1)
    return imgs, labels.astype(np.int64)


def _read_idx_images(path: str) -> np.ndarray:
    with gzip.open(path, "rb") as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        data = np.frombuffer(f.read(), np.uint8).reshape(n, rows * cols)
        return data.astype(np.float32) / 127.5 - 1.0


def _read_idx_labels(path: str) -> np.ndarray:
    with gzip.open(path, "rb") as f:
        struct.unpack(">II", f.read(8))
        return np.frombuffer(f.read(), np.uint8).astype(np.int64)


def _mnist_paths(img_name, lab_name):
    img_p = _cache_path("mnist", img_name)
    lab_p = _cache_path("mnist", lab_name)
    if not (os.path.exists(img_p) and os.path.exists(lab_p)):
        for name in (img_name, lab_name):
            _try_download(MNIST_URL_PREFIX + name, "mnist", MNIST_MD5[name])
    return img_p, lab_p


def mnist_train(n_synth: int = 8192):
    """Reader of (image[784] in [-1,1], label) — ``v2/dataset/mnist.py``."""
    img_p, lab_p = _mnist_paths("train-images-idx3-ubyte.gz",
                                "train-labels-idx1-ubyte.gz")

    def reader():
        if os.path.exists(img_p) and os.path.exists(lab_p):
            imgs, labs = _read_idx_images(img_p), _read_idx_labels(lab_p)
        else:
            imgs, labs = _synthetic_images(n_synth, 28, 10, seed=7)
        for i in range(len(labs)):
            yield imgs[i], int(labs[i])

    return reader


def mnist_test(n_synth: int = 1024):
    img_p, lab_p = _mnist_paths("t10k-images-idx3-ubyte.gz",
                                "t10k-labels-idx1-ubyte.gz")

    def reader():
        if os.path.exists(img_p) and os.path.exists(lab_p):
            imgs, labs = _read_idx_images(img_p), _read_idx_labels(lab_p)
        else:
            imgs, labs = _synthetic_images(n_synth, 28, 10, seed=8)
        for i in range(len(labs)):
            yield imgs[i], int(labs[i])

    return reader




# ----------------------------------------------------- real-corpus parsers
# Each takes LOCAL file paths (unit-tested on bundled tiny fixtures); the
# public loaders below wire them to the download cache with synthetic
# fallback.  Formats match the reference parsers exactly
# (``python/paddle/v2/dataset/{cifar,imdb,uci_housing,wmt14}.py``).

def parse_cifar(tar_path: str, sub_name: str
                ) -> Iterator[Tuple[np.ndarray, int]]:
    """Yield (image[3072] float in [0,1] CHW, label) from a CIFAR python
    tarball (pickled batches; ``cifar.py:46`` reads b'data' +
    b'labels'/b'fine_labels')."""
    with tarfile.open(tar_path, mode="r") as f:
        names = sorted(m.name for m in f if sub_name in m.name)
        for name in names:
            batch = pickle.load(f.extractfile(name), encoding="bytes")
            data = batch[b"data"]
            labels = batch.get(b"labels", batch.get(b"fine_labels"))
            assert labels is not None
            for sample, label in zip(data, labels):
                yield (sample / 255.0).astype(np.float32), int(label)


def imdb_tokenize(tar_path: str, pattern: "re.Pattern"
                  ) -> Iterator[list]:
    """Tokenized docs from the aclImdb tarball (``imdb.py:38``:
    punctuation stripped, lowercased, whitespace split; sequential
    tarfile.next() access)."""
    table = str.maketrans("", "", string.punctuation)
    with tarfile.open(tar_path) as tarf:
        tf = tarf.next()
        while tf is not None:
            if bool(pattern.match(tf.name)):
                text = tarf.extractfile(tf).read().decode(
                    "utf-8", errors="ignore")
                yield text.rstrip("\n\r").translate(table).lower().split()
            tf = tarf.next()


def imdb_build_dict(tar_path: str, pattern_str: str, cutoff: int = 150
                    ) -> Dict[str, int]:
    """Frequency-sorted word dict with trailing <unk> (``imdb.py:62``)."""
    import collections
    word_freq: Dict[str, int] = collections.defaultdict(int)
    for doc in imdb_tokenize(tar_path, re.compile(pattern_str)):
        for word in doc:
            word_freq[word] += 1
    items = [x for x in word_freq.items() if x[1] > cutoff]
    items.sort(key=lambda x: (-x[1], x[0]))
    word_idx = {w: i for i, (w, _) in enumerate(items)}
    word_idx["<unk>"] = len(word_idx)
    return word_idx


def parse_imdb(tar_path: str, pos_pattern: str, neg_pattern: str,
               word_idx: Dict[str, int]
               ) -> Iterator[Tuple[list, int]]:
    """Yield (word_ids, label) pairs, label 0=positive 1=negative as the
    reference encodes them (``imdb.py:91``: pos first, label 0)."""
    unk = word_idx["<unk>"]
    for label, pat in ((0, pos_pattern), (1, neg_pattern)):
        for doc in imdb_tokenize(tar_path, re.compile(pat)):
            yield [word_idx.get(w, unk) for w in doc], label


def parse_uci_housing(path: str, feature_num: int = 14, ratio: float = 0.8
                      ) -> Tuple[np.ndarray, np.ndarray]:
    """(train, test) arrays, features mean-centered and range-scaled
    (``uci_housing.py:57`` load_data, 80/20 split)."""
    data = np.fromfile(path, sep=" ")
    data = data.reshape(data.shape[0] // feature_num, feature_num)
    maximums = data.max(axis=0)
    minimums = data.min(axis=0)
    avgs = data.sum(axis=0) / data.shape[0]
    for i in range(feature_num - 1):
        data[:, i] = (data[:, i] - avgs[i]) / (maximums[i] - minimums[i])
    offset = int(data.shape[0] * ratio)
    return data[:offset], data[offset:]


def wmt14_read_dicts(tar_path: str, dict_size: int
                     ) -> Tuple[Dict[str, int], Dict[str, int]]:
    """(src_dict, trg_dict) from the wmt14 tarball's src.dict/trg.dict
    members (``wmt14.py:45`` __read_to_dict__)."""
    def to_dict(fd, size):
        out = {}
        for i, line in enumerate(fd):
            if i >= size:
                break
            out[line.strip().decode("utf-8", errors="ignore")] = i
        return out

    with tarfile.open(tar_path, mode="r") as f:
        src_name = [m.name for m in f if m.name.endswith("src.dict")]
        trg_name = [m.name for m in f if m.name.endswith("trg.dict")]
        assert len(src_name) == 1 and len(trg_name) == 1
        src = to_dict(f.extractfile(src_name[0]), dict_size)
        trg = to_dict(f.extractfile(trg_name[0]), dict_size)
    return src, trg


def parse_wmt14(tar_path: str, file_name: str, dict_size: int,
                max_len: int = 80) -> Iterator[Tuple[list, list, list]]:
    """Yield (src_ids, trg_ids_with_<s>, trg_next_ids) triples
    (``wmt14.py:72`` reader_creator: <s>/<e> wrapping, UNK id 2,
    sentences over ``max_len`` dropped)."""
    src_dict, trg_dict = wmt14_read_dicts(tar_path, dict_size)
    start_tok, end_tok = "<s>", "<e>"
    with tarfile.open(tar_path, mode="r") as f:
        names = [m.name for m in f if m.name.endswith(file_name)]
        for name in names:
            for line in f.extractfile(name):
                parts = line.decode("utf-8", errors="ignore").strip() \
                    .split("\t")
                if len(parts) != 2:
                    continue
                src_words = [start_tok] + parts[0].split() + [end_tok]
                src_ids = [src_dict.get(w, UNK) for w in src_words]
                trg_words = parts[1].split()
                trg_ids = [trg_dict.get(w, UNK) for w in trg_words]
                if len(src_ids) > max_len or len(trg_ids) > max_len:
                    continue
                trg_next = trg_ids + [trg_dict[end_tok]]
                trg_ids = [trg_dict[start_tok]] + trg_ids
                yield src_ids, trg_ids, trg_next


# --------------------------------------------------------------------- cifar

def _cifar_reader(sub_name: str, n_synth: int, seed: int):
    # resolved ONCE (download() md5-hashes the tarball; per-epoch would
    # re-hash 163MB every pass)
    tar = _try_download(CIFAR10_URL, "cifar", CIFAR10_MD5)

    def reader():
        if tar:
            yield from parse_cifar(tar, sub_name)
            return
        imgs, labs = _synthetic_images(n_synth, 32, 10, seed=seed)
        imgs3 = np.repeat(imgs, 3, axis=1)[:, : 3 * 32 * 32]
        for i in range(len(labs)):
            yield imgs3[i], int(labs[i])

    return reader


def cifar10_train(n_synth: int = 4096):
    """Reader of (image[3072] CHW float, label) — ``v2/dataset/cifar.py``."""
    return _cifar_reader("data_batch", n_synth, seed=9)


def cifar10_test(n_synth: int = 512):
    return _cifar_reader("test_batch", n_synth, seed=10)


# ---------------------------------------------------------------------- imdb

def _synthetic_text(n: int, vocab: int, classes: int, min_len: int,
                    max_len: int, seed: int, proto_seed: int = 4321):
    """Class-dependent unigram distributions; label recoverable from text.
    Boost vocabularies come from ``proto_seed`` (shared across splits)."""
    prng = np.random.RandomState(proto_seed)
    class_boost = [prng.permutation(vocab)[: vocab // 4]
                   for _ in range(classes)]
    rng = np.random.RandomState(seed)
    for _ in range(n):
        y = int(rng.randint(classes))
        length = int(rng.randint(min_len, max_len + 1))
        base = rng.randint(2, vocab, length)
        boost_mask = rng.rand(length) < 0.5
        boosted = class_boost[y][rng.randint(0, len(class_boost[y]), length)]
        words = np.where(boost_mask, boosted, base)
        yield words.astype(np.int64), y


def imdb_word_dict(vocab: int = 5148):
    """Real corpus dict when available (``imdb.py`` build_dict over the
    train split, cutoff 150), else a synthetic stand-in."""
    tar = _try_download(IMDB_URL, "imdb", IMDB_MD5)
    if tar:
        return imdb_build_dict(
            tar, "aclImdb/((train)|(test))/((pos)|(neg))/.*\\.txt$", 150)
    return {f"w{i}": i for i in range(vocab)}


def _imdb_reader(split: str, word_dict, n_synth: int, seed: int):
    vocab = len(word_dict) if word_dict else 5148
    tar = _try_download(IMDB_URL, "imdb", IMDB_MD5)

    def reader():
        if tar and word_dict and "<unk>" in word_dict:
            yield from parse_imdb(
                tar, f"aclImdb/{split}/pos/.*\\.txt$",
                f"aclImdb/{split}/neg/.*\\.txt$", word_dict)
            return
        yield from _synthetic_text(n_synth, vocab, 2, 10, 120, seed=seed)

    return reader


def imdb_train(word_dict=None, n_synth: int = 2000):
    return _imdb_reader("train", word_dict, n_synth, seed=11)


def imdb_test(word_dict=None, n_synth: int = 400):
    return _imdb_reader("test", word_dict, n_synth, seed=12)


# ------------------------------------------------------------------ imikolov

def imikolov_train(word_dict=None, n: int = 5, n_synth: int = 5000):
    """n-gram LM samples (``v2/dataset/imikolov.py``)."""
    vocab = len(word_dict) if word_dict else 2000

    def reader():
        rng = np.random.RandomState(13)
        for _ in range(n_synth):
            yield tuple(int(x) for x in rng.randint(0, vocab, n))

    return reader


# --------------------------------------------------------------- uci_housing

def _uci_housing_reader(test: bool, n_synth: int, seed: int):
    path = _try_download(UCI_HOUSING_URL, "uci_housing", UCI_HOUSING_MD5)

    def reader():
        if path:
            train, tst = parse_uci_housing(path)
            for row in (tst if test else train):
                yield (row[:-1].astype(np.float32),
                       row[-1:].astype(np.float32))
            return
        rng = np.random.RandomState(seed + 100)
        w = np.random.RandomState(14).randn(13).astype(np.float32)
        for _ in range(n_synth):
            x = rng.randn(13).astype(np.float32)
            y = float(x @ w + 0.1 * rng.randn())
            yield x, np.array([y], np.float32)

    return reader


def uci_housing_train(n_synth: int = 404):
    return _uci_housing_reader(False, n_synth, seed=14)


def uci_housing_test(n_synth: int = 102):
    return _uci_housing_reader(True, n_synth, seed=15)


# --------------------------------------------------------------------- wmt14

def wmt14_dicts(dict_size: int = 30000):
    tar = _try_download(WMT14_TRAIN_URL, "wmt14", WMT14_TRAIN_MD5)
    if tar:
        return wmt14_read_dicts(tar, dict_size)
    src = {f"s{i}": i for i in range(dict_size)}
    trg = {f"t{i}": i for i in range(dict_size)}
    return src, trg


START, END, UNK = 0, 1, 2


def wmt14_train(dict_size: int = 30000, n_synth: int = 2000):
    """Reader of (src_ids, trg_ids_with_<s>, trg_next_ids) triples
    (``v2/dataset/wmt14.py`` convention)."""

    tar = _try_download(WMT14_TRAIN_URL, "wmt14", WMT14_TRAIN_MD5)

    def reader():
        if tar:
            yield from parse_wmt14(tar, "train/train", dict_size)
            return
        rng = np.random.RandomState(16)
        for _ in range(n_synth):
            slen = int(rng.randint(5, 30))
            src = rng.randint(3, dict_size, slen).astype(np.int64)
            # synthetic transduction: reverse + offset, bounded vocab
            trg = ((src[::-1] * 7) % (dict_size - 3) + 3)[: max(3, slen - 2)]
            trg_in = np.concatenate([[START], trg])
            trg_next = np.concatenate([trg, [END]])
            yield src, trg_in, trg_next

    return reader


def wmt14_test(dict_size: int = 30000, n_synth: int = 200):
    tar = _try_download(WMT14_TRAIN_URL, "wmt14", WMT14_TRAIN_MD5)

    def reader():
        if tar:
            yield from parse_wmt14(tar, "test/test", dict_size)
            return
        rng = np.random.RandomState(17)
        for _ in range(n_synth):
            slen = int(rng.randint(5, 30))
            src = rng.randint(3, dict_size, slen).astype(np.int64)
            trg = ((src[::-1] * 7) % (dict_size - 3) + 3)[: max(3, slen - 2)]
            yield src, np.concatenate([[START], trg]), np.concatenate([trg, [END]])

    return reader


# ------------------------------------------------------------------- conll05

def conll05_train(n_synth: int = 1000, vocab: int = 5000, num_labels: int = 19):
    """SRL sequence-tagging samples: (words, predicate, labels)."""

    def reader():
        rng = np.random.RandomState(18)
        for _ in range(n_synth):
            length = int(rng.randint(5, 40))
            words = rng.randint(0, vocab, length).astype(np.int64)
            pred = int(rng.randint(0, length))
            labels = ((words + pred) % num_labels).astype(np.int64)
            yield words, pred, labels

    return reader


# -------------------------------------------------------------------- criteo

def criteo_ctr_train(n_synth: int = 5000, dense_dim: int = 13,
                     sparse_dim: int = 10 ** 6, slots: int = 26):
    """Wide&deep CTR samples: (dense[13], sparse_ids[26], label) —
    the sparse large-model workload (BASELINE config 5)."""

    def reader():
        rng = np.random.RandomState(19)
        w_dense = rng.randn(dense_dim).astype(np.float32)
        for _ in range(n_synth):
            dense = rng.randn(dense_dim).astype(np.float32)
            ids = rng.randint(0, sparse_dim, slots).astype(np.int64)
            logit = dense @ w_dense + 0.3 * ((ids[0] % 97) / 48.5 - 1.0)
            yield dense, ids, int(logit + 0.2 * rng.randn() > 0)

    return reader

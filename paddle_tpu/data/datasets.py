"""Bundled datasets.

Port of ``python/paddle/v2/dataset`` (mnist, cifar, imdb, imikolov,
uci_housing, movielens, conll05, wmt14 — auto-downloading corpora cached
under ``~/.cache/paddle/dataset``).  This environment has **zero egress**, so
each dataset loads from the same cache layout if present and otherwise falls
back to a deterministic synthetic surrogate with identical shapes/vocab
sizes — keeping every demo/benchmark runnable and CI hermetic (the bundled
``mnist_bin_part``-style fixture trick, ``paddle/trainer/tests``).
"""

from __future__ import annotations

import gzip
import os
import pickle
import re
import string
import struct
import tarfile
from typing import Callable, Dict, Iterator, Optional, Tuple

import numpy as np

from .download import DownloadError, download
from ..utils import get_logger

log = get_logger("dataset")

# Official corpus URLs + md5s (python/paddle/v2/dataset/*.py constants)
MNIST_URL_PREFIX = "http://yann.lecun.com/exdb/mnist/"
MNIST_MD5 = {
    "train-images-idx3-ubyte.gz": "f68b3c2dcbeaaa9fbdd348bbdeb94873",
    "train-labels-idx1-ubyte.gz": "d53e105ee54ea40749a09fcbcd1e9432",
    "t10k-images-idx3-ubyte.gz": "9fb629c4189551a2d022fa330f9573f3",
    "t10k-labels-idx1-ubyte.gz": "ec29112dd5afa0611ce80d1b7f02629c",
}
CIFAR10_URL = "https://www.cs.toronto.edu/~kriz/cifar-10-python.tar.gz"
CIFAR10_MD5 = "c58f30108f718f92721af3b95e74349a"
IMDB_URL = "http://ai.stanford.edu/%7Eamaas/data/sentiment/aclImdb_v1.tar.gz"
IMDB_MD5 = "7c2ac02c03563afcf9b574c7e56c153a"
UCI_HOUSING_URL = ("https://archive.ics.uci.edu/ml/machine-learning-"
                   "databases/housing/housing.data")
UCI_HOUSING_MD5 = "d4accdce7a25600298819f8e28e8d593"
WMT14_TRAIN_URL = ("http://paddlepaddle.cdn.bcebos.com/demo/"
                   "wmt_shrinked_data/wmt14.tgz")
WMT14_TRAIN_MD5 = "0791583d57d5beb693b9414c5b36798c"


_download_failed: set = set()


def _try_download(url: str, module: str, md5: str) -> Optional[str]:
    """Cached-or-downloaded path, or None (loaders then fall back to
    their synthetic surrogate).  A failed URL is not retried within the
    process — readers re-run every pass."""
    if url in _download_failed:
        return None
    try:
        return download(url, module, md5)
    except DownloadError as e:
        log.warning("%s unavailable (%s); using synthetic surrogate",
                    module, e)
        _download_failed.add(url)
        return None

CACHE_ROOT = os.path.expanduser(
    os.environ.get("PADDLE_TPU_DATASET_CACHE", "~/.cache/paddle/dataset"))


def _cache_path(*parts: str) -> str:
    return os.path.join(CACHE_ROOT, *parts)


# --------------------------------------------------------------------- mnist

def _synthetic_images(n: int, side: int, classes: int, seed: int,
                      proto_seed: int = 1234):
    """Deterministic class-conditional blobs — learnable but non-trivial.
    Prototypes come from ``proto_seed`` (shared by train/test splits so the
    test set measures generalization); only the sample draw uses ``seed``."""
    protos = np.random.RandomState(proto_seed).randn(
        classes, side * side).astype(np.float32)
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, classes, n)
    noise = rng.randn(n, side * side).astype(np.float32) * 0.7
    imgs = np.clip(protos[labels] * 0.8 + noise, -1, 1)
    return imgs, labels.astype(np.int64)


def _read_idx_images(path: str) -> np.ndarray:
    with gzip.open(path, "rb") as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        data = np.frombuffer(f.read(), np.uint8).reshape(n, rows * cols)
        return data.astype(np.float32) / 127.5 - 1.0


def _read_idx_labels(path: str) -> np.ndarray:
    with gzip.open(path, "rb") as f:
        struct.unpack(">II", f.read(8))
        return np.frombuffer(f.read(), np.uint8).astype(np.int64)


def _mnist_paths(img_name, lab_name):
    img_p = _cache_path("mnist", img_name)
    lab_p = _cache_path("mnist", lab_name)
    if not (os.path.exists(img_p) and os.path.exists(lab_p)):
        for name in (img_name, lab_name):
            _try_download(MNIST_URL_PREFIX + name, "mnist", MNIST_MD5[name])
    return img_p, lab_p


def mnist_train(n_synth: int = 8192):
    """Reader of (image[784] in [-1,1], label) — ``v2/dataset/mnist.py``."""
    img_p, lab_p = _mnist_paths("train-images-idx3-ubyte.gz",
                                "train-labels-idx1-ubyte.gz")

    def reader():
        if os.path.exists(img_p) and os.path.exists(lab_p):
            imgs, labs = _read_idx_images(img_p), _read_idx_labels(lab_p)
        else:
            imgs, labs = _synthetic_images(n_synth, 28, 10, seed=7)
        for i in range(len(labs)):
            yield imgs[i], int(labs[i])

    return reader


def mnist_test(n_synth: int = 1024):
    img_p, lab_p = _mnist_paths("t10k-images-idx3-ubyte.gz",
                                "t10k-labels-idx1-ubyte.gz")

    def reader():
        if os.path.exists(img_p) and os.path.exists(lab_p):
            imgs, labs = _read_idx_images(img_p), _read_idx_labels(lab_p)
        else:
            imgs, labs = _synthetic_images(n_synth, 28, 10, seed=8)
        for i in range(len(labs)):
            yield imgs[i], int(labs[i])

    return reader




# ----------------------------------------------------- real-corpus parsers
# Each takes LOCAL file paths (unit-tested on bundled tiny fixtures); the
# public loaders below wire them to the download cache with synthetic
# fallback.  Formats match the reference parsers exactly
# (``python/paddle/v2/dataset/{cifar,imdb,uci_housing,wmt14}.py``).

def parse_cifar(tar_path: str, sub_name: str
                ) -> Iterator[Tuple[np.ndarray, int]]:
    """Yield (image[3072] float in [0,1] CHW, label) from a CIFAR python
    tarball (pickled batches; ``cifar.py:46`` reads b'data' +
    b'labels'/b'fine_labels')."""
    with tarfile.open(tar_path, mode="r") as f:
        names = sorted(m.name for m in f if sub_name in m.name)
        for name in names:
            batch = pickle.load(f.extractfile(name), encoding="bytes")
            data = batch[b"data"]
            labels = batch.get(b"labels", batch.get(b"fine_labels"))
            assert labels is not None
            for sample, label in zip(data, labels):
                yield (sample / 255.0).astype(np.float32), int(label)


def imdb_tokenize(tar_path: str, pattern: "re.Pattern"
                  ) -> Iterator[list]:
    """Tokenized docs from the aclImdb tarball (``imdb.py:38``:
    punctuation stripped, lowercased, whitespace split; sequential
    tarfile.next() access)."""
    table = str.maketrans("", "", string.punctuation)
    with tarfile.open(tar_path) as tarf:
        tf = tarf.next()
        while tf is not None:
            if bool(pattern.match(tf.name)):
                text = tarf.extractfile(tf).read().decode(
                    "utf-8", errors="ignore")
                yield text.rstrip("\n\r").translate(table).lower().split()
            tf = tarf.next()


def imdb_build_dict(tar_path: str, pattern_str: str, cutoff: int = 150
                    ) -> Dict[str, int]:
    """Frequency-sorted word dict with trailing <unk> (``imdb.py:62``)."""
    import collections
    word_freq: Dict[str, int] = collections.defaultdict(int)
    for doc in imdb_tokenize(tar_path, re.compile(pattern_str)):
        for word in doc:
            word_freq[word] += 1
    items = [x for x in word_freq.items() if x[1] > cutoff]
    items.sort(key=lambda x: (-x[1], x[0]))
    word_idx = {w: i for i, (w, _) in enumerate(items)}
    word_idx["<unk>"] = len(word_idx)
    return word_idx


def parse_imdb(tar_path: str, pos_pattern: str, neg_pattern: str,
               word_idx: Dict[str, int]
               ) -> Iterator[Tuple[list, int]]:
    """Yield (word_ids, label) pairs, label 0=positive 1=negative as the
    reference encodes them (``imdb.py:91``: pos first, label 0)."""
    unk = word_idx["<unk>"]
    for label, pat in ((0, pos_pattern), (1, neg_pattern)):
        for doc in imdb_tokenize(tar_path, re.compile(pat)):
            yield [word_idx.get(w, unk) for w in doc], label


def parse_uci_housing(path: str, feature_num: int = 14, ratio: float = 0.8
                      ) -> Tuple[np.ndarray, np.ndarray]:
    """(train, test) arrays, features mean-centered and range-scaled
    (``uci_housing.py:57`` load_data, 80/20 split)."""
    data = np.fromfile(path, sep=" ")
    data = data.reshape(data.shape[0] // feature_num, feature_num)
    maximums = data.max(axis=0)
    minimums = data.min(axis=0)
    avgs = data.sum(axis=0) / data.shape[0]
    for i in range(feature_num - 1):
        data[:, i] = (data[:, i] - avgs[i]) / (maximums[i] - minimums[i])
    offset = int(data.shape[0] * ratio)
    return data[:offset], data[offset:]


def wmt14_read_dicts(tar_path: str, dict_size: int
                     ) -> Tuple[Dict[str, int], Dict[str, int]]:
    """(src_dict, trg_dict) from the wmt14 tarball's src.dict/trg.dict
    members (``wmt14.py:45`` __read_to_dict__)."""
    def to_dict(fd, size):
        out = {}
        for i, line in enumerate(fd):
            if i >= size:
                break
            out[line.strip().decode("utf-8", errors="ignore")] = i
        return out

    with tarfile.open(tar_path, mode="r") as f:
        src_name = [m.name for m in f if m.name.endswith("src.dict")]
        trg_name = [m.name for m in f if m.name.endswith("trg.dict")]
        assert len(src_name) == 1 and len(trg_name) == 1
        src = to_dict(f.extractfile(src_name[0]), dict_size)
        trg = to_dict(f.extractfile(trg_name[0]), dict_size)
    return src, trg


def parse_wmt14(tar_path: str, file_name: str, dict_size: int,
                max_len: int = 80) -> Iterator[Tuple[list, list, list]]:
    """Yield (src_ids, trg_ids_with_<s>, trg_next_ids) triples
    (``wmt14.py:72`` reader_creator: <s>/<e> wrapping, UNK id 2,
    sentences over ``max_len`` dropped)."""
    src_dict, trg_dict = wmt14_read_dicts(tar_path, dict_size)
    start_tok, end_tok = "<s>", "<e>"
    with tarfile.open(tar_path, mode="r") as f:
        names = [m.name for m in f if m.name.endswith(file_name)]
        for name in names:
            for line in f.extractfile(name):
                parts = line.decode("utf-8", errors="ignore").strip() \
                    .split("\t")
                if len(parts) != 2:
                    continue
                src_words = [start_tok] + parts[0].split() + [end_tok]
                src_ids = [src_dict.get(w, UNK) for w in src_words]
                trg_words = parts[1].split()
                trg_ids = [trg_dict.get(w, UNK) for w in trg_words]
                if len(src_ids) > max_len or len(trg_ids) > max_len:
                    continue
                trg_next = trg_ids + [trg_dict[end_tok]]
                trg_ids = [trg_dict[start_tok]] + trg_ids
                yield src_ids, trg_ids, trg_next


# --------------------------------------------------------------------- cifar

def _cifar_reader(sub_name: str, n_synth: int, seed: int):
    # resolved ONCE (download() md5-hashes the tarball; per-epoch would
    # re-hash 163MB every pass)
    tar = _try_download(CIFAR10_URL, "cifar", CIFAR10_MD5)

    def reader():
        if tar:
            yield from parse_cifar(tar, sub_name)
            return
        imgs, labs = _synthetic_images(n_synth, 32, 10, seed=seed)
        imgs3 = np.repeat(imgs, 3, axis=1)[:, : 3 * 32 * 32]
        for i in range(len(labs)):
            yield imgs3[i], int(labs[i])

    return reader


def cifar10_train(n_synth: int = 4096):
    """Reader of (image[3072] CHW float, label) — ``v2/dataset/cifar.py``."""
    return _cifar_reader("data_batch", n_synth, seed=9)


def cifar10_test(n_synth: int = 512):
    return _cifar_reader("test_batch", n_synth, seed=10)


# ---------------------------------------------------------------------- imdb

def _synthetic_text(n: int, vocab: int, classes: int, min_len: int,
                    max_len: int, seed: int, proto_seed: int = 4321):
    """Class-dependent unigram distributions; label recoverable from text.
    Boost vocabularies come from ``proto_seed`` (shared across splits)."""
    prng = np.random.RandomState(proto_seed)
    class_boost = [prng.permutation(vocab)[: vocab // 4]
                   for _ in range(classes)]
    rng = np.random.RandomState(seed)
    for _ in range(n):
        y = int(rng.randint(classes))
        length = int(rng.randint(min_len, max_len + 1))
        base = rng.randint(2, vocab, length)
        boost_mask = rng.rand(length) < 0.5
        boosted = class_boost[y][rng.randint(0, len(class_boost[y]), length)]
        words = np.where(boost_mask, boosted, base)
        yield words.astype(np.int64), y


def imdb_word_dict(vocab: int = 5148):
    """Real corpus dict when available (``imdb.py`` build_dict over the
    train split, cutoff 150), else a synthetic stand-in."""
    tar = _try_download(IMDB_URL, "imdb", IMDB_MD5)
    if tar:
        return imdb_build_dict(
            tar, "aclImdb/((train)|(test))/((pos)|(neg))/.*\\.txt$", 150)
    return {f"w{i}": i for i in range(vocab)}


def _imdb_reader(split: str, word_dict, n_synth: int, seed: int):
    vocab = len(word_dict) if word_dict else 5148
    tar = _try_download(IMDB_URL, "imdb", IMDB_MD5)

    def reader():
        if tar and word_dict and "<unk>" in word_dict:
            yield from parse_imdb(
                tar, f"aclImdb/{split}/pos/.*\\.txt$",
                f"aclImdb/{split}/neg/.*\\.txt$", word_dict)
            return
        yield from _synthetic_text(n_synth, vocab, 2, 10, 120, seed=seed)

    return reader


def imdb_train(word_dict=None, n_synth: int = 2000):
    return _imdb_reader("train", word_dict, n_synth, seed=11)


def imdb_test(word_dict=None, n_synth: int = 400):
    return _imdb_reader("test", word_dict, n_synth, seed=12)


# ------------------------------------------------------------------ imikolov

def imikolov_train(word_dict=None, n: int = 5, n_synth: int = 5000):
    """n-gram LM samples (``v2/dataset/imikolov.py``)."""
    vocab = len(word_dict) if word_dict else 2000

    def reader():
        rng = np.random.RandomState(13)
        for _ in range(n_synth):
            yield tuple(int(x) for x in rng.randint(0, vocab, n))

    return reader


# --------------------------------------------------------------- uci_housing

def _uci_housing_reader(test: bool, n_synth: int, seed: int):
    path = _try_download(UCI_HOUSING_URL, "uci_housing", UCI_HOUSING_MD5)

    def reader():
        if path:
            train, tst = parse_uci_housing(path)
            for row in (tst if test else train):
                yield (row[:-1].astype(np.float32),
                       row[-1:].astype(np.float32))
            return
        rng = np.random.RandomState(seed + 100)
        w = np.random.RandomState(14).randn(13).astype(np.float32)
        for _ in range(n_synth):
            x = rng.randn(13).astype(np.float32)
            y = float(x @ w + 0.1 * rng.randn())
            yield x, np.array([y], np.float32)

    return reader


def uci_housing_train(n_synth: int = 404):
    return _uci_housing_reader(False, n_synth, seed=14)


def uci_housing_test(n_synth: int = 102):
    return _uci_housing_reader(True, n_synth, seed=15)


# --------------------------------------------------------------------- wmt14

def wmt14_dicts(dict_size: int = 30000):
    tar = _try_download(WMT14_TRAIN_URL, "wmt14", WMT14_TRAIN_MD5)
    if tar:
        return wmt14_read_dicts(tar, dict_size)
    src = {f"s{i}": i for i in range(dict_size)}
    trg = {f"t{i}": i for i in range(dict_size)}
    return src, trg


START, END, UNK = 0, 1, 2


def wmt14_train(dict_size: int = 30000, n_synth: int = 2000):
    """Reader of (src_ids, trg_ids_with_<s>, trg_next_ids) triples
    (``v2/dataset/wmt14.py`` convention)."""

    tar = _try_download(WMT14_TRAIN_URL, "wmt14", WMT14_TRAIN_MD5)

    def reader():
        if tar:
            yield from parse_wmt14(tar, "train/train", dict_size)
            return
        rng = np.random.RandomState(16)
        for _ in range(n_synth):
            slen = int(rng.randint(5, 30))
            src = rng.randint(3, dict_size, slen).astype(np.int64)
            # synthetic transduction: reverse + offset, bounded vocab
            trg = ((src[::-1] * 7) % (dict_size - 3) + 3)[: max(3, slen - 2)]
            trg_in = np.concatenate([[START], trg])
            trg_next = np.concatenate([trg, [END]])
            yield src, trg_in, trg_next

    return reader


def wmt14_test(dict_size: int = 30000, n_synth: int = 200):
    tar = _try_download(WMT14_TRAIN_URL, "wmt14", WMT14_TRAIN_MD5)

    def reader():
        if tar:
            yield from parse_wmt14(tar, "test/test", dict_size)
            return
        rng = np.random.RandomState(17)
        for _ in range(n_synth):
            slen = int(rng.randint(5, 30))
            src = rng.randint(3, dict_size, slen).astype(np.int64)
            trg = ((src[::-1] * 7) % (dict_size - 3) + 3)[: max(3, slen - 2)]
            yield src, np.concatenate([[START], trg]), np.concatenate([trg, [END]])

    return reader


# ------------------------------------------------------------------- conll05

def conll05_train(n_synth: int = 1000, vocab: int = 5000, num_labels: int = 19):
    """SRL sequence-tagging samples: (words, predicate, labels)."""

    def reader():
        rng = np.random.RandomState(18)
        for _ in range(n_synth):
            length = int(rng.randint(5, 40))
            words = rng.randint(0, vocab, length).astype(np.int64)
            pred = int(rng.randint(0, length))
            labels = ((words + pred) % num_labels).astype(np.int64)
            yield words, pred, labels

    return reader


# -------------------------------------------------------------------- criteo

def criteo_ctr_train(n_synth: int = 5000, dense_dim: int = 13,
                     sparse_dim: int = 10 ** 6, slots: int = 26):
    """Wide&deep CTR samples: (dense[13], sparse_ids[26], label) —
    the sparse large-model workload (BASELINE config 5)."""

    def reader():
        rng = np.random.RandomState(19)
        w_dense = rng.randn(dense_dim).astype(np.float32)
        for _ in range(n_synth):
            dense = rng.randn(dense_dim).astype(np.float32)
            ids = rng.randint(0, sparse_dim, slots).astype(np.int64)
            logit = dense @ w_dense + 0.3 * ((ids[0] % 97) / 48.5 - 1.0)
            yield dense, ids, int(logit + 0.2 * rng.randn() > 0)

    return reader


# ----------------------------------------------------------------- movielens

MOVIELENS_URL = "http://files.grouplens.org/datasets/movielens/ml-1m.zip"
MOVIELENS_MD5 = "c4d9eecfca2ab87c1945afe126590906"

AGE_TABLE = [1, 18, 25, 35, 45, 50, 56]     # movielens.py:41 age buckets


def parse_movielens_meta(zip_path: str):
    """Parse ``ml-1m/{movies,users}.dat`` from the MovieLens-1M zip
    (reference ``movielens.py:102`` ``__initialize_meta_info__``).

    Returns ``(movies, users, title_dict, categories_dict)`` where
    ``movies[id] = (category_ids, title_word_ids)`` and
    ``users[id] = [uid, gender(0=M,1=F), age_index, job_id]``.
    """
    import zipfile

    title_pattern = re.compile(r"^(.*)\((\d+)\)\s*$")
    raw_movies: Dict[int, Tuple[list, str]] = {}
    title_words: set = set()
    categories: set = set()
    with zipfile.ZipFile(zip_path) as z:
        with z.open("ml-1m/movies.dat") as f:
            for line in f:
                movie_id, title, cats = \
                    line.decode("latin-1").strip().split("::")
                cats = cats.split("|")
                categories.update(cats)
                m = title_pattern.match(title)
                title = m.group(1).strip() if m else title
                raw_movies[int(movie_id)] = (cats, title)
                title_words.update(w.lower() for w in title.split())
        users: Dict[int, list] = {}
        with z.open("ml-1m/users.dat") as f:
            for line in f:
                uid, gender, age, job, _zip = \
                    line.decode("latin-1").strip().split("::")
                users[int(uid)] = [int(uid), 0 if gender == "M" else 1,
                                   AGE_TABLE.index(int(age)), int(job)]
    title_dict = {w: i for i, w in enumerate(sorted(title_words))}
    categories_dict = {c: i for i, c in enumerate(sorted(categories))}
    movies = {mid: ([categories_dict[c] for c in cats],
                    [title_dict[w.lower()] for w in title.split()])
              for mid, (cats, title) in raw_movies.items()}
    return movies, users, title_dict, categories_dict


def parse_movielens_ratings(zip_path: str, movies, users, is_test: bool,
                            test_ratio: float = 0.1, rand_seed: int = 0):
    """Yield reference-format rating records from ``ml-1m/ratings.dat``:
    ``[uid, gender, age_idx, job, movie_id, category_ids, title_ids,
    [rating*2-5]]`` with the same random train/test split
    (``movielens.py:145``)."""
    import random
    import zipfile

    rand = random.Random(x=rand_seed)
    with zipfile.ZipFile(zip_path) as z:
        with z.open("ml-1m/ratings.dat") as f:
            for line in f:
                if (rand.random() < test_ratio) != is_test:
                    continue
                uid, mov_id, rating, _ts = \
                    line.decode("latin-1").strip().split("::")
                mov = movies[int(mov_id)]
                yield (users[int(uid)]
                       + [int(mov_id), mov[0], mov[1]]
                       + [[float(rating) * 2 - 5.0]])


class _MovielensMeta:
    """Lazily-resolved corpus metadata with a synthetic surrogate
    (120 users x 80 movies, 6 categories, latent-factor ratings)."""

    N_USERS, N_MOVIES, N_CATS, N_JOBS, N_TITLE_WORDS = 120, 80, 6, 21, 40

    def __init__(self):
        self._resolved = False

    def resolve(self):
        if self._resolved:
            return self
        self.zip_path = _try_download(MOVIELENS_URL, "movielens",
                                      MOVIELENS_MD5)
        if self.zip_path:
            (self.movies, self.users, self.title_dict,
             self.categories_dict) = parse_movielens_meta(self.zip_path)
        else:
            rng = np.random.RandomState(77)
            self.categories_dict = {f"cat{i}": i for i in range(self.N_CATS)}
            self.title_dict = {f"word{i}": i
                               for i in range(self.N_TITLE_WORDS)}
            self.movies = {
                m: (sorted(set(rng.randint(0, self.N_CATS, 2).tolist())),
                    rng.randint(0, self.N_TITLE_WORDS, 3).tolist())
                for m in range(1, self.N_MOVIES + 1)}
            self.users = {
                u: [u, int(rng.randint(2)), int(rng.randint(len(AGE_TABLE))),
                    int(rng.randint(self.N_JOBS))]
                for u in range(1, self.N_USERS + 1)}
        self._resolved = True
        return self

    def synthetic_ratings(self, is_test: bool, n: int = 3000,
                          test_ratio: float = 0.1):
        rng = np.random.RandomState(177)
        u_f = np.random.RandomState(78).randn(self.N_USERS + 1, 4)
        m_f = np.random.RandomState(79).randn(self.N_MOVIES + 1, 4)
        for _ in range(n):
            if (rng.rand() < test_ratio) != is_test:
                continue
            u = int(rng.randint(1, self.N_USERS + 1))
            m = int(rng.randint(1, self.N_MOVIES + 1))
            score = float(np.clip(np.round(
                2.5 + 1.2 * (u_f[u] @ m_f[m]) + 0.5 * rng.randn()), 1, 5))
            mov = self.movies[m]
            yield self.users[u] + [m, mov[0], mov[1]] + [[score * 2 - 5.0]]


_MOVIELENS = _MovielensMeta()


def _movielens_reader(is_test: bool):
    def reader():
        meta = _MOVIELENS.resolve()
        if meta.zip_path:
            yield from parse_movielens_ratings(
                meta.zip_path, meta.movies, meta.users, is_test)
        else:
            yield from meta.synthetic_ratings(is_test)

    return reader


def movielens_train():
    """Reader of [uid, gender, age, job, mov_id, cats, title, [rating]]
    — ``v2/dataset/movielens.py``."""
    return _movielens_reader(is_test=False)


def movielens_test():
    return _movielens_reader(is_test=True)


def movielens_movie_categories():
    return _MOVIELENS.resolve().categories_dict


def movielens_get_movie_title_dict():
    return _MOVIELENS.resolve().title_dict


def movielens_max_user_id():
    return max(u[0] for u in _MOVIELENS.resolve().users.values())


def movielens_max_movie_id():
    return max(_MOVIELENS.resolve().movies)


def movielens_max_job_id():
    return max(u[3] for u in _MOVIELENS.resolve().users.values())


def movielens_user_info():
    return dict(_MOVIELENS.resolve().users)


def movielens_movie_info():
    return dict(_MOVIELENS.resolve().movies)


# ----------------------------------------------------------------- sentiment

# the nltk_data package mirror (reference sentiment.py downloads via
# nltk.download('movie_reviews'))
SENTIMENT_URL = ("https://raw.githubusercontent.com/nltk/nltk_data/"
                 "gh-pages/packages/corpora/movie_reviews.zip")
SENTIMENT_MD5 = "385ca9ac1d150113358dd62a1b600e99"


def parse_sentiment(zip_path: str):
    """Parse the nltk ``movie_reviews`` zip (``movie_reviews/{neg,pos}/
    *.txt``) into the reference's format (``sentiment.py:87``):
    a freq-sorted word dict and an interleaved neg/pos sample list of
    ``(word_ids, label)`` with label 0=neg, 1=pos."""
    import collections
    import zipfile

    token_re = re.compile(r"[a-z0-9']+|[^\sa-z0-9']", re.I)
    docs = {"neg": [], "pos": []}
    with zipfile.ZipFile(zip_path) as z:
        for info in sorted(z.infolist(), key=lambda i: i.filename):
            parts = info.filename.split("/")
            if len(parts) == 3 and parts[1] in docs \
                    and parts[2].endswith(".txt"):
                words = token_re.findall(
                    z.read(info).decode("latin-1").lower())
                docs[parts[1]].append(words)
    freq = collections.defaultdict(int)
    for cat in ("neg", "pos"):
        for words in docs[cat]:
            for w in words:
                freq[w] += 1
    # frequency-sorted ids (ties broken lexically for determinism)
    word_dict = {w: i for i, (w, _) in enumerate(
        sorted(freq.items(), key=lambda kv: (-kv[1], kv[0])))}
    data = []
    # cross-read neg/pos (sentiment.py:74 sort_files interleaving)
    for neg, pos in zip(docs["neg"], docs["pos"]):
        data.append(([word_dict[w] for w in neg], 0))
        data.append(([word_dict[w] for w in pos], 1))
    return word_dict, data


_SENTIMENT_CACHE: dict = {}


def _sentiment_data():
    if "data" in _SENTIMENT_CACHE:
        return _SENTIMENT_CACHE["word_dict"], _SENTIMENT_CACHE["data"]
    zip_path = _try_download(SENTIMENT_URL, "sentiment", SENTIMENT_MD5)
    if zip_path:
        word_dict, data = parse_sentiment(zip_path)
    else:
        word_dict = {f"w{i}": i for i in range(5000)}
        data = [(w.tolist(), y) for w, y in
                _synthetic_text(1600, 5000, 2, 20, 200, seed=21)]
    _SENTIMENT_CACHE.update(word_dict=word_dict, data=data)
    return word_dict, data


def sentiment_word_dict():
    return _sentiment_data()[0]


def sentiment_train(train_ratio: float = 0.8):
    def reader():
        _, data = _sentiment_data()
        yield from data[: int(len(data) * train_ratio)]

    return reader


def sentiment_test(train_ratio: float = 0.8):
    def reader():
        _, data = _sentiment_data()
        yield from data[int(len(data) * train_ratio):]

    return reader


# ------------------------------------------------------------------- voc2012

VOC_URL = ("http://host.robots.ox.ac.uk/pascal/VOC/voc2012/"
           "VOCtrainval_11-May-2012.tar")
VOC_MD5 = "6cd6e144f989b92b3379bac3b3de84fd"
_VOC_SET_FILE = "VOCdevkit/VOC2012/ImageSets/Segmentation/{}.txt"
_VOC_DATA_FILE = "VOCdevkit/VOC2012/JPEGImages/{}.jpg"
_VOC_LABEL_FILE = "VOCdevkit/VOC2012/SegmentationClass/{}.png"


def parse_voc2012(tar_path: str, sub_name: str):
    """Yield ``(image HWC uint8, label HW uint8)`` pairs for the given
    segmentation split (reference ``voc2012.py:42``)."""
    import io

    from PIL import Image

    with tarfile.open(tar_path) as tar:
        members = {m.name: m for m in tar.getmembers()}
        set_file = tar.extractfile(members[_VOC_SET_FILE.format(sub_name)])
        for line in set_file.read().decode().splitlines():
            line = line.strip()
            if not line:
                continue
            img = np.array(Image.open(io.BytesIO(
                tar.extractfile(members[_VOC_DATA_FILE.format(line)]).read())))
            lab = np.array(Image.open(io.BytesIO(
                tar.extractfile(
                    members[_VOC_LABEL_FILE.format(line)]).read())))
            yield img, lab


def _voc_reader(sub_name: str, n_synth: int, seed: int):
    tar = _try_download(VOC_URL, "voc2012", VOC_MD5)

    def reader():
        if tar:
            yield from parse_voc2012(tar, sub_name)
            return
        rng = np.random.RandomState(seed)
        for _ in range(n_synth):
            h, w = int(rng.randint(96, 160)), int(rng.randint(96, 160))
            img = rng.randint(0, 256, (h, w, 3)).astype(np.uint8)
            lab = rng.randint(0, 21, (h, w)).astype(np.uint8)
            yield img, lab

    return reader


def voc2012_train(n_synth: int = 64):
    """Segmentation reader of (image, label) — ``v2/dataset/voc2012.py``
    (train() reads the 'trainval' split, as the reference does)."""
    return _voc_reader("trainval", n_synth, seed=31)


def voc2012_test(n_synth: int = 16):
    return _voc_reader("train", n_synth, seed=32)


def voc2012_val(n_synth: int = 16):
    return _voc_reader("val", n_synth, seed=33)


# ------------------------------------------------------------------- flowers

FLOWERS_DATA_URL = ("http://www.robots.ox.ac.uk/~vgg/data/flowers/102/"
                    "102flowers.tgz")
FLOWERS_LABEL_URL = ("http://www.robots.ox.ac.uk/~vgg/data/flowers/102/"
                     "imagelabels.mat")
FLOWERS_SETID_URL = ("http://www.robots.ox.ac.uk/~vgg/data/flowers/102/"
                     "setid.mat")
FLOWERS_DATA_MD5 = "52808999861908f626f3c1f4e79d11fa"
FLOWERS_LABEL_MD5 = "e0620be6f572b9609742df49c70aed4d"
FLOWERS_SETID_MD5 = "a5357ecc9cb78c4bef273ce3793fc85c"
# flowers.py:50-55 — official readme flags, train/test deliberately
# exchanged (tstid is the larger split)
FLOWERS_TRAIN_FLAG, FLOWERS_TEST_FLAG, FLOWERS_VALID_FLAG = \
    "tstid", "trnid", "valid"


def flowers_default_mapper(is_train: bool, sample):
    """jpeg bytes → (flat float32 CHW 3x224x224 image, 0-based label)
    (reference ``flowers.py:58``)."""
    from ..v2 import image as v2_image

    img_bytes, label = sample
    img = v2_image.load_image_bytes(img_bytes)
    # the reference's mean is BGR-ordered (cv2 loader); our loader
    # decodes RGB, so reverse it to hit the right channels
    img = v2_image.simple_transform(
        img, 256, 224, is_train, mean=[123.68, 116.78, 103.94])
    return img.flatten().astype(np.float32), label


def parse_flowers(data_tgz: str, label_mat: str, setid_mat: str,
                  set_flag: str):
    """Yield ``(jpeg_bytes, 0-based_label)`` for the images in the given
    setid split (reference ``flowers.py:73`` minus the on-disk pickle
    batching, which was an optimization for cPickle-era IO)."""
    import scipy.io as scio

    labels = scio.loadmat(label_mat)["labels"][0]
    indexes = scio.loadmat(setid_mat)[set_flag][0]
    wanted = {"jpg/image_%05d.jpg" % i: int(labels[i - 1]) for i in indexes}
    with tarfile.open(data_tgz) as tar:
        for m in tar.getmembers():
            if m.name in wanted:
                yield tar.extractfile(m).read(), wanted[m.name] - 1


def _flowers_reader(set_flag: str, is_train: bool, mapper, n_synth: int,
                    seed: int):
    data = _try_download(FLOWERS_DATA_URL, "flowers", FLOWERS_DATA_MD5)
    label = _try_download(FLOWERS_LABEL_URL, "flowers", FLOWERS_LABEL_MD5)
    setid = _try_download(FLOWERS_SETID_URL, "flowers", FLOWERS_SETID_MD5)
    mapper = mapper or (lambda s: flowers_default_mapper(is_train, s))

    def reader():
        if data and label and setid:
            samples = parse_flowers(data, label, setid, set_flag)
        else:
            samples = _synthetic_flowers_jpegs(n_synth, seed)
        for sample in samples:
            yield mapper(sample)

    return reader


def _synthetic_flowers_jpegs(n: int, seed: int):
    """(jpeg_bytes, label) surrogates so the fallback path exercises the
    SAME mapper contract as real data (raw bytes in, mapper out)."""
    import io

    from PIL import Image

    imgs, labs = _synthetic_images(n, 64, 102, seed=seed)
    for i in range(len(labs)):
        arr = ((imgs[i].reshape(64, 64) + 1) * 127.5).astype(np.uint8)
        rgb = np.stack([arr] * 3, axis=-1)
        buf = io.BytesIO()
        Image.fromarray(rgb, "RGB").save(buf, "JPEG")
        yield buf.getvalue(), int(labs[i])


def flowers_train(mapper=None, n_synth: int = 512):
    """Reader of (flat 3x224x224 float image, label in [0,102)) —
    ``v2/dataset/flowers.py``."""
    return _flowers_reader(FLOWERS_TRAIN_FLAG, True, mapper, n_synth, 41)


def flowers_test(mapper=None, n_synth: int = 128):
    return _flowers_reader(FLOWERS_TEST_FLAG, False, mapper, n_synth, 42)


def flowers_valid(mapper=None, n_synth: int = 128):
    return _flowers_reader(FLOWERS_VALID_FLAG, False, mapper, n_synth, 43)


# -------------------------------------------------------------------- mq2007

# LETOR 4.0 MQ2007; the reference URL serves a .rar (mq2007.py:34) —
# stdlib cannot extract rar, so the loader consumes an already-extracted
# Fold directory from the cache (or any user-supplied path) and otherwise
# falls back to synthetic query lists.
MQ2007_FEATURES = 46


def parse_mq2007_line(line: str, fill_missing: float = -1.0):
    """One LETOR line: ``label qid:N 1:v ... 46:v #docid = X ...`` →
    ``(qid, label, feature_vector[46])`` (reference ``mq2007.py:49``
    ``Query._parse_``); returns None on malformed lines."""
    body = line.split("#")[0].strip()
    if not body:
        return None
    parts = body.split()
    try:
        label = float(parts[0])
        qid = int(parts[1].split(":")[1])
    except (IndexError, ValueError):
        return None
    feats = np.full(MQ2007_FEATURES, fill_missing, np.float32)
    for tok in parts[2:]:
        k, _, v = tok.partition(":")
        try:
            feats[int(k) - 1] = float(v)
        except (IndexError, ValueError):
            continue
    return qid, label, feats


def parse_mq2007(path: str, fill_missing: float = -1.0):
    """Parse a LETOR text file into ordered query lists:
    ``[(qid, [(label, features), ...]), ...]`` (``mq2007.py:268``
    load_from_text, without the shuffle)."""
    queries: Dict[int, list] = {}
    order = []
    with open(path) as f:
        for line in f:
            rec = parse_mq2007_line(line, fill_missing)
            if rec is None:
                continue
            qid, label, feats = rec
            if qid not in queries:
                queries[qid] = []
                order.append(qid)
            queries[qid].append((label, feats))
    return [(qid, queries[qid]) for qid in order]


def _mq2007_pairwise(docs):
    """All (higher, lower) relevance pairs within one query
    (``mq2007.py:187`` gen_pair, full partial order)."""
    for i, (li, fi) in enumerate(docs):
        for lj, fj in docs[i + 1:]:
            if li > lj:
                yield 1.0, fi, fj
            elif lj > li:
                yield 1.0, fj, fi


def _synthetic_querylists(n_queries: int, seed: int):
    rng = np.random.RandomState(seed)
    w = np.random.RandomState(49).randn(MQ2007_FEATURES).astype(np.float32)
    out = []
    for q in range(n_queries):
        n_docs = int(rng.randint(5, 40))
        feats = rng.randn(n_docs, MQ2007_FEATURES).astype(np.float32)
        scores = feats @ w + 0.5 * rng.randn(n_docs)
        labels = np.digitize(scores, np.percentile(scores, [60, 90]))
        out.append((q, [(float(labels[i]), feats[i])
                        for i in range(n_docs)]))
    return out


def _mq2007_reader(split: str, format: str, n_synth_queries: int,
                   seed: int):
    path = _cache_path("MQ2007", "MQ2007", "Fold1", f"{split}.txt")

    def reader():
        querylists = parse_mq2007(path) if os.path.exists(path) \
            else _synthetic_querylists(n_synth_queries, seed)
        for qid, docs in querylists:
            if format == "pointwise":
                for label, feats in docs:
                    yield label, feats
            elif format == "pairwise":
                yield from _mq2007_pairwise(docs)
            elif format == "listwise":
                yield [l for l, _ in docs], [f for _, f in docs]
            else:
                raise ValueError(f"unknown mq2007 format {format!r}")

    return reader


def mq2007_train(format: str = "pairwise", n_synth_queries: int = 300):
    """LETOR learning-to-rank reader — ``v2/dataset/mq2007.py``.
    pointwise: (label, feat[46]); pairwise: (1.0, better, worse);
    listwise: (labels, feats)."""
    return _mq2007_reader("train", format, n_synth_queries, seed=51)


def mq2007_test(format: str = "pairwise", n_synth_queries: int = 60):
    return _mq2007_reader("test", format, n_synth_queries, seed=52)

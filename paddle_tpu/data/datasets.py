"""Bundled datasets.

Port of ``python/paddle/v2/dataset`` (mnist, cifar, imdb, imikolov,
uci_housing, movielens, conll05, wmt14 — auto-downloading corpora cached
under ``~/.cache/paddle/dataset``).  This environment has **zero egress**, so
each dataset loads from the same cache layout if present and otherwise falls
back to a deterministic synthetic surrogate with identical shapes/vocab
sizes — keeping every demo/benchmark runnable and CI hermetic (the bundled
``mnist_bin_part``-style fixture trick, ``paddle/trainer/tests``).
"""

from __future__ import annotations

import gzip
import os
import struct
from typing import Callable, Iterator, Tuple

import numpy as np

CACHE_ROOT = os.path.expanduser(
    os.environ.get("PADDLE_TPU_DATASET_CACHE", "~/.cache/paddle/dataset"))


def _cache_path(*parts: str) -> str:
    return os.path.join(CACHE_ROOT, *parts)


# --------------------------------------------------------------------- mnist

def _synthetic_images(n: int, side: int, classes: int, seed: int,
                      proto_seed: int = 1234):
    """Deterministic class-conditional blobs — learnable but non-trivial.
    Prototypes come from ``proto_seed`` (shared by train/test splits so the
    test set measures generalization); only the sample draw uses ``seed``."""
    protos = np.random.RandomState(proto_seed).randn(
        classes, side * side).astype(np.float32)
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, classes, n)
    noise = rng.randn(n, side * side).astype(np.float32) * 0.7
    imgs = np.clip(protos[labels] * 0.8 + noise, -1, 1)
    return imgs, labels.astype(np.int64)


def _read_idx_images(path: str) -> np.ndarray:
    with gzip.open(path, "rb") as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        data = np.frombuffer(f.read(), np.uint8).reshape(n, rows * cols)
        return data.astype(np.float32) / 127.5 - 1.0


def _read_idx_labels(path: str) -> np.ndarray:
    with gzip.open(path, "rb") as f:
        struct.unpack(">II", f.read(8))
        return np.frombuffer(f.read(), np.uint8).astype(np.int64)


def mnist_train(n_synth: int = 8192):
    """Reader of (image[784] in [-1,1], label) — ``v2/dataset/mnist.py``."""
    img_p = _cache_path("mnist", "train-images-idx3-ubyte.gz")
    lab_p = _cache_path("mnist", "train-labels-idx1-ubyte.gz")

    def reader():
        if os.path.exists(img_p) and os.path.exists(lab_p):
            imgs, labs = _read_idx_images(img_p), _read_idx_labels(lab_p)
        else:
            imgs, labs = _synthetic_images(n_synth, 28, 10, seed=7)
        for i in range(len(labs)):
            yield imgs[i], int(labs[i])

    return reader


def mnist_test(n_synth: int = 1024):
    img_p = _cache_path("mnist", "t10k-images-idx3-ubyte.gz")
    lab_p = _cache_path("mnist", "t10k-labels-idx1-ubyte.gz")

    def reader():
        if os.path.exists(img_p) and os.path.exists(lab_p):
            imgs, labs = _read_idx_images(img_p), _read_idx_labels(lab_p)
        else:
            imgs, labs = _synthetic_images(n_synth, 28, 10, seed=8)
        for i in range(len(labs)):
            yield imgs[i], int(labs[i])

    return reader


# --------------------------------------------------------------------- cifar

def cifar10_train(n_synth: int = 4096):
    """Reader of (image[3072] CHW float, label) — ``v2/dataset/cifar.py``."""

    def reader():
        imgs, labs = _synthetic_images(n_synth, 32, 10, seed=9)
        imgs3 = np.repeat(imgs, 3, axis=1)[:, : 3 * 32 * 32]
        for i in range(len(labs)):
            yield imgs3[i], int(labs[i])

    return reader


def cifar10_test(n_synth: int = 512):
    def reader():
        imgs, labs = _synthetic_images(n_synth, 32, 10, seed=10)
        imgs3 = np.repeat(imgs, 3, axis=1)[:, : 3 * 32 * 32]
        for i in range(len(labs)):
            yield imgs3[i], int(labs[i])

    return reader


# ---------------------------------------------------------------------- imdb

def _synthetic_text(n: int, vocab: int, classes: int, min_len: int,
                    max_len: int, seed: int, proto_seed: int = 4321):
    """Class-dependent unigram distributions; label recoverable from text.
    Boost vocabularies come from ``proto_seed`` (shared across splits)."""
    prng = np.random.RandomState(proto_seed)
    class_boost = [prng.permutation(vocab)[: vocab // 4]
                   for _ in range(classes)]
    rng = np.random.RandomState(seed)
    for _ in range(n):
        y = int(rng.randint(classes))
        length = int(rng.randint(min_len, max_len + 1))
        base = rng.randint(2, vocab, length)
        boost_mask = rng.rand(length) < 0.5
        boosted = class_boost[y][rng.randint(0, len(class_boost[y]), length)]
        words = np.where(boost_mask, boosted, base)
        yield words.astype(np.int64), y


def imdb_word_dict(vocab: int = 5148):
    return {f"w{i}": i for i in range(vocab)}


def imdb_train(word_dict=None, n_synth: int = 2000):
    vocab = len(word_dict) if word_dict else 5148

    def reader():
        yield from _synthetic_text(n_synth, vocab, 2, 10, 120, seed=11)

    return reader


def imdb_test(word_dict=None, n_synth: int = 400):
    vocab = len(word_dict) if word_dict else 5148

    def reader():
        yield from _synthetic_text(n_synth, vocab, 2, 10, 120, seed=12)

    return reader


# ------------------------------------------------------------------ imikolov

def imikolov_train(word_dict=None, n: int = 5, n_synth: int = 5000):
    """n-gram LM samples (``v2/dataset/imikolov.py``)."""
    vocab = len(word_dict) if word_dict else 2000

    def reader():
        rng = np.random.RandomState(13)
        for _ in range(n_synth):
            yield tuple(int(x) for x in rng.randint(0, vocab, n))

    return reader


# --------------------------------------------------------------- uci_housing

def uci_housing_train(n_synth: int = 404):
    def reader():
        rng = np.random.RandomState(14)
        w = rng.randn(13).astype(np.float32)
        for _ in range(n_synth):
            x = rng.randn(13).astype(np.float32)
            y = float(x @ w + 0.1 * rng.randn())
            yield x, np.array([y], np.float32)

    return reader


def uci_housing_test(n_synth: int = 102):
    def reader():
        rng = np.random.RandomState(15)
        w = np.random.RandomState(14).randn(13).astype(np.float32)
        for _ in range(n_synth):
            x = rng.randn(13).astype(np.float32)
            y = float(x @ w + 0.1 * rng.randn())
            yield x, np.array([y], np.float32)

    return reader


# --------------------------------------------------------------------- wmt14

def wmt14_dicts(dict_size: int = 30000):
    src = {f"s{i}": i for i in range(dict_size)}
    trg = {f"t{i}": i for i in range(dict_size)}
    return src, trg


START, END, UNK = 0, 1, 2


def wmt14_train(dict_size: int = 30000, n_synth: int = 2000):
    """Reader of (src_ids, trg_ids_with_<s>, trg_next_ids) triples
    (``v2/dataset/wmt14.py`` convention)."""

    def reader():
        rng = np.random.RandomState(16)
        for _ in range(n_synth):
            slen = int(rng.randint(5, 30))
            src = rng.randint(3, dict_size, slen).astype(np.int64)
            # synthetic transduction: reverse + offset, bounded vocab
            trg = ((src[::-1] * 7) % (dict_size - 3) + 3)[: max(3, slen - 2)]
            trg_in = np.concatenate([[START], trg])
            trg_next = np.concatenate([trg, [END]])
            yield src, trg_in, trg_next

    return reader


def wmt14_test(dict_size: int = 30000, n_synth: int = 200):
    def reader():
        rng = np.random.RandomState(17)
        for _ in range(n_synth):
            slen = int(rng.randint(5, 30))
            src = rng.randint(3, dict_size, slen).astype(np.int64)
            trg = ((src[::-1] * 7) % (dict_size - 3) + 3)[: max(3, slen - 2)]
            yield src, np.concatenate([[START], trg]), np.concatenate([trg, [END]])

    return reader


# ------------------------------------------------------------------- conll05

def conll05_train(n_synth: int = 1000, vocab: int = 5000, num_labels: int = 19):
    """SRL sequence-tagging samples: (words, predicate, labels)."""

    def reader():
        rng = np.random.RandomState(18)
        for _ in range(n_synth):
            length = int(rng.randint(5, 40))
            words = rng.randint(0, vocab, length).astype(np.int64)
            pred = int(rng.randint(0, length))
            labels = ((words + pred) % num_labels).astype(np.int64)
            yield words, pred, labels

    return reader


# -------------------------------------------------------------------- criteo

def criteo_ctr_train(n_synth: int = 5000, dense_dim: int = 13,
                     sparse_dim: int = 10 ** 6, slots: int = 26):
    """Wide&deep CTR samples: (dense[13], sparse_ids[26], label) —
    the sparse large-model workload (BASELINE config 5)."""

    def reader():
        rng = np.random.RandomState(19)
        w_dense = rng.randn(dense_dim).astype(np.float32)
        for _ in range(n_synth):
            dense = rng.randn(dense_dim).astype(np.float32)
            ids = rng.randint(0, sparse_dim, slots).astype(np.int64)
            logit = dense @ w_dense + 0.3 * ((ids[0] % 97) / 48.5 - 1.0)
            yield dense, ids, int(logit + 0.2 * rng.randn() > 0)

    return reader

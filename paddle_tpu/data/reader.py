"""Reader creators and combinators.

Port of the v2 functional reader stack
(``python/paddle/v2/reader/decorator.py``: map_readers, shuffle, chain,
compose, buffered, firstn, xmap_readers; ``creator.py``: np_array,
text_file).  A *reader* is a zero-arg callable returning an iterable of
samples — identical contract to the reference, so user reader code ports
unchanged.
"""

from __future__ import annotations

import itertools
import queue
import random
import threading
from typing import Any, Callable, Iterable, List, Sequence

from ..utils import get_logger
from .pipeline import IO_THREAD_PREFIX

Reader = Callable[[], Iterable[Any]]


def _put_until(q: "queue.Queue", item: Any, stop: threading.Event,
               poll_s: float = 0.05) -> bool:
    """``q.put`` that gives up when ``stop`` is set — a producer thread
    must never stay blocked against a full queue after its consumer
    abandoned the generator.  Returns False when it gave up."""
    while not stop.is_set():
        try:
            q.put(item, timeout=poll_s)
            return True
        except queue.Full:
            continue
    return False


def _close_iter(it: Any) -> None:
    """Close a (possibly generator) iterator, best-effort: propagates
    GeneratorExit through reader chains so teardown contracts (e.g.
    ``master_reader`` FAILing its in-flight lease) run deterministically
    instead of waiting on GC."""
    close = getattr(it, "close", None)
    if close is not None:
        try:
            close()
        except Exception as e:  # noqa: BLE001 — teardown is best-effort
            get_logger("reader").debug(
                "iterator close failed during teardown: %s: %s",
                type(e).__name__, e)


def np_array(x) -> Reader:
    def reader():
        for row in x:
            yield row

    return reader


def text_file(path: str) -> Reader:
    def reader():
        with open(path) as f:
            for line in f:
                yield line.rstrip("\n")

    return reader


def map_readers(func: Callable, *readers: Reader) -> Reader:
    def reader():
        for items in zip(*[r() for r in readers]):
            yield func(*items)

    return reader


def shuffle(reader: Reader, buf_size: int, seed: int = None) -> Reader:
    def shuffled():
        rng = random.Random(seed)
        buf: List[Any] = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                rng.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            rng.shuffle(buf)
            yield from buf

    return shuffled


def chain(*readers: Reader) -> Reader:
    def reader():
        for r in readers:
            yield from r()

    return reader


def compose(*readers: Reader, check_alignment: bool = True) -> Reader:
    """Zip readers into tuple samples (flattening tuple components)."""

    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def reader():
        if check_alignment:
            for items in zip(*[r() for r in readers]):
                yield sum((make_tuple(i) for i in items), ())
        else:
            for items in itertools.zip_longest(*[r() for r in readers]):
                yield sum((make_tuple(i) for i in items if i is not None), ())

    return reader


def buffered(reader: Reader, size: int) -> Reader:
    """Double-buffering via a background thread — the TPU-host overlap
    equivalent of ``DataProvider.h:360``'s double-buffer queue.

    A consumer that abandons the generator mid-pass (break / ``close()``
    / GC → GeneratorExit) shuts the producer down and joins it: the
    producer must not stay blocked on ``q.put`` against a full queue
    forever (the classic thread leak), and the inner reader is closed so
    its own teardown (lease FAILs, socket closes) runs.
    """

    class _End:
        pass

    def buffered_reader():
        q: "queue.Queue" = queue.Queue(maxsize=size)

        error: List[BaseException] = []
        stop = threading.Event()

        def producer():
            it = None
            try:
                # inside the try: a reader that raises EAGERLY (before
                # returning its iterable) must still reach the consumer
                it = iter(reader())
                for e in it:
                    if not _put_until(q, e, stop):
                        return               # consumer gone
            except BaseException as exc:  # re-raised in the consumer
                error.append(exc)
            finally:
                if it is not None:
                    _close_iter(it)
                _put_until(q, _End, stop)

        t = threading.Thread(target=producer, daemon=True,
                             name=IO_THREAD_PREFIX + "buffered")
        t.start()
        try:
            while True:
                e = q.get()
                if e is _End:
                    if error:
                        raise error[0]
                    break
                yield e
        finally:
            stop.set()
            t.join(timeout=5.0)

    return buffered_reader


def firstn(reader: Reader, n: int) -> Reader:
    def reader_n():
        return itertools.islice(reader(), n)

    return reader_n


def cache(reader: Reader) -> Reader:
    """Materialize once, then replay from memory (pass-in-memory cache,
    ``PyDataProvider2.cpp:70``)."""
    data: List[Any] = []
    filled = [False]

    def cached():
        if not filled[0]:
            fresh: List[Any] = []  # discarded if this pass stops early
            for e in reader():
                fresh.append(e)
                yield e
            data[:] = fresh
            filled[0] = True
        else:
            yield from data

    return cached


def xmap_readers(mapper: Callable, reader: Reader, process_num: int,
                 buffer_size: int, order: bool = False) -> Reader:
    """Parallel map over a reader with worker threads (reference uses
    threads too — CPython-level parallelism for IO/numpy work).

    Fault contract: an exception in ``mapper`` or in the feed thread is
    caught, recorded, and re-raised in the consumer — the dying thread
    still delivers its ``_End`` so the consumer never blocks forever on
    ``out_q.get()`` (the pre-round-11 hang).  A consumer that abandons
    the generator mid-pass shuts down and joins the threads.
    """

    class _End:
        pass

    def xreader():
        in_q: "queue.Queue" = queue.Queue(buffer_size)
        out_q: "queue.Queue" = queue.Queue(buffer_size)
        error: List[BaseException] = []
        stop = threading.Event()

        def feed():
            it = None
            try:
                # inside the try: an eagerly-raising reader must still
                # deliver the _End markers below, or the consumer wedges
                it = iter(reader())
                for i, e in enumerate(it):
                    if not _put_until(in_q, (i, e), stop):
                        return               # consumer gone
            except BaseException as exc:  # re-raised in the consumer
                error.append(exc)
            finally:
                if it is not None:
                    _close_iter(it)
                # every worker gets its end marker even when the source
                # died mid-pass — a missing _End wedges the consumer
                for _ in range(process_num):
                    if not _put_until(in_q, _End, stop):
                        return

        def work():
            try:
                while True:
                    try:
                        item = in_q.get(timeout=0.05)
                    except queue.Empty:
                        if stop.is_set():
                            return
                        continue
                    if item is _End:
                        _put_until(out_q, _End, stop)
                        return
                    i, e = item
                    if not _put_until(out_q, (i, mapper(e)), stop):
                        return
            except BaseException as exc:  # re-raised in the consumer
                error.append(exc)
                _put_until(out_q, _End, stop)

        threads = [threading.Thread(target=feed, daemon=True,
                                    name=IO_THREAD_PREFIX + "xmap-feed")]
        threads += [threading.Thread(target=work, daemon=True,
                                     name=f"{IO_THREAD_PREFIX}xmap-w{i}")
                    for i in range(process_num)]
        for t in threads:
            t.start()
        try:
            finished = 0
            if order:
                pending = {}
                next_i = 0
                while finished < process_num:
                    if error:     # fault: stop draining NOW, not after
                        raise error[0]   # the rest of the stream maps
                    item = out_q.get()
                    if item is _End:
                        finished += 1
                        continue
                    i, e = item
                    pending[i] = e
                    while next_i in pending:
                        yield pending.pop(next_i)
                        next_i += 1
                if error:
                    raise error[0]
                while next_i in pending:
                    yield pending.pop(next_i)
                    next_i += 1
            else:
                while finished < process_num:
                    if error:
                        raise error[0]
                    item = out_q.get()
                    if item is _End:
                        finished += 1
                        continue
                    yield item[1]
                if error:
                    raise error[0]
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=5.0)

    return xreader


def batch(reader: Reader, batch_size: int, drop_last: bool = True) -> Reader:
    """Group samples into lists (``paddle.v2.minibatch.batch``).

    drop_last defaults True on TPU: fixed batch shapes avoid recompiles.
    """

    def batch_reader():
        b = []
        for e in reader():
            b.append(e)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b

    return batch_reader


# ------------------------------------------------- recordio-backed creators

def recordio(paths, buf_size: int = 100) -> Reader:
    """Reader over chunked record files written by :func:`convert` /
    :class:`paddle_tpu.data.recordio.Writer` (``creator.py:60``).
    Records are pickled samples — these files are framework-produced
    dataset caches, the reference's own convention."""
    import pickle

    from . import recordio as rio

    def reader():
        for rec in rio.reader(paths):
            yield pickle.loads(rec)

    return buffered(reader, buf_size)


def cloud_reader(paths, master, buf_size: int = 64,
                 read_ahead: int = 2) -> Reader:
    """Master-coordinated distributed reader (``creator.py:91``): the
    master leases recordio *chunks* to trainers so each record is
    consumed once per pass cluster-wide, with failed leases re-queued.

    :param master: a :class:`paddle_tpu.distributed.Master` /
        ``MasterClient`` (replaces the reference's etcd endpoint — no
        external coordinator in the TPU build).
    :param read_ahead: chunks the lease/fetch thread keeps ahead of
        training, so the next chunk's disk read + unpickle overlaps the
        current chunk's steps (``master_reader(read_ahead=...)``); 0
        restores the fetch-on-demand path.  Leases still FAIL on
        abandonment — including prefetched-but-unconsumed chunks.
    """
    import pickle

    from . import recordio as rio

    from ..distributed.master import master_reader

    # every trainer calls set_dataset; the master honors only the FIRST
    # call (initDone guard, go/master/service.go:287) so a trainer
    # joining mid-pass cannot wipe the shared queue
    master.set_dataset(rio.chunk_payloads(paths))

    def load_chunk(payload):
        path, off = payload.rsplit("\t", 1)
        for rec in rio.read_chunk(path, int(off)):
            yield pickle.loads(rec)

    # the shared client outlives each pass's generator: don't let
    # master_reader's teardown close it between passes
    inner = master_reader(master, load_chunk, close_client=False,
                          read_ahead=read_ahead)
    # offset the local pass counter by the master's epoch so a trainer
    # (re)joining a long-lived or snapshot-recovered master doesn't send
    # reset requests the master has already performed
    epoch_base = master.current_epoch()
    pass_num = [0]

    def reader():
        # the trainer re-invokes reader() once per pass; request the
        # next epoch for passes 2..N, carrying the pass number (the
        # reference's start_get_records(pass_num) handshake). The master
        # resets exactly once per epoch no matter how N trainers'
        # requests interleave — duplicates for an already-performed
        # reset are no-ops, and a request made while peers still hold
        # leases is armed and performed when the queue drains, so an
        # early-finishing trainer never sees a zero-sample next pass.
        if pass_num[0]:
            master.reset_epoch(epoch_base + pass_num[0])
        pass_num[0] += 1
        yield from inner()

    return buffered(reader, buf_size)


def mix_readers(readers, ratios=None, main: int = 0) -> Reader:
    """Ratio-weighted mixing of sample streams — the
    ``MultiDataProvider`` capability (``MultiDataProvider.cpp:79-117``):
    each pass interleaves samples from every reader in proportion to its
    ratio; the pass ends when the *main* reader is exhausted, while the
    other readers restart transparently (the reference resets non-main
    sub-providers mid-pass).

    :param readers: list of readers.
    :param ratios: per-reader positive weights (``data_ratio``);
        defaults to uniform.
    :param main: index of the main reader (``is_main_data``).
    """
    ratios = list(ratios) if ratios is not None else [1.0] * len(readers)
    if len(ratios) != len(readers):
        raise ValueError("mix_readers: one ratio per reader required")
    if any(r <= 0 for r in ratios):
        raise ValueError("mix_readers: ratios must be positive")
    if not 0 <= main < len(readers):
        raise ValueError(
            f"mix_readers: main index {main} out of range for "
            f"{len(readers)} readers")

    def reader():
        iters = [iter(r()) for r in readers]
        # error-accumulator interleave: at every step pull from the
        # stream whose emitted count is furthest below its ratio share;
        # shares are maintained incrementally (O(n_readers) per sample,
        # no per-sample re-summation)
        counts = [0] * len(readers)
        total = sum(ratios)
        shares = [r / total for r in ratios]
        step = 0
        while True:
            step += 1
            i = max(range(len(readers)),
                    key=lambda j: shares[j] * step - counts[j])
            try:
                sample = next(iters[i])
            except StopIteration:
                if i == main:
                    return            # main stream exhausted: pass ends
                iters[i] = iter(readers[i]())   # non-main: restart
                try:
                    sample = next(iters[i])
                except StopIteration:
                    raise ValueError(
                        f"mix_readers: reader {i} is empty")
            counts[i] += 1
            yield i, sample

    return reader

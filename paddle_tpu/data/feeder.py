"""Input-type declarations and the batch feeder.

Port of the reference's data-type vocabulary
(``python/paddle/trainer/PyDataProvider2.py``: dense_vector,
sparse_binary_vector, sparse_float_vector, integer_value, plus ``_sequence``
/ ``_sub_sequence`` variants) and the v2 ``DataFeeder``
(``python/paddle/v2/data_feeder.py`` + ``py_paddle/dataprovider_converter.py``)
that turns a minibatch of Python tuples into device arrays.

TPU specifics: sequences become padded :class:`SequenceBatch` (bucketed
lengths bound recompilation); sparse vectors densify by default (XLA) or
stay as (ids, values) pairs for the sharded-embedding path.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from ..core.dtypes import np_dtype
from ..core.sequence import SequenceBatch, pad_batch, pad_nested_batch
from ..utils import ConfigError, enforce


@dataclasses.dataclass(frozen=True)
class InputType:
    dim: int
    seq_level: int = 0  # 0: none, 1: sequence, 2: sub-sequence
    kind: str = "dense"  # dense | sparse_binary | sparse_float | index
    # storage dtype of dense feeds ("float32" default; "bfloat16" halves
    # feed H2D traffic under the bf16 policy — resolved through
    # core.dtypes.np_dtype, which plain numpy name parsing can't do).
    # Index kinds always feed int32.
    dtype: str = "float32"


def dense_vector(dim: int, dtype: str = "float32") -> InputType:
    return InputType(dim, 0, "dense", dtype)


def dense_vector_sequence(dim: int, dtype: str = "float32") -> InputType:
    return InputType(dim, 1, "dense", dtype)


def sparse_binary_vector(dim: int) -> InputType:
    return InputType(dim, 0, "sparse_binary")


def sparse_binary_vector_sequence(dim: int) -> InputType:
    return InputType(dim, 1, "sparse_binary")


def sparse_float_vector(dim: int) -> InputType:
    return InputType(dim, 0, "sparse_float")


def sparse_float_vector_sequence(dim: int) -> InputType:
    return InputType(dim, 1, "sparse_float")


def integer_value(value_range: int) -> InputType:
    return InputType(value_range, 0, "index")


def integer_value_sequence(value_range: int) -> InputType:
    return InputType(value_range, 1, "index")


def integer_value_sub_sequence(value_range: int) -> InputType:
    return InputType(value_range, 2, "index")


def dense_vector_sub_sequence(dim: int) -> InputType:
    return InputType(dim, 2, "dense")


class DataFeeder:
    """feeding: list of (data_layer_name, InputType) in sample tuple order."""

    def __init__(self, feeding: Sequence, buckets: Optional[Sequence[int]] = None):
        self.feeding = [(name, t) for name, t in feeding]
        self.buckets = buckets

    def _densify(self, row, dim: int, kind: str,
                 dtype: str = "float32") -> np.ndarray:
        dt = np_dtype(dtype)
        if kind == "sparse_binary":
            out = np.zeros(dim, dt)
            out[np.asarray(row, np.int64)] = 1.0
            return out
        if kind == "sparse_float":
            ids, vals = zip(*row) if row else ((), ())
            out = np.zeros(dim, dt)
            out[np.asarray(ids, np.int64)] = np.asarray(vals, np.float32)
            return out
        # copy=False keeps the pre-round-12 zero-copy fast path for
        # rows already stored at the target dtype (hot host feed path)
        return np.asarray(row).astype(dt, copy=False)

    @staticmethod
    def _materialize(row):
        """List-ify one-shot iterators (py2-era providers ``yield
        map(int, xs)`` — ``benchmark/paddle/rnn/provider.py:72``)."""
        if isinstance(row, (list, tuple, np.ndarray, int, float, str,
                            bytes)):
            return row
        if hasattr(row, "__iter__"):
            return list(row)
        return row

    def convert(self, batch: List[Sequence]) -> Dict[str, Any]:
        """minibatch (list of sample tuples OR dicts keyed by data-layer
        name — both PyDataProvider2 sample conventions) → feed dict."""
        from ..observe import histogram

        with histogram(
                "data_feed_convert_seconds",
                "host time densifying/padding a minibatch into device "
                "arrays (DataFeeder.convert)").time():
            return self._convert(batch)

    def _convert(self, batch: List[Sequence]) -> Dict[str, Any]:
        feed: Dict[str, Any] = {}
        for slot, (name, itype) in enumerate(self.feeding):
            col = [self._materialize(sample[name]
                                     if isinstance(sample, dict)
                                     else sample[slot])
                   for sample in batch]
            dt = getattr(itype, "dtype", "float32")
            if itype.seq_level == 0:
                if itype.kind == "index":
                    feed[name] = jnp.asarray(np.asarray(col, np.int32))
                else:
                    rows = [self._densify(r, itype.dim, itype.kind, dt)
                            for r in col]
                    feed[name] = jnp.asarray(np.stack(rows))
            elif itype.seq_level == 1:
                if itype.kind == "index":
                    seqs = [np.asarray(r, np.int32) for r in col]
                    feed[name] = pad_batch(seqs, buckets=self.buckets,
                                           dtype=np.int32)
                else:
                    seqs = [np.stack([self._densify(x, itype.dim,
                                                    itype.kind, dt)
                                      for x in r]) if len(r) else
                            np.zeros((0, itype.dim), np_dtype(dt))
                            for r in col]
                    feed[name] = pad_batch(seqs, buckets=self.buckets)
            else:  # sub-sequence
                if itype.kind == "index":
                    nested = [[np.asarray(s, np.int32) for s in r] for r in col]
                    feed[name] = pad_nested_batch(nested, dtype=np.int32)
                else:
                    nested = [[np.stack([self._densify(x, itype.dim,
                                                       itype.kind, dt)
                                         for x in s]) for s in r] for r in col]
                    feed[name] = pad_nested_batch(nested)
        return feed

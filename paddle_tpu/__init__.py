"""paddle_tpu — a TPU-native deep-learning framework.

Re-implements the capability surface of 2017-era PaddlePaddle
(reference: onelcq/Paddle) on JAX/XLA/Pallas: a config-driven layer engine,
v2-style Python API, trainer CLI, data-parallel + sharded-embedding
distribution over a ``jax.sharding.Mesh``, and a ProgramDesc→Executor graph
runtime that lowers whole blocks to single XLA computations.
"""

__version__ = "0.1.0"

from . import core, utils
from .utils import FLAGS

__all__ = ["core", "utils", "FLAGS", "__version__"]

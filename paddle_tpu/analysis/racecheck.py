"""PT-RACE — cross-thread shared-state race detection.

The framework now runs a small fleet of threads inside one process —
pipeline/reader workers, the trace writer, the metrics reporter, the
observability HTTP server, the fleet aggregator, the master read-ahead
fetcher, SIGUSR2/SIGTERM helpers — every one named with the ``ptpu-``
prefix the conftest leak guard audits.  This rule derives, from the
one-parse callgraph, the set of instance attributes and module globals
**reachable from two distinct ``ptpu-*`` thread entrypoints** (or from
one entrypoint started as a pool) **with at least one write and no
common ``named_lock`` guard on all access paths** — the static shape of
a data race.

Model (under-approximate, like every rule in this package — a finding
is near-certain):

- **entrypoints** are the statically-resolved ``target=`` of
  ``threading.Thread(...)`` constructions whose ``name=`` constant-
  propagates to a ``ptpu-`` prefix (the PT-RESOURCE machinery).  A
  construction inside a loop/comprehension is a *pool*: the entrypoint
  is concurrent with itself.
- **reachability** follows the conservative call resolution of
  :mod:`~paddle_tpu.analysis.callgraph`, carrying the set of lock
  identities (:mod:`~paddle_tpu.analysis.lockorder` names, shared with
  PT-LOCK) that are *always held* on every discovered path — the
  intersection over call sites, shrunk to fixpoint.
- **shared state**: ``self.attr`` loads/stores grouped per
  ``(module, class, attr)``, and module globals written through a
  ``global`` declaration (or mutated via a method call on the global).
  Attributes/globals bound to thread-safe primitives (locks,
  conditions, events, semaphores, queues, ``threading.local``) are
  exempt — their methods are their guard.  ``__init__`` is never
  thread-entrypoint-reachable, so construction-time writes are
  happens-before and invisible here.
- a **finding** needs: accesses from ≥ 2 distinct entrypoints (a pool
  counts twice), ≥ 1 write among them, and an empty intersection of
  the guard sets over all access sites.  It is reported once per
  shared variable, anchored at the first unguarded write, with the
  witnessing entrypoints and sites in the message.

Deliberate benign races (e.g. a joined writer thread's teardown field)
carry ``# ptpu: lint-ok[PT-RACE]`` pragmas with a justification, same
as every other rule.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from .callgraph import FunctionInfo, ModuleInfo, Project, dotted_name
from .engine import Finding


def _lock_helpers():
    """PT-LOCK's lock-identity machinery (shared so PT-RACE guards and
    the lock graph name the same nodes).  Imported lazily: the rules
    package imports this module, so a top-level import would be
    circular when racecheck is imported first."""
    from .rules.lock_order import _collect_locks, _with_lock_ids

    return _collect_locks, _with_lock_ids


def _name_helpers():
    from .rules.resource import (THREAD_PREFIX, _imported_constant,
                                 _static_name_prefix)

    return THREAD_PREFIX, _imported_constant, _static_name_prefix


RULE = "PT-RACE"

#: Constructors whose objects are internally synchronized — an
#: attribute/global bound to one of these is not shared *state*, it is
#: the synchronization itself.
_THREADSAFE_CTORS = {
    "Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore",
    "Event", "Barrier", "Queue", "SimpleQueue", "LifoQueue",
    "PriorityQueue", "local", "named_lock", "named_condition",
}

#: Method calls that mutate their receiver (container mutation counts
#: as a write to the shared variable holding the container).
_MUTATORS = {
    "append", "appendleft", "extend", "extendleft", "insert", "add",
    "update", "setdefault", "pop", "popitem", "popleft", "remove",
    "discard", "clear", "sort", "reverse",
}

Site = Tuple[str, int]              # (abs path, line)
AttrKey = Tuple[str, str, str]      # ("attr", mod.Class, attr) — rendered
                                    # ("global", mod, name) for globals


class _Entry:
    __slots__ = ("fn", "thread_name", "pooled", "site")

    def __init__(self, fn: FunctionInfo, thread_name: str, pooled: bool,
                 site: Site):
        self.fn = fn
        self.thread_name = thread_name
        self.pooled = pooled
        self.site = site

    def label(self) -> str:
        return (f"{self.fn.module.short()}.{self.fn.qualname} "
                f"[{self.thread_name}{'*' if self.pooled else ''}]")


class _Access:
    __slots__ = ("key", "kind", "guards", "site", "fn")

    def __init__(self, key: AttrKey, kind: str,
                 guards: FrozenSet[str], site: Site, fn: FunctionInfo):
        self.key = key
        self.kind = kind            # "read" | "write"
        self.guards = guards
        self.site = site
        self.fn = fn


# ----------------------------------------------------------- entrypoints
def _resolve_ref(project: Project, mod: ModuleInfo,
                 fn: Optional[FunctionInfo],
                 node: ast.AST) -> Optional[FunctionInfo]:
    """Resolve a function *reference* expression (not a call) — the
    ``target=`` of a Thread construction."""
    if isinstance(node, ast.Name):
        return project.resolve_name(mod, fn, node.id)
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name):
        base, attr = node.value.id, node.attr
        if base == "self" and fn is not None and fn.class_name:
            return mod.functions.get(fn.class_name + "." + attr)
        if base in mod.imports:
            return project._function_in(mod.imports[base], attr)
        tgt = mod.from_imports.get(base)
        if tgt is not None:
            dotted = (tgt[0] + "." + tgt[1]) if tgt[0] else tgt[1]
            return project._function_in(dotted, attr)
        cls = mod.instance_of.get(base)
        if cls is None and fn is not None:
            cls = _local_instance_class(mod, fn, base)
        if cls is not None and "." not in cls:
            return mod.functions.get(cls + "." + attr)
    return None


def _local_instance_class(mod: ModuleInfo, fn: FunctionInfo,
                          var: str) -> Optional[str]:
    """``c = ClassName(...)`` directly in ``fn`` → "ClassName" when the
    class is defined in this module (the module-level ``instance_of``
    table, scoped to a function body)."""
    hit: Optional[str] = None
    for node in ast.walk(fn.node):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == var \
                and isinstance(node.value, ast.Call):
            cls = dotted_name(node.value.func)
            if cls is not None and cls in mod.classes:
                hit = cls
            else:
                return None         # rebound to something else: give up
    return hit


def _is_thread_ctor(project: Project, mod: ModuleInfo,
                    call: ast.Call) -> bool:
    chain = dotted_name(call.func)
    if chain is None or chain.split(".")[-1] != "Thread":
        return False
    root = chain.split(".")[0]
    if root == "Thread":
        return mod.from_imports.get("Thread", ("", ""))[0] == "threading"
    return project.names_module(mod, root, "threading")


def _enclosing_fn(mod: ModuleInfo, node: ast.AST) -> Optional[FunctionInfo]:
    best: Optional[FunctionInfo] = None
    for f in mod.functions.values():
        for n in ast.walk(f.node):
            if n is node:
                if best is None or len(f.qualname) > len(best.qualname):
                    best = f
                break
    return best


def _is_pooled(owner_node: ast.AST, call: ast.Call) -> bool:
    """Thread construction inside a loop or comprehension — N instances
    of the same entrypoint run concurrently with each other."""
    loops = (ast.For, ast.While, ast.AsyncFor, ast.ListComp, ast.SetComp,
             ast.DictComp, ast.GeneratorExp)
    for n in ast.walk(owner_node):
        if isinstance(n, loops):
            for inner in ast.walk(n):
                if inner is call:
                    return True
    return False


def find_entrypoints(project: Project) -> List[_Entry]:
    THREAD_PREFIX, _imported_constant, _static_name_prefix = \
        _name_helpers()
    out: List[_Entry] = []
    seen: Set[Tuple[FunctionInfo, str]] = set()
    for mod in project.iter_modules():
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call) \
                    or not _is_thread_ctor(project, mod, node):
                continue
            name_kw = next((kw.value for kw in node.keywords
                            if kw.arg == "name"), None)
            if name_kw is None:
                continue
            prefix = _static_name_prefix(mod, name_kw)
            if prefix is None and isinstance(name_kw, ast.Name):
                prefix = _imported_constant(project, mod, name_kw.id)
            if prefix is None and isinstance(name_kw, ast.BinOp) \
                    and isinstance(name_kw.op, ast.Add) \
                    and isinstance(name_kw.left, ast.Name):
                prefix = _imported_constant(project, mod, name_kw.left.id)
            if prefix is None and isinstance(name_kw, ast.JoinedStr) \
                    and name_kw.values \
                    and isinstance(name_kw.values[0], ast.FormattedValue) \
                    and isinstance(name_kw.values[0].value, ast.Name):
                prefix = _imported_constant(
                    project, mod, name_kw.values[0].value.id)
            if prefix is None or not prefix.startswith(THREAD_PREFIX):
                continue
            tgt_node = next((kw.value for kw in node.keywords
                             if kw.arg == "target"), None)
            if tgt_node is None and len(node.args) >= 2:
                tgt_node = node.args[1]
            if tgt_node is None:
                continue
            owner = _enclosing_fn(mod, node)
            fn = _resolve_ref(project, mod, owner, tgt_node)
            if fn is None:
                continue
            pooled = owner is not None and _is_pooled(owner.node, node)
            key = (fn, prefix)
            if key in seen:
                # a second *distinct* construction site of the same
                # target makes it pool-like too
                for e in out:
                    if e.fn is fn and e.thread_name == prefix \
                            and e.site != (mod.path, node.lineno):
                        e.pooled = True
                continue
            seen.add(key)
            out.append(_Entry(fn, prefix, pooled,
                              (mod.path, node.lineno)))
    out.extend(_http_handler_entrypoints(project, seen))
    return out


_SERVER_CTORS = {"ThreadingHTTPServer", "make_threading_server"}
_HANDLER_METHODS = ("do_GET", "do_POST", "do_PUT", "do_DELETE",
                    "do_HEAD")


def _http_handler_entrypoints(project: Project,
                              seen: Set[Tuple[FunctionInfo, str]]
                              ) -> List[_Entry]:
    """A request-handler class handed to a threading HTTP server runs
    its ``do_*`` methods on per-request threads — each is a *pooled*
    entrypoint (two requests race each other), even though no explicit
    ``threading.Thread`` construction names them."""
    out: List[_Entry] = []
    for mod in project.iter_modules():
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = dotted_name(node.func)
            if chain is None \
                    or chain.split(".")[-1] not in _SERVER_CTORS:
                continue
            for arg in node.args:
                if not isinstance(arg, ast.Name) \
                        or arg.id not in mod.classes:
                    continue
                for meth in _HANDLER_METHODS:
                    fn = mod.functions.get(f"{arg.id}.{meth}")
                    if fn is None:
                        continue
                    key = (fn, f"http:{arg.id}")
                    if key in seen:
                        continue
                    seen.add(key)
                    out.append(_Entry(fn, f"http:{arg.id}", True,
                                      (mod.path, node.lineno)))
    return out


# --------------------------------------------------- per-function summary
class _FnSummary:
    __slots__ = ("calls", "accesses")

    def __init__(self) -> None:
        # (callee, lexical-held-at-site)
        self.calls: List[Tuple[FunctionInfo, FrozenSet[str]]] = []
        # (key, kind, lexical-held, site)
        self.accesses: List[Tuple[AttrKey, str, FrozenSet[str], Site]] = []


def _threadsafe_members(project: Project) -> Tuple[Set[AttrKey],
                                                   Set[AttrKey]]:
    """(exempt attr keys, exempt global keys): members bound to
    internally-synchronized objects anywhere in the project."""
    attrs: Set[AttrKey] = set()
    globs: Set[AttrKey] = set()

    def ctor_leaf(value: ast.AST) -> Optional[str]:
        if not isinstance(value, ast.Call):
            return None
        chain = dotted_name(value.func)
        return chain.split(".")[-1] if chain else None

    for mod in project.iter_modules():
        for fn in mod.functions.values():
            if fn.class_name is None:
                continue
            for node in ast.walk(fn.node):
                if not isinstance(node, ast.Assign):
                    continue
                leaf = ctor_leaf(node.value)
                if leaf not in _THREADSAFE_CTORS:
                    continue
                for t in node.targets:
                    if isinstance(t, ast.Attribute) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id == "self":
                        attrs.add(("attr",
                                   f"{mod.name}.{fn.class_name}", t.attr))
        for node in ast.iter_child_nodes(mod.tree):
            if isinstance(node, ast.Assign):
                leaf = ctor_leaf(node.value)
                if leaf in _THREADSAFE_CTORS:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            globs.add(("global", mod.name, t.id))
    return attrs, globs


def _module_globals(mod: ModuleInfo) -> Set[str]:
    out: Set[str] = set()
    for node in ast.iter_child_nodes(mod.tree):
        targets: List[ast.AST] = []
        if isinstance(node, ast.Assign):
            targets = list(node.targets)
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
            targets = [node.target]
        for t in targets:
            if isinstance(t, ast.Name):
                out.add(t.id)
    return out


def _summarize(project: Project, locks, fn: FunctionInfo,
               mod_globals: Set[str]) -> _FnSummary:
    _, _with_lock_ids = _lock_helpers()
    mod = fn.module
    s = _FnSummary()
    declared_global: Set[str] = set()
    for n in ast.walk(fn.node):
        if isinstance(n, ast.Global):
            declared_global.update(n.names)

    cls_key = f"{mod.name}.{fn.class_name}" if fn.class_name else None

    def is_self_attr(node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Attribute) \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "self" and cls_key is not None:
            return node.attr
        return None

    def global_name(node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Name) and node.id in mod_globals \
                and node.id not in fn.params \
                and (node.id in declared_global
                     or node.id not in fn.locals):
            return node.id
        return None

    def access(node: ast.AST, kind: str, held: FrozenSet[str]) -> None:
        site = (mod.path, node.lineno)
        attr = is_self_attr(node)
        if attr is not None:
            s.accesses.append((("attr", cls_key, attr), kind, held, site))
            return
        g = global_name(node)
        if g is not None:
            s.accesses.append((("global", mod.name, g), kind, held, site))

    def walk(node: ast.AST, held: FrozenSet[str]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return                      # separate function
        if isinstance(node, (ast.With, ast.AsyncWith)):
            new_held = held
            for item in node.items:
                walk(item.context_expr, new_held)
                lid = _with_lock_ids(project, locks, mod, fn, item)
                if lid is not None:
                    new_held = new_held | {lid}
            for child in node.body:
                walk(child, new_held)
            return
        if isinstance(node, ast.Assign):
            walk(node.value, held)
            for t in node.targets:
                _walk_target(t, held)
            return
        if isinstance(node, ast.AugAssign):
            walk(node.value, held)
            _walk_target(node.target, held, aug=True)
            return
        if isinstance(node, ast.Delete):
            for t in node.targets:
                _walk_target(t, held)
            return
        if isinstance(node, ast.Call):
            f = node.func
            # mutating method call on a shared container
            if isinstance(f, ast.Attribute) and f.attr in _MUTATORS:
                access(f.value, "write", held)
            tgt = project.resolve_call(mod, fn, node)
            if tgt is not None:
                s.calls.append((tgt, held))
            # by-reference function args stay on this thread's stack
            for a in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(a, ast.Name):
                    ref = project.resolve_name(mod, fn, a.id)
                    if ref is not None:
                        s.calls.append((ref, held))
        if isinstance(node, (ast.Attribute, ast.Name)) \
                and isinstance(getattr(node, "ctx", None), ast.Load):
            access(node, "read", held)
        for child in ast.iter_child_nodes(node):
            walk(child, held)

    def _walk_target(t: ast.AST, held: FrozenSet[str],
                     aug: bool = False) -> None:
        if isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                _walk_target(e, held, aug)
            return
        if isinstance(t, ast.Attribute):
            access(t, "write", held)
            walk(t.value, held)
            return
        if isinstance(t, ast.Subscript):
            # container[k] = v mutates the container the name holds
            access(t.value, "write", held)
            walk(t.value, held)
            walk(t.slice, held)
            return
        if isinstance(t, ast.Name):
            if aug or t.id in declared_global:
                access(t, "write", held)

    for child in ast.iter_child_nodes(fn.node):
        walk(child, frozenset())
    return s


# ------------------------------------------------------------ the engine
def analyze(project: Project) -> List[Finding]:
    _collect_locks, _ = _lock_helpers()
    locks = _collect_locks(project)
    entries = find_entrypoints(project)
    if not entries:
        return []
    exempt_attrs, exempt_globals = _threadsafe_members(project)
    mod_globals: Dict[str, Set[str]] = {}
    lock_globals: Set[Tuple[str, str]] = set(locks.module)

    summaries: Dict[FunctionInfo, _FnSummary] = {}

    def summary(fn: FunctionInfo) -> _FnSummary:
        if fn not in summaries:
            mg = mod_globals.get(fn.module.name)
            if mg is None:
                mg = {n for n in _module_globals(fn.module)
                      if (fn.module.name, n) not in lock_globals}
                mod_globals[fn.module.name] = mg
            summaries[fn] = _summarize(project, locks, fn, mg)
        return summaries[fn]

    # per-entrypoint must-hold fixpoint: inc[fn] = locks held on EVERY
    # discovered path from the entry to fn (intersection; shrinking)
    per_entry_access: Dict[AttrKey, Dict[int, List[_Access]]] = {}
    for ei, entry in enumerate(entries):
        inc: Dict[FunctionInfo, FrozenSet[str]] = {entry.fn: frozenset()}
        work = [entry.fn]
        while work:
            fn = work.pop()
            base = inc[fn]
            for callee, lexical in summary(fn).calls:
                held = base | lexical
                prev = inc.get(callee)
                new = held if prev is None else (prev & held)
                if prev is None or new != prev:
                    inc[callee] = new
                    work.append(callee)
        for fn, base in inc.items():
            for key, kind, lexical, site in summary(fn).accesses:
                if key[0] == "attr" and ("attr", key[1], key[2]) \
                        in exempt_attrs:
                    continue
                if key[0] == "global" and key in exempt_globals:
                    continue
                per_entry_access.setdefault(key, {}).setdefault(
                    ei, []).append(
                    _Access(key, kind, base | lexical, site, fn))

    findings: List[Finding] = []
    for key in sorted(per_entry_access):
        by_entry = per_entry_access[key]
        eids = sorted(by_entry)
        concurrent = len(eids) >= 2 \
            or any(entries[ei].pooled for ei in eids)
        if not concurrent:
            continue
        accesses = [a for ei in eids for a in by_entry[ei]]
        writes = [a for a in accesses if a.kind == "write"]
        if not writes:
            continue
        common = frozenset.intersection(*(a.guards for a in accesses))
        if common:
            continue
        kind, owner, member = key
        if kind == "attr":
            short_owner = ".".join(owner.rsplit(".", 2)[-2:])
            what = f"attribute `{short_owner}.{member}`"
        else:
            what = f"module global `{owner}.{member}`"
        witnesses = sorted({entries[ei].label() for ei in eids})
        unguarded = sorted({f"{os.path.basename(a.site[0])}:{a.site[1]}"
                            for a in accesses if not a.guards})[:4]
        # anchor at the racy side: the first unguarded write, else the
        # first unguarded access, else the first write — so a justified
        # `lint-ok[PT-RACE]` pragma lands on the line that IS the race
        def first(cands: Sequence[_Access]) -> Optional[Site]:
            sites = [a.site for a in cands]
            return min(sites) if sites else None

        anchor = first([a for a in writes if not a.guards]) \
            or first([a for a in accesses if not a.guards]) \
            or first(writes)
        findings.append(Finding(
            RULE, anchor[0], anchor[1], 0,
            f"{what} is shared between thread entrypoints "
            f"{', '.join(witnesses)} with a write and no common "
            "named_lock guard on all access paths (unguarded sites: "
            f"{', '.join(unguarded) or 'n/a'}) — a cross-thread data "
            "race; guard every access with one named_lock, or make "
            "the member a thread-safe primitive"))
    return findings


def run(project: Project) -> List[Finding]:
    return analyze(project)

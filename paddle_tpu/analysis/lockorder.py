"""Runtime lock-order checker: the dynamic half of PT-LOCK.

Static analysis (:mod:`paddle_tpu.analysis.rules.lock_order`) derives
the cross-module lock-acquisition graph from ``with lock:`` nesting and
proves it acyclic — but only for the nestings it can resolve.  This
module is the runtime witness for the rest: every framework lock is
created through :func:`named_lock` / :func:`named_condition`, and in
debug mode each *blocking* acquire records an edge from every lock the
thread already holds to the one it is about to take.  The accumulated
graph must stay acyclic; a cycle means two threads can acquire the same
pair of locks in opposite orders — a potential deadlock — and is
recorded as a violation **before** the acquire blocks, so the checker
reports the deadlock it just prevented from going silent instead of
hanging with it.

Production cost is one module-global bool test per acquire: with the
checker off (the default), ``_NamedLock.acquire`` is a flag check and a
delegation to the underlying ``threading`` primitive.  Debug mode is
enabled in tests (the chaos and pipeline suites) via::

    PADDLE_TPU_LOCK_ORDER_CHECK=1 pytest tests/test_chaos.py

or programmatically with :func:`enable`; violations accumulate in
:func:`violations` (and raise immediately when
``PADDLE_TPU_LOCK_ORDER_RAISE=1``), so a suite can run to completion
and assert the list is empty at teardown.

Naming: instances share a node per *name* — ``named_lock("stat.item")``
called N times yields N locks but one graph node, because lock-order
discipline is a property of the code path, not the instance.  Two
different instances under one name never form a self-edge (peers of one
class are unordered by design); re-acquiring the *same* non-reentrant
lock object on one thread is a guaranteed self-deadlock and is flagged.

Stdlib-only, imports nothing from the framework: every lock-owning
module (``utils.logger`` up) pulls this at interpreter startup.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional, Set, Tuple

__all__ = ["named_lock", "named_condition", "enable", "disable",
           "enabled", "reset", "edges", "violations", "check_acyclic",
           "LockOrderError"]

ENV_CHECK = "PADDLE_TPU_LOCK_ORDER_CHECK"
ENV_RAISE = "PADDLE_TPU_LOCK_ORDER_RAISE"


class LockOrderError(RuntimeError):
    """A lock-acquisition order violated the derived hierarchy."""


# The checker's own state guard.  Deliberately a PLAIN lock, not a
# named one: it is acquired while arbitrary production locks are held
# (production -> _graph_lock edges only, never the reverse — nothing
# under it acquires anything), so it can neither deadlock nor recurse.
_graph_lock = threading.Lock()
#: held-name -> {acquired-while-held names}
_edges: Dict[str, Set[str]] = {}
#: (src, dst) -> first witness "thread: held [..] -> acquired dst"
_edge_sites: Dict[Tuple[str, str], str] = {}
_violations: List[str] = []

_tls = threading.local()        # .held: List[(name, lock_obj_id)]

_enabled = os.environ.get(ENV_CHECK, "") not in ("", "0")
_raise = os.environ.get(ENV_RAISE, "") not in ("", "0")


def enabled() -> bool:
    return _enabled


def enable(raise_on_violation: Optional[bool] = None) -> None:
    """Turn the checker on (tests).  Locks created earlier participate
    too — checked-ness is a process-wide mode, not a creation-time
    property, so module-global locks born at import are covered."""
    global _enabled, _raise
    if raise_on_violation is not None:
        _raise = bool(raise_on_violation)
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def reset() -> None:
    """Drop the accumulated graph and violations (tests)."""
    with _graph_lock:
        _edges.clear()
        _edge_sites.clear()
        del _violations[:]


def edges() -> Dict[str, Set[str]]:
    """Copy of the observed acquisition graph: held -> {acquired}."""
    with _graph_lock:
        return {k: set(v) for k, v in _edges.items()}


def violations() -> List[str]:
    """Every recorded ordering violation (empty = hierarchy held)."""
    with _graph_lock:
        return list(_violations)


def check_acyclic() -> None:
    """Raise :class:`LockOrderError` if any violation was recorded —
    the one-call teardown assertion for a test suite."""
    v = violations()
    if v:
        raise LockOrderError(
            "lock-order violations observed:\n  " + "\n  ".join(v))


# ------------------------------------------------------------ recording
def _held() -> List[Tuple[str, int]]:
    held = getattr(_tls, "held", None)
    if held is None:
        held = _tls.held = []
    return held


def _path_exists(src: str, dst: str) -> bool:
    """DFS over _edges; caller holds _graph_lock."""
    stack, seen = [src], set()
    while stack:
        n = stack.pop()
        if n == dst:
            return True
        if n in seen:
            continue
        seen.add(n)
        stack.extend(_edges.get(n, ()))
    return False


def _cycle_path(src: str, dst: str) -> List[str]:
    """One src->dst path (exists by construction); holds _graph_lock."""
    stack: List[Tuple[str, List[str]]] = [(src, [src])]
    seen = set()
    while stack:
        n, path = stack.pop()
        if n == dst:
            return path
        if n in seen:
            continue
        seen.add(n)
        for m in _edges.get(n, ()):
            stack.append((m, path + [m]))
    return [src, dst]           # pragma: no cover — defensive


def _record_violation(msg: str) -> None:
    # caller holds _graph_lock
    _violations.append(msg)
    if _raise:
        raise LockOrderError(msg)


def _before_acquire(name: str, obj_id: int, deadlockable: bool) -> None:
    """Record ordering edges for a blocking acquire of ``name`` given
    the thread's current hold set — BEFORE blocking, so a true cycle is
    reported rather than demonstrated."""
    held = _held()
    if not held:
        return
    tname = threading.current_thread().name
    with _graph_lock:
        for hname, hid in held:
            if hname == name:
                if hid == obj_id and deadlockable:
                    _record_violation(
                        f"self-deadlock: thread {tname!r} re-acquiring "
                        f"non-reentrant lock {name!r} it already holds")
                # a *different* instance under the same name: peers of
                # one class are unordered, no edge
                continue
            if (hname, name) in _edge_sites:
                continue        # edge already witnessed
            if _path_exists(name, hname):
                cyc = _cycle_path(name, hname) + [name]
                _record_violation(
                    f"lock-order cycle: thread {tname!r} holds "
                    f"{hname!r} and acquires {name!r}, but the reverse "
                    f"order {' -> '.join(cyc)} was already observed "
                    f"({_edge_sites.get((name, cyc[1]), 'unknown site')})")
            _edges.setdefault(hname, set()).add(name)
            _edge_sites[(hname, name)] = (
                f"thread {tname!r} held [" +
                ", ".join(h for h, _ in held) + f"] -> acquired {name!r}")


def _after_acquire(name: str, obj_id: int) -> None:
    _held().append((name, obj_id))


def _after_release(name: str, obj_id: int) -> None:
    held = _held()
    for i in range(len(held) - 1, -1, -1):
        if held[i] == (name, obj_id):
            del held[i]
            return
    # release of a lock acquired before enable(): nothing tracked


# ------------------------------------------------------------- wrappers
class _NamedLock:
    """A ``threading.Lock``/``RLock`` under a graph-node name.

    Transparent when the checker is off; in debug mode every blocking
    acquire records hierarchy edges first.  Works as the lock argument
    of ``threading.Condition`` (bound ``acquire``/``release`` are all
    it uses), so condition waits release/re-acquire through the
    tracking too.
    """

    __slots__ = ("name", "_inner", "_reentrant")

    def __init__(self, name: str, inner, reentrant: bool):
        self.name = name
        self._inner = inner
        self._reentrant = reentrant

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if _enabled and blocking:
            # non-blocking probes (Condition._is_owned tests ownership
            # with acquire(False)) can't deadlock and are not ordering
            _before_acquire(self.name, id(self._inner),
                            deadlockable=(timeout < 0
                                          and not self._reentrant))
        got = self._inner.acquire(blocking, timeout)
        if got and _enabled:
            _after_acquire(self.name, id(self._inner))
        return got

    def release(self) -> None:
        self._inner.release()
        # always clean TLS, not only when enabled: a disable() between
        # acquire and release must not strand a held entry that fakes
        # hierarchy edges on this thread after the next enable()
        if getattr(_tls, "held", None):
            _after_release(self.name, id(self._inner))

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<named_lock {self.name!r} {self._inner!r}>"


def named_lock(name: str, reentrant: bool = False) -> _NamedLock:
    """A mutex that is a node named ``name`` in the lock-order graph.
    Drop-in for ``threading.Lock()`` (``reentrant=True`` for RLock)."""
    inner = threading.RLock() if reentrant else threading.Lock()
    return _NamedLock(name, inner, reentrant)


def named_condition(name: str) -> threading.Condition:
    """A ``threading.Condition`` whose underlying mutex is
    :func:`named_lock(name) <named_lock>` — waits release and
    re-acquire through the order tracking."""
    return threading.Condition(named_lock(name, reentrant=False))

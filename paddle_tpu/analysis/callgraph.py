"""Project index + best-effort call resolution for the lint rules.

One parse of every analyzed file feeds all five rules.  Resolution is
deliberately conservative: a call is resolved only when the target is
statically unambiguous (same-module function, ``self.method`` on the
enclosing class, a ``from x import f`` / ``import x as m; m.f()``
target inside the analyzed set, or a name bound to ``ClassName(...)``
in the same module).  Everything else is *unresolved* and simply does
not contribute edges — under-approximating the call graph keeps
PT-TRACE reachability and PT-LOCK edges free of false positives, at
the cost of not seeing through duck-typed attribute calls.
"""

from __future__ import annotations

import ast
import hashlib
import os
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

# ------------------------------------------------------------ parse cache
#: Indexed modules keyed by (abs path, content sha256): the tier-1 repo
#: sweep, the lock-graph dump, and every engine.run in one process
#: parse each file exactly ONCE for its content — rules always shared a
#: Project within a run; this shares the parse across runs too, so the
#: sweep cost stays flat as the rule count grows.  An edited file (new
#: hash) re-parses; the stale entry ages out at the next clear.
_MODULE_CACHE: Dict[Tuple[str, str], "ModuleInfo"] = {}
_MODULE_CACHE_MAX = 4096

#: Observability for the single-parse property (pinned by a test):
#: ``parses`` counts real ast.parse calls, ``cache_hits`` counts
#: content-hash reuses.
parse_stats = {"parses": 0, "cache_hits": 0}


def clear_parse_cache() -> None:
    _MODULE_CACHE.clear()
    parse_stats["parses"] = 0
    parse_stats["cache_hits"] = 0

# ------------------------------------------------------------------ data


class FunctionInfo:
    __slots__ = ("node", "module", "qualname", "class_name", "parent",
                 "params", "locals")

    def __init__(self, node: ast.AST, module: "ModuleInfo", qualname: str,
                 class_name: Optional[str], parent: Optional[str]):
        self.node = node
        self.module = module
        self.qualname = qualname
        self.class_name = class_name
        self.parent = parent        # enclosing function qualname (or None)
        self.params: Set[str] = set()
        self.locals: Set[str] = set()
        args = node.args
        for a in (list(args.posonlyargs) + list(args.args)
                  + list(args.kwonlyargs)):
            self.params.add(a.arg)
        if args.vararg:
            self.params.add(args.vararg.arg)
        if args.kwarg:
            self.params.add(args.kwarg.arg)

    def __repr__(self) -> str:
        return f"<fn {self.module.name}:{self.qualname}>"


def _local_names(node: ast.AST) -> Set[str]:
    """Names bound by assignment/for/with/comprehension DIRECTLY in this
    function (nested function bodies excluded)."""
    out: Set[str] = set()

    class V(ast.NodeVisitor):
        def visit_FunctionDef(self, n):   # don't descend into nested defs
            out.add(n.name)

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_ClassDef(self, n):
            out.add(n.name)

        def visit_Name(self, n):
            if isinstance(n.ctx, (ast.Store, ast.Del)):
                out.add(n.id)

        def visit_Import(self, n):
            for al in n.names:
                out.add((al.asname or al.name).split(".")[0])

        def visit_ImportFrom(self, n):
            for al in n.names:
                out.add(al.asname or al.name)

    v = V()
    for child in ast.iter_child_nodes(node):
        v.visit(child)
    return out


class ModuleInfo:
    def __init__(self, path: str, name: str, tree: ast.Module, source: str,
                 is_package: bool = False):
        self.path = path
        self.name = name            # dotted, e.g. paddle_tpu.data.pipeline
        self.is_package = is_package   # an __init__.py (name = the package)
        self.tree = tree
        self.source = source
        self.content_hash = ""      # sha256 of source (parse-cache key)
        self.lines = source.splitlines()
        self.imports: Dict[str, str] = {}        # alias -> dotted module
        self.from_imports: Dict[str, Tuple[str, str]] = {}  # n -> (mod, orig)
        self.functions: Dict[str, FunctionInfo] = {}
        self.classes: Dict[str, List[str]] = {}  # class -> method names
        self.str_constants: Dict[str, str] = {}  # NAME -> literal value
        self.instance_of: Dict[str, str] = {}    # var -> class qualname

    def short(self) -> str:
        n = self.name
        return n[len("paddle_tpu."):] if n.startswith("paddle_tpu.") else n


# ----------------------------------------------------------------- index


def _module_name_for(path: str) -> Tuple[str, bool]:
    """(dotted module name, is_package) from a file path: the file's
    stem prefixed with every ancestor directory that is itself a
    package (has an ``__init__.py``) — i.e. the name Python would
    import it under from the package root.  Two same-named files in
    different packages get distinct names instead of colliding."""
    path = os.path.normpath(os.path.abspath(path))
    stem = os.path.basename(path)
    if stem.endswith(".py"):
        stem = stem[:-3]
    parts = [stem]
    d = os.path.dirname(path)
    while d and os.path.isfile(os.path.join(d, "__init__.py")):
        parts.insert(0, os.path.basename(d))
        d = os.path.dirname(d)
    is_package = parts[-1] == "__init__"
    if is_package:
        parts = parts[:-1]
    return ".".join(parts) or "<module>", is_package


def _resolve_relative(base: str, is_package: bool, level: int,
                      module: Optional[str]) -> str:
    """``from ..x import y`` inside ``base`` → dotted absolute module.

    For a plain module ``a.b.c``, level 1 is its package ``a.b``; for a
    package ``__init__`` (base IS the package ``a.b``), level 1 is
    ``a.b`` itself — a package's name already ends at its own level.
    """
    pkg = base.split(".")
    if not is_package:
        pkg = pkg[:-1]
    up = level - 1
    pkg = pkg[: len(pkg) - up] if up <= len(pkg) else []
    if module:
        pkg = pkg + module.split(".")
    return ".".join(pkg)


class _Indexer(ast.NodeVisitor):
    def __init__(self, mod: ModuleInfo):
        self.mod = mod
        self.scope: List[str] = []          # qualname parts
        self.class_stack: List[str] = []
        self.func_stack: List[str] = []     # enclosing function qualnames

    # ---- imports (collected wherever they appear, incl. inside funcs)
    def visit_Import(self, node: ast.Import) -> None:
        for al in node.names:
            if al.asname:               # import a.b as m -> m: a.b
                self.mod.imports[al.asname] = al.name
            else:                       # import a.b -> binds a
                root = al.name.split(".")[0]
                self.mod.imports[root] = root

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        src = _resolve_relative(self.mod.name, self.mod.is_package,
                                node.level, node.module) \
            if node.level else (node.module or "")
        for al in node.names:
            self.mod.from_imports[al.asname or al.name] = (src, al.name)

    # ---- defs
    def _enter_def(self, node) -> None:
        qual = ".".join(self.scope + [node.name])
        cls = self.class_stack[-1] if self.class_stack else None
        parent = self.func_stack[-1] if self.func_stack else None
        info = FunctionInfo(node, self.mod, qual, cls, parent)
        info.locals = _local_names(node)
        self.mod.functions[qual] = info
        if cls is not None and not self.func_stack:
            self.mod.classes.setdefault(cls, []).append(node.name)
        self.scope.append(node.name)
        self.func_stack.append(qual)
        self.generic_visit(node)
        self.func_stack.pop()
        self.scope.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._enter_def(node)

    def visit_AsyncFunctionDef(self, node) -> None:
        self._enter_def(node)

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.mod.classes.setdefault(node.name, [])
        self.scope.append(node.name)
        self.class_stack.append(node.name)
        self.generic_visit(node)
        self.class_stack.pop()
        self.scope.pop()

    # ---- module-level simple facts
    def visit_Assign(self, node: ast.Assign) -> None:
        if not self.func_stack and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            tgt = node.targets[0].id
            if isinstance(node.value, ast.Constant) \
                    and isinstance(node.value.value, str):
                self.mod.str_constants[tgt] = node.value.value
            elif isinstance(node.value, ast.Call):
                cls = dotted_name(node.value.func)
                if cls:
                    self.mod.instance_of[tgt] = cls
        self.generic_visit(node)


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` attribute chain → "a.b.c" (None for anything else)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class Project:
    def __init__(self) -> None:
        self.modules: Dict[str, ModuleInfo] = {}       # dotted name -> info
        self.by_path: Dict[str, ModuleInfo] = {}

    # ------------------------------------------------------------ loading
    def add_file(self, path: str) -> Optional[ModuleInfo]:
        path = os.path.abspath(path)
        if path in self.by_path:
            return self.by_path[path]
        try:
            with open(path, "r", encoding="utf-8") as f:
                source = f.read()
        except (OSError, ValueError):
            return None
        sha = hashlib.sha256(source.encode("utf-8", "replace")) \
            .hexdigest()
        mod = _MODULE_CACHE.get((path, sha))
        if mod is None:
            try:
                tree = ast.parse(source, filename=path)
            except (SyntaxError, ValueError):
                return None
            parse_stats["parses"] += 1
            name, is_package = _module_name_for(path)
            mod = ModuleInfo(path, name, tree, source, is_package)
            mod.content_hash = sha
            _Indexer(mod).visit(tree)
            if len(_MODULE_CACHE) >= _MODULE_CACHE_MAX:
                _MODULE_CACHE.clear()       # simple bound; re-warm
            _MODULE_CACHE[(path, sha)] = mod
        else:
            parse_stats["cache_hits"] += 1
        # first registration wins the NAME (the import-resolution key);
        # the file is analyzed either way — rules iterate by path
        self.modules.setdefault(mod.name, mod)
        self.by_path[path] = mod
        return mod

    def iter_modules(self):
        """Every parsed file, exactly once — rule loops use this, not
        ``modules.values()``, so a module-name collision can never
        silently drop a file from analysis."""
        return self.by_path.values()

    # --------------------------------------------------------- resolution
    def module_for(self, dotted: str) -> Optional[ModuleInfo]:
        return self.modules.get(dotted)

    def _function_in(self, dotted_mod: str, name: str) \
            -> Optional[FunctionInfo]:
        mod = self.modules.get(dotted_mod)
        if mod is None:
            return None
        fn = mod.functions.get(name)
        if fn is not None:
            return fn
        # re-export through a package __init__: follow one from-import hop
        tgt = mod.from_imports.get(name)
        if tgt is not None and tgt[0] in self.modules:
            return self.modules[tgt[0]].functions.get(tgt[1])
        return None

    def resolve_name(self, mod: ModuleInfo, fn: Optional[FunctionInfo],
                     name: str) -> Optional[FunctionInfo]:
        """A bare ``Name`` in call position → FunctionInfo (or None)."""
        # innermost nested def first: f.qualname + "." + name, walking up
        cur = fn
        while cur is not None:
            cand = mod.functions.get(cur.qualname + "." + name)
            if cand is not None:
                return cand
            cur = mod.functions.get(cur.parent) if cur.parent else None
        cand = mod.functions.get(name)
        if cand is not None:
            return cand
        tgt = mod.from_imports.get(name)
        if tgt is not None:
            return self._function_in(tgt[0], tgt[1])
        return None

    def resolve_call(self, mod: ModuleInfo, fn: Optional[FunctionInfo],
                     call: ast.Call) -> Optional[FunctionInfo]:
        func = call.func
        if isinstance(func, ast.Name):
            return self.resolve_name(mod, fn, func.id)
        if isinstance(func, ast.Attribute):
            base = func.value
            attr = func.attr
            if isinstance(base, ast.Name):
                if base.id == "self" and fn is not None and fn.class_name:
                    return mod.functions.get(fn.class_name + "." + attr)
                # import x as m; m.f()
                if base.id in mod.imports:
                    return self._function_in(mod.imports[base.id], attr)
                # from . import observe; observe.f()
                tgt = mod.from_imports.get(base.id)
                if tgt is not None:
                    dotted = (tgt[0] + "." + tgt[1]) if tgt[0] else tgt[1]
                    got = self._function_in(dotted, attr)
                    if got is not None:
                        return got
                # _global = ClassName(...); _global.f()
                cls = mod.instance_of.get(base.id)
                if cls is not None and "." not in cls:
                    return mod.functions.get(cls + "." + attr)
            # a.b.c.f(): resolve the chain as a module path
            chain = dotted_name(func)
            if chain:
                parts = chain.split(".")
                root = parts[0]
                if root in mod.imports:
                    parts = mod.imports[root].split(".") + parts[1:]
                elif root in mod.from_imports:
                    src, orig = mod.from_imports[root]
                    parts = (src.split(".") if src else []) + [orig] \
                        + parts[1:]
                for cut in range(len(parts) - 1, 0, -1):
                    m2 = ".".join(parts[:cut])
                    if m2 in self.modules:
                        return self._function_in(m2, ".".join(parts[cut:]))
        return None

    # ---------------------------------------------------- name → module ref
    def names_module(self, mod: ModuleInfo, name: str,
                     target: str) -> bool:
        """Does ``name`` in ``mod`` refer to (a submodule of) the
        external module ``target`` (e.g. "numpy", "time", "jax")?"""
        dotted = mod.imports.get(name)
        if dotted is not None:
            return dotted == target or dotted.startswith(target + ".")
        fi = mod.from_imports.get(name)
        if fi is not None:
            full = (fi[0] + "." + fi[1]) if fi[0] else fi[1]
            return full == target or full.startswith(target + ".")
        return False


def iter_calls(node: ast.AST) -> Iterable[ast.Call]:
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            yield n


def own_statements(fn_node: ast.AST) -> Iterable[ast.AST]:
    """Walk a function body WITHOUT descending into nested defs/lambdas
    (their bodies are separate functions with their own reachability)."""
    stack: List[ast.AST] = list(ast.iter_child_nodes(fn_node))
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(n))

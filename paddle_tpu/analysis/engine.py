"""Lint engine: file walking, pragma suppression, baselines, reporting.

The engine owns everything rule-agnostic: it parses every target file
once into a shared :class:`~paddle_tpu.analysis.callgraph.Project`,
runs each registered rule over it, filters findings through the
``# ptpu: lint-ok[RULE]`` pragmas and an optional baseline file, and
renders text/JSON reports.  Rules never read files or comments — they
see ASTs and emit :class:`Finding`s; suppression is centralized here so
every rule gets identical pragma semantics for free.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import io
import json
import os
import re
import tokenize
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .callgraph import ModuleInfo, Project

#: Every rule family, in report order.
RULE_CODES = ("PT-TRACE", "PT-RECOMPILE", "PT-RESOURCE", "PT-DTYPE",
              "PT-LOCK", "PT-METRIC", "PT-SHAPE", "PT-SHARD", "PT-RACE")

_PRAGMA_RE = re.compile(
    r"#\s*ptpu:\s*lint-ok\[([A-Za-z0-9_, \-]+)\]")


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str          # absolute
    line: int
    col: int
    message: str

    def relpath(self, root: Optional[str] = None) -> str:
        base = root or os.getcwd()
        try:
            rel = os.path.relpath(self.path, base)
        except ValueError:          # different drive (windows)
            return self.path
        return self.path if rel.startswith("..") else rel

    @property
    def fingerprint(self) -> str:
        """Line-number-free identity, so a baseline survives unrelated
        edits above the finding.  Keyed on the cwd-relative path (write
        and consume baselines from the same directory, i.e. the repo
        root) — a bare basename would let a baselined finding in one
        ``__init__.py`` mask a brand-new identical one in another."""
        raw = f"{self.rule}|{self.relpath()}|{self.message}"
        return hashlib.sha256(raw.encode()).hexdigest()[:16]

    def render(self, root: Optional[str] = None) -> str:
        return (f"{self.relpath(root)}:{self.line}:{self.col}: "
                f"{self.rule} {self.message}")


#: Pragma tables keyed by file content hash — tokenizing is the other
#: per-file cost the repo sweep pays; cached alongside the parse cache
#: (callgraph._MODULE_CACHE) so repeated runs tokenize each file once.
_PRAGMA_CACHE: Dict[str, Dict[int, Set[str]]] = {}
_PRAGMA_CACHE_MAX = 4096


def _pragmas_for(mod: ModuleInfo) -> Dict[int, Set[str]]:
    key = getattr(mod, "content_hash", "")
    if key and key in _PRAGMA_CACHE:
        return _PRAGMA_CACHE[key]
    table = _pragmas(mod.source)
    if key:
        if len(_PRAGMA_CACHE) >= _PRAGMA_CACHE_MAX:
            _PRAGMA_CACHE.clear()
        _PRAGMA_CACHE[key] = table
    return table


def _pragmas(source: str) -> Dict[int, Set[str]]:
    """line number → set of rule codes suppressed on that line."""
    out: Dict[int, Set[str]] = {}
    try:
        toks = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in toks:
            if tok.type != tokenize.COMMENT:
                continue
            m = _PRAGMA_RE.search(tok.string)
            if m:
                codes = {c.strip() for c in m.group(1).split(",")
                         if c.strip()}
                out.setdefault(tok.start[0], set()).update(codes)
    except tokenize.TokenError:
        pass
    return out


def _is_suppressed(f: Finding, pragmas: Dict[int, Set[str]],
                   lines: Sequence[str]) -> bool:
    for ln in (f.line, f.line - 1):
        codes = pragmas.get(ln)
        if not codes:
            continue
        if f.rule in codes or "ALL" in codes:
            if ln == f.line:
                return True
            # the line above only suppresses when it is a comment-only
            # line (a trailing pragma governs its own line, not the next)
            text = lines[ln - 1].strip() if 0 < ln <= len(lines) else ""
            if text.startswith("#"):
                return True
    return False


@dataclasses.dataclass
class Result:
    findings: List[Finding]
    suppressed: List[Finding]
    baselined: List[Finding]
    files: int

    @property
    def exit_code(self) -> int:
        return 1 if self.findings else 0

    def to_json(self, root: Optional[str] = None) -> str:
        def row(f: Finding) -> Dict[str, object]:
            return {"rule": f.rule, "path": f.relpath(root),
                    "line": f.line, "col": f.col, "message": f.message,
                    "fingerprint": f.fingerprint}

        return json.dumps({
            "files": self.files,
            "findings": [row(f) for f in self.findings],
            "suppressed": len(self.suppressed),
            "baselined": len(self.baselined),
        }, indent=2)

    def to_text(self, root: Optional[str] = None) -> str:
        lines = [f.render(root) for f in self.findings]
        lines.append(
            f"ptpu-lint: {len(self.findings)} finding(s) in "
            f"{self.files} file(s) "
            f"({len(self.suppressed)} suppressed by pragma, "
            f"{len(self.baselined)} baselined)")
        return "\n".join(lines)


# ----------------------------------------------------------------- walk
def collect_files(paths: Sequence[str]) -> List[str]:
    """Expand files/dirs into a sorted list of .py files (pycache and
    hidden dirs skipped)."""
    out: Set[str] = set()
    for p in paths:
        p = os.path.abspath(p)
        if os.path.isfile(p):
            if p.endswith(".py"):
                out.add(p)
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = [d for d in dirnames
                           if d != "__pycache__" and not d.startswith(".")]
            for fn in filenames:
                if fn.endswith(".py"):
                    out.add(os.path.join(dirpath, fn))
    return sorted(out)


def load_baseline(path: str) -> Set[str]:
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    if isinstance(data, dict):
        return set(data.get("fingerprints", []))
    return set(data)


def write_baseline(path: str, result: Result) -> None:
    fps = sorted({f.fingerprint for f in result.findings}
                 | {f.fingerprint for f in result.baselined})
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"fingerprints": fps}, f, indent=2)
        f.write("\n")


# ------------------------------------------------------------------ run
def build_project(paths: Sequence[str]) -> Tuple[Project, List[str]]:
    project = Project()
    files = collect_files(paths)
    loaded = []
    for path in files:
        if project.add_file(path) is not None:
            loaded.append(path)
    return project, loaded


def run(paths: Sequence[str],
        rules: Optional[Sequence[str]] = None,
        baseline: Optional[Set[str]] = None) -> Result:
    """Analyze ``paths`` with the selected rule families (default all)."""
    from .rules import ALL_RULES

    project, files = build_project(paths)
    selected = list(rules) if rules else list(RULE_CODES)
    unknown = [r for r in selected if r not in ALL_RULES]
    if unknown:
        raise ValueError(f"unknown rule(s) {unknown!r}; "
                         f"choose from {sorted(ALL_RULES)}")

    raw: List[Finding] = []
    for code in selected:
        raw.extend(ALL_RULES[code](project))

    kept: List[Finding] = []
    suppressed: List[Finding] = []
    baselined: List[Finding] = []
    for f in sorted(raw, key=lambda f: (f.path, f.line, f.col, f.rule)):
        mod = project.by_path.get(f.path)
        if mod is None:                      # pragma: no cover — defensive
            kept.append(f)
            continue
        pragmas = _pragmas_for(mod)     # content-hash cached
        if _is_suppressed(f, pragmas, mod.lines):
            suppressed.append(f)
        elif baseline and f.fingerprint in baseline:
            baselined.append(f)
        else:
            kept.append(f)
    return Result(kept, suppressed, baselined, len(files))

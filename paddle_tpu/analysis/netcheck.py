"""PT-SHAPE / PT-SHARD core: whole-model shape, dtype and sharding
verification WITHOUT building a device program.

The reference verified its proto-configured layer graph at config time
— ``paddle/gserver`` layers were checked against ``ModelConfig`` before
``paddle train`` ever touched a device — and this module restores that
capability for the rebuild: an abstract interpreter that walks a
``ModelConfig``'s layer graph propagating symbolic per-layer shapes and
dtypes (no jax import, no tracing), a static re-derivation of the
conv→BN fusion peepholes (:func:`fusion_plan` is the ONE implementation
``layers/network.py`` builds from, so the static census can never drift
from the runtime ``network_conv_bn_fused_pairs`` gauge), and a
``ShardingRules``-table verifier that fails a bad rule in milliseconds
instead of at pod-compile time.

Everything here is **duck-typed** over the config IR: a "config" is
anything with ``.layers`` / ``.sub_models`` / ``.output_layer_names`` /
``.evaluators``, a "layer" anything with ``.name`` / ``.type`` /
``.size`` / ``.inputs`` / ``.attrs``, an "input" anything with
``.input_layer_name``.  The real
:class:`paddle_tpu.config.model_config.ModelConfig` satisfies this, and
so do the lightweight records the PT-SHAPE lint rule extracts from DSL
call sites — which is what keeps this module (and the whole analysis
package) stdlib-only and jax-free.

Issue severities: ``"error"`` findings are contradictions that will
fail at trace/compile time (the preflight raises on them);
``"warn"`` findings are order/coverage surprises worth a look but
legal (the lint rule only reports errors).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Iterable, List, Optional, Sequence, Set, Tuple

#: conv/BN layer-type families — mirrors layers/network.py's registry
#: aliases (``register_layer`` names for the conv and batch_norm layers).
CONV_TYPES = ("exconv", "cudnn_conv", "conv", "mkldnn_conv")
BN_TYPES = ("batch_norm", "cudnn_batch_norm", "mkldnn_batch_norm")


@dataclasses.dataclass(frozen=True)
class Issue:
    """One verifier finding.  ``path`` is the layer-path provenance:
    the producer chain that feeds the offending layer (innermost
    last), prefixed with the sub-model name for group layers."""

    kind: str                # "shape" | "dtype" | "shard"
    severity: str            # "error" | "warn"
    where: str               # layer name or rule/param identity
    message: str
    path: Tuple[str, ...] = ()

    def render(self) -> str:
        prov = " -> ".join(self.path)
        loc = f"{self.where}" + (f" (via {prov})" if prov else "")
        return f"[{self.kind}/{self.severity}] {loc}: {self.message}"


# ===================================================== shape inference
@dataclasses.dataclass
class ValueInfo:
    """Abstract value of one layer output: symbolic batch (and time for
    sequences) with a concrete feature size when statically known."""

    size: Optional[int] = None      # feature width; None = unknown
    dtype: str = "float"            # "float" | "int" | "?" (unknown)
    seq: bool = False               # carries a time dimension
    channels: Optional[int] = None  # image geometry when known
    img_x: Optional[int] = None
    img_y: Optional[int] = None

    def shape_str(self) -> str:
        dims = ["B"]
        if self.seq:
            dims.append("T")
        if self.channels and self.img_x:
            dims += [str(self.img_x), str(self.img_y or self.img_x),
                     str(self.channels)]
        else:
            dims.append(str(self.size) if self.size else "?")
        return "[" + ", ".join(dims) + "]"


def _conv_out(img: int, filt: int, pad: int, stride: int) -> int:
    return (img + 2 * pad - filt) // stride + 1


# cost-layer types whose (input, label) sizes must agree and whose
# label must be an integer class id
_CLASS_COSTS = ("multi-class-cross-entropy", "cross-entropy",
                "cross-entropy-with-selfnorm")
# regression costs: input and label are same-width dense floats
_REG_COSTS = ("square_error", "smooth_l1", "huber_regression")
# width-preserving elementwise layers: output size == input size
_ELEMENTWISE = ("dropout", "clip", "scale_shift", "slope_intercept",
                "batch_norm", "cudnn_batch_norm", "mkldnn_batch_norm",
                "norm", "layer_norm", "prelu")


class _Graph:
    """One (sub-)graph's layer records + the environment of inferred
    values, shared with the parent graph for in-links/memories."""

    def __init__(self, layers: Sequence[Any], env: Dict[str, ValueInfo],
                 group: str = "", float_name: str = "float32"):
        self.layers = list(layers)
        self.env = env
        self.group = group
        self.float_name = float_name    # policy output dtype for floats
        self.by_name = {l.name: l for l in self.layers}


def _layer_path(graph: _Graph, name: str, depth: int = 4) -> Tuple[str, ...]:
    """Producer chain feeding ``name`` (oldest first), for provenance."""
    chain: List[str] = []
    cur = name
    seen: Set[str] = set()
    while cur in graph.by_name and cur not in seen and len(chain) < depth:
        seen.add(cur)
        chain.append(cur)
        ins = [i.input_layer_name for i in graph.by_name[cur].inputs]
        if not ins:
            break
        cur = ins[0]
    if cur not in seen and cur:
        chain.append(cur)
    prefix = (graph.group + "/") if graph.group else ""
    return tuple(prefix + n for n in reversed(chain))


def check_model(config: Any, policy: Optional[Tuple[str, str]] = None
                ) -> List[Issue]:
    """Verify a ModelConfig-like object; returns all issues found.

    ``policy``: the resolved precision policy as ``(compute_dtype,
    output_dtype)`` NAMES (``core/dtypes.py`` vocabulary —
    ``NeuralNetwork.verify()`` passes the live one).  Float values
    propagate as the policy *output* dtype, so a report under
    ``--bf16_activations`` says ``bfloat16`` where it means it; the
    mismatch lattice itself only distinguishes int / float-like / "?".
    """
    float_name = (policy or ("float32", "float32"))[1]
    issues: List[Issue] = []
    sub_layer_names: Set[str] = set()
    for sm in getattr(config, "sub_models", []) or []:
        if sm.name == "root":
            continue
        sub_layer_names.update(sm.layer_names)

    root_layers = [l for l in config.layers
                   if l.name not in sub_layer_names or l.type == "data"]
    env: Dict[str, ValueInfo] = {}
    # pre-seed declared sizes of group layers + memory links so group
    # out-links and boot layers resolve when the root graph reads them
    for l in config.layers:
        if l.name in sub_layer_names:
            env.setdefault(l.name, ValueInfo(size=l.size or None))
    for sm in getattr(config, "sub_models", []) or []:
        for mem in sm.memories:
            link = mem.get("link_name") or mem.get("layer_name", "") + "@pre"
            size = mem.get("size", 0)
            if not size and mem.get("layer_name") in env:
                size = env[mem["layer_name"]].size or 0
            env[link] = ValueInfo(size=size or None, seq=False)

    graph = _Graph(root_layers, env, float_name=float_name)
    _check_graph(graph, issues)

    # group bodies: same interpreter, sized in/out links pre-seeded
    for sm in getattr(config, "sub_models", []) or []:
        if sm.name == "root" or sm.is_generating:
            continue
        body = [l for l in config.layers if l.name in set(sm.layer_names)]
        sub = _Graph(body, env, group=sm.name, float_name=float_name)
        _check_graph(sub, issues)

    issues.extend(_check_shared_params(config))
    return issues


def _value_of(graph: _Graph, name: str) -> Optional[ValueInfo]:
    if name in graph.env:
        return graph.env[name]
    base = name.split(".", 1)[0]     # sub-output ("fc.logits")
    return graph.env.get(base)


def _err(issues: List[Issue], graph: _Graph, layer: Any,
         msg: str, kind: str = "shape", severity: str = "error") -> None:
    issues.append(Issue(kind, severity, layer.name, msg,
                        _layer_path(graph, layer.name)))


def _check_graph(graph: _Graph, issues: List[Issue]) -> None:
    for layer in graph.layers:
        lt = layer.type
        name = layer.name
        attrs = getattr(layer, "attrs", {}) or {}
        ins: List[Optional[ValueInfo]] = []
        for li in layer.inputs:
            v = _value_of(graph, li.input_layer_name)
            if v is None and lt != "data":
                _err(issues, graph, layer,
                     f"input {li.input_layer_name!r} has no producer "
                     "in this graph")
            ins.append(v)

        out = ValueInfo()
        if lt == "data":
            kind = attrs.get("kind", "dense")
            out = ValueInfo(size=layer.size or None,
                            dtype="?" if kind == "?"
                            else ("int" if kind == "index" else "float"),
                            seq=bool(attrs.get("seq_level", 0)))
        elif lt == "embedding":
            if ins and ins[0] is not None \
                    and ins[0].dtype not in ("int", "?"):
                _err(issues, graph, layer,
                     "embedding lookup over a non-integer input "
                     f"(producer is {ins[0].dtype}, shape "
                     f"{ins[0].shape_str()}) — ids must be an index "
                     "input", kind="dtype")
            vocab = attrs.get("vocab_size")
            if vocab and ins and ins[0] is not None \
                    and ins[0].dtype == "int" and ins[0].size \
                    and vocab < ins[0].size:
                _err(issues, graph, layer,
                     f"embedding table has {vocab} rows but its id "
                     f"input declares a {ins[0].size}-value range — "
                     f"ids {vocab}..{ins[0].size - 1} index past the "
                     "table (size the table to the id space, or the "
                     "lookup clips/zero-fills silently)")
            out = ValueInfo(size=layer.size or None,
                            seq=bool(ins and ins[0] and ins[0].seq))
        elif lt in CONV_TYPES:
            # NB: "exconvt" (transposed conv) deliberately falls to the
            # opaque branch — its output geometry is the transpose
            # formula, not _conv_out's, so no forward-conv check may
            # judge it (no-false-positive discipline)
            out = _check_conv(graph, layer, attrs, ins, issues)
        elif lt == "pool":
            out = _check_pool(graph, layer, attrs, ins, issues)
        elif lt in _ELEMENTWISE:
            src = ins[0] if ins else None
            if src is not None and src.size and layer.size \
                    and src.size != layer.size:
                _err(issues, graph, layer,
                     f"{lt} declares size {layer.size} but its input "
                     f"is {src.shape_str()} — width-preserving layers "
                     "cannot change the feature size")
            out = dataclasses.replace(src) if src is not None \
                else ValueInfo(size=layer.size or None)
            if lt in BN_TYPES:
                _check_bn_channels(graph, layer, attrs, src, issues)
        elif lt == "addto":
            sizes = {v.size for v in ins if v is not None and v.size}
            if len(sizes) > 1:
                _err(issues, graph, layer,
                     "addto inputs disagree on width: "
                     + ", ".join(f"{li.input_layer_name}="
                                 f"{v.size if v else '?'}"
                                 for li, v in zip(layer.inputs, ins)))
            src = next((v for v in ins if v is not None), None)
            out = dataclasses.replace(src) if src is not None \
                else ValueInfo(size=layer.size or None)
        elif lt == "concat":
            known = [v.size for v in ins if v is not None]
            if all(known) and known and layer.size \
                    and sum(known) != layer.size:
                _err(issues, graph, layer,
                     f"concat declares size {layer.size} but its "
                     f"inputs sum to {sum(known)}")
            out = ValueInfo(size=layer.size or None,
                            seq=bool(ins and ins[0] and ins[0].seq))
        elif lt == "cos_sim":
            if len(ins) == 2 and all(v is not None and v.size
                                     for v in ins) \
                    and ins[0].size != ins[1].size \
                    and 1 not in (ins[0].size, ins[1].size):
                _err(issues, graph, layer,
                     f"cos_sim inputs have different widths "
                     f"{ins[0].size} vs {ins[1].size}")
            out = ValueInfo(size=1)
        elif lt in _CLASS_COSTS:
            _check_class_cost(graph, layer, ins, issues)
            out = ValueInfo(size=1)
        elif lt in _REG_COSTS:
            if len(ins) >= 2 and all(v is not None and v.size
                                     for v in ins[:2]) \
                    and ins[0].size != ins[1].size:
                _err(issues, graph, layer,
                     f"{lt} input width {ins[0].size} != label width "
                     f"{ins[1].size}")
            if len(ins) >= 2 and ins[1] is not None \
                    and ins[1].dtype == "int":
                _err(issues, graph, layer,
                     f"{lt} regresses against an integer label — use "
                     "a dense target (or a classification cost)",
                     kind="dtype")
            out = ValueInfo(size=1)
        elif lt in ("seqlastins", "seqfirstins", "max_id"):
            src = ins[0] if ins else None
            out = ValueInfo(size=(src.size if src else None)
                            if lt != "max_id" else 1,
                            dtype="int" if lt == "max_id"
                            else (src.dtype if src else "float"))
        else:
            # unknown/opaque layer type: trust the declared size, keep
            # sequence-ness of the first input (under-approximation —
            # no checks, no false positives)
            out = ValueInfo(size=layer.size or None,
                            seq=bool(ins and ins[0] and ins[0].seq),
                            dtype=(ins[0].dtype if ins and ins[0]
                                   else "float"))
        # fc consumes any input width (per-timestep over sequences)
        if lt == "fc":
            out = ValueInfo(size=layer.size or None,
                            seq=bool(ins and ins[0] and ins[0].seq))
        # float values carry the policy-resolved output dtype name, so
        # reports under --bf16_activations say bfloat16 where they
        # mean it (the mismatch lattice is int / float-like / "?")
        if out.dtype == "float":
            out = dataclasses.replace(out, dtype=graph.float_name)
        graph.env[name] = out


def _check_conv(graph: _Graph, layer: Any, attrs: Dict[str, Any],
                ins: List[Optional[ValueInfo]],
                issues: List[Issue]) -> ValueInfo:
    c = attrs.get("channels")
    img = attrs.get("img_size")
    img_y = attrs.get("img_size_y", img)
    nf = attrs.get("num_filters")
    fs = attrs.get("filter_size")
    stride = attrs.get("stride", 1)
    pad = attrs.get("padding", 0)
    groups = attrs.get("groups", 1)
    src = ins[0] if ins else None
    if c and img and img_y and src is not None and src.size \
            and c * img * img_y != src.size:
        _err(issues, graph, layer,
             f"conv expects input {c}ch × {img}×{img_y} = "
             f"{c * img * img_y} values but its producer supplies "
             f"{src.shape_str()} — wrong num_channels/img_size for "
             "this input")
    if groups and c and c % groups:
        _err(issues, graph, layer,
             f"conv groups={groups} does not divide input "
             f"channels={c}")
    if groups and nf and nf % groups:
        _err(issues, graph, layer,
             f"conv groups={groups} does not divide "
             f"num_filters={nf}")
    out_x = attrs.get("output_x")
    out_y = attrs.get("output_y")
    if img and fs is not None and out_x is None:
        out_x = _conv_out(img, fs, pad, stride)
        out_y = _conv_out(img_y, fs, pad, stride)
    if out_x is not None and out_x <= 0:
        _err(issues, graph, layer,
             f"conv geometry collapses: image {img}×{img_y} with "
             f"filter {fs}, stride {stride}, padding {pad} yields a "
             f"{out_x}-wide output")
    if nf and out_x and out_y and layer.size \
            and nf * out_x * out_y != layer.size:
        _err(issues, graph, layer,
             f"conv declares size {layer.size} but computes "
             f"{nf}×{out_x}×{out_y} = {nf * out_x * out_y}")
    return ValueInfo(size=layer.size or (nf * out_x * out_y
                                         if nf and out_x and out_y
                                         else None),
                     channels=nf, img_x=out_x, img_y=out_y)


def _check_pool(graph: _Graph, layer: Any, attrs: Dict[str, Any],
                ins: List[Optional[ValueInfo]],
                issues: List[Issue]) -> ValueInfo:
    c = attrs.get("channels")
    img = attrs.get("img_size")
    img_y = attrs.get("img_size_y", img)
    ps = attrs.get("pool_size")
    stride = attrs.get("stride", 2)
    pad = attrs.get("padding", 0)
    src = ins[0] if ins else None
    if c and img and img_y and src is not None and src.size \
            and c * img * img_y != src.size:
        _err(issues, graph, layer,
             f"pool expects input {c}ch × {img}×{img_y} = "
             f"{c * img * img_y} values but its producer supplies "
             f"{src.shape_str()}")
    out_x = out_y = None
    if img and ps is not None:
        out_x = _conv_out(img, ps, pad, stride)
        out_y = _conv_out(img_y, ps, pad, stride)
        if out_x <= 0:
            _err(issues, graph, layer,
                 f"pool geometry collapses: image {img}×{img_y} with "
                 f"window {ps}, stride {stride}, padding {pad}")
        elif c and layer.size and c * out_x * out_y != layer.size:
            _err(issues, graph, layer,
                 f"pool declares size {layer.size} but computes "
                 f"{c}×{out_x}×{out_y} = {c * out_x * out_y}")
    return ValueInfo(size=layer.size or None, channels=c,
                     img_x=out_x, img_y=out_y)


def _check_bn_channels(graph: _Graph, layer: Any, attrs: Dict[str, Any],
                       src: Optional[ValueInfo],
                       issues: List[Issue]) -> None:
    c = attrs.get("channels")
    img = attrs.get("img_size")
    img_y = attrs.get("img_size_y", img)
    size = layer.size or (src.size if src else None)
    if c and img and img_y and size and c * img * img_y != size:
        _err(issues, graph, layer,
             f"batch_norm normalizes {c} channels over a {img}×{img_y}"
             f" image = {c * img * img_y} values, but the layer is "
             f"{size} wide — wrong num_channels for this input")
    elif c and not img and size and c != size:
        _err(issues, graph, layer,
             f"batch_norm (no image geometry) normalizes {c} channels "
             f"but the layer is {size} wide")


def _check_class_cost(graph: _Graph, layer: Any,
                      ins: List[Optional[ValueInfo]],
                      issues: List[Issue]) -> None:
    if len(ins) < 2:
        return
    pred, label = ins[0], ins[1]
    if pred is not None and label is not None \
            and pred.size and label.size and pred.size != label.size:
        _err(issues, graph, layer,
             f"classification cost reads {pred.size} class "
             f"probabilities but the label layer declares "
             f"{label.size} classes")
    if label is not None and label.dtype not in ("int", "?"):
        _err(issues, graph, layer,
             "classification cost needs an integer class-id label, "
             f"got a {label.dtype} input {label.shape_str()}",
             kind="dtype")


def _check_shared_params(config: Any) -> List[Issue]:
    """Statically derivable parameter shapes must agree across sharing
    layers (the static twin of NeuralNetwork._collect_specs enforce)."""
    issues: List[Issue] = []
    lmap = {l.name: l for l in config.layers}
    seen: Dict[str, Tuple[str, Tuple[int, ...]]] = {}
    for layer in config.layers:
        if layer.type != "fc":
            continue
        for li in layer.inputs:
            pname = getattr(li, "input_parameter_name", "")
            if not pname:
                continue
            src = lmap.get(li.input_layer_name)
            if src is None or not src.size or not layer.size:
                continue
            dims = (src.size, layer.size)
            prev = seen.get(pname)
            if prev is not None and prev[1] != dims:
                issues.append(Issue(
                    "shape", "error", layer.name,
                    f"shared parameter {pname!r} is [{dims[0]}, "
                    f"{dims[1]}] here but [{prev[1][0]}, {prev[1][1]}] "
                    f"in layer {prev[0]!r}",
                    (li.input_layer_name, layer.name)))
            else:
                seen.setdefault(pname, (layer.name, dims))
    return issues


# ==================================================== conv→BN fusion plan
def _root_and_outputs(config: Any) -> Tuple[Set[str], List[str]]:
    sub_layer_names: Set[str] = set()
    for sm in getattr(config, "sub_models", []) or []:
        if sm.name != "root":
            sub_layer_names.update(sm.layer_names)
    order = [l.name for l in config.layers
             if l.name not in sub_layer_names or l.type == "data"]
    outputs = list(getattr(config, "output_layer_names", []) or []) \
        or (order[-1:] if order else [])
    return set(order), outputs


def fusion_plan(config: Any, root_layers: Optional[Set[str]] = None,
                output_names: Optional[Sequence[str]] = None,
                fuse_bwd: bool = True, fuse_fwd: bool = True
                ) -> Tuple[Dict[str, str], Dict[str, str]]:
    """The build-time conv/BN fusion resolution, as a pure function of
    the config: returns ``(bwd, fwd)`` where ``bwd`` maps a batch-norm
    to the 3×3 conv it back-fuses (``conv2d_bn``) and ``fwd`` maps a
    consuming conv to the batch-norm whose apply pass defers into it
    (``affine_act_conv2d``).  :class:`~paddle_tpu.layers.network.
    NeuralNetwork` builds its peephole tables by calling THIS function,
    so a static census computed here is the runtime census by
    construction.
    """
    lmap = {l.name: l for l in config.layers}
    if root_layers is None or output_names is None:
        derived_root, derived_out = _root_and_outputs(config)
        root_layers = root_layers if root_layers is not None \
            else derived_root
        output_names = output_names if output_names is not None \
            else derived_out

    n_consumers: Dict[str, int] = {}
    for lc in config.layers:
        for iname in (i.input_layer_name for i in lc.inputs):
            n_consumers[iname] = n_consumers.get(iname, 0) + 1
    # consumers that read values by name OUTSIDE layer input lists:
    # group in/out links, memory boot layers, generator static inputs,
    # and evaluator inputs — a conv referenced by any of these must
    # keep its standalone value
    extra: Set[str] = set()
    for sm in getattr(config, "sub_models", []) or []:
        if sm.name == "root":
            continue
        extra.update(sm.in_links)
        extra.update(sm.out_links)
        for m in sm.memories:
            if m.get("boot_layer_name"):
                extra.add(m["boot_layer_name"])
        extra.update(sm.generator.get("static_inputs", ()))
    for ev in getattr(config, "evaluators", []) or []:
        for key in ("input_layer_name", "label_layer_name"):
            if ev.get(key):
                extra.add(ev[key])
    outputs = set(output_names) | extra

    bwd: Dict[str, str] = {}
    if fuse_bwd:
        for lconf in config.layers:
            if lconf.type not in BN_TYPES or len(lconf.inputs) != 1 \
                    or lconf.name not in root_layers:
                continue
            pname = lconf.inputs[0].input_layer_name
            pconf = lmap.get(pname)
            if pconf is None or pconf.type not in CONV_TYPES \
                    or pname not in root_layers:
                continue
            a = pconf.attrs
            f = a.get("filter_size")
            s = a.get("stride", 1)
            p = a.get("padding", 0)
            if (f == 3 and a.get("filter_size_y", f) == 3
                    and s == 1 and a.get("stride_y", s) == 1
                    and p == 1 and a.get("padding_y", p) == 1
                    and a.get("groups", 1) == 1
                    and len(pconf.inputs) == 1
                    and pconf.active_type in ("", "linear")
                    and pconf.drop_rate == 0
                    and pconf.error_clipping_threshold == 0
                    and n_consumers.get(pname, 0) == 1
                    and pname not in outputs):
                bwd[lconf.name] = pname

    fwd: Dict[str, str] = {}
    if fuse_fwd:
        for lconf in config.layers:        # lconf = the consuming conv
            if lconf.type not in CONV_TYPES \
                    or len(lconf.inputs) != 1 \
                    or lconf.name not in root_layers:
                continue
            a = lconf.attrs
            f = a.get("filter_size")
            fy = a.get("filter_size_y", f)
            s = a.get("stride", 1)
            sy = a.get("stride_y", s)
            p = a.get("padding", 0)
            py = a.get("padding_y", p)
            geom3 = (f == 3 and fy == 3 and s == 1 and sy == 1
                     and p == 1 and py == 1)
            geom1 = (f == 1 and fy == 1 and s == 1 and sy == 1
                     and p == 0 and py == 0)
            if not (geom3 or geom1) or a.get("groups", 1) != 1:
                continue
            pname = lconf.inputs[0].input_layer_name
            pconf = lmap.get(pname)
            if pconf is None or pconf.type not in BN_TYPES \
                    or pname not in root_layers:
                continue
            if (pconf.active_type not in ("", "linear", "relu")
                    or pconf.drop_rate != 0
                    or pconf.error_clipping_threshold != 0
                    or len(pconf.inputs) != 1
                    or pconf.attrs.get("img_size") is None):
                continue
            if n_consumers.get(pname, 0) != 1 or pname in outputs:
                continue
            fwd[lconf.name] = pname
        # a deferred BN publishes (z, a, c) instead of its applied
        # output, so it can no longer be the OUTPUT of a backward-fused
        # pair — its upstream conv reverts to a standalone value.  (A
        # bwd entry whose CONV is a fwd consumer stays: that pair runs
        # as the chain op with the deferred affine as its prologue.)
        for bn in fwd.values():
            bwd.pop(bn, None)
    return bwd, fwd


def fused_pair_census(config: Any, fuse_bwd: bool = True,
                      fuse_fwd: bool = True) -> Dict[str, int]:
    """Static census keyed exactly like the runtime
    ``network_conv_bn_fused_pairs{direction,kernel}`` gauge."""
    bwd, fwd = fusion_plan(config, fuse_bwd=fuse_bwd, fuse_fwd=fuse_fwd)
    lmap = {l.name: l for l in config.layers}
    fwd3 = sum(1 for cv in fwd
               if lmap[cv].attrs.get("filter_size") == 3)
    return {"bwd_3x3": len(bwd), "fwd_3x3": fwd3,
            "fwd_1x1": len(fwd) - fwd3}


# ======================================================= sharding verify
def _spec_axes(spec: Any) -> List[List[str]]:
    """PartitionSpec-like → per-dim list of mesh-axis names (a dim may
    carry one axis, a tuple of axes, or None = replicated)."""
    dims: List[List[str]] = []
    for entry in tuple(spec):
        if entry is None:
            dims.append([])
        elif isinstance(entry, (tuple, list)):
            dims.append([str(a) for a in entry])
        else:
            dims.append([str(entry)])
    return dims


def check_sharding(rules: Any, param_dims: Dict[str, Sequence[int]],
                   mesh_axes: Dict[str, int],
                   strict: bool = False) -> List[Issue]:
    """Verify a ShardingRules table against a model's parameter tree.

    ``rules``: a ``ShardingRules`` (duck-typed: ``.rules`` list of
    ``(compiled_pattern, PartitionSpec)``) or the list itself.
    ``param_dims``: parameter name → dims.  ``mesh_axes``: axis name →
    size for ONE topology; call once per topology.

    Errors (preflight-fatal): a resolved spec names an unknown mesh
    axis, or a sharded dim is not divisible by the product of its mesh
    axes; in ``strict`` mode an unmatched parameter too.  Warnings:
    unmatched parameters (silently replicated — the table has no
    opinion), rules that match nothing in this model, higher-priority
    matches excluded by rank, and multi-matches that first-match-wins
    resolves (ambiguity worth an explicit pattern).
    """
    table = list(getattr(rules, "rules", rules))
    issues: List[Issue] = []
    matched_any = [False] * len(table)

    for pname in sorted(param_dims):
        dims = [int(d) for d in param_dims[pname]]
        ndim = len(dims)
        matching = [(i, pat, spec) for i, (pat, spec) in enumerate(table)
                    if pat.search(pname)]
        applicable = [(i, pat, spec) for i, pat, spec in matching
                      if len(tuple(spec)) <= ndim]
        for i, _, _ in matching:
            matched_any[i] = True
        if not matching:
            issues.append(Issue(
                "shard", "error" if strict else "warn", pname,
                f"parameter matches NO sharding rule — silently "
                f"replicated over the {dict(mesh_axes)} mesh"))
            continue
        if not applicable:
            issues.append(Issue(
                "shard", "error", pname,
                f"every matching rule's spec rank exceeds the "
                f"parameter rank {ndim} (dims {dims}) — the table "
                "cannot place this parameter (rank-excluded rules: "
                + ", ".join(f"#{i} {pat.pattern!r}"
                            for i, pat, _ in matching) + ")"))
            continue
        first_i, first_pat, spec = applicable[0]
        if matching[0][0] != first_i:
            i, pat, s = matching[0]
            issues.append(Issue(
                "shard", "warn", pname,
                f"highest-priority match #{i} {pat.pattern!r} is "
                f"rank-excluded (spec rank {len(tuple(s))} > param "
                f"rank {ndim}); rule #{first_i} {first_pat.pattern!r} "
                "applies instead — tighten the pattern if unintended"))
        distinct = {tuple(s) for _, _, s in applicable}
        if len(distinct) > 1:
            issues.append(Issue(
                "shard", "warn", pname,
                "ambiguous coverage: rules "
                + ", ".join(f"#{i} {p.pattern!r}→{tuple(s)}"
                            for i, p, s in applicable)
                + f" all match; first-match-wins resolves to "
                  f"#{first_i} {first_pat.pattern!r}"))
        # divisibility + axis existence of the RESOLVED spec
        for d, axes in enumerate(_spec_axes(spec)):
            shard = 1
            for ax in axes:
                if ax not in mesh_axes:
                    issues.append(Issue(
                        "shard", "error", pname,
                        f"rule #{first_i} {first_pat.pattern!r} "
                        f"shards dim {d} over mesh axis {ax!r} which "
                        f"does not exist in {dict(mesh_axes)}"))
                    shard = 0
                    break
                shard *= int(mesh_axes[ax])
            if shard > 1 and dims[d] % shard:
                issues.append(Issue(
                    "shard", "error", pname,
                    f"dim {d} of size {dims[d]} is not divisible by "
                    f"the {'×'.join(axes)} mesh extent {shard} "
                    f"(rule #{first_i} {first_pat.pattern!r}, dims "
                    f"{dims}) — this table cannot compile on "
                    f"{dict(mesh_axes)}"))
    for i, hit in enumerate(matched_any):
        if not hit and param_dims:
            pat, spec = table[i]
            issues.append(Issue(
                "shard", "warn", f"rule #{i}",
                f"pattern {pat.pattern!r} matches no parameter of "
                "this model — dead rule (or a typo shadowing a real "
                "one)"))
    return issues


def errors(issues: Iterable[Issue]) -> List[Issue]:
    return [i for i in issues if i.severity == "error"]


def render_report(issues: Sequence[Issue]) -> str:
    if not issues:
        return "netcheck: clean"
    lines = [i.render() for i in issues]
    n_err = len(errors(issues))
    lines.append(f"netcheck: {n_err} error(s), "
                 f"{len(issues) - n_err} warning(s)")
    return "\n".join(lines)

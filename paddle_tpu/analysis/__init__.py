"""ptpu-lint: framework-invariant static analysis.

The invariants the last rounds made load-bearing are enforced here
instead of by convention:

- **PT-TRACE**    trace purity: no host syncs / wall clocks / captured-
                  container mutation inside functions reachable from a
                  jitted step body (the round-12 ``buffers`` trap);
- **PT-RECOMPILE** jit cache hazards: ``jax.jit`` in a loop, jit-and-
                  call-in-one-expression, loop variables closed over by
                  a jitted function, f-string cache keys;
- **PT-RESOURCE** resource hygiene: manual ``__enter__``/``__exit__``,
                  ``lock.acquire()`` outside ``with``/try-finally, bare
                  or broad silent ``except: pass``, threads without the
                  ``ptpu-`` name prefix the conftest leak guard keys on;
- **PT-DTYPE**    precision-policy bypass: direct ``jnp.dot`` /
                  ``jnp.einsum`` / ``lax.conv*`` outside ``ops/``;
- **PT-LOCK**     deadlock analysis: the cross-module lock-acquisition
                  graph derived from ``with lock:`` nesting must stay
                  acyclic (plus the runtime checker in
                  :mod:`paddle_tpu.analysis.lockorder`);
- **PT-SHAPE**    config-time shape/dtype verification: the
                  :mod:`~paddle_tpu.analysis.netcheck` abstract
                  interpreter over literal DSL model configs (the
                  runtime half verifies whole ``ModelConfig``s and
                  powers the ``dryrun_multichip`` preflight);
- **PT-SHARD**    sharding-rule verification: broken literal
                  ``ShardingRules`` tables statically, and (runtime
                  half) unmatched/ambiguous params, rank and
                  mesh-divisibility per topology;
- **PT-RACE**     cross-thread shared-state races: attributes/globals
                  reachable from two ``ptpu-*`` thread entrypoints
                  with a write and no common ``named_lock`` guard
                  (:mod:`~paddle_tpu.analysis.racecheck`).

Run it::

    python -m paddle_tpu.analysis [paths] [--format text|json]
                                  [--baseline FILE] [--lock-graph]
                                  [--rules ...] [--list-rules]

Suppress a single deliberate finding with a justified pragma on the
same line (or the line above)::

    annot.__enter__()   # ptpu: lint-ok[PT-RESOURCE] guarded: see below

This package is stdlib-only and never imports jax — the tier-1
zero-findings test stays fast and the serving loader can't be dragged
into a jax import by a lint run.

This ``__init__`` is deliberately import-light: production modules
import :mod:`paddle_tpu.analysis.lockorder` (the runtime lock-order
checker's ``named_lock`` indirection) at interpreter startup, which
must not pay for the analyzer's AST machinery.
"""

__all__ = ["engine", "lockorder", "netcheck", "racecheck"]

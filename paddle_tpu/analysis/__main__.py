"""CLI: ``python -m paddle_tpu.analysis [paths] [options]``.

Exit status: 0 = no non-suppressed findings, 1 = findings, 2 = usage
error.  ``--baseline`` filters findings whose fingerprint is recorded
(grandfathered debt); ``--write-baseline`` records the current
findings as that debt.  ``--lock-graph`` prints the derived
lock-acquisition hierarchy instead of linting.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

from . import engine
from .rules import lock_order


def _default_paths() -> List[str]:
    # the package this analyzer ships in: lint paddle_tpu/ itself
    return [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.analysis",
        description="ptpu-lint + ptpu-verify: framework-invariant "
                    "static analysis (see --list-rules for the rule "
                    "catalog)")
    p.add_argument("paths", nargs="*",
                   help="files/dirs to analyze (default: the installed "
                        "paddle_tpu package)")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--rules", default="",
                   help="comma-separated rule codes to run "
                        f"(default: all of {', '.join(engine.RULE_CODES)})")
    p.add_argument("--baseline", default=None, metavar="FILE",
                   help="JSON baseline: findings fingerprinted here are "
                        "reported separately and do not fail the run")
    p.add_argument("--write-baseline", default=None, metavar="FILE",
                   help="write the current findings as a baseline and "
                        "exit 0")
    p.add_argument("--lock-graph", action="store_true",
                   help="print the derived lock-acquisition graph / "
                        "hierarchy (PT-LOCK's model) and exit")
    p.add_argument("--list-rules", action="store_true",
                   help="print every rule id with its one-line "
                        "description and exit")
    args = p.parse_args(argv)

    if args.list_rules:
        from .rules import RULE_DOCS

        width = max(len(c) for c in engine.RULE_CODES)
        for code in engine.RULE_CODES:
            print(f"{code:<{width}}  {RULE_DOCS.get(code, '')}")
        return 0

    paths = args.paths or _default_paths()
    for path in paths:
        if not os.path.exists(path):
            print(f"ptpu-lint: no such path: {path}", file=sys.stderr)
            return 2

    if args.lock_graph:
        project, _ = engine.build_project(paths)
        print(lock_order.render_graph(project))
        return 0

    rules = [r.strip() for r in args.rules.split(",") if r.strip()] \
        or None
    baseline = None
    if args.baseline:
        try:
            baseline = engine.load_baseline(args.baseline)
        except (OSError, ValueError) as e:
            print(f"ptpu-lint: cannot read baseline "
                  f"{args.baseline}: {e}", file=sys.stderr)
            return 2
    try:
        result = engine.run(paths, rules=rules, baseline=baseline)
    except ValueError as e:         # unknown rule code
        print(f"ptpu-lint: {e}", file=sys.stderr)
        return 2

    if args.write_baseline:
        engine.write_baseline(args.write_baseline, result)
        print(f"ptpu-lint: wrote {len(result.findings) + len(result.baselined)} "
              f"fingerprint(s) to {args.write_baseline}")
        return 0

    out = result.to_json() if args.format == "json" else result.to_text()
    print(out)
    return result.exit_code


if __name__ == "__main__":
    sys.exit(main())

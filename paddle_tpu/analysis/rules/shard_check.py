"""PT-SHARD — static verification of literal ``ShardingRules`` tables.

The runtime half is :func:`paddle_tpu.analysis.netcheck.check_sharding`
(driven by ``ShardingRules.verify`` and the ``dryrun_multichip``
preflight): it needs a real parameter tree and a mesh topology, which
only exist at run time.  This engine rule checks what IS static about a
rule table — the literals at the construction site:

- a pattern that does not compile (``re.error``) — the rule can never
  match and ``spec_for`` would raise at first use;
- a pattern identical to an earlier rule's in the same table — under
  first-match-wins the later rule is dead (identical spec: duplicate;
  different spec: silently shadowed, the dangerous one);
- a ``PartitionSpec`` entry that is a non-string constant — mesh axes
  are named, ``P(0)`` never matches an axis.

Recognized sites: ``ShardingRules([ (pattern, P(...)), ... ])``
constructions and ``<rules>.add(pattern, P(...))`` calls.  Non-literal
patterns/specs are skipped (no-false-positive discipline).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Tuple

from ..callgraph import ModuleInfo, Project, dotted_name
from ..engine import Finding

RULE = "PT-SHARD"

_SPEC_NAMES = {"P", "PartitionSpec"}


def _literal_pattern(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _spec_key(node: ast.AST) -> Optional[Tuple]:
    """Structural identity of a literal P(...) spec (None = not a
    statically readable spec)."""
    if not isinstance(node, ast.Call):
        return None
    chain = dotted_name(node.func)
    if chain is None or chain.split(".")[-1] not in _SPEC_NAMES:
        return None
    key: List = []
    for a in node.args:
        if isinstance(a, ast.Constant):
            key.append(a.value)
        elif isinstance(a, (ast.Tuple, ast.List)) and all(
                isinstance(e, ast.Constant) for e in a.elts):
            key.append(tuple(e.value for e in a.elts))
        else:
            return None
    return tuple(key)


def _check_spec_args(mod: ModuleInfo, spec: ast.AST,
                     out: List[Finding]) -> None:
    if not isinstance(spec, ast.Call):
        return
    chain = dotted_name(spec.func)
    if chain is None or chain.split(".")[-1] not in _SPEC_NAMES:
        return
    for a in spec.args:
        consts = [a] if isinstance(a, ast.Constant) else (
            [e for e in a.elts if isinstance(e, ast.Constant)]
            if isinstance(a, (ast.Tuple, ast.List)) else [])
        for c in consts:
            if c.value is not None and not isinstance(c.value, str):
                out.append(Finding(
                    RULE, mod.path, c.lineno, c.col_offset,
                    f"PartitionSpec entry {c.value!r} is not a mesh-"
                    "axis NAME — axes are strings ('data', 'model'); "
                    "a non-string entry never matches an axis"))


def _check_pattern(mod: ModuleInfo, node: ast.AST,
                   pattern: str, out: List[Finding]) -> None:
    try:
        re.compile(pattern)
    except re.error as e:
        out.append(Finding(
            RULE, mod.path, node.lineno, node.col_offset,
            f"sharding-rule pattern {pattern!r} does not compile "
            f"({e}) — ShardingRules would raise at construction/first "
            "use"))


def _table_entries(ctor: ast.Call):
    """(pattern_node, spec_node) pairs of a literal ctor table."""
    table = ctor.args[0] if ctor.args else None
    if not isinstance(table, (ast.List, ast.Tuple)):
        return
    for entry in table.elts:
        if isinstance(entry, (ast.Tuple, ast.List)) \
                and len(entry.elts) == 2:
            yield entry.elts[0], entry.elts[1]


def run(project: Project) -> List[Finding]:
    out: List[Finding] = []
    for mod in project.iter_modules():
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = dotted_name(node.func)
            if chain is None:
                continue
            leaf = chain.split(".")[-1]
            if leaf == "ShardingRules":
                seen: Dict[str, Tuple[int, Optional[Tuple]]] = {}
                for pat_node, spec_node in _table_entries(node):
                    pattern = _literal_pattern(pat_node)
                    _check_spec_args(mod, spec_node, out)
                    if pattern is None:
                        continue
                    _check_pattern(mod, pat_node, pattern, out)
                    key = _spec_key(spec_node)
                    prev = seen.get(pattern)
                    if prev is not None:
                        prev_line, prev_key = prev
                        same = (key is not None and key == prev_key)
                        out.append(Finding(
                            RULE, mod.path, pat_node.lineno,
                            pat_node.col_offset,
                            f"pattern {pattern!r} duplicates the rule "
                            f"on line {prev_line} — first-match-wins "
                            + ("makes this entry dead (identical "
                               "spec); drop it"
                               if same else
                               "means this entry NEVER fires and its "
                               "different spec is silently shadowed")))
                    else:
                        seen[pattern] = (pat_node.lineno, key)
            elif leaf == "add" and isinstance(node.func, ast.Attribute):
                # <rules>.add(pattern, P(...)): check the literals —
                # only when the spec side looks like a PartitionSpec,
                # so unrelated .add(str, x) calls never match
                if len(node.args) >= 2 \
                        and _spec_key(node.args[1]) is not None:
                    pattern = _literal_pattern(node.args[0])
                    if pattern is not None:
                        _check_pattern(mod, node.args[0], pattern, out)
                    _check_spec_args(mod, node.args[1], out)
    return out

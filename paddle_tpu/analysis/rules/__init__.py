"""Rule registry: code → ``run(project) -> [Finding]``."""

from __future__ import annotations

from .dtype_policy import run as _dtype
from .lock_order import run as _lock
from .metric_names import run as _metric
from .recompile import run as _recompile
from .resource import run as _resource
from .trace_purity import run as _trace

ALL_RULES = {
    "PT-TRACE": _trace,
    "PT-RECOMPILE": _recompile,
    "PT-RESOURCE": _resource,
    "PT-DTYPE": _dtype,
    "PT-LOCK": _lock,
    "PT-METRIC": _metric,
}

__all__ = ["ALL_RULES"]

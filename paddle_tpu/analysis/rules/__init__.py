"""Rule registry: code → ``run(project) -> [Finding]`` (+ one-line
docs for the CLI's ``--list-rules``)."""

from __future__ import annotations

from .dtype_policy import run as _dtype
from .lock_order import run as _lock
from .metric_names import run as _metric
from .recompile import run as _recompile
from .resource import run as _resource
from .trace_purity import run as _trace
# racecheck imports rules.lock_order/.resource — keep it after them
from ..racecheck import run as _race
from .shape_check import run as _shape
from .shard_check import run as _shard

ALL_RULES = {
    "PT-TRACE": _trace,
    "PT-RECOMPILE": _recompile,
    "PT-RESOURCE": _resource,
    "PT-DTYPE": _dtype,
    "PT-LOCK": _lock,
    "PT-METRIC": _metric,
    "PT-SHAPE": _shape,
    "PT-SHARD": _shard,
    "PT-RACE": _race,
}

#: One-line summaries, printed by ``python -m paddle_tpu.analysis
#: --list-rules``.
RULE_DOCS = {
    "PT-TRACE": "host syncs/clocks/captured-container mutation inside "
                "jit-reachable functions (trace purity)",
    "PT-RECOMPILE": "jit cache hazards: jit-in-loop, build-and-discard, "
                    "loop-var closures, f-string cache keys",
    "PT-RESOURCE": "manual __enter__/__exit__, bare lock.acquire, "
                   "silent broad except, unprefixed framework threads",
    "PT-DTYPE": "direct jnp/lax contractions outside ops//core/ that "
                "bypass the precision policy",
    "PT-LOCK": "static lock-acquisition graph cycles and singleton "
               "self-deadlocks (named_lock identities)",
    "PT-METRIC": "dynamic metric/span names at registration sites "
                 "(unbounded-cardinality leak)",
    "PT-SHAPE": "shape/dtype contradictions in literal DSL model "
                "configs (static netcheck front-end)",
    "PT-SHARD": "broken literal ShardingRules tables: bad regexes, "
                "shadowed duplicates, non-string mesh axes",
    "PT-RACE": "state shared across ptpu-* thread entrypoints with a "
               "write and no common named_lock guard",
}

__all__ = ["ALL_RULES", "RULE_DOCS"]

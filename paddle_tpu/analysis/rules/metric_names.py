"""PT-METRIC — metric/span names must be string constants.

The metrics registry (``observe/metrics.py``) and the span recorder
(``observe/trace.py``) key on their ``name`` argument: every distinct
name is a new registry entry / a new series in the JSONL sink and the
Prometheus dump.  A name built at the call site from runtime values —
``counter(f"rnn_{kind}_total")``, ``span("step_" + str(i))`` — is an
**unbounded-cardinality leak**: the registry grows without bound, every
flush serializes the whole accumulated family, and dashboards see a new
metric per request instead of one metric with labels.  The fix is
always the same: a literal name, variability in labels
(``counter("rnn_dispatch_total").inc(kind=kind)``) or span attrs
(``span("train_step", step=i)``).

Flagged registration sites (resolution deliberately under-approximate,
matching the other rules' no-false-positive discipline):

- ``counter(...)`` / ``gauge(...)`` / ``histogram(...)`` — bare names
  imported from :mod:`paddle_tpu.observe` (or ``observe.metrics``),
  or attribute calls on ``observe`` / ``REGISTRY`` / a name that
  resolves to the observe module;
- ``trace.span(...)`` / ``trace.record_span(...)`` — same treatment
  against :mod:`paddle_tpu.observe.trace`.

A ``Name`` argument that is a module-level string constant (the
``SERVER_THREAD_NAME`` pattern) counts as constant — the cardinality
is still one.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from ..callgraph import ModuleInfo, Project, dotted_name
from ..engine import Finding

RULE = "PT-METRIC"

_REGISTRY_FNS = ("counter", "gauge", "histogram")
_TRACE_FNS = ("span", "record_span")


def _module_is(full: str, *targets: str) -> bool:
    return any(full == t or full.endswith("." + t) for t in targets)


def _imported_from(mod: ModuleInfo, name: str, *targets: str) -> bool:
    """``name`` is a from-import binding a MODULE that matches
    ``targets`` (``from paddle_tpu import observe``)."""
    fi = mod.from_imports.get(name)
    if fi is None:
        return False
    full = (fi[0] + "." + fi[1]) if fi[0] else fi[1]
    return _module_is(full, *targets)


def _fn_imported_from(mod: ModuleInfo, name: str, *targets: str) -> bool:
    """``name`` is a from-import binding a FUNCTION defined in a module
    that matches ``targets`` (``from paddle_tpu.observe import
    counter``)."""
    fi = mod.from_imports.get(name)
    return fi is not None and _module_is(fi[0], *targets)


def _base_is_observe(mod: ModuleInfo, base: str) -> bool:
    if base in ("observe", "REGISTRY"):
        return True
    return _imported_from(mod, base, "observe", "observe.metrics") \
        or _module_is(mod.imports.get(base, ""), "observe",
                      "observe.metrics")


def _base_is_trace(mod: ModuleInfo, parts: List[str]) -> bool:
    # observe.trace.span(...): a `trace` component counts only when the
    # chain's base resolves to the observe package — `self.trace.span`
    # on some unrelated tracer object must NOT match (the rule's
    # no-false-positive discipline)
    if len(parts) >= 3 and parts[-2] == "trace" \
            and _base_is_observe(mod, parts[0]):
        return True
    base = parts[0]
    return _imported_from(mod, base, "observe.trace") \
        or _module_is(mod.imports.get(base, ""), "observe.trace")


def _is_registration(mod: ModuleInfo, call: ast.Call) -> Optional[str]:
    """The registration family ("metric" | "span") this call belongs
    to, or None."""
    chain = dotted_name(call.func)
    if chain is None:
        return None
    parts = chain.split(".")
    last = parts[-1]
    if last in _REGISTRY_FNS:
        if len(parts) == 1:
            if _fn_imported_from(mod, last, "observe",
                                 "observe.metrics"):
                return "metric"
            return None
        if _base_is_observe(mod, parts[0]):
            return "metric"
        return None
    if last in _TRACE_FNS:
        if len(parts) == 1:
            if _fn_imported_from(mod, last, "observe.trace"):
                return "span"
            return None
        if _base_is_trace(mod, parts):
            return "span"
    return None


def _describe(arg: ast.AST) -> str:
    if isinstance(arg, ast.JoinedStr):
        return "an f-string"
    if isinstance(arg, ast.BinOp):
        return "a concatenation/expression"
    if isinstance(arg, ast.Name):
        return f"the variable {arg.id!r}"
    if isinstance(arg, ast.Call):
        return "a call result"
    return f"a {type(arg).__name__} expression"


def run(project: Project) -> List[Finding]:
    out: List[Finding] = []
    for mod in project.iter_modules():
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            family = _is_registration(mod, node)
            if family is None:
                continue
            arg: Optional[ast.AST] = node.args[0] if node.args else None
            if arg is None:
                for kw in node.keywords:
                    if kw.arg == "name":
                        arg = kw.value
                        break
            if arg is None:
                continue
            if isinstance(arg, ast.Constant) \
                    and isinstance(arg.value, str):
                continue
            if isinstance(arg, ast.Name) \
                    and arg.id in mod.str_constants:
                continue        # module-level literal: cardinality one
            kind = "metric" if family == "metric" else "span"
            out.append(Finding(
                RULE, mod.path, arg.lineno, arg.col_offset,
                f"{kind} name is {_describe(arg)} — a dynamic name at "
                "a registration site is an unbounded-cardinality leak "
                "in the registry and the JSONL/Prometheus sinks; use a "
                "string literal and put the variability in "
                f"{'labels' if kind == 'metric' else 'span attrs'}"))
    return out

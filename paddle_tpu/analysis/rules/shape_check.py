"""PT-SHAPE — static shape/dtype verification of literal DSL configs.

The runtime half of this rule is :mod:`paddle_tpu.analysis.netcheck`:
an abstract interpreter over a built ``ModelConfig`` (symbolic shapes,
abstract dtypes, full layer-path provenance) that the trainer's
preflight and the tests drive directly.  This engine rule is the
*static front-end*: it finds straight-line
:mod:`paddle_tpu.config.dsl` model construction in the analyzed files,
re-derives the layer records the DSL would build — sizes computed with
the same formulas (``conv_out``, channel × image products) — and runs
the SAME interpreter over them, anchoring each contradiction at the
offending DSL call.

Extraction is deliberately partial (the no-false-positive discipline):
only literal/constant-foldable arguments and locally-assigned
``LayerOutput`` variables are followed; a helper call, loop-carried
variable, or non-literal size poisons the value and every check
touching it is skipped.  What remains — a conv whose explicit
``num_channels`` contradicts its input, a classification cost whose
prediction width disagrees with its label's class count, an ``addto``
over different widths, an embedding over a dense input — is exactly
the class of config bug that otherwise explodes deep inside a jit
trace with a reshape error and no layer name attached.
"""

from __future__ import annotations

import ast
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .. import netcheck
from ..callgraph import FunctionInfo, ModuleInfo, Project, dotted_name
from ..engine import Finding

RULE = "PT-SHAPE"

#: feeder input-type constructors: name → (kind, seq_level)
_FEED_TYPES = {
    "dense_vector": ("dense", 0),
    "dense_vector_sequence": ("dense", 1),
    "integer_value": ("index", 0),
    "integer_value_sequence": ("index", 1),
    "integer_value_sub_sequence": ("index", 2),
    "sparse_binary_vector": ("sparse_binary", 0),
    "sparse_float_vector": ("sparse_float", 0),
}

#: dsl constructors this extractor models.  Everything else poisons.
_SUPPORTED = {
    "data", "data_layer", "fc", "fc_layer", "embedding",
    "embedding_layer", "img_conv", "img_conv_layer", "img_pool",
    "img_pool_layer", "batch_norm", "batch_norm_layer", "addto",
    "addto_layer", "concat", "concat_layer", "cos_sim", "dropout",
    "dropout_layer", "pooling", "pooling_layer", "last_seq",
    "first_seq", "classification_cost", "cross_entropy_cost",
    "square_error_cost",
}


class _Rec:
    """Statically-extracted layer record — the duck-typed LayerConfig
    the netcheck interpreter consumes (plus the source line)."""

    __slots__ = ("name", "type", "size", "active_type", "inputs",
                 "attrs", "drop_rate", "error_clipping_threshold",
                 "line", "channels", "img_x", "img_y")

    def __init__(self, name: str, ltype: str, size: Optional[int],
                 inputs: Sequence["_In"], attrs: Dict[str, Any],
                 line: int):
        self.name = name
        self.type = ltype
        self.size = size or 0
        self.active_type = ""
        self.inputs = list(inputs)
        self.attrs = attrs
        self.drop_rate = 0.0
        self.error_clipping_threshold = 0.0
        self.line = line
        self.channels: Optional[int] = None
        self.img_x: Optional[int] = None
        self.img_y: Optional[int] = None


class _In:
    __slots__ = ("input_layer_name", "input_parameter_name", "proj",
                 "attrs")

    def __init__(self, name: str):
        self.input_layer_name = name
        self.input_parameter_name = ""
        self.proj = None
        self.attrs: Dict[str, Any] = {}


class _Config:
    """Duck-typed ModelConfig over the extracted records."""

    def __init__(self, layers: Sequence[_Rec]):
        self.layers = list(layers)
        self.sub_models: list = []
        self.output_layer_names: list = []
        self.evaluators: list = []


# ONE conv-geometry formula for the whole analysis package — the lint
# front-end must never disagree with the runtime verifier it feeds
_conv_out = netcheck._conv_out


def _is_dsl_call(project: Project, mod: ModuleInfo,
                 call: ast.Call) -> Optional[str]:
    """The dsl constructor name this call invokes, or None."""
    chain = dotted_name(call.func)
    if chain is None:
        return None
    parts = chain.split(".")
    leaf = parts[-1]
    if leaf not in _SUPPORTED:
        return None
    if len(parts) == 1:
        fi = mod.from_imports.get(leaf)
        if fi is not None and (fi[0].endswith("config.dsl")
                               or fi[0].endswith(".dsl")
                               or fi[0] == "dsl"):
            return leaf
        return None
    base = parts[0]
    if project.names_module(mod, base, "paddle_tpu.config.dsl"):
        return leaf
    # `from paddle_tpu.config import dsl` / `from ..config import dsl`
    fi = mod.from_imports.get(base)
    if fi is not None and fi[1] == "dsl":
        return leaf
    return None


def _feed_type_of(project: Project, mod: ModuleInfo, node: ast.AST,
                  consts: Dict[str, int]
                  ) -> Optional[Tuple[str, int, Optional[int]]]:
    """``dense_vector(128)``-style expression → (kind, seq_level, dim)."""
    if not isinstance(node, ast.Call):
        return None
    chain = dotted_name(node.func)
    if chain is None:
        return None
    leaf = chain.split(".")[-1]
    if leaf not in _FEED_TYPES:
        return None
    kind, seq = _FEED_TYPES[leaf]
    dim = _int_of(node.args[0], consts) if node.args else None
    return kind, seq, dim


def _int_of(node: ast.AST, consts: Dict[str, int]) -> Optional[int]:
    """Constant-fold an int expression (literals, +-*//, named module/
    local int constants); None when not statically known."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return node.value
    if isinstance(node, ast.Name):
        return consts.get(node.id)
    if isinstance(node, ast.BinOp):
        left = _int_of(node.left, consts)
        right = _int_of(node.right, consts)
        if left is None or right is None:
            return None
        if isinstance(node.op, ast.Add):
            return left + right
        if isinstance(node.op, ast.Sub):
            return left - right
        if isinstance(node.op, ast.Mult):
            return left * right
        if isinstance(node.op, ast.FloorDiv) and right:
            return left // right
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        v = _int_of(node.operand, consts)
        return -v if v is not None else None
    return None


def _kw(call: ast.Call, name: str, pos: Optional[int] = None
        ) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    if pos is not None and len(call.args) > pos:
        return call.args[pos]
    return None


class _Extractor:
    """Straight-line symbolic execution of one scope's dsl calls."""

    def __init__(self, project: Project, mod: ModuleInfo,
                 fn: Optional[FunctionInfo]):
        self.project = project
        self.mod = mod
        self.fn = fn
        self.env: Dict[str, _Rec] = {}      # var -> layer record
        self.consts: Dict[str, int] = {}    # var -> folded int
        self.records: List[_Rec] = []
        self._n = 0

    def _fresh(self, ltype: str) -> str:
        self._n += 1
        return f"__{ltype}_{self._n}__"

    # -------------------------------------------------------- statements
    def run(self, body: Sequence[ast.stmt]) -> List[_Rec]:
        self._stmts(body)
        return self.records

    def _stmts(self, body: Sequence[ast.stmt]) -> None:
        for stmt in body:
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name):
                self._assign(stmt.targets[0].id, stmt.value)
            elif isinstance(stmt, (ast.Assign, ast.AnnAssign,
                                   ast.AugAssign)):
                # any other rebinding shape (tuple unpack, chained
                # a = b = ..., annotated, augmented) invalidates the
                # old bindings — a stale record would turn valid code
                # into a false positive
                targets = stmt.targets if isinstance(stmt, ast.Assign) \
                    else [stmt.target]
                for t in targets:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name):
                            self.env.pop(n.id, None)
                            self.consts.pop(n.id, None)
            elif isinstance(stmt, ast.Expr):
                self._eval(stmt.value)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                self._stmts(stmt.body)
            elif isinstance(stmt, ast.Return) and stmt.value is not None:
                self._eval(stmt.value)
            elif isinstance(stmt, (ast.For, ast.While, ast.If, ast.Try)):
                # control flow: poison every name bound inside — the
                # extractor only trusts straight-line construction
                for n in ast.walk(stmt):
                    if isinstance(n, ast.Name) \
                            and isinstance(n.ctx, ast.Store):
                        self.env.pop(n.id, None)
                        self.consts.pop(n.id, None)

    def _assign(self, name: str, value: ast.AST) -> None:
        iv = _int_of(value, self.consts)
        if iv is not None:
            self.consts[name] = iv
            self.env.pop(name, None)
            return
        rec = self._eval(value)
        if rec is not None:
            self.env[name] = rec
        else:
            self.env.pop(name, None)
            self.consts.pop(name, None)

    # ------------------------------------------------------- expressions
    def _value(self, node: ast.AST) -> Optional[_Rec]:
        if isinstance(node, ast.Name):
            return self.env.get(node.id)
        if isinstance(node, ast.Call):
            return self._eval(node)
        return None

    def _values(self, node: ast.AST) -> List[Optional[_Rec]]:
        if isinstance(node, (ast.List, ast.Tuple)):
            return [self._value(e) for e in node.elts]
        v = self._value(node)
        return [v]

    def _unknown_input(self, line: int) -> _Rec:
        """Placeholder producer for a value the extractor can't see —
        keeps the interpreter from reporting missing producers on
        partial extractions."""
        rec = _Rec(self._fresh("opaque"), "data", None, [],
                   {"kind": "?", "seq_level": 0}, line)
        self.records.append(rec)
        return rec

    def _input_names(self, vals: List[Optional[_Rec]],
                     line: int) -> List[_In]:
        out = []
        for v in vals:
            rec = v if v is not None else self._unknown_input(line)
            out.append(_In(rec.name))
        return out

    def _eval(self, node: ast.AST) -> Optional[_Rec]:
        if not isinstance(node, ast.Call):
            return None
        name = _is_dsl_call(self.project, self.mod, node)
        if name is None:
            return None
        line = node.lineno
        C = self.consts
        if name in ("data", "data_layer"):
            tnode = _kw(node, "type", 1)
            ft = _feed_type_of(self.project, self.mod, tnode, C) \
                if tnode is not None else None
            size_node = _kw(node, "size")
            if ft is None and size_node is not None:
                ft = ("dense", 0, _int_of(size_node, C))
            if ft is None and tnode is not None:
                iv = _int_of(tnode, C)      # v1: data_layer(name, size)
                if iv is not None:
                    ft = ("dense", 0, iv)
            kind, seq, dim = ft if ft else ("dense", 0, None)
            lname = self._layer_name(node, f"__data_{line}__")
            rec = _Rec(lname, "data", dim, [],
                       {"kind": kind, "seq_level": seq}, line)
            self.records.append(rec)
            return rec
        if name in ("fc", "fc_layer"):
            vals = self._values(_kw(node, "input", 0) or ast.Tuple(
                elts=[], ctx=ast.Load()))
            size = _int_of(_kw(node, "size", 1) or ast.Constant(None), C)
            rec = _Rec(self._fresh("fc"), "fc", size,
                       self._input_names(vals, line), {}, line)
            self.records.append(rec)
            return rec
        if name in ("embedding", "embedding_layer"):
            vals = self._values(_kw(node, "input", 0)
                                or ast.Constant(None))
            size = _int_of(_kw(node, "size", 1) or ast.Constant(None), C)
            # table geometry, mirroring dsl.embedding's own derivation
            # (vocab_size kwarg, else the id input's declared range) —
            # netcheck's PT-SHAPE embedding branch judges it against
            # the producer's id space
            vs_node = _kw(node, "vocab_size")
            vocab = _int_of(vs_node, C) if vs_node is not None else None
            if vocab is None and vals and vals[0] is not None:
                vocab = vals[0].size or None
            attrs = {"vocab_size": vocab} if vocab else {}
            rec = _Rec(self._fresh("embedding"), "embedding", size,
                       self._input_names(vals[:1], line), attrs, line)
            self.records.append(rec)
            return rec
        if name in ("img_conv", "img_conv_layer"):
            return self._conv(node, line)
        if name in ("img_pool", "img_pool_layer"):
            return self._pool(node, line)
        if name in ("batch_norm", "batch_norm_layer"):
            return self._bn(node, line)
        if name in ("addto", "addto_layer", "concat", "concat_layer"):
            vals = self._values(_kw(node, "input", 0)
                                or ast.Constant(None))
            base = name.split("_")[0]
            known = [v.size for v in vals if v is not None and v.size]
            if base == "addto":
                size = known[0] if known else None
            else:
                size = sum(known) if vals and all(
                    v is not None and v.size for v in vals) else None
            rec = _Rec(self._fresh(base), base, size,
                       self._input_names(vals, line), {}, line)
            self.records.append(rec)
            return rec
        if name == "cos_sim":
            a = self._value(_kw(node, "a", 0) or ast.Constant(None))
            b = self._value(_kw(node, "b", 1) or ast.Constant(None))
            rec = _Rec(self._fresh("cos_sim"), "cos_sim", 1,
                       self._input_names([a, b], line), {}, line)
            self.records.append(rec)
            return rec
        if name in ("dropout", "dropout_layer", "pooling",
                    "pooling_layer", "last_seq", "first_seq"):
            vals = self._values(_kw(node, "input", 0)
                                or ast.Constant(None))
            src = vals[0]
            ltype = {"dropout": "dropout", "dropout_layer": "dropout",
                     "pooling": "pooling", "pooling_layer": "pooling",
                     "last_seq": "seqlastins",
                     "first_seq": "seqfirstins"}[name]
            rec = _Rec(self._fresh(ltype), ltype,
                       src.size if src is not None else None,
                       self._input_names([src], line), {}, line)
            self.records.append(rec)
            return rec
        if name in ("classification_cost", "cross_entropy_cost",
                    "square_error_cost"):
            pred = self._value(_kw(node, "input", 0)
                               or ast.Constant(None))
            lab = self._value(_kw(node, "label", 1)
                              or ast.Constant(None))
            ltype = {"classification_cost": "multi-class-cross-entropy",
                     "cross_entropy_cost": "multi-class-cross-entropy",
                     "square_error_cost": "square_error"}[name]
            rec = _Rec(self._fresh("cost"), ltype, 1,
                       self._input_names([pred, lab], line), {}, line)
            self.records.append(rec)
            return rec
        return None

    def _layer_name(self, node: ast.Call, default: str) -> str:
        arg = _kw(node, "name", 0)
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            return arg.value
        return default

    # geometry constructors mirror the DSL's own derivations exactly
    def _conv(self, node: ast.Call, line: int) -> Optional[_Rec]:
        C = self.consts
        src = self._value(_kw(node, "input", 0) or ast.Constant(None))
        fs = _int_of(_kw(node, "filter_size", 1)
                     or ast.Constant(None), C)
        nf = _int_of(_kw(node, "num_filters", 2)
                     or ast.Constant(None), C)
        nc = _kw(node, "num_channels")
        stride = _int_of(_kw(node, "stride") or ast.Constant(1), C)
        pad = _int_of(_kw(node, "padding") or ast.Constant(0), C)
        groups = _int_of(_kw(node, "groups") or ast.Constant(1), C)
        c = _int_of(nc, C) if nc is not None else (
            src.channels if src is not None else 1) or 1
        img_kw = _kw(node, "img_size")
        img = _int_of(img_kw, C) if img_kw is not None else None
        if img is None and src is not None:
            img = src.img_x
        if img is None and src is not None and src.size and c:
            img = int(round((src.size / c) ** 0.5))
        if None in (fs, nf, stride, pad, img) or not c:
            return None
        out_x = _conv_out(img, fs, pad, stride)
        attrs = {"channels": c, "filter_size": fs, "num_filters": nf,
                 "stride": stride, "padding": pad, "groups": groups or 1,
                 "img_size": img, "img_size_y": img,
                 "output_x": out_x, "output_y": out_x}
        rec = _Rec(self._fresh("conv"), "exconv",
                   nf * out_x * out_x if out_x > 0 else None,
                   self._input_names([src], line), attrs, line)
        rec.channels, rec.img_x, rec.img_y = nf, out_x, out_x
        self.records.append(rec)
        return rec

    def _pool(self, node: ast.Call, line: int) -> Optional[_Rec]:
        C = self.consts
        src = self._value(_kw(node, "input", 0) or ast.Constant(None))
        ps = _int_of(_kw(node, "pool_size", 1) or ast.Constant(None), C)
        stride = _int_of(_kw(node, "stride") or ast.Constant(2), C)
        pad = _int_of(_kw(node, "padding") or ast.Constant(0), C)
        nc = _kw(node, "num_channels")
        c = _int_of(nc, C) if nc is not None else (
            src.channels if src is not None else 1) or 1
        img = src.img_x if src is not None else None
        if img is None and src is not None and src.size and c:
            img = int(round((src.size / c) ** 0.5))
        if None in (ps, stride, pad, img) or not c:
            return None
        out_x = _conv_out(img, ps, pad, stride)
        attrs = {"channels": c, "pool_size": ps, "stride": stride,
                 "padding": pad, "img_size": img, "img_size_y": img}
        rec = _Rec(self._fresh("pool"), "pool",
                   c * out_x * out_x if out_x > 0 else None,
                   self._input_names([src], line), attrs, line)
        rec.channels, rec.img_x, rec.img_y = c, out_x, out_x
        self.records.append(rec)
        return rec

    def _bn(self, node: ast.Call, line: int) -> Optional[_Rec]:
        C = self.consts
        src = self._value(_kw(node, "input", 0) or ast.Constant(None))
        nc = _kw(node, "num_channels")
        c = _int_of(nc, C) if nc is not None else (
            src.channels if src is not None else None)
        if c is None and src is not None:
            c = src.size
        attrs: Dict[str, Any] = {}
        if c:
            attrs["channels"] = c
        if src is not None and src.img_x:
            attrs["img_size"] = src.img_x
            attrs["img_size_y"] = src.img_y or src.img_x
        rec = _Rec(self._fresh("batch_norm"), "batch_norm",
                   src.size if src is not None else None,
                   self._input_names([src], line), attrs, line)
        if src is not None:
            rec.channels = c
            rec.img_x, rec.img_y = src.img_x, src.img_y
        self.records.append(rec)
        return rec


def _scopes(mod: ModuleInfo):
    """Module body + every function body, each its own extraction."""
    yield None, mod.tree.body
    for fn in mod.functions.values():
        yield fn, fn.node.body


def run(project: Project) -> List[Finding]:
    out: List[Finding] = []
    for mod in project.iter_modules():
        # cheap pre-filter: no dsl import, no extraction
        has_dsl = any(v == "dsl" or v.endswith(".dsl")
                      for v in mod.imports.values()) \
            or any(fi[1] == "dsl" or fi[0].endswith(".dsl")
                   or fi[0] == "dsl"
                   for fi in mod.from_imports.values())
        if not has_dsl:
            continue
        for fn, body in _scopes(mod):
            ex = _Extractor(project, mod, fn)
            records = ex.run(body)
            if not records:
                continue
            cfg = _Config(records)
            lines = {r.name: r.line for r in records}
            for issue in netcheck.check_model(cfg):
                if issue.severity != "error":
                    continue
                line = lines.get(issue.where, records[0].line)
                prov = " -> ".join(issue.path)
                out.append(Finding(
                    RULE, mod.path, line, 0,
                    f"{issue.message}"
                    + (f" [layer path: {prov}]" if prov else "")))
    return out

"""PT-RESOURCE — resource hygiene.

Four checks, all born from real rounds of review pain:

- **manual-ctx**: a call to ``x.__enter__()`` / ``x.__exit__(...)``
  outside a class's own ``__enter__``/``__exit__`` definition.  Round
  13's review pass rewrote every such site after a fault between
  ``__enter__`` and the ``try`` leaked the thread-local trace context
  for the thread's lifetime — ``with`` blocks are the only shape that
  cannot leak.
- **bare-acquire**: ``lock.acquire()`` on a lock-ish name (``*lock*``,
  ``*cond*``, ``*mutex*``) that is neither ``with``-scoped nor
  immediately guarded by ``try/finally: release`` — an exception
  between acquire and release deadlocks every later acquirer.
- **silent-except**: a bare ``except:`` with any body, or a broad
  ``except Exception/BaseException:`` whose body is ONLY ``pass`` —
  the failure class that hid the round-9 abandoned-lease bug.  Narrow
  handlers (``except OSError: pass``) are allowed; broad ones must at
  least log.
- **thread-name**: ``threading.Thread(...)`` without a ``name=`` that
  statically starts with ``ptpu-`` — the conftest thread-leak guard
  audits framework threads BY prefix, so an unprefixed thread is
  invisible to it.
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional

from ..callgraph import ModuleInfo, Project, dotted_name
from ..engine import Finding

RULE = "PT-RESOURCE"
_LOCKISH = re.compile(r"lock|cond|mutex", re.IGNORECASE)
THREAD_PREFIX = "ptpu-"


def _find(mod: ModuleInfo, node: ast.AST, msg: str) -> Finding:
    return Finding(RULE, mod.path, node.lineno, node.col_offset, msg)


# ------------------------------------------------------------ manual ctx
def _enclosing_dunder_ok(stack: List[ast.AST]) -> bool:
    """Inside a def named __enter__/__exit__ (a context manager that
    delegates to another is legitimate)."""
    for n in reversed(stack):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return n.name in ("__enter__", "__exit__")
    return False


# --------------------------------------------------------- bare acquire
def _acquire_target(call: ast.Call) -> Optional[str]:
    f = call.func
    if isinstance(f, ast.Attribute) and f.attr == "acquire":
        name = dotted_name(f.value)
        if name is None and isinstance(f.value, ast.Attribute):
            name = f.value.attr
        if name and _LOCKISH.search(name):
            return name
    return None


def _release_in(node: ast.AST, target: str) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) \
                and n.func.attr == "release":
            name = dotted_name(n.func.value) or \
                (n.func.value.attr
                 if isinstance(n.func.value, ast.Attribute) else None)
            if name == target:
                return True
    return False


def _guarded_by_try_finally(stmts: list, idx: int, target: str) -> bool:
    """acquire at stmts[idx] is OK when the NEXT statement is a
    ``try/finally`` whose finally releases the same lock (the classic
    pre-with idiom)."""
    if idx + 1 < len(stmts):
        nxt = stmts[idx + 1]
        if isinstance(nxt, ast.Try) and nxt.finalbody \
                and any(_release_in(s, target) for s in nxt.finalbody):
            return True
    return False


# ------------------------------------------------------------ except/pass
def _body_is_pass(body: list) -> bool:
    return all(isinstance(s, ast.Pass) for s in body)


_BROAD = ("Exception", "BaseException")


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = []
    if isinstance(t, ast.Tuple):
        names = [dotted_name(e) or "" for e in t.elts]
    else:
        names = [dotted_name(t) or ""]
    return any(n.split(".")[-1] in _BROAD for n in names)


# ------------------------------------------------------------ thread name
def _static_name_prefix(mod: ModuleInfo, node: ast.AST) -> Optional[str]:
    """Best-effort static prefix of a thread-name expression; None when
    unresolvable (unresolvable names are not flagged)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.Name):
        return mod.str_constants.get(node.id)
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        return _static_name_prefix(mod, node.left)
    if isinstance(node, ast.JoinedStr) and node.values:
        first = node.values[0]
        if isinstance(first, ast.Constant):
            return str(first.value)
        if isinstance(first, ast.FormattedValue):
            return _static_name_prefix(mod, first.value)
    return None


def _imported_constant(project: Project, mod: ModuleInfo,
                       name: str) -> Optional[str]:
    tgt = mod.from_imports.get(name)
    if tgt is None:
        return None
    src = project.module_for(tgt[0])
    return src.str_constants.get(tgt[1]) if src is not None else None


def _thread_name_finding(project: Project, mod: ModuleInfo,
                         call: ast.Call) -> Optional[str]:
    chain = dotted_name(call.func)
    if chain is None or chain.split(".")[-1] != "Thread":
        return None
    root = chain.split(".")[0]
    if root != "Thread" and not project.names_module(
            mod, root, "threading"):
        return None
    if root == "Thread" and mod.from_imports.get(
            "Thread", ("", ""))[0] != "threading":
        return None
    name_kw = next((kw.value for kw in call.keywords
                    if kw.arg == "name"), None)
    if name_kw is None:
        return ("threading.Thread without a name= — framework threads "
                f"must carry the {THREAD_PREFIX!r} prefix so the "
                "conftest leak guard can audit them")
    prefix = _static_name_prefix(mod, name_kw)
    if prefix is None and isinstance(name_kw, ast.Name):
        prefix = _imported_constant(project, mod, name_kw.id)
    if prefix is None and isinstance(name_kw, ast.BinOp) \
            and isinstance(name_kw.op, ast.Add) \
            and isinstance(name_kw.left, ast.Name):
        prefix = _imported_constant(project, mod, name_kw.left.id)
    if prefix is None and isinstance(name_kw, ast.JoinedStr) \
            and name_kw.values \
            and isinstance(name_kw.values[0], ast.FormattedValue) \
            and isinstance(name_kw.values[0].value, ast.Name):
        prefix = _imported_constant(project, mod,
                                    name_kw.values[0].value.id)
    if prefix is not None and not prefix.startswith(THREAD_PREFIX):
        return (f"thread name {prefix!r} lacks the {THREAD_PREFIX!r} "
                "prefix the conftest thread-leak guard keys on")
    return None


# -------------------------------------------------------------- the rule
def run(project: Project) -> List[Finding]:
    out: List[Finding] = []
    for mod in project.iter_modules():
        stack: List[ast.AST] = []

        def visit(node: ast.AST) -> None:
            # manual __enter__/__exit__
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in ("__enter__", "__exit__") \
                    and not _enclosing_dunder_ok(stack):
                out.append(_find(
                    mod, node,
                    f"manual {node.func.attr}() call — use a `with` "
                    "block (a fault between enter and try leaks the "
                    "resource; round-13 trace-context bug class)"))
            # bare acquire
            if isinstance(node, ast.Call):
                tgt = _acquire_target(node)
                if tgt is not None:
                    parent = stack[-1] if stack else None
                    ok = False
                    # with lock.acquire()? nonsense — only Expr stmts
                    # followed by try/finally or inside one count
                    for holder in reversed(stack):
                        found = False
                        for fieldname in ("body", "orelse", "finalbody"):
                            body = getattr(holder, fieldname, None)
                            if not isinstance(body, list):
                                continue
                            for i, s in enumerate(body):
                                if s is parent or s is node or (
                                        isinstance(s, ast.Expr)
                                        and s.value is node):
                                    ok = _guarded_by_try_finally(
                                        body, i, tgt)
                                    found = True
                                    break
                            if found:
                                break
                        if found:
                            break
                    if not ok:
                        out.append(_find(
                            mod, node,
                            f"{tgt}.acquire() outside `with`/"
                            "try-finally — an exception before "
                            "release() deadlocks every later "
                            "acquirer"))
            # silent except
            if isinstance(node, ast.ExceptHandler):
                if node.type is None:
                    out.append(_find(
                        mod, node,
                        "bare `except:` — catches SystemExit/"
                        "KeyboardInterrupt too; name the exceptions"))
                elif _is_broad(node) and _body_is_pass(node.body):
                    out.append(_find(
                        mod, node,
                        "broad silent `except "
                        f"{ast.unparse(node.type) if node.type else ''}"
                        ": pass` — swallow narrowly or at least log"))
            # thread names
            if isinstance(node, ast.Call):
                msg = _thread_name_finding(project, mod, node)
                if msg is not None:
                    out.append(_find(mod, node, msg))
            stack.append(node)
            for child in ast.iter_child_nodes(node):
                visit(child)
            stack.pop()

        visit(mod.tree)
    return out

"""PT-DTYPE — precision-policy bypass.

Every MXU-shaped op (matmul/einsum/conv) must route through
``paddle_tpu/ops/`` so the ``core/dtypes.py`` policy decides its
compute/accumulate dtypes and ``precision_dispatch_total`` sees it.
A direct ``jnp.dot`` / ``jnp.matmul`` / ``jnp.einsum`` /
``lax.conv*`` / ``lax.dot_general`` call anywhere else silently pins
fp32 (or whatever the operand dtypes happen to be), exactly the bug
class round 12 fixed in the attention projections.  Deliberate
bypasses (fp32-by-design numerics) carry a justified pragma.
"""

from __future__ import annotations

import ast
from typing import List

from ..callgraph import Project, dotted_name
from ..engine import Finding

RULE = "PT-DTYPE"

_JNP_OPS = {"dot", "matmul", "einsum", "tensordot", "vdot", "inner"}
_LAX_PREFIXES = ("conv",)
_LAX_OPS = {"dot_general", "dot"}

#: modules whose JOB is dtype dispatch (the policy lives there) — keyed
#: on the dotted module name, NOT the filesystem path: a checkout under
#: e.g. /home/ci/core/ must not exempt the whole repo
_EXEMPT_PREFIXES = ("paddle_tpu.ops", "paddle_tpu.core")


def _is_exempt(mod) -> bool:
    return any(mod.name == p or mod.name.startswith(p + ".")
               for p in _EXEMPT_PREFIXES)


def run(project: Project) -> List[Finding]:
    out: List[Finding] = []
    for mod in project.iter_modules():
        if _is_exempt(mod):
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = dotted_name(node.func)
            if chain is None or "." not in chain:
                continue
            parts = chain.split(".")
            root, attr = parts[0], parts[-1]
            # `import jax; jax.numpy.dot(...)` / `jax.lax.dot_general`
            # spell the submodule through the jax root
            via_jax = (len(parts) == 3
                       and project.names_module(mod, root, "jax"))
            is_jnp = project.names_module(mod, root, "jax.numpy") or (
                via_jax and parts[1] == "numpy")
            is_lax = project.names_module(mod, root, "jax.lax") or (
                via_jax and parts[1] == "lax")
            if is_jnp and attr in _JNP_OPS:
                op = f"jnp.{attr}"
            elif is_lax and (attr in _LAX_OPS
                             or attr.startswith(_LAX_PREFIXES)):
                op = f"lax.{attr}"
            else:
                continue
            out.append(Finding(
                RULE, mod.path, node.lineno, node.col_offset,
                f"direct {op} outside ops/ bypasses the precision "
                "policy (core/dtypes.py) and the "
                "precision_dispatch_total census — route through "
                "paddle_tpu.ops (e.g. math_ops.matmul/einsum) or "
                "pragma a deliberate fp32-by-design site"))
    return out

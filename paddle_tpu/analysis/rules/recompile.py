"""PT-RECOMPILE — jit cache hazards.

``jax.jit`` keys its cache on the function object plus argument
shapes/dtypes.  Three shapes of code defeat that cache and silently
recompile on a hot path:

- **jit-in-loop**: ``jax.jit(...)`` inside a ``for``/``while`` body
  builds a fresh jitted callable (fresh cache) every iteration;
- **jit-and-call**: ``jax.jit(f)(x)`` in one expression builds and
  discards the callable — every execution of the statement retraces;
- **loop-var closure**: a function defined in a loop and jitted closes
  over the loop variable; each iteration bakes a different constant
  into an otherwise identical trace (the "Python scalars closed over
  instead of passed" trap — pass them as arguments or mark them
  static);
- **f-string cache key**: caching compiled artifacts under an f-string
  key interpolating runtime objects (reprs are not stable identities —
  two equal shapes can render differently, two different dtypes can
  render the same).  Flagged when the subscripted/``.get``-ed mapping
  name contains "cache".
"""

from __future__ import annotations

import ast
from typing import List, Optional

from ..callgraph import ModuleInfo, Project, dotted_name
from ..engine import Finding

RULE = "PT-RECOMPILE"


def _is_jit_call(project: Project, mod: ModuleInfo,
                 call: ast.Call) -> bool:
    chain = dotted_name(call.func)
    if chain is None:
        return False
    parts = chain.split(".")
    if parts[-1] != "jit":
        return False
    if len(parts) == 1:
        return mod.from_imports.get("jit", ("", ""))[0] == "jax"
    return project.names_module(mod, parts[0], "jax")


def _loop_vars(loop: ast.AST) -> set:
    out = set()
    if isinstance(loop, ast.For):
        for n in ast.walk(loop.target):
            if isinstance(n, ast.Name):
                out.add(n.id)
    return out


def run(project: Project) -> List[Finding]:
    out: List[Finding] = []
    for mod in project.iter_modules():
        loop_stack: List[ast.AST] = []

        def visit(node: ast.AST) -> None:
            if isinstance(node, ast.Call) \
                    and _is_jit_call(project, mod, node):
                if loop_stack:
                    out.append(Finding(
                        RULE, mod.path, node.lineno, node.col_offset,
                        "jax.jit called inside a loop — a fresh jitted "
                        "callable (and cache) per iteration; hoist the "
                        "jit out of the loop"))
                    # loop-variable closure through the jitted function
                    lv = set()
                    for lp in loop_stack:
                        lv |= _loop_vars(lp)
                    arg = node.args[0] if node.args else None
                    if isinstance(arg, ast.Lambda) and lv:
                        free = {n.id for n in ast.walk(arg.body)
                                if isinstance(n, ast.Name)}
                        captured = sorted(free & lv)
                        if captured:
                            out.append(Finding(
                                RULE, mod.path, arg.lineno,
                                arg.col_offset,
                                f"jitted lambda closes over loop "
                                f"variable(s) {captured} — each "
                                "iteration bakes a new constant and "
                                "retraces; pass them as arguments"))
            # jit-and-call in one expression: jax.jit(f)(x)
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Call) \
                    and _is_jit_call(project, mod, node.func):
                out.append(Finding(
                    RULE, mod.path, node.lineno, node.col_offset,
                    "jax.jit(f)(...) builds and discards the jitted "
                    "callable — every execution retraces; bind "
                    "`g = jax.jit(f)` once and call g"))
            # f-string cache keys
            key: Optional[ast.AST] = None
            target: Optional[str] = None
            if isinstance(node, ast.Subscript) \
                    and isinstance(node.slice, ast.JoinedStr):
                key, target = node.slice, dotted_name(node.value)
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in ("get", "setdefault") \
                    and node.args \
                    and isinstance(node.args[0], ast.JoinedStr):
                key, target = node.args[0], dotted_name(node.func.value)
            if key is not None and target is not None \
                    and "cache" in target.lower():
                out.append(Finding(
                    RULE, mod.path, key.lineno, key.col_offset,
                    f"f-string used as a cache key on {target!r} — "
                    "reprs are not stable shape/dtype identities; key "
                    "on a tuple of (shape, dtype, flags) instead"))

            if isinstance(node, (ast.For, ast.While)):
                loop_stack.append(node)
                for child in ast.iter_child_nodes(node):
                    visit(child)
                loop_stack.pop()
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.Lambda)):
                # a def inside a loop runs once per iteration, but the
                # jit hazard is about CALL frequency, which the
                # jit-in-loop check above already covers at the jit
                # site; don't carry the loop context into the body
                saved, loop_stack[:] = list(loop_stack), []
                for child in ast.iter_child_nodes(node):
                    visit(child)
                loop_stack[:] = saved
            else:
                for child in ast.iter_child_nodes(node):
                    visit(child)

        visit(mod.tree)
    return out

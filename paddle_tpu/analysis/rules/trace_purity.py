"""PT-TRACE — trace purity of jitted step bodies.

A function traced by ``jax.jit`` runs ONCE per (shape, dtype) bucket;
anything impure inside it either crashes at trace time
(``UnexpectedTracerError`` — the round-12 ``buffers`` trap), silently
bakes a trace-time value into the compiled program (``time.time()``,
``float(x)``), or forces a host round-trip per call
(``block_until_ready`` / ``device_get`` / ``.item()`` /
``np.asarray``).  This rule derives the set of functions statically
reachable from jit roots (functions passed to ``jax.jit`` or decorated
with it, plus any function a reachable function passes by reference —
``jax.value_and_grad(loss_fn)`` et al.) and flags, inside them:

- host syncs: ``.block_until_ready()``, ``jax.device_get``,
  ``.item()``, ``np.asarray``/``np.array``, ``float(x)``/``int(x)`` on
  a non-literal;
- wall clocks: ``time.time()``/``perf_counter()``/``monotonic()``;
- mutation of captured containers: subscript-store, or a
  ``.update()``/``.setdefault()``/``.pop()``/… call whose result is
  DISCARDED (an expression statement) on a container that is a
  parameter or closure variable — a used result means a functional
  API (``new_state = ls.update(...)``), not mutation; locals are fine
  either way (the trace owns them);
- ``print`` (runs once per retrace, not per step — a lie at best).

Resolution is conservative (see ``callgraph.py``): only statically
certain calls extend reachability, so a finding here is near-certain.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ..callgraph import (FunctionInfo, ModuleInfo, Project, dotted_name,
                         iter_calls, own_statements)
from ..engine import Finding

RULE = "PT-TRACE"

_CLOCKS = {"time", "perf_counter", "monotonic", "process_time",
           "thread_time"}
_SYNC_ATTRS = {"block_until_ready", "device_get", "item"}


def _is_jit_expr(project: Project, mod: ModuleInfo,
                 call: ast.Call) -> bool:
    chain = dotted_name(call.func)
    if chain is None:
        return False
    parts = chain.split(".")
    if parts[-1] == "jit":
        if len(parts) == 1:
            return mod.from_imports.get("jit", ("", ""))[0] == "jax"
        return project.names_module(mod, parts[0], "jax")
    # functools.partial(jax.jit, ...) — treat as jit when arg0 is jit
    if parts[-1] == "partial" and call.args:
        inner = dotted_name(call.args[0])
        if inner and inner.split(".")[-1] == "jit":
            return True
    return False


def _jit_roots(project: Project) -> Set[FunctionInfo]:
    roots: Set[FunctionInfo] = set()
    for mod in project.iter_modules():
        # decorators
        for fn in mod.functions.values():
            for dec in getattr(fn.node, "decorator_list", []):
                chain = dotted_name(dec if not isinstance(dec, ast.Call)
                                    else dec.func)
                if chain and chain.split(".")[-1] == "jit":
                    roots.add(fn)
                elif isinstance(dec, ast.Call) \
                        and _is_jit_expr(project, mod, dec):
                    roots.add(fn)
        # jax.jit(f) call sites — resolve f in the enclosing scope
        for qual, fn in mod.functions.items():
            for call in iter_calls(fn.node):
                if not _is_jit_expr(project, mod, call):
                    continue
                args = list(call.args)
                # partial(jax.jit, f): the wrapped fn is args[1]
                if args and dotted_name(args[0]) \
                        and dotted_name(args[0]).endswith("jit"):
                    args = args[1:]
                for a in args[:1]:
                    if isinstance(a, ast.Name):
                        tgt = project.resolve_name(mod, fn, a.id)
                        if tgt is not None:
                            roots.add(tgt)
        # module-level jit calls
        for call in iter_calls(mod.tree):
            if _is_jit_expr(project, mod, call) and call.args \
                    and isinstance(call.args[0], ast.Name):
                tgt = project.resolve_name(mod, None, call.args[0].id)
                if tgt is not None:
                    roots.add(tgt)
    return roots


def _reachable(project: Project,
               roots: Set[FunctionInfo]) -> Set[FunctionInfo]:
    seen: Set[FunctionInfo] = set()
    frontier = list(roots)
    while frontier:
        fn = frontier.pop()
        if fn in seen:
            continue
        seen.add(fn)
        mod = fn.module
        for call in iter_calls(fn.node):
            tgt = project.resolve_call(mod, fn, call)
            if tgt is not None and tgt not in seen:
                frontier.append(tgt)
            # function references passed along (value_and_grad(loss_fn),
            # tree_map(f, ...)) stay inside the traced program
            for a in list(call.args) + [kw.value for kw in call.keywords]:
                if isinstance(a, ast.Name):
                    ref = project.resolve_name(mod, fn, a.id)
                    if ref is not None and ref not in seen:
                        frontier.append(ref)
    return seen


def _float_arg_is_literal(call: ast.Call) -> bool:
    return bool(call.args) and isinstance(call.args[0], ast.Constant)


def _check_function(project: Project, fn: FunctionInfo,
                    out: List[Finding]) -> None:
    mod = fn.module
    # calls whose value is thrown away: only these count as mutation
    # (`buffers.update(x)` mutates; `new = ls.update(x)` is functional)
    discarded = {id(n.value) for n in own_statements(fn.node)
                 if isinstance(n, ast.Expr)
                 and isinstance(n.value, ast.Call)}

    def is_captured(name: str) -> bool:
        # a parameter, closure variable, or module global — anything the
        # trace does not own; plain locals are the trace's to mutate
        return name in fn.params or name not in fn.locals

    def flag(node: ast.AST, msg: str) -> None:
        out.append(Finding(RULE, mod.path, node.lineno, node.col_offset,
                           f"in jit-reachable `{fn.qualname}`: {msg}"))

    for node in own_statements(fn.node):
        if isinstance(node, ast.Call):
            f = node.func
            chain = dotted_name(f)
            # host syncs via attribute
            if isinstance(f, ast.Attribute) and f.attr in _SYNC_ATTRS:
                flag(node, f".{f.attr}() forces a host sync inside the "
                     "traced step — move it outside the jit boundary")
                continue
            if chain:
                parts = chain.split(".")
                root, leaf = parts[0], parts[-1]
                if leaf in ("asarray", "array") and \
                        project.names_module(mod, root, "numpy"):
                    flag(node, f"np.{leaf}() materializes on host at "
                         "trace time — use jnp, or feed the value as "
                         "an argument")
                    continue
                if leaf in _CLOCKS and (
                        project.names_module(mod, root, "time")
                        or (len(parts) == 1 and mod.from_imports.get(
                            leaf, ("", ""))[0] == "time")):
                    flag(node, f"{chain}() reads the wall clock at "
                         "TRACE time — the compiled step reuses that "
                         "constant forever")
                    continue
            if isinstance(f, ast.Name) and f.id in ("float", "int") \
                    and node.args and not _float_arg_is_literal(node):
                flag(node, f"{f.id}() on a traced value host-syncs "
                     "(or bakes a trace-time constant) — keep it an "
                     "array, or pass the scalar as an argument")
                continue
            if isinstance(f, ast.Name) and f.id == "print":
                flag(node, "print() runs once per retrace, not per "
                     "step — use jax.debug.print or host callbacks")
                continue
            # captured-container mutation via method (discarded result)
            if isinstance(f, ast.Attribute) \
                    and f.attr in ("update", "setdefault", "pop",
                                   "clear", "append", "extend") \
                    and isinstance(f.value, ast.Name) \
                    and is_captured(f.value.id) \
                    and id(node) in discarded:
                flag(node, f"`{f.value.id}.{f.attr}(...)` mutates a "
                     "captured container inside the trace — the "
                     "round-12 buffers trap (hand the callee a copy)")
                continue
        # captured-container mutation via subscript store
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                if isinstance(t, ast.Subscript) \
                        and isinstance(t.value, ast.Name) \
                        and is_captured(t.value.id):
                    flag(node, f"`{t.value.id}[...] = ...` mutates a "
                         "captured container inside the trace — the "
                         "round-12 buffers trap (build a new dict "
                         "instead)")


def run(project: Project) -> List[Finding]:
    out: List[Finding] = []
    roots = _jit_roots(project)
    for fn in _reachable(project, roots):
        _check_function(project, fn, out)
    return out

"""PT-LOCK — cross-module lock-acquisition graph must stay acyclic.

Ten threaded modules (pipeline, trace writer/ring, metrics registry +
reporter, the metrics HTTP endpoint, master client, stat timers, the
logger's warn-once table) now interleave under locks.  Two code paths
that acquire the same pair of locks in opposite orders are a deadlock
waiting for the right two threads — and unlike a thread leak, nothing
at runtime flags the hazard until it fires.

This rule derives the acquisition graph statically:

- **nodes** are lock identities: the literal name of a
  ``named_lock("...")`` / ``named_condition("...")`` creation
  (:mod:`paddle_tpu.analysis.lockorder` — the same node names the
  runtime checker uses), or a ``module.Class.attr`` synthetic for a raw
  ``threading.Lock()``;
- **edges** come from lexical ``with a: ... with b:`` nesting, plus
  interprocedural reach: a call made while holding ``a`` to a function
  whose transitive may-acquire set contains ``b`` adds ``a -> b``
  (may-acquire is a fixpoint over the conservatively-resolved call
  graph, so only statically certain paths contribute);
- a **cycle** in the graph is the finding, reported once per cycle
  with every witnessing site;
- holding a *module-level singleton* lock while calling a function
  that (transitively) re-acquires the same lock is reported as a
  self-deadlock (instance locks are exempt — two instances of one
  class are distinct locks under one node name).

:func:`build_lock_graph` exposes the derived graph for the CLI's
``--lock-graph`` dump — the hierarchy documented in PERF_NOTES and
asserted at runtime by the chaos/pipeline suites.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from ..callgraph import FunctionInfo, ModuleInfo, Project, dotted_name
from ..engine import Finding

RULE = "PT-LOCK"

_CTORS = {"Lock", "RLock", "Condition"}
_NAMED = {"named_lock", "named_condition"}


# -------------------------------------------------------- lock registry
class _Locks:
    """Every statically-known lock creation in the project."""

    def __init__(self) -> None:
        self.module: Dict[Tuple[str, str], str] = {}   # (mod, var) -> id
        self.cls: Dict[Tuple[str, str, str], str] = {}  # (mod,C,attr)->id
        self.local: Dict[Tuple[str, str, str], str] = {}  # (mod,fn,var)
        self.singletons: Set[str] = set()   # ids with exactly one
        #                                     module-level instance

    def resolve_name(self, mod: ModuleInfo, fn: Optional[FunctionInfo],
                     name: str) -> Optional[str]:
        cur = fn
        while cur is not None:
            lid = self.local.get((mod.name, cur.qualname, name))
            if lid is not None:
                return lid
            cur = mod.functions.get(cur.parent) if cur.parent else None
        return self.module.get((mod.name, name))

    def resolve_self_attr(self, mod: ModuleInfo, cls: Optional[str],
                          attr: str) -> Optional[str]:
        if cls is not None:
            lid = self.cls.get((mod.name, cls, attr))
            if lid is not None:
                return lid
        # unique definition anywhere in the module (covers inheritance
        # inside one file, e.g. subclasses using a base's self._lock)
        hits = {v for (m, _, a), v in self.cls.items()
                if m == mod.name and a == attr}
        return hits.pop() if len(hits) == 1 else None


def _ctor_lock_id(project: Project, mod: ModuleInfo,
                  node: ast.AST) -> Optional[str]:
    """Lock id for a creation expression: the literal of a
    ``named_lock``/``named_condition`` call, ``""`` (anonymous — caller
    names it from the assignment target) for a raw ``threading``
    constructor, None for anything else."""
    if not isinstance(node, ast.Call):
        return None
    chain = dotted_name(node.func)
    if chain is None:
        return None
    leaf = chain.split(".")[-1]
    if leaf in _NAMED:
        if node.args and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str):
            return node.args[0].value
        return ""
    if leaf in _CTORS:
        root = chain.split(".")[0]
        if root == leaf:
            return "" if mod.from_imports.get(
                leaf, ("", ""))[0] == "threading" else None
        return "" if project.names_module(mod, root, "threading") \
            else None
    return None


def _collect_locks(project: Project) -> _Locks:
    locks = _Locks()
    module_counts: Dict[str, int] = {}
    for mod in project.iter_modules():
        for node in ast.walk(mod.tree):
            value = None
            target = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                target, value = node.targets[0], node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                target, value = node.target, node.value
            if value is None:
                continue
            # dataclass field(default_factory=<lock factory>)
            if isinstance(value, ast.Call) \
                    and dotted_name(value.func) is not None \
                    and dotted_name(value.func).split(".")[-1] == "field":
                for kw in value.keywords:
                    if kw.arg != "default_factory":
                        continue
                    factory = kw.value
                    if isinstance(factory, ast.Lambda):
                        factory = factory.body
                    lid = _ctor_lock_id(project, mod, factory)
                    if lid is None and isinstance(factory, (ast.Name,
                                                            ast.Attribute)):
                        chain = dotted_name(factory)
                        if chain and chain.split(".")[-1] in _CTORS:
                            lid = ""
                    if lid is not None:
                        value = None    # consumed; register below
                        cls = _enclosing_class_of(mod, node)
                        if cls is not None \
                                and isinstance(target, ast.Name):
                            name = lid or (f"{mod.short()}.{cls}"
                                           f".{target.id}")
                            locks.cls[(mod.name, cls, target.id)] = name
                    break
            if value is None:
                continue
            lid = _ctor_lock_id(project, mod, value)
            if lid is None:
                continue
            owner = _owner_of(mod, node)
            if isinstance(target, ast.Name):
                if owner is None:                       # module level
                    name = lid or f"{mod.short()}.{target.id}"
                    locks.module[(mod.name, target.id)] = name
                    module_counts[name] = module_counts.get(name, 0) + 1
                else:                                   # function local
                    name = lid or (f"{mod.short()}.{owner.qualname}"
                                   f".{target.id}")
                    locks.local[(mod.name, owner.qualname,
                                 target.id)] = name
            elif isinstance(target, ast.Attribute) \
                    and isinstance(target.value, ast.Name) \
                    and target.value.id == "self" \
                    and owner is not None and owner.class_name:
                cls = owner.class_name
                name = lid or f"{mod.short()}.{cls}.{target.attr}"
                locks.cls[(mod.name, cls, target.attr)] = name
    locks.singletons = {n for n, c in module_counts.items() if c == 1}
    return locks


def _enclosing_class_of(mod: ModuleInfo, node: ast.AST) -> Optional[str]:
    """Class whose body directly contains ``node`` (for dataclass
    field annotations)."""
    for n in ast.walk(mod.tree):
        if isinstance(n, ast.ClassDef) and node in n.body:
            return n.name
    return None


def _owner_of(mod: ModuleInfo, stmt: ast.AST) -> Optional[FunctionInfo]:
    """Innermost function whose body contains ``stmt`` (None = module
    level / class body)."""
    best: Optional[FunctionInfo] = None
    for fn in mod.functions.values():
        for n in ast.walk(fn.node):
            if n is stmt:
                if best is None \
                        or len(fn.qualname) > len(best.qualname):
                    best = fn
                break
    return best


# ---------------------------------------------------------- graph build
Site = Tuple[str, int]          # (abs path, line)


class LockGraph:
    def __init__(self) -> None:
        self.edges: Dict[Tuple[str, str], Site] = {}   # first witness
        self.adj: Dict[str, Set[str]] = {}
        self.self_deadlocks: List[Tuple[str, Site, str]] = []

    def add(self, src: str, dst: str, site: Site) -> None:
        if src == dst:
            return
        self.adj.setdefault(src, set()).add(dst)
        self.edges.setdefault((src, dst), site)

    def nodes(self) -> List[str]:
        out: Set[str] = set(self.adj)
        for tos in self.adj.values():
            out |= tos
        return sorted(out)

    def cycles(self) -> List[List[str]]:
        """Elementary cycles via SCC decomposition: one representative
        cycle per non-trivial strongly connected component."""
        index: Dict[str, int] = {}
        low: Dict[str, int] = {}
        on: Set[str] = set()
        stack: List[str] = []
        sccs: List[List[str]] = []
        counter = [0]

        def strong(v: str) -> None:      # iterative Tarjan
            work = [(v, iter(sorted(self.adj.get(v, ()))))]
            index[v] = low[v] = counter[0]
            counter[0] += 1
            stack.append(v)
            on.add(v)
            while work:
                node, it = work[-1]
                advanced = False
                for w in it:
                    if w not in index:
                        index[w] = low[w] = counter[0]
                        counter[0] += 1
                        stack.append(w)
                        on.add(w)
                        work.append((w, iter(sorted(
                            self.adj.get(w, ())))))
                        advanced = True
                        break
                    if w in on:
                        low[node] = min(low[node], index[w])
                if advanced:
                    continue
                work.pop()
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[node])
                if low[node] == index[node]:
                    comp = []
                    while True:
                        w = stack.pop()
                        on.discard(w)
                        comp.append(w)
                        if w == node:
                            break
                    if len(comp) > 1:
                        sccs.append(sorted(comp))

        for v in self.nodes():
            if v not in index:
                strong(v)
        return sccs

    def topo_order(self) -> List[str]:
        """Kahn topological order of the (acyclic part of the) graph —
        the derived hierarchy: a thread may only acquire rightward."""
        indeg: Dict[str, int] = {n: 0 for n in self.nodes()}
        for (_, dst) in self.edges:
            indeg[dst] += 1
        ready = sorted(n for n, d in indeg.items() if d == 0)
        out: List[str] = []
        while ready:
            n = ready.pop(0)
            out.append(n)
            for m in sorted(self.adj.get(n, ())):
                indeg[m] -= 1
                if indeg[m] == 0:
                    ready.append(m)
            ready.sort()
        return out


def _with_lock_ids(project: Project, locks: _Locks, mod: ModuleInfo,
                   fn: FunctionInfo,
                   item: ast.withitem) -> Optional[str]:
    expr = item.context_expr
    if isinstance(expr, ast.Name):
        return locks.resolve_name(mod, fn, expr.id)
    if isinstance(expr, ast.Attribute) \
            and isinstance(expr.value, ast.Name):
        if expr.value.id == "self":
            return locks.resolve_self_attr(mod, fn.class_name, expr.attr)
        # module-level lock referenced through an import alias
        tgt = mod.from_imports.get(expr.value.id)
        if tgt is not None:
            return locks.module.get((tgt[0], expr.attr)) \
                or locks.module.get((tgt[0] + "." + tgt[1], expr.attr))
        if expr.value.id in mod.imports:
            return locks.module.get((mod.imports[expr.value.id],
                                     expr.attr))
    return None


def _analyze_function(project: Project, locks: _Locks,
                      fn: FunctionInfo, graph: LockGraph,
                      direct: Dict[FunctionInfo, Set[str]],
                      callees: Dict[FunctionInfo, Set[FunctionInfo]],
                      held_calls: List[Tuple[Tuple[str, ...],
                                             FunctionInfo, Site]]) -> None:
    mod = fn.module
    direct.setdefault(fn, set())
    callees.setdefault(fn, set())

    def walk(node: ast.AST, held: Tuple[str, ...]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return                  # separate function, own analysis
        if isinstance(node, (ast.With, ast.AsyncWith)):
            new_held = held
            for item in node.items:
                # the context expression evaluates while every
                # earlier-listed (and outer) lock is held — a call in
                # it (`with a, open_b():`) contributes edges too
                walk(item.context_expr, new_held)
                lid = _with_lock_ids(project, locks, mod, fn, item)
                if lid is None:
                    continue
                site = (mod.path, node.lineno)
                for h in new_held:
                    graph.add(h, lid, site)
                direct[fn].add(lid)
                new_held = new_held + (lid,)
            for child in node.body:
                walk(child, new_held)
            return
        if isinstance(node, ast.Call):
            tgt = project.resolve_call(mod, fn, node)
            if tgt is not None:
                callees[fn].add(tgt)
                if held:
                    held_calls.append(
                        (held, tgt, (mod.path, node.lineno)))
        for child in ast.iter_child_nodes(node):
            walk(child, held)

    for child in ast.iter_child_nodes(fn.node):
        walk(child, ())


def build_lock_graph(project: Project) \
        -> Tuple[LockGraph, List[Finding]]:
    locks = _collect_locks(project)
    graph = LockGraph()
    direct: Dict[FunctionInfo, Set[str]] = {}
    callees: Dict[FunctionInfo, Set[FunctionInfo]] = {}
    held_calls: List[Tuple[Tuple[str, ...], FunctionInfo, Site]] = []
    for mod in project.iter_modules():
        for fn in mod.functions.values():
            _analyze_function(project, locks, fn, graph, direct,
                              callees, held_calls)

    # transitive may-acquire fixpoint
    may: Dict[FunctionInfo, Set[str]] = {f: set(s)
                                         for f, s in direct.items()}
    changed = True
    while changed:
        changed = False
        for f, cs in callees.items():
            for c in cs:
                add = may.get(c, set()) - may[f]
                if add:
                    may[f] |= add
                    changed = True

    findings: List[Finding] = []
    for held, callee, site in held_calls:
        for lid in sorted(may.get(callee, ())):
            for h in held:
                if lid == h:
                    if lid in locks.singletons:
                        graph.self_deadlocks.append((lid, site,
                                                     callee.qualname))
                else:
                    graph.add(h, lid, site)

    seen_self: Set[Tuple[str, Site]] = set()
    for lid, site, callee in graph.self_deadlocks:
        if (lid, site) in seen_self:
            continue
        seen_self.add((lid, site))
        findings.append(Finding(
            RULE, site[0], site[1], 0,
            f"call made while holding {lid!r} reaches a re-acquire of "
            f"the same non-reentrant lock (via `{callee}`) — "
            "guaranteed self-deadlock"))

    for comp in graph.cycles():
        sites = []
        for i, a in enumerate(comp):
            b = comp[(i + 1) % len(comp)]
            s = graph.edges.get((a, b)) or graph.edges.get((b, a))
            if s:
                sites.append(f"{os.path.basename(s[0])}:{s[1]}")
        anchor = None
        for i, a in enumerate(comp):
            b = comp[(i + 1) % len(comp)]
            anchor = graph.edges.get((a, b))
            if anchor:
                break
        anchor = anchor or (next(iter(project.by_path)), 1)
        findings.append(Finding(
            RULE, anchor[0], anchor[1], 0,
            "lock-order cycle between {" + ", ".join(comp) + "} — two "
            "threads taking these locks in opposite orders deadlock; "
            f"witnesses: {', '.join(sites) or 'n/a'}"))
    return graph, findings


def run(project: Project) -> List[Finding]:
    _, findings = build_lock_graph(project)
    return findings


def render_graph(project: Project) -> str:
    """Human dump for the CLI's ``--lock-graph``: every derived edge
    with its witness site, then the topological hierarchy."""
    graph, findings = build_lock_graph(project)
    lines = ["derived lock-acquisition graph "
             f"({len(graph.edges)} edge(s)):"]
    root = os.getcwd()
    for (src, dst), (path, line) in sorted(graph.edges.items()):
        try:
            rel = os.path.relpath(path, root)
        except ValueError:          # pragma: no cover — windows drives
            rel = path
        lines.append(f"  {src} -> {dst}   ({rel}:{line})")
    if findings:
        lines.append("CYCLES / self-deadlocks:")
        lines.extend("  " + f.render() for f in findings)
    else:
        lines.append("acyclic; hierarchy (acquire left before right):")
        lines.append("  " + " < ".join(graph.topo_order()))
    return "\n".join(lines)

"""Reference-config compatibility: the ``paddle.*`` import surface.

Reference v1 configs begin with ``from paddle.trainer_config_helpers
import *`` and their data providers with ``from paddle.trainer.
PyDataProvider2 import *`` (e.g. ``benchmark/paddle/image/alexnet.py:3``,
``provider.py:4``).  SURVEY §7 requires those files to run UNMODIFIED, so
this module registers alias modules under ``sys.modules['paddle'...]``
that re-export the TPU-native DSL / provider protocol.

Because the era's configs are Python 2 (``xrange``, ``file``,
``cPickle`` — ``benchmark/paddle/rnn/rnn.py:29``, ``imdb.py:38``),
``install()`` also adds those three names as py2 compatibility shims
(``builtins.xrange = range`` etc.) — they only exist in processes that
opted into the v1 config path (CLI / config_parser).
"""

from __future__ import annotations

import builtins
import pickle
import sys
import types

_installed = False


class CacheType:
    """``PyDataProvider2.CacheType`` (cache levels NO_CACHE /
    CACHE_PASS_IN_MEM, ``python/paddle/trainer/PyDataProvider2.py``)."""

    NO_CACHE = 0
    CACHE_PASS_IN_MEM = 1


def _mk_module(name: str, attrs: dict) -> types.ModuleType:
    mod = types.ModuleType(name)
    for k, v in attrs.items():
        setattr(mod, k, v)
    mod.__all__ = [k for k in attrs if not k.startswith("_")]
    sys.modules[name] = mod
    return mod


def install() -> None:
    """Idempotently register ``paddle``, ``paddle.trainer_config_helpers``,
    ``paddle.trainer.PyDataProvider2`` aliases + py2 shims."""
    global _installed
    if _installed:
        _install_py2_shims()
        return
    if "paddle" in sys.modules:
        # a foreign 'paddle' (e.g. a real PaddlePaddle install) is
        # already imported: don't shadow it, don't latch — a later
        # call can still install if it gets removed
        if not getattr(sys.modules["paddle"], "_paddle_tpu_compat", False):
            import warnings
            warnings.warn(
                "paddle_tpu.compat.install(): a 'paddle' module is "
                "already imported; not overriding it with the "
                "paddle_tpu aliases")
            _install_py2_shims()
            return
        _installed = True
        _install_py2_shims()
        return

    import importlib

    from ..config.config_parser import config_namespace
    from ..data import feeder
    provider_mod = importlib.import_module("paddle_tpu.data.provider")

    helpers = config_namespace()
    paddle = _mk_module("paddle", {})
    paddle._paddle_tpu_compat = True
    trainer = _mk_module("paddle.trainer", {})
    _mk_module("paddle.trainer_config_helpers", helpers)

    pdp2 = {
        "provider": provider_mod.provider,
        "CacheType": CacheType,
    }
    for k in ("dense_vector", "integer_value", "integer_value_sequence",
              "sparse_binary_vector", "sparse_float_vector",
              "dense_vector_sequence", "sparse_binary_vector_sequence",
              "sparse_float_vector_sequence"):
        if hasattr(feeder, k):
            pdp2[k] = getattr(feeder, k)
    _mk_module("paddle.trainer.PyDataProvider2", pdp2)

    from ..config import config_parser
    _mk_module("paddle.trainer.config_parser",
               {"parse_config": config_parser.parse_config})

    paddle.trainer = trainer
    paddle.trainer_config_helpers = sys.modules[
        "paddle.trainer_config_helpers"]
    trainer.PyDataProvider2 = sys.modules["paddle.trainer.PyDataProvider2"]
    trainer.config_parser = sys.modules["paddle.trainer.config_parser"]

    # v2 user scripts: ``import paddle.v2 as paddle`` runs against the
    # real paddle_tpu.v2 package (plus per-submodule aliases so
    # ``from paddle.v2.X import ...`` resolves)
    import paddle_tpu.v2 as v2mod

    sys.modules["paddle.v2"] = v2mod
    paddle.v2 = v2mod
    # alias every paddle_tpu.v2 submodule (derived, so new submodules
    # are picked up automatically)
    for sub, m in vars(v2mod).items():
        if isinstance(m, types.ModuleType) \
                and m.__name__.startswith("paddle_tpu.v2."):
            sys.modules[f"paddle.v2.{sub}"] = m
    # third dotted level: dataset corpora are classes on the dataset
    # module; register them so ``import paddle.v2.dataset.mnist``
    # resolves (the import system honors existing sys.modules entries)
    for cname, cobj in vars(v2mod.dataset).items():
        if isinstance(cobj, type) and not cname.startswith("_"):
            sys.modules[f"paddle.v2.dataset.{cname}"] = cobj

    _install_py2_shims()
    _installed = True


def _install_py2_shims() -> None:
    if not hasattr(builtins, "xrange"):
        builtins.xrange = range
    if not hasattr(builtins, "file"):
        builtins.file = open
    if "cPickle" not in sys.modules:
        sys.modules["cPickle"] = pickle

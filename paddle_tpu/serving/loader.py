"""Standalone serving loader.

Deliberately imports ONLY ``jax``, ``numpy`` and the stdlib — never the
layer engine, DSL, or trainer.  This is the deployment boundary the
reference draws with ``paddle/capi`` (a C library embedding none of the
trainer): a serving process ships the artifact directory plus this one
file's worth of code.

    from paddle_tpu.serving.loader import ServedModel
    model = ServedModel.load("exported_mnist/")
    probs = model(img=batch)["prediction"]
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict

import time

import jax
# explicit submodule import: pre-0.5 jax does not expose jax.export as
# an attribute of the bare `import jax`
import jax.export
import numpy as np

# telemetry is OPTIONAL here: paddle_tpu.observe.metrics is stdlib-only,
# but a serving process that ships just this file (the capi-style
# deployment story) runs fine without it
try:
    from ..observe import counter as _counter, histogram as _histogram
except ImportError:  # standalone copy: no package context
    _counter = _histogram = None


class ServedModel:
    """A loaded StableHLO inference artifact (pure function; reentrant —
    the multi-thread story ``_create_shared_param`` exists for in the
    reference C API comes for free)."""

    def __init__(self, manifest: Dict[str, Any], exported):
        self.manifest = manifest
        self._exported = exported
        self.feed_names = [f["name"] for f in manifest["feeds"]]
        self.fetch_names = list(manifest["fetches"])

    @classmethod
    def load(cls, dirname: str) -> "ServedModel":
        with open(os.path.join(dirname, "manifest.json")) as f:
            manifest = json.load(f)
        if manifest.get("format") != "paddle-tpu-serving":
            raise ValueError(f"{dirname}: not a paddle-tpu-serving artifact")
        if manifest.get("version", 0) > 1:
            raise ValueError(
                f"{dirname}: artifact version {manifest['version']} is newer "
                "than this loader (supports <= 1)")
        with open(os.path.join(dirname, manifest["module"]), "rb") as f:
            exported = jax.export.deserialize(f.read())
        return cls(manifest, exported)

    def __call__(self, **feeds) -> Dict[str, np.ndarray]:
        args = []
        for spec in self.manifest["feeds"]:
            name = spec["name"]
            if name not in feeds:
                raise KeyError(f"missing feed {name!r} "
                               f"(expected {self.feed_names})")
            a = np.asarray(feeds[name], dtype=spec["dtype"])
            want = spec["shape"]
            got = list(a.shape)
            if len(want) != len(got) or any(
                    w is not None and w != g for w, g in zip(want, got)):
                raise ValueError(
                    f"feed {name!r}: shape {got} incompatible with {want}")
            args.append(a)
        t0 = time.perf_counter()
        outs = self._exported.call(*args)
        result = {n: np.asarray(v)
                  for n, v in zip(self.fetch_names, outs)}
        # np.asarray above synchronized the device, so this is true
        # end-to-end inference latency
        if _histogram is not None:
            _histogram("serve_infer_seconds",
                       "end-to-end ServedModel call latency").observe(
                time.perf_counter() - t0)
            _counter("serve_requests", "ServedModel calls served").inc()
        return result

"""Standalone serving loader.

Deliberately imports ONLY ``jax``, ``numpy`` and the stdlib — never the
layer engine, DSL, or trainer.  This is the deployment boundary the
reference draws with ``paddle/capi`` (a C library embedding none of the
trainer): a serving process ships the artifact directory plus this one
file's worth of code.

    from paddle_tpu.serving.loader import ServedModel
    model = ServedModel.load("exported_mnist/")
    probs = model(img=batch)["prediction"]

Version 2 artifacts (int8 weights-only quantization, see
``serving/export.py``) carry their weights in ``weights.npz`` instead of
baked constants: quantized entries are dequantized ONCE at load —
``w = q.astype(f32) * scale`` per output channel, cast to the manifest's
``dequant_dtype`` (bf16 by default) — and prepended to every module
call.  Version-1 artifacts load exactly as before.
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import os
from typing import Any, Dict, List

import time

import jax
# explicit submodule import: pre-0.5 jax does not expose jax.export as
# an attribute of the bare `import jax`
import jax.export
import jax.numpy as jnp
import numpy as np


def _np_dtype(name: str) -> np.dtype:
    """Dtype by name, bfloat16 included — plain ``np.dtype("bfloat16")``
    raises (the type lives in ml_dtypes, re-exported by jax.numpy);
    local on purpose so the standalone-copy deployment keeps working."""
    try:
        return np.dtype(name)
    except TypeError:
        return np.dtype(getattr(jnp, name))


def _dequantize(q: np.ndarray, scale: np.ndarray, axis: int,
                dtype: np.dtype) -> np.ndarray:
    shape = [1] * q.ndim
    shape[axis % q.ndim] = -1
    return (q.astype(np.float32) * scale.reshape(shape)).astype(dtype)

# telemetry is OPTIONAL here: paddle_tpu.observe is stdlib-only, but a
# serving process that ships just this file (the capi-style deployment
# story) runs fine without it
try:
    from ..observe import counter as _counter, gauge as _gauge
    from ..observe import histogram as _histogram
    from ..observe import fleet as _fleet, trace as _trace
except ImportError:  # standalone copy: no package context
    _counter = _gauge = _histogram = _trace = _fleet = None


class TornArtifact(ValueError):
    """An artifact whose payload does not match its manifest digests —
    truncated, bit-flipped, or mid-write.  The rollout pipeline treats
    this as "skip and keep serving the old model", never as fatal."""


def verify_artifact(dirname: str, manifest: Dict[str, Any] = None) -> bool:
    """Re-hash every payload file against the manifest ``files`` section.

    Returns True when the digests all match, False when the manifest
    predates digest stamping (nothing to verify against — pre-rollout
    artifacts still load, they just cannot be proven whole).  Raises
    :class:`TornArtifact` on a missing, short, long, or corrupt file.
    """
    if manifest is None:
        manifest = read_manifest(dirname)
    files = manifest.get("files")
    if not files:
        return False
    for fn, meta in sorted(files.items()):
        path = os.path.join(dirname, fn)
        if not os.path.exists(path):
            raise TornArtifact(f"{dirname}: missing payload file {fn!r}")
        size = os.path.getsize(path)
        if size != meta["bytes"]:
            raise TornArtifact(
                f"{dirname}: {fn} is {size} bytes, manifest says "
                f"{meta['bytes']} (truncated or partially written)")
        h = hashlib.sha256()
        with open(path, "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                h.update(chunk)
        if h.hexdigest() != meta["sha256"]:
            raise TornArtifact(f"{dirname}: {fn} sha256 mismatch "
                               f"(expected {meta['sha256'][:12]}…, got "
                               f"{h.hexdigest()[:12]}…)")
    return True


def artifact_digest(manifest: Dict[str, Any]) -> str:
    """Content-stable version id of an artifact: sha256 over the sorted
    per-file digests.  Two exports of identical payload bytes get the
    same id; any payload change changes it.  This is the
    ``model_version`` the server, fleet topology, and rollout
    coordinator all speak."""
    files = manifest.get("files")
    if not files:
        return "unversioned"
    h = hashlib.sha256()
    for fn in sorted(files):
        h.update(fn.encode())
        h.update(files[fn]["sha256"].encode())
    return h.hexdigest()


def read_manifest(dirname: str, max_version: int = 2) -> Dict[str, Any]:
    """Read and validate an artifact manifest (format + version gate);
    shared by :class:`ServedModel` and the decoder-artifact loader in
    ``serving/model.py``."""
    with open(os.path.join(dirname, "manifest.json")) as f:
        manifest = json.load(f)
    if manifest.get("format") != "paddle-tpu-serving":
        raise ValueError(f"{dirname}: not a paddle-tpu-serving artifact")
    if manifest.get("version", 0) > max_version:
        raise ValueError(
            f"{dirname}: artifact version {manifest['version']} is newer "
            f"than this loader (supports <= {max_version})")
    return manifest


def load_weight_entries(dirname: str,
                        wsec: Dict[str, Any]) -> List[np.ndarray]:
    """Materialize a manifest ``weights`` section: dequantize int8
    entries ONCE (per-output-channel ``q.astype(f32) * scale``), pass
    raw entries through, in manifest order."""
    weights: List[np.ndarray] = []
    npz = np.load(os.path.join(dirname, wsec["file"]))
    for e in wsec["entries"]:
        dt = _np_dtype(e["dtype"])
        if e["quantized"]:
            ax = e.get("axis")
            w = _dequantize(npz["q::" + e["name"]],
                            npz["s::" + e["name"]],
                            -1 if ax is None else ax, dt)
        else:
            w = np.asarray(npz["w::" + e["name"]], dtype=dt)
        weights.append(w)
    return weights


class ServedModel:
    """A loaded StableHLO inference artifact (pure function; reentrant —
    the multi-thread story ``_create_shared_param`` exists for in the
    reference C API comes for free)."""

    def __init__(self, manifest: Dict[str, Any], exported,
                 weights: List[np.ndarray] = ()):
        self.manifest = manifest
        self._exported = exported
        # v2: dequantized weights in call order, committed to device
        # ONCE here — passing host numpy instead would re-pay the full
        # weight H2D transfer on every inference call
        self._weights = [jax.device_put(w) for w in weights]
        self.feed_names = [f["name"] for f in manifest["feeds"]]
        self.fetch_names = list(manifest["fetches"])

    @classmethod
    def load(cls, dirname: str, verify: bool = True) -> "ServedModel":
        if _fleet is not None:
            # a process loading a serving artifact pushes (when
            # --fleet_addr is set) as role=serving; a dict write, free
            _fleet.set_identity(role="serving")
        manifest = read_manifest(dirname)
        if verify:
            # raises TornArtifact on digest mismatch; manifests without
            # a files section (pre-rollout exports) load unverified
            verify_artifact(dirname, manifest)
        if manifest.get("kind") == "decoder":
            raise ValueError(
                f"{dirname}: decoder artifact — load it with "
                "paddle_tpu.serving.DecoderModel.from_artifact, not "
                "ServedModel (no StableHLO module to call)")
        with open(os.path.join(dirname, manifest["module"]), "rb") as f:
            exported = jax.export.deserialize(f.read())
        weights: List[np.ndarray] = []
        wsec = manifest.get("weights")
        if wsec:   # v2 quantized artifact: dequantize once, at load
            weights = load_weight_entries(dirname, wsec)
        return cls(manifest, exported, weights)

    def __call__(self, n_requests: int = 1, **feeds) -> Dict[str, np.ndarray]:
        """Run one inference call carrying ``n_requests`` logical
        requests (a continuous-batching decode step batches N of them
        into one launch).  Telemetry is per REQUEST, not per launch:
        ``serve_requests`` ticks by N and ``serve_infer_seconds`` gets N
        observations, so fleet dashboards and reservoir quantiles stay
        comparable between batched and sequential serving."""
        if n_requests < 1:
            raise ValueError(f"n_requests must be >= 1, got {n_requests}")
        args = []
        for spec in self.manifest["feeds"]:
            name = spec["name"]
            if name not in feeds:
                raise KeyError(f"missing feed {name!r} "
                               f"(expected {self.feed_names})")
            a = np.asarray(feeds[name], dtype=_np_dtype(spec["dtype"]))
            want = spec["shape"]
            got = list(a.shape)
            if len(want) != len(got) or any(
                    w is not None and w != g for w, g in zip(want, got)):
                raise ValueError(
                    f"feed {name!r}: shape {got} incompatible with {want}")
            args.append(a)
        t0 = time.perf_counter()
        # per-request span: a serving process with tracing on gets one
        # trace per inference call (root span unless the caller opened
        # a request-level span around us)
        infer_span = _trace.span("serve_infer") if _trace is not None \
            else contextlib.nullcontext()
        with infer_span:
            outs = self._exported.call(*self._weights, *args)
            result = {n: np.asarray(v)
                      for n, v in zip(self.fetch_names, outs)}
        # np.asarray above synchronized the device, so this is true
        # end-to-end inference latency
        if _histogram is not None:
            # amortized per-request latency, observed once PER REQUEST:
            # quantiles over requests, not over launches of varying width
            per_req = (time.perf_counter() - t0) / n_requests
            h = _histogram("serve_infer_seconds",
                           "per-request ServedModel inference latency")
            for _ in range(n_requests):
                h.observe(per_req)
            _counter("serve_requests",
                     "requests served").inc(n_requests)
            _gauge("serve_batch_size",
                   "requests in the most recent inference launch").set(
                n_requests)
        return result

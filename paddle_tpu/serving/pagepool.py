"""Page-pool allocator for the serving KV cache.

One shared physical pool of ``n_pages`` uniform pages (the
``k_pages`` / ``v_pages`` axis of
:func:`~paddle_tpu.ops.pallas_attention.paged_decode_attention`) backs
every in-flight request; each request holds a **page table** — the
ordered list of physical page ids its tokens live in — and returns the
pages on completion for immediate recycling.  Uniform page granularity
makes the allocator trivially fragmentation-free: an allocation of
``ceil(tokens / page_size)`` pages succeeds exactly when that many free
pages exist, regardless of how churned the free list is (the
no-starvation bound the tests pin).  Recycling needs no pool scrub —
the decode kernel's pinned permuted-pool/stale-page immunity means a
page full of a dead request's K/V is invisible the moment no live page
table points at it.

Page 0 is reserved as the **scratch page**: the continuous-batching
decode loop pads its fixed-width batch with inactive slots whose page
table points at page 0 (length 1, zero query), so the kernel never
reads memory no slot owns.  Capacity is therefore ``n_pages - 1``.

Crash safety: :meth:`snapshot` persists the allocator state (tables +
lengths + a content checksum) with the write-tmp-fsync-rename
discipline of ``trainer/checkpoint.py``, so a SIGKILL mid-write leaves
either the previous complete snapshot or a tmp file nobody reads.
:meth:`PagePool.restore` refuses anything torn — bad JSON, a checksum
mismatch, or tables that violate the pool invariants — with
:class:`TornSnapshot`, and the server then starts FRESH rather than
serving a corrupt page table (the chaos contract in
``tests/test_serving_server.py``).
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
from typing import Dict, List, Optional

from ..analysis.lockorder import named_lock
from ..utils import enforce

try:                         # telemetry is optional at this layer
    from ..observe import gauge as _gauge
except ImportError:          # pragma: no cover - standalone copy
    _gauge = None

SNAPSHOT_VERSION = 1

#: Physical page id every padded (inactive) decode slot points at.
SCRATCH_PAGE = 0


class PagePoolExhausted(RuntimeError):
    """No free pages for the requested allocation (admission must wait
    for a release — the caller's backpressure signal, never a crash)."""


class TornSnapshot(ValueError):
    """A persisted pool snapshot failed validation (truncated write,
    bit rot, or tables violating the pool invariants).  The safe
    response is a fresh pool: recycling semantics make a cold start
    always correct, a torn table never."""


class PagePool:
    """Fixed-size physical page allocator with per-owner page tables.

    Thread-safe: admission and the decode loop share it, so every
    mutation runs under ``named_lock("serve.pagepool")`` (one graph
    node for the lock-order checker regardless of pool instances).
    """

    def __init__(self, n_pages: int, page_size: int):
        enforce(n_pages >= 2,
                f"PagePool needs >= 2 pages (1 scratch + capacity), "
                f"got {n_pages}")
        enforce(page_size >= 1, f"page_size must be >= 1, got {page_size}")
        self.n_pages = int(n_pages)
        self.page_size = int(page_size)
        self._lock = named_lock("serve.pagepool")
        # LIFO free list: the hottest (most recently released) pages are
        # reissued first — deliberate, it maximizes stale-data reuse and
        # keeps the kernel's stale-page immunity under permanent test
        self._free: List[int] = list(range(self.n_pages - 1, SCRATCH_PAGE,
                                           -1))
        self._tables: Dict[str, List[int]] = {}
        self._lengths: Dict[str, int] = {}
        self._publish()

    # ------------------------------------------------------------ queries
    @property
    def capacity(self) -> int:
        """Allocatable pages (scratch page excluded)."""
        return self.n_pages - 1

    def free_pages(self) -> int:
        with self._lock:
            return len(self._free)

    def used_pages(self) -> int:
        with self._lock:
            return sum(len(t) for t in self._tables.values())

    def pages_needed(self, n_tokens: int) -> int:
        return max((int(n_tokens) + self.page_size - 1) // self.page_size,
                   1)

    def table_of(self, owner: str) -> List[int]:
        with self._lock:
            enforce(owner in self._tables,
                    f"page pool: unknown owner {owner!r}")
            return list(self._tables[owner])

    def length_of(self, owner: str) -> int:
        with self._lock:
            enforce(owner in self._lengths,
                    f"page pool: unknown owner {owner!r}")
            return self._lengths[owner]

    def owners(self) -> List[str]:
        with self._lock:
            return sorted(self._tables)

    # -------------------------------------------------------- allocation
    def alloc(self, owner: str, n_tokens: int) -> List[int]:
        """Issue a page table covering ``n_tokens`` to a new owner.

        Raises :class:`PagePoolExhausted` (taking nothing) when fewer
        free pages exist than needed — with uniform pages this is the
        ONLY failure mode, so no allocation pattern can starve a
        request while enough free pages exist.
        """
        need = self.pages_needed(n_tokens)
        with self._lock:
            enforce(owner not in self._tables,
                    f"page pool: owner {owner!r} already holds pages")
            if need > len(self._free):
                raise PagePoolExhausted(
                    f"{owner}: need {need} pages, {len(self._free)} free "
                    f"(capacity {self.capacity})")
            pages = [self._free.pop() for _ in range(need)]
            self._tables[owner] = pages
            self._lengths[owner] = int(n_tokens)
            self._publish_locked()
            return list(pages)

    def extend(self, owner: str, n_tokens: int) -> List[int]:
        """Grow an owner's table to cover ``n_tokens`` total (the decode
        loop calls this when a generated token crosses a page
        boundary).  Returns the full updated table."""
        with self._lock:
            enforce(owner in self._tables,
                    f"page pool: unknown owner {owner!r}")
            enforce(n_tokens >= self._lengths[owner],
                    f"page pool: {owner!r} cannot shrink "
                    f"({n_tokens} < {self._lengths[owner]})")
            need = self.pages_needed(n_tokens)
            grow = need - len(self._tables[owner])
            if grow > len(self._free):
                raise PagePoolExhausted(
                    f"{owner}: extend needs {grow} pages, "
                    f"{len(self._free)} free")
            for _ in range(grow):
                self._tables[owner].append(self._free.pop())
            self._lengths[owner] = int(n_tokens)
            self._publish_locked()
            return list(self._tables[owner])

    def release(self, owner: str) -> int:
        """Return an owner's pages to the free list; returns how many.
        Releasing an unknown owner is a no-op returning 0 (the crash-
        recovery path releases optimistically)."""
        with self._lock:
            pages = self._tables.pop(owner, None)
            self._lengths.pop(owner, None)
            if pages is None:
                return 0
            self._free.extend(reversed(pages))
            self._publish_locked()
            return len(pages)

    # -------------------------------------------------------- invariants
    def verify(self) -> None:
        """Assert the pool invariants; raises ``ValueError`` naming the
        first breach.  A passing pool can always serve its tables:
        every page id in range, scratch never issued, no page owned
        twice or simultaneously free and owned, free + used = capacity.
        """
        with self._lock:
            seen: Dict[int, str] = {}
            for owner, pages in self._tables.items():
                if not pages:
                    raise ValueError(f"owner {owner!r}: empty page table")
                want = self.pages_needed(self._lengths.get(owner, -1))
                if len(pages) != want:
                    raise ValueError(
                        f"owner {owner!r}: table has {len(pages)} pages, "
                        f"length {self._lengths.get(owner)} needs {want}")
                for p in pages:
                    if not (SCRATCH_PAGE < p < self.n_pages):
                        raise ValueError(
                            f"owner {owner!r}: page id {p} out of range")
                    if p in seen:
                        raise ValueError(
                            f"page {p} owned by both {seen[p]!r} "
                            f"and {owner!r}")
                    seen[p] = owner
            for p in self._free:
                if not (SCRATCH_PAGE < p < self.n_pages):
                    raise ValueError(f"free-list page id {p} out of range")
                if p in seen:
                    raise ValueError(
                        f"page {p} both free and owned by {seen[p]!r}")
            if len(set(self._free)) != len(self._free):
                raise ValueError("free list holds duplicate page ids")
            if len(self._free) + len(seen) != self.capacity:
                raise ValueError(
                    f"page leak: {len(self._free)} free + {len(seen)} "
                    f"used != capacity {self.capacity}")

    # --------------------------------------------------------- snapshots
    def _state(self) -> Dict:
        return {"version": SNAPSHOT_VERSION, "n_pages": self.n_pages,
                "page_size": self.page_size,
                "free": list(self._free),
                "tables": {k: list(v) for k, v in self._tables.items()},
                "lengths": dict(self._lengths)}

    @staticmethod
    def _checksum(state: Dict) -> str:
        payload = json.dumps(state, sort_keys=True).encode()
        return hashlib.sha256(payload).hexdigest()

    def snapshot(self, path: str) -> str:
        """Atomically persist the allocator state: write to a tmp file
        in the target directory, fsync, then ``os.replace`` — a SIGKILL
        at any instant leaves either the old complete snapshot or none,
        never a half-written one under the real name."""
        with self._lock:
            state = self._state()
        doc = dict(state, checksum=self._checksum(state))
        d = os.path.dirname(os.path.abspath(path)) or "."
        fd, tmp = tempfile.mkstemp(prefix=".pagepool-", dir=d)
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(doc, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except Exception:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        return path

    @classmethod
    def restore(cls, path: str) -> "PagePool":
        """Rebuild a pool from a snapshot, REFUSING anything torn with
        :class:`TornSnapshot` — unparseable, checksum-mismatched, or
        invariant-violating state never becomes a servable pool."""
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            raise TornSnapshot(f"{path}: unreadable snapshot ({e})")
        if not isinstance(doc, dict) \
                or doc.get("version") != SNAPSHOT_VERSION:
            raise TornSnapshot(
                f"{path}: unknown snapshot version "
                f"{doc.get('version') if isinstance(doc, dict) else doc!r}")
        claimed = doc.pop("checksum", None)
        if claimed != cls._checksum(doc):
            raise TornSnapshot(f"{path}: checksum mismatch (torn write "
                               "or corruption)")
        try:
            pool = cls(doc["n_pages"], doc["page_size"])
            with pool._lock:
                pool._free = [int(p) for p in doc["free"]]
                pool._tables = {str(k): [int(p) for p in v]
                                for k, v in doc["tables"].items()}
                pool._lengths = {str(k): int(v)
                                 for k, v in doc["lengths"].items()}
            pool.verify()
        except (KeyError, TypeError, ValueError) as e:
            raise TornSnapshot(f"{path}: invalid snapshot state ({e})")
        pool._publish()
        return pool

    # --------------------------------------------------------- telemetry
    def _publish(self) -> None:
        with self._lock:
            self._publish_locked()

    def _publish_locked(self) -> None:
        if _gauge is None:
            return
        g = _gauge("serve_page_pool_pages",
                   "serving KV page pool census, labeled by state")
        g.set(len(self._free), state="free")
        g.set(self.capacity - len(self._free), state="used")

"""Zero-downtime train→serve pipeline (ISSUE 19 tentpole).

Three cooperating pieces connect the trainer's checkpoint dir to live
serving replicas without a restart anywhere:

1. :class:`CheckpointWatcher` — polls the checkpoint dir and picks up
   each **digest-verified retained** checkpoint exactly once, keyed by
   the checkpoint's manifest digest (``checkpoint_digest``), never a
   ``.corrupt-*`` quarantine or an in-progress ``.tmp-ckpt-*`` dir.
   Exactly-once survives watcher restarts with no side-channel state:
   every exported artifact records its ``source_ckpt_digest`` in its
   own manifest, and the watcher seeds its seen-set from the export
   dir on startup (:func:`exported_source_digests`).
2. :func:`export_checkpoint` — exports a checkpoint to a (quantized)
   serving artifact via the manifest-v2 decoder path, **under an
   export lease** (``trainer.checkpoint.export_lease``) so the
   retention sweep cannot reap the source mid-read, written
   ``.tmp-export-*`` + atomic rename to ``model-<digest12>`` so a
   SIGKILLed exporter never leaves a half-artifact that loads.
3. :func:`swap_from_artifact` — the full hot-swap: verify the artifact
   digests, build the :class:`~paddle_tpu.serving.model.DecoderModel`,
   run a first-inference probe — all OFF the decode thread — then park
   a :class:`~paddle_tpu.serving.server.SwapTicket` for the decode
   loop's atomic pointer flip.  Any failure before the flip rolls back
   (the old model was never unhooked) with the reason on ``/healthz``
   (``server.record_swap_failure``) and ``rollout_swap_total{result}``.

:class:`RollingCoordinator` upgrades the single-server swap to a
cluster rollout: it walks N serving replicas, reads ``/fleet/healthz``
before each step and **refuses to land on a degraded/missing replica**
(that replica keeps its old version — skipping preserves availability,
landing on a sick replica does not), POSTs ``/v1/swap`` to healthy
ones, and **halts the whole rollout** if a swap fails or a freshly
swapped replica degrades — the not-yet-walked replicas keep serving
the old version, which is the zero-downtime property.

Threads are ``ptpu-rollout-*`` (conftest leak guard + ptpu-lint);
spans are ``rollout_export`` / ``rollout_swap`` so one merged fleet
timeline shows a checkpoint travelling train→export→swap→first-request
across pids; metrics are the ``rollout_*`` family asserted by the
chaos gauntlet (``tests/test_rollout_chaos.py``).
"""

from __future__ import annotations

import contextlib
import http.client
import json
import os
import shutil
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..analysis.lockorder import named_condition
from ..trainer import checkpoint as _ckpt
from ..utils import FLAGS, enforce, get_logger
from .loader import TornArtifact, artifact_digest, read_manifest, \
    verify_artifact
from .model import DecoderConfig, DecoderModel, export_decoder
from .server import InferenceServer

try:                         # telemetry optional, as in server.py
    from ..observe import REGISTRY as _registry
    from ..observe import counter as _counter
    from ..observe import histogram as _histogram, trace as _trace
except ImportError:  # pragma: no cover - standalone copy
    _counter = _histogram = _trace = _registry = None

log = get_logger("serving")

#: Checkpoint-watcher thread name (leak guard + ptpu-lint contract).
WATCHER_THREAD_NAME = "ptpu-rollout-watcher"

#: Exported artifacts are ``model-<digest12>`` dirs; anything else in
#: the export dir is a temp, a quarantine, or not ours.
ARTIFACT_PREFIX = "model-"


def _span_export(**attrs):
    return contextlib.nullcontext() if _trace is None \
        else _trace.span("rollout_export", **attrs)


def _span_swap(**attrs):
    return contextlib.nullcontext() if _trace is None \
        else _trace.span("rollout_swap", **attrs)


def _span_coordinator(**attrs):
    return contextlib.nullcontext() if _trace is None \
        else _trace.span("rollout_coordinator", **attrs)


# ------------------------------------------------------------- export
def default_export_dir(save_dir: str) -> str:
    configured = str(FLAGS.get("rollout_export_dir") or "")
    return configured or os.path.join(save_dir, "export")


def export_checkpoint(ckpt_dir: str, export_dir: str, cfg: DecoderConfig,
                      quantize: Optional[str] = None,
                      dequant_dtype: str = "float32") -> str:
    """Export one checkpoint to a serving artifact; returns the final
    ``model-<digest12>`` dir.

    Runs under an export lease so ``sweep_retention`` cannot reap the
    source mid-read (the retention/export race), writes into a
    ``.tmp-export-*`` dir and atomically renames — a SIGKILL at any
    instant leaves either no artifact or a whole one, never a torn dir
    under the ``model-`` prefix.  An identical re-export (same content
    digest) is a no-op returning the existing dir."""
    if quantize is None:
        quantize = str(FLAGS.get("rollout_quantize"))
    q = None if quantize in ("none", "") else quantize
    os.makedirs(export_dir, exist_ok=True)
    src_digest = _ckpt.checkpoint_digest(ckpt_dir)
    t0 = time.perf_counter()
    with _span_export(ckpt=os.path.basename(ckpt_dir),
                      src_digest=(src_digest or "?")[:12]):
        try:
            with _ckpt.export_lease(ckpt_dir):
                params = _ckpt.load_params(ckpt_dir)
                tmp = tempfile.mkdtemp(dir=export_dir,
                                       prefix=".tmp-export-")
                try:
                    export_decoder(
                        params, cfg, tmp, quantize=q,
                        dequant_dtype=dequant_dtype,
                        extra_meta={
                            "source_ckpt_digest": src_digest,
                            "source_ckpt": os.path.basename(ckpt_dir)})
                    digest = artifact_digest(read_manifest(tmp))
                    final = os.path.join(
                        export_dir, f"{ARTIFACT_PREFIX}{digest[:12]}")
                    if os.path.isdir(final):
                        # identical content already exported (e.g. a
                        # restarted exporter re-walking the ckpt dir)
                        shutil.rmtree(tmp)
                    else:
                        os.replace(tmp, final)
                except Exception:
                    shutil.rmtree(tmp, ignore_errors=True)
                    raise
        except Exception:
            if _counter is not None:
                _counter("rollout_exports_total",
                         "checkpoint-to-artifact exports by outcome"
                         ).inc(result="error")
            raise
    if _counter is not None:
        _counter("rollout_exports_total",
                 "checkpoint-to-artifact exports by outcome").inc(
            result="ok")
        _histogram("rollout_export_seconds",
                   "wall time of one checkpoint-to-artifact export "
                   "(load + quantize + digest + rename)").observe(
            time.perf_counter() - t0)
    log.info("exported %s -> %s", ckpt_dir, final)
    return final


def _artifact_dirs(export_dir: str) -> List[str]:
    if not os.path.isdir(export_dir):
        return []
    return sorted(d for d in os.listdir(export_dir)
                  if d.startswith(ARTIFACT_PREFIX))


def _manifest_or_none(path: str) -> Optional[Dict[str, Any]]:
    try:
        return read_manifest(path)
    except (OSError, ValueError):
        return None


def latest_valid_artifact(export_dir: str) -> Optional[str]:
    """Newest digest-valid artifact in the export dir (by its
    ``exported_at_unix`` stamp, mtime fallback), scanning past torn
    ones — a restarted serving process resumes from here.  Never
    considers ``.tmp-export-*`` (in-progress/orphaned) dirs."""
    candidates: List[Tuple[float, str]] = []
    for name in _artifact_dirs(export_dir):
        path = os.path.join(export_dir, name)
        man = _manifest_or_none(path)
        if man is None:
            continue
        ts = man.get("exported_at_unix")
        if not isinstance(ts, (int, float)):
            try:
                ts = os.path.getmtime(path)
            except OSError:
                continue
        candidates.append((float(ts), path))
    for _, path in sorted(candidates, reverse=True):
        try:
            verify_artifact(path)
            return path
        except TornArtifact as e:
            log.warning("artifact %s failed verification (%s); "
                        "falling back", path, e)
    return None


def exported_source_digests(export_dir: str) -> set:
    """The ``source_ckpt_digest`` of every artifact already in the
    export dir — the watcher's exactly-once seen-set, reconstructed
    from the artifacts themselves so it survives restarts."""
    out = set()
    for name in _artifact_dirs(export_dir):
        man = _manifest_or_none(os.path.join(export_dir, name))
        if man and man.get("source_ckpt_digest"):
            out.add(man["source_ckpt_digest"])
    return out


def sweep_export_dir(export_dir: str, keep: Optional[int] = None
                     ) -> List[str]:
    """Retention for the export dir: keep the newest ``keep`` artifacts
    (default ``--ckpt_keep``), reap the rest plus orphaned
    ``.tmp-export-*`` dirs from SIGKILLed exporters (same stale-mtime
    rule as checkpoint temp dirs)."""
    keep = int(FLAGS.get("ckpt_keep")) if keep is None else keep
    if keep <= 0 or not os.path.isdir(export_dir):
        return []
    stamped = []
    for name in _artifact_dirs(export_dir):
        path = os.path.join(export_dir, name)
        man = _manifest_or_none(path) or {}
        ts = man.get("exported_at_unix")
        try:
            ts = float(ts) if isinstance(ts, (int, float)) \
                else os.path.getmtime(path)
        except OSError:
            continue
        stamped.append((ts, path))
    doomed = [p for _, p in sorted(stamped)[:-keep]]
    now = time.time()
    for name in (os.listdir(export_dir) if os.path.isdir(export_dir)
                 else []):
        if not name.startswith(".tmp-export-"):
            continue
        path = os.path.join(export_dir, name)
        try:
            if now - os.path.getmtime(path) > _ckpt._TMP_STALE_S:
                doomed.append(path)
        except OSError:
            pass
    removed = []
    for path in doomed:
        try:
            shutil.rmtree(path)
        except OSError as e:
            log.warning("export sweep could not remove %s (%s)", path, e)
            continue
        removed.append(path)
    if removed:
        log.info("export sweep (keep=%d): removed %s", keep,
                 [os.path.basename(p) for p in removed])
    return removed


# --------------------------------------------------------- canary bake
def _window_signals() -> Tuple[Optional[float], float]:
    """This process's windowed serving signals: (p99 TTFT seconds or
    None, failures/sec) over the last 60 s — the canary bake's
    before/after comparison inputs."""
    if _registry is None:
        return None, 0.0
    p99 = None
    h = _registry.find("serve_ttft_seconds")
    if h is not None and hasattr(h, "window_quantile"):
        p99 = h.window_quantile(0.99, 60.0)
    err = 0.0
    f = _registry.find("serve_request_failures")
    if f is not None and hasattr(f, "window_rate"):
        err = f.window_rate(60.0)
    return p99, err


def _canary_verdict(p99: Optional[float], err: float,
                    base_p99: Optional[float], base_err: float,
                    factor: float) -> Optional[str]:
    """None when the canary passes its bake, else the breach reason.

    p99 is compared only when BOTH sides measured one (no traffic on
    either side is no evidence).  A baseline error rate of zero makes
    ANY canary errors a breach — an error-free pool sets the bar."""
    if p99 is not None and base_p99 is not None and base_p99 > 0 \
            and p99 > factor * base_p99:
        return (f"canary p99 TTFT {p99 * 1e3:.1f}ms > {factor:g}x "
                f"baseline {base_p99 * 1e3:.1f}ms")
    err_bar = factor * base_err if base_err > 0 else 0.0
    if err > err_bar:
        if base_err > 0:
            return (f"canary error rate {err:.4f}/s > {factor:g}x "
                    f"baseline {base_err:.4f}/s")
        return (f"canary error rate {err:.4f}/s on an error-free "
                "baseline")
    return None


def _count_canary(result: str) -> None:
    if _counter is not None:
        _counter("rollout_canary_total",
                 "canary bakes by outcome (promoted | rolled_back | "
                 "missing)").inc(result=result)


def previous_artifact_dir(artifact: str, prev_version: str
                          ) -> Optional[str]:
    """The sibling ``model-<digest12>`` dir a canary rolls back to, or
    None when the predecessor artifact is gone (swept) or the server
    never served an artifact (``unversioned``)."""
    if not prev_version or "/" in prev_version:
        return None
    prev = os.path.join(os.path.dirname(artifact),
                        f"{ARTIFACT_PREFIX}{prev_version[:12]}")
    return prev if os.path.isdir(prev) else None


# ------------------------------------------------------------ hot swap
def _probe_model(model: DecoderModel) -> None:
    """First-inference probe: one tiny prefill on scratch pools.  A
    model that cannot produce finite logits for a one-token prompt must
    never reach the decode loop — this is the last gate before the
    pointer flip is requested."""
    import numpy as np

    k_pool, v_pool = model.new_pools(2, 8)
    nxt, logits, _, _ = model.prefill(
        k_pool, v_pool, [[0]], [1], [[1]])
    if not np.all(np.isfinite(np.asarray(logits))):
        raise FloatingPointError("probe inference produced non-finite "
                                 "logits")
    del nxt


def swap_from_artifact(server: InferenceServer, dirname: str,
                       inflight: Optional[str] = None,
                       timeout_s: float = 120.0,
                       canary: Optional[bool] = None,
                       bake_s: Optional[float] = None,
                       canary_factor: Optional[float] = None
                       ) -> Dict[str, Any]:
    """The full hot-swap pipeline against a live server.

    Verify → load → probe run on the CALLING thread (never the decode
    thread); only then is a :class:`SwapTicket` parked for the decode
    loop's pointer flip.  Every failure path rolls back — the old model
    keeps serving, ``/healthz`` carries the reason, and
    ``rollout_swap_total{result}`` records which gate failed.  Returns
    the swap report (``result`` ∈ ``ok`` | ``unchanged`` |
    ``rolled_back``).

    With ``--rollout_canary`` and ``--rollout_bake_s > 0`` (or the
    matching keyword overrides) a successful flip is followed by the
    single-server **bake-then-commit window**: the windowed p99 TTFT /
    error rate captured just before the flip become the baseline, the
    new model serves for ``bake_s`` seconds, and a post-bake comparison
    beyond ``canary_factor`` rolls BACK to the predecessor artifact
    (reason on ``/healthz``, ``rollout_canary_total{result}``) — the
    same policy :class:`RollingCoordinator` applies fleet-wide.  The
    bake blocks the CALLING thread, never the decode loop."""
    canary = bool(FLAGS.get("rollout_canary")) if canary is None \
        else bool(canary)
    bake_s = float(FLAGS.get("rollout_bake_s")) if bake_s is None \
        else float(bake_s)
    factor = float(FLAGS.get("rollout_canary_factor")) \
        if canary_factor is None else float(canary_factor)
    t0 = time.perf_counter()
    report: Dict[str, Any] = {"artifact": dirname}
    prev_version = server.model_version
    baseline = _window_signals() if canary and bake_s > 0 else None

    def _fail(gate: str, e: Exception) -> Dict[str, Any]:
        reason = f"{gate}: {type(e).__name__}: {e}"
        server.record_swap_failure(reason)
        if _counter is not None:
            _counter("rollout_swap_total",
                     "hot-swap attempts by outcome").inc(
                result=f"{gate}_failed")
        log.error("swap from %s rolled back (%s)", dirname, reason)
        report.update(result="rolled_back", error=reason)
        return report

    with _span_swap(artifact=os.path.basename(dirname)):
        try:
            manifest = read_manifest(dirname)
            verify_artifact(dirname, manifest)
        except Exception as e:  # noqa: BLE001 - every verify fault rolls back
            return _fail("verify", e)
        version = artifact_digest(manifest)
        report["version"] = version
        if version == server.model_version:
            report["result"] = "unchanged"
            return report
        try:
            # digests re-checked a moment ago; don't pay them twice
            model = DecoderModel.from_artifact(dirname, verify=False)
        except Exception as e:  # noqa: BLE001
            return _fail("load", e)
        try:
            _probe_model(model)
        except Exception as e:  # noqa: BLE001
            return _fail("probe", e)
        report["build_s"] = time.perf_counter() - t0
        ticket = server.request_swap(
            model, version=version, inflight=inflight,
            exported_at=manifest.get("exported_at_unix"))
        report.update(ticket.wait(timeout_s))
    report["swap_s"] = time.perf_counter() - t0
    if _histogram is not None:
        _histogram("rollout_swap_seconds",
                   "end-to-end hot-swap latency: artifact verify + "
                   "model build + probe (off-thread) + pointer flip"
                   ).observe(report["swap_s"])
    if canary and bake_s > 0 and report.get("result") == "ok":
        report.update(_bake_single(
            server, dirname, prev_version, baseline, bake_s, factor,
            inflight, timeout_s))
    return report


def _bake_single(server: InferenceServer, dirname: str,
                 prev_version: str,
                 baseline: Tuple[Optional[float], float],
                 bake_s: float, factor: float,
                 inflight: Optional[str],
                 timeout_s: float) -> Dict[str, Any]:
    """Single-server bake-then-commit: serve ``bake_s`` seconds on the
    fresh model, then compare the windowed signals against the
    pre-flip baseline.  Pass → promoted; breach → swap back to the
    predecessor artifact and record the reason on ``/healthz``."""
    base_p99, base_err = baseline
    time.sleep(bake_s)
    p99, err = _window_signals()
    reason = _canary_verdict(p99, err, base_p99, base_err, factor)
    out: Dict[str, Any] = {
        "canary": {"bake_s": bake_s,
                   "baseline_p99_s": base_p99, "p99_s": p99,
                   "baseline_error_rate_s": base_err,
                   "error_rate_s": err}}
    if reason is None:
        out["canary"]["result"] = "promoted"
        _count_canary("promoted")
        log.info("canary bake promoted %s (p99 %.1fms vs baseline "
                 "%.1fms)", os.path.basename(dirname),
                 (p99 or 0.0) * 1e3, (base_p99 or 0.0) * 1e3)
        return out
    out["canary"].update(result="rolled_back", reason=reason)
    out.update(result="rolled_back", error=reason)
    prev_dir = previous_artifact_dir(dirname, prev_version)
    if prev_dir is not None:
        rb = swap_from_artifact(server, prev_dir, inflight=inflight,
                                timeout_s=timeout_s, canary=False)
        out["canary"]["rollback"] = rb.get("result")
    else:
        out["canary"]["rollback"] = "no_predecessor"
        log.error("canary bake breached but predecessor artifact for "
                  "%r is gone; serving stays on the canary", prev_version)
    # AFTER the rollback swap (which clears the swap-error state): the
    # bake verdict is what /healthz must carry
    server.record_swap_failure(f"canary bake: {reason}")
    _count_canary("rolled_back")
    log.error("canary bake rolled back %s (%s)",
              os.path.basename(dirname), reason)
    return out


# ------------------------------------------------------------- watcher
class CheckpointWatcher:
    """Polls a checkpoint dir; exports each digest-verified retained
    checkpoint exactly once and (optionally) hot-swaps the newest
    export into a live server.

    Runs on the ``ptpu-rollout-watcher`` thread.  ``poll_once`` is the
    whole step and is callable synchronously from tests — the thread
    only adds the timer."""

    def __init__(self, save_dir: str, cfg: DecoderConfig,
                 export_dir: Optional[str] = None,
                 server: Optional[InferenceServer] = None,
                 poll_s: Optional[float] = None,
                 quantize: Optional[str] = None,
                 inflight: Optional[str] = None,
                 keep: Optional[int] = None):
        enforce(bool(FLAGS.get("rollout")),
                "rollout disabled (--rollout=false): no watcher")
        self.save_dir = save_dir
        self.cfg = cfg
        self.export_dir = export_dir or default_export_dir(save_dir)
        self.server = server
        self.poll_s = float(FLAGS.get("rollout_poll_s")
                            if poll_s is None else poll_s)
        self.quantize = quantize
        self.inflight = inflight
        self.keep = keep
        # exactly-once across restarts: the artifacts themselves are
        # the ledger
        self._seen = exported_source_digests(self.export_dir)
        self._cond = named_condition("rollout.watcher")
        self._stop = False
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------ step
    def poll_once(self) -> List[str]:
        """One watcher step: export every not-yet-seen digest-valid
        checkpoint (oldest first, so versions roll forward in order),
        swap the newest export into the server, sweep export
        retention.  Returns the artifact dirs exported this step."""
        exported: List[str] = []
        if not os.path.isdir(self.save_dir):
            return exported
        for name in _ckpt._pass_dirs(self.save_dir):
            # _pass_dirs yields only pass-* names: .corrupt-* and
            # .tmp-ckpt-* can never be picked up by construction
            path = os.path.join(self.save_dir, name)
            digest = _ckpt.checkpoint_digest(path)
            if digest is None or digest in self._seen:
                continue
            # quarantine=False: the trainer owns its checkpoint dir;
            # the watcher only refuses to export what fails its digest
            if _ckpt._verify_result(path) != "ok":
                log.warning("watcher: %s fails verification, skipping",
                            path)
                continue
            try:
                artifact = export_checkpoint(
                    path, self.export_dir, self.cfg,
                    quantize=self.quantize)
            except FileNotFoundError:
                # the retention sweep won the race before our lease
                # landed; the checkpoint is gone, nothing to export
                log.warning("watcher: %s vanished mid-export", path)
                continue
            self._seen.add(digest)
            exported.append(artifact)
        if exported and self.server is not None:
            # several checkpoints may have landed in one poll window:
            # serving only ever wants the newest
            swap_from_artifact(self.server, exported[-1],
                               inflight=self.inflight)
        if exported:
            sweep_export_dir(self.export_dir, keep=self.keep)
        return exported

    # ------------------------------------------------------- lifecycle
    def _loop(self) -> None:
        while True:
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 - one bad poll must not
                log.exception("watcher poll failed; retrying")  # die
            with self._cond:
                if self._stop:
                    return
                self._cond.wait(self.poll_s)
                if self._stop:
                    return

    def start(self) -> "CheckpointWatcher":
        if self._thread is None:
            self._stop = False
            self._thread = threading.Thread(
                target=self._loop, name=WATCHER_THREAD_NAME, daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=30.0)

    def __enter__(self) -> "CheckpointWatcher":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


# --------------------------------------------------------- coordinator
def _http_post_json(addr: str, path: str, payload: Dict[str, Any],
                    timeout_s: float = 120.0
                    ) -> Tuple[int, Dict[str, Any]]:
    """POST JSON to ``host:port``; returns (status, decoded body)."""
    host, _, port = addr.rpartition(":")
    body = json.dumps(payload)
    conn = http.client.HTTPConnection(host or "127.0.0.1", int(port),
                                      timeout=timeout_s)
    try:
        conn.request("POST", path, body=body,
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        data = resp.read()
    finally:
        conn.close()
    try:
        doc = json.loads(data.decode("utf-8", "replace"))
    except ValueError:
        doc = {"error": data[:200].decode("utf-8", "replace")}
    return resp.status, doc


class RollingCoordinator:
    """Fleet-supervised rolling rollout across N serving replicas.

    ``replicas`` is a sequence of ``(fleet_name, serve_addr)`` pairs:
    the fleet name keys the replica's row in the aggregator's
    ``/fleet/healthz`` rollup, the serve addr is its ``/v1/swap``
    endpoint.  Per replica: pre-check fleet health — a replica that is
    not ``ok`` is SKIPPED (it keeps its old version; landing a swap on
    a sick replica is how availability is lost, skipping is how it is
    kept) — then swap, then post-check: a failed swap or a freshly
    swapped replica going degraded HALTS the rollout so every
    not-yet-walked replica keeps serving the old version.

    With ``--rollout_canary`` the walk gains the **canary bake
    policy**: the first healthy replica swaps alone and bakes for
    ``--rollout_bake_s``, its windowed p99 TTFT / error rate (pushed
    on its fleet frames) compared against the POOLED remaining
    baseline replicas each poll.  A breach rolls the canary back to
    the predecessor artifact (reason lands on its ``/healthz``) and
    HALTS; a canary that vanishes mid-bake (fleet status missing —
    e.g. SIGKILL) halts without a rollback target; only a clean bake
    lets the remaining replicas walk.  Outcomes land on
    ``rollout_canary_total{result}``."""

    def __init__(self, fleet_addr: str,
                 replicas: Sequence[Tuple[str, str]],
                 inflight: Optional[str] = None,
                 swap_timeout_s: float = 120.0,
                 canary: Optional[bool] = None,
                 bake_s: Optional[float] = None,
                 canary_factor: Optional[float] = None,
                 poll_s: float = 0.5):
        self.fleet_addr = fleet_addr
        self.replicas = list(replicas)
        self.inflight = inflight
        self.swap_timeout_s = swap_timeout_s
        self.canary = bool(FLAGS.get("rollout_canary")) \
            if canary is None else bool(canary)
        self.bake_s = float(FLAGS.get("rollout_bake_s")) \
            if bake_s is None else float(bake_s)
        self.canary_factor = float(FLAGS.get("rollout_canary_factor")) \
            if canary_factor is None else float(canary_factor)
        self.poll_s = float(poll_s)

    def _fleet_status(self, name: str) -> str:
        from ..observe.fleet import _http_get

        try:
            doc = json.loads(_http_get(self.fleet_addr, "/fleet/healthz"))
        except (OSError, ValueError) as e:
            log.warning("coordinator: fleet healthz unreachable (%s)", e)
            return "missing"
        return str(doc.get("procs", {}).get(name, {}).get(
            "status", "missing"))

    def _fleet_topology(self) -> Dict[str, Any]:
        from ..observe.fleet import _http_get

        try:
            doc = json.loads(_http_get(self.fleet_addr,
                                       "/fleet/topology"))
        except (OSError, ValueError) as e:
            log.warning("coordinator: fleet topology unreachable (%s)",
                        e)
            return {}
        return doc.get("procs", {})

    def _bake_signals(self, canary_name: str
                      ) -> Tuple[Optional[float], float,
                                 Optional[float], float]:
        """(canary p99, canary err, pooled baseline p99, pooled
        baseline err) straight off the replicas' fleet frames."""
        procs = self._fleet_topology()
        c = procs.get(canary_name, {})
        c_p99 = c.get("ttft_p99_s")
        c_err = float(c.get("error_rate_s") or 0.0)
        base_p99s, base_errs = [], []
        for name, _ in self.replicas:
            if name == canary_name:
                continue
            p = procs.get(name, {})
            if p.get("ttft_p99_s") is not None:
                base_p99s.append(float(p["ttft_p99_s"]))
            base_errs.append(float(p.get("error_rate_s") or 0.0))
        base_p99 = sum(base_p99s) / len(base_p99s) if base_p99s \
            else None
        base_err = sum(base_errs) / len(base_errs) if base_errs \
            else 0.0
        return (None if c_p99 is None else float(c_p99), c_err,
                base_p99, base_err)

    def _step(self, name: str, addr: str, artifact: str
              ) -> Dict[str, Any]:
        step: Dict[str, Any] = {"replica": name, "addr": addr}
        status = self._fleet_status(name)
        step["pre_status"] = status
        if status != "ok":
            # refuse to land on a degraded/missing/down replica: it
            # keeps its old (working) version
            step["action"] = "skipped"
            if _counter is not None:
                _counter("rollout_coordinator_steps_total",
                         "rolling-rollout per-replica steps by outcome"
                         ).inc(result="skipped")
            log.warning("coordinator: skipping %s (fleet status %s)",
                        name, status)
            return step
        code, doc = _http_post_json(
            addr, "/v1/swap",
            {"artifact": artifact,
             **({"inflight": self.inflight} if self.inflight else {})},
            timeout_s=self.swap_timeout_s)
        step["swap"] = doc
        ok = code == 200 and doc.get("result") in ("ok", "unchanged")
        post = self._fleet_status(name)
        step["post_status"] = post
        # a replica that answered its swap 200 is alive; "missing" here
        # just means its next fleet frame has not landed yet — only an
        # actively DEGRADED verdict proves the new version made it sick
        step["action"] = "swapped" if ok and post != "degraded" \
            else "halt"
        if _counter is not None:
            _counter("rollout_coordinator_steps_total",
                     "rolling-rollout per-replica steps by outcome").inc(
                result="ok" if step["action"] == "swapped" else "halted")
        return step

    def _bake_fleet(self, name: str, addr: str, artifact: str,
                    prev_version: str) -> Dict[str, Any]:
        """Bake the freshly swapped canary: each ``poll_s`` read the
        fleet for its status and windowed signals vs the pooled
        baseline until ``bake_s`` elapses.  ``result`` is ``promoted``
        (clean bake), ``rolled_back`` (signal breach — the canary was
        swapped back to the predecessor with the reason), or
        ``missing`` (the canary vanished mid-bake; nothing to roll
        back, the halt keeps the baselines untouched)."""
        out: Dict[str, Any] = {"replica": name, "bake_s": self.bake_s}
        deadline = time.monotonic() + self.bake_s
        reason: Optional[str] = None
        while True:
            status = self._fleet_status(name)
            if status == "missing":
                out.update(result="missing",
                           reason="canary vanished mid-bake (fleet "
                                  "status missing)")
                _count_canary("missing")
                log.error("coordinator: canary %s went missing "
                          "mid-bake; halting", name)
                return out
            c_p99, c_err, b_p99, b_err = self._bake_signals(name)
            out.update(p99_s=c_p99, error_rate_s=c_err,
                       baseline_p99_s=b_p99,
                       baseline_error_rate_s=b_err)
            reason = _canary_verdict(c_p99, c_err, b_p99, b_err,
                                     self.canary_factor)
            if reason is not None:
                break
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                out["result"] = "promoted"
                _count_canary("promoted")
                log.info("coordinator: canary %s promoted after "
                         "%.1fs bake", name, self.bake_s)
                return out
            time.sleep(min(self.poll_s, max(remaining, 0.01)))
        out.update(result="rolled_back", reason=reason)
        prev_dir = previous_artifact_dir(artifact, prev_version)
        if prev_dir is not None:
            # the "reason" key makes the replica record the bake
            # verdict on its /healthz after the rollback swap lands
            code, doc = _http_post_json(
                addr, "/v1/swap",
                {"artifact": prev_dir,
                 "reason": f"canary bake: {reason}",
                 **({"inflight": self.inflight}
                    if self.inflight else {})},
                timeout_s=self.swap_timeout_s)
            out["rollback"] = doc.get("result") if code == 200 \
                else f"failed({code})"
        else:
            out["rollback"] = "no_predecessor"
        _count_canary("rolled_back")
        log.error("coordinator: canary %s rolled back (%s)", name,
                  reason)
        return out

    def _halt(self, report: Dict[str, Any], name: str,
              step: Dict[str, Any]) -> None:
        report["result"] = "halted"
        log.error("coordinator: rollout halted at %s "
                  "(swap=%s post_status=%s)", name,
                  (step.get("swap") or {}).get("result"),
                  step.get("post_status"))

    def rollout(self, artifact: str) -> Dict[str, Any]:
        """Walk the replicas; returns the rollout report.  ``result``
        is ``ok`` when every healthy replica swapped (skipped replicas
        are reported, not fatal), ``halted`` when a swap failed or a
        swapped replica degraded — the walk stops there and every
        remaining replica keeps the old version.

        Canary mode (``self.canary``, ≥ 2 replicas): the first healthy
        replica swaps and bakes (:meth:`_bake_fleet`) BEFORE anyone
        else moves; only ``promoted`` lets the walk continue, and the
        bake verdict rides the report under ``"canary"``."""
        report: Dict[str, Any] = {"artifact": artifact, "steps": [],
                                  "result": "ok"}
        with _span_coordinator(artifact=os.path.basename(artifact),
                               replicas=len(self.replicas)):
            walk = list(self.replicas)
            if self.canary and len(walk) > 1:
                baked = self._canary_leg(report, walk, artifact)
                if not baked:
                    walk = []
            for name, addr in walk:
                step = self._step(name, addr, artifact)
                report["steps"].append(step)
                if step["action"] == "halt":
                    self._halt(report, name, step)
                    break
        report["skipped"] = [s["replica"] for s in report["steps"]
                             if s["action"] == "skipped"]
        return report

    def _canary_leg(self, report: Dict[str, Any],
                    walk: List[Tuple[str, str]], artifact: str) -> bool:
        """Swap + bake the canary (first HEALTHY replica); consumes the
        walked prefix of ``walk`` in place.  True iff the remaining
        replicas may proceed."""
        while walk:
            name, addr = walk.pop(0)
            # the canary's pre-swap artifact digest is the rollback
            # target — read it before the swap changes it
            prev_version = str(self._fleet_topology().get(
                name, {}).get("model_version") or "")
            step = self._step(name, addr, artifact)
            report["steps"].append(step)
            if step["action"] == "halt":
                self._halt(report, name, step)
                return False
            if step["action"] == "swapped":
                bake = self._bake_fleet(name, addr, artifact,
                                        prev_version)
                report["canary"] = bake
                if bake["result"] != "promoted":
                    report["result"] = "halted"
                    log.error("coordinator: rollout halted — canary "
                              "%s bake %s", name, bake["result"])
                    return False
                return True
            # skipped: try the next replica as the canary
        return False   # nobody healthy enough to canary on

"""Deployment/serving — the TPU-native answer to ``paddle/capi``.

The reference ships a pure-C inference API
(``paddle/capi/gradient_machine.h:36-88``: create-for-inference, forward,
shared-parameter clones for multi-threaded serving) so trained models run
in processes that embed none of the training framework.  On TPU the
equivalent artifact is a **StableHLO module** (`jax.export`): the whole
inference function — topology and weights — compiled to a stable,
versioned IR that any PJRT runtime can execute with zero framework code.

- :mod:`paddle_tpu.serving.export` — build the artifact from a trained
  network / v2 inferer / framework program.
- :mod:`paddle_tpu.serving.loader` — standalone loader (imports only
  jax + numpy + json; never the layer engine).
"""

from .export import export_inference_fn, export_network  # noqa: F401
from .loader import ServedModel  # noqa: F401

"""Deployment/serving — the TPU-native answer to ``paddle/capi``.

The reference ships a pure-C inference API
(``paddle/capi/gradient_machine.h:36-88``: create-for-inference, forward,
shared-parameter clones for multi-threaded serving) so trained models run
in processes that embed none of the training framework.  On TPU the
equivalent artifact is a **StableHLO module** (`jax.export`): the whole
inference function — topology and weights — compiled to a stable,
versioned IR that any PJRT runtime can execute with zero framework code.

- :mod:`paddle_tpu.serving.export` — build the artifact from a trained
  network / v2 inferer / framework program.
- :mod:`paddle_tpu.serving.loader` — standalone loader (imports only
  jax + numpy + json; never the layer engine).

The request-serving half (ISSUE 16) turns the PR-14/15 kernels into
sustained req/s:

- :mod:`paddle_tpu.serving.pagepool` — shared KV page-pool allocator
  issuing per-request page tables, recycling freed pages, with atomic
  checksummed snapshots (crash safety).
- :mod:`paddle_tpu.serving.model` — decoder transformer whose prefill
  is one ``flash_attention_packed`` launch and whose decode step is
  ``paged_decode_attention`` over the pool; int8 decoder artifacts.
- :mod:`paddle_tpu.serving.server` — the continuous-batching
  :class:`InferenceServer` (admission queue, fixed-width decode batch,
  sequential kill switch, HTTP front, per-request telemetry).
- :mod:`paddle_tpu.serving.rollout` — the zero-downtime train→serve
  pipeline (ISSUE 19): checkpoint watcher, atomic hot-swap with
  rollback, fleet-supervised rolling rollout.
"""

from .export import export_inference_fn, export_network  # noqa: F401
from .loader import (ServedModel, TornArtifact,  # noqa: F401
                     artifact_digest, verify_artifact)
from .pagepool import (PagePool, PagePoolExhausted,  # noqa: F401
                       TornSnapshot)

# The decoder/server half pulls in the attention kernels
# (paddle_tpu.ops) — resolved lazily (PEP 562) so a process that only
# LOADS artifacts keeps the loader contract: importing
# paddle_tpu.serving.loader must never drag in the layer engine
# (pinned by tests/test_serving.py's fresh-process check).
_LAZY = {
    "DecoderConfig": "model", "DecoderModel": "model",
    "export_decoder": "model", "init_decoder_params": "model",
    "InferenceServer": "server", "Request": "server",
    "SwapTicket": "server",
    "CheckpointWatcher": "rollout", "RollingCoordinator": "rollout",
    "swap_from_artifact": "rollout", "export_checkpoint": "rollout",
    "latest_valid_artifact": "rollout", "sweep_export_dir": "rollout",
}


def __getattr__(name):
    if name in _LAZY:
        import importlib

        mod = importlib.import_module(f".{_LAZY[name]}", __name__)
        return getattr(mod, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

"""Export an inference function to a standalone StableHLO artifact.

Artifact layout (versioned, like ``trainer/checkpoint.py``'s manifest):

    <dir>/manifest.json   {"format": "paddle-tpu-serving", "version": 1,
                           "feeds": [{name, shape, dtype}...],
                           "fetches": [name...],
                           "module": "model.stablehlo",
                           "batch_polymorphic": bool}
    <dir>/model.stablehlo  jax.export serialized bytes (weights baked in)

Weights are baked into the module as constants — the artifact is the
whole deployable model, the same way ``paddle_merge_model`` fuses config
+ parameters into one self-contained file for the C inference API
(``paddle/trainer/MergeModel.cpp``, ``paddle/capi/gradient_machine.h:36``).

Reference parity: replaces ``paddle_gradient_machine_create_for_inference
_with_parameters`` + ``_forward``; multi-threaded serving needs no
``_create_shared_param`` equivalent — the loaded module is a pure
function, reentrant by construction.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Sequence

import jax
# explicit submodule import: pre-0.5 jax does not expose jax.export as
# an attribute of the bare `import jax`
import jax.export
import numpy as np

from ..utils import enforce, get_logger

log = get_logger("serving")

FORMAT_NAME = "paddle-tpu-serving"
FORMAT_VERSION = 1
MODULE_FILE = "model.stablehlo"


def _feed_spec(name: str, arr: np.ndarray, poly_batch: bool) -> Dict[str, Any]:
    return {"name": name,
            "shape": [None if (poly_batch and i == 0) else int(d)
                      for i, d in enumerate(np.shape(arr))],
            "dtype": str(np.asarray(arr).dtype)}


def export_inference_fn(fn, example_feed: Dict[str, Any], dirname: str,
                        fetch_names: Sequence[str],
                        batch_polymorphic: bool = True) -> str:
    """Export ``fn(feed_dict) -> dict[name, array]`` to ``dirname``.

    ``fn`` must be traceable (weights closed over; they are baked into
    the module).  With ``batch_polymorphic`` the leading axis of every
    feed is exported symbolically so one artifact serves any batch size.
    """
    feed_names = sorted(example_feed)
    examples = {k: np.asarray(example_feed[k]) for k in feed_names}

    def flat_fn(*args):
        out = fn(dict(zip(feed_names, args)))
        return [out[n] for n in fetch_names]

    def specs(poly: bool):
        if not poly:
            return [jax.ShapeDtypeStruct(a.shape, a.dtype)
                    for a in (examples[k] for k in feed_names)]
        scope = jax.export.SymbolicScope()
        b = jax.export.symbolic_shape("b", scope=scope)[0]
        out = []
        for k in feed_names:
            a = examples[k]
            shape = ((b,) + a.shape[1:]) if a.ndim >= 1 else a.shape
            out.append(jax.ShapeDtypeStruct(shape, a.dtype))
        return out

    # one artifact serves every runtime: lower for cpu AND tpu
    # (jax.export multi-platform lowering)
    platforms = ("cpu", "tpu")

    def do_export(poly: bool):
        return jax.export.export(jax.jit(flat_fn),
                                 platforms=platforms)(*specs(poly))

    exported = None
    poly = batch_polymorphic
    if poly:
        try:
            exported = do_export(True)
        except Exception as e:  # shapes data-dependent on batch size
            log.warning(
                "batch-polymorphic export failed (%s: %s); falling back "
                "to fixed batch %s", type(e).__name__, e,
                {k: np.shape(v) for k, v in examples.items()})
            poly = False
    if exported is None:
        exported = do_export(False)

    os.makedirs(dirname, exist_ok=True)
    with open(os.path.join(dirname, MODULE_FILE), "wb") as f:
        f.write(exported.serialize())
    manifest = {
        "format": FORMAT_NAME,
        "version": FORMAT_VERSION,
        "feeds": [_feed_spec(k, examples[k], poly) for k in feed_names],
        "fetches": list(fetch_names),
        "module": MODULE_FILE,
        "batch_polymorphic": poly,
    }
    with open(os.path.join(dirname, "manifest.json"), "w") as f:
        json.dump(manifest, f, indent=2)
    return dirname


def export_network(network, params: Dict[str, jax.Array],
                   example_feed: Dict[str, Any], dirname: str,
                   output_names: Optional[Sequence[str]] = None,
                   buffers: Optional[Dict[str, jax.Array]] = None,
                   batch_polymorphic: bool = True) -> str:
    """Export a layer-engine :class:`NeuralNetwork` for inference.

    ``output_names`` defaults to the network's declared outputs (cost
    layers replaced by their prediction input, as ``v2.infer`` does).

    :class:`SequenceBatch` feeds are flattened into TWO artifact feeds —
    ``<name>`` (padded data) and ``<name>_len`` (int32 lengths) — so the
    standalone loader's plain-array contract covers sequence models.
    """
    from ..core.sequence import SequenceBatch, value_of

    if output_names is None:
        output_names = []
        for n in network.output_names:
            lyr = network.layers.get(n)
            if lyr is not None and getattr(lyr, "is_cost", False) \
                    and lyr.conf.inputs:
                output_names.append(lyr.conf.inputs[0].input_layer_name)
            else:
                output_names.append(n)
    enforce(output_names, "export_network: no output names")
    bufs = buffers if buffers is not None else network.init_buffers()

    seq_feeds = {k for k, v in example_feed.items()
                 if isinstance(v, SequenceBatch)}
    for k in seq_feeds:
        enforce(k + "_len" not in example_feed,
                f"export_network: feed {k + '_len'!r} collides with the "
                f"flattened lengths of sequence feed {k!r}")
    flat_examples: Dict[str, Any] = {}
    for k, v in example_feed.items():
        if k in seq_feeds:
            flat_examples[k] = np.asarray(v.data)
            flat_examples[k + "_len"] = np.asarray(v.length)
        else:
            flat_examples[k] = v

    def fn(feed):
        rebuilt = {k: SequenceBatch(feed[k], feed[k + "_len"])
                   if k in seq_feeds else feed[k] for k in example_feed}
        values, _ = network.forward(params, rebuilt, bufs,
                                    is_training=False, only=output_names)
        return {n: value_of(values[n]) for n in output_names}

    return export_inference_fn(fn, flat_examples, dirname, output_names,
                               batch_polymorphic=batch_polymorphic)

"""Export an inference function to a standalone StableHLO artifact.

Artifact layout (versioned, like ``trainer/checkpoint.py``'s manifest):

    <dir>/manifest.json   {"format": "paddle-tpu-serving", "version": 1,
                           "feeds": [{name, shape, dtype}...],
                           "fetches": [name...],
                           "module": "model.stablehlo",
                           "batch_polymorphic": bool}
    <dir>/model.stablehlo  jax.export serialized bytes (weights baked in)

Weights are baked into the module as constants — the artifact is the
whole deployable model, the same way ``paddle_merge_model`` fuses config
+ parameters into one self-contained file for the C inference API
(``paddle/trainer/MergeModel.cpp``, ``paddle/capi/gradient_machine.h:36``).

**Version 2 — int8 weights-only post-training quantization**
(``quantize="int8"``): instead of baking fp32 constants, every ≥2-D
float parameter is stored as int8 with per-output-channel symmetric
scales (last axis; ``scale_c = max|w[..., c]| / 127``, no zero point) in
``weights.npz``, and the module takes the weights as runtime ARGUMENTS.
The loader dequantizes to ``dequant_dtype`` (bf16 by default — the TPU
serving compute dtype) once at load and prepends them on every call;
1-D tensors (biases, BN stats) ship raw fp32.  The manifest gains:

    "version": 2,
    "weights": {"file": "weights.npz",
                "scheme": "int8-weights-per-channel",
                "dequant_dtype": "bfloat16",
                "entries": [{name, shape, dtype, quantized, axis}...]}

Every manifest additionally carries a ``files`` section (per-file
SHA-256 + byte size, written LAST like the checkpoint manifest) and an
``exported_at_unix`` stamp — ``loader.verify_artifact`` re-hashes the
payload against it, which is what makes a truncated or bit-flipped
artifact detectable before it ever reaches a live server
(``serving/rollout.py``).  Manifests without a ``files`` section
(pre-rollout artifacts) still load; they just cannot be
digest-verified.

Version-1 artifacts keep loading unchanged (``serving/loader.py``
supports both).  The measurement template is the Gemma-on-TPU study
(PAPERS.md, arxiv 2605.25645): ~4× smaller weight payload, with the
latency/accuracy delta reported by ``bench.py --only precision``.

Reference parity: replaces ``paddle_gradient_machine_create_for_inference
_with_parameters`` + ``_forward``; multi-threaded serving needs no
``_create_shared_param`` equivalent — the loaded module is a pure
function, reentrant by construction.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
# explicit submodule import: pre-0.5 jax does not expose jax.export as
# an attribute of the bare `import jax`
import jax.export
import numpy as np

from ..core.dtypes import dtype_name, np_dtype
from ..utils import enforce, get_logger

log = get_logger("serving")

FORMAT_NAME = "paddle-tpu-serving"
FORMAT_VERSION = 1
QUANT_FORMAT_VERSION = 2
MODULE_FILE = "model.stablehlo"
WEIGHTS_FILE = "weights.npz"
QUANT_SCHEME = "int8-weights-per-channel"


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def artifact_file_digests(dirname: str, fnames: Sequence[str]
                          ) -> Dict[str, Dict[str, Any]]:
    """The manifest ``files`` section: per-file SHA-256 + size, same
    shape as ``trainer/checkpoint.py``'s checkpoint manifest so the
    rollout pipeline verifies artifacts and checkpoints identically.
    The manifest itself is excluded (it is written last and carries
    the digests)."""
    return {fn: {"sha256": _sha256_file(os.path.join(dirname, fn)),
                 "bytes": os.path.getsize(os.path.join(dirname, fn))}
            for fn in fnames}


def stamp_manifest(manifest: Dict[str, Any], dirname: str,
                   fnames: Sequence[str]) -> Dict[str, Any]:
    """Add the integrity + provenance fields every serving manifest
    carries: per-file digests and the export wall-clock time.  Must be
    called after every payload file is on disk, right before the
    manifest write (the manifest is the commit record)."""
    manifest["files"] = artifact_file_digests(dirname, fnames)
    manifest["exported_at_unix"] = time.time()
    return manifest


def _feed_spec(name: str, arr: np.ndarray, poly_batch: bool) -> Dict[str, Any]:
    return {"name": name,
            "shape": [None if (poly_batch and i == 0) else int(d)
                      for i, d in enumerate(np.shape(arr))],
            # dtype_name handles bfloat16 feeds (str() of the ml_dtypes
            # extension type round-trips through core.dtypes.np_dtype)
            "dtype": dtype_name(np.asarray(arr).dtype)}


# ------------------------------------------------------------ int8 PTQ
def quantize_int8(arr: np.ndarray, axis: int = -1
                  ) -> Tuple[np.ndarray, np.ndarray]:
    """Symmetric per-channel int8 quantization along ``axis`` (the
    output-channel axis: HWIO convs and [in, out] fc weights both keep
    it last).  Returns ``(q int8, scale f32[channels])`` with
    ``q = clip(round(w / scale), -127, 127)`` — max dequant error is
    ``scale/2`` per channel."""
    a = np.asarray(arr, np.float32)
    ax = axis % a.ndim
    red = tuple(i for i in range(a.ndim) if i != ax)
    amax = np.max(np.abs(a), axis=red) if red else np.abs(a)
    scale = (np.maximum(amax, 1e-12) / 127.0).astype(np.float32)
    shape = [1] * a.ndim
    shape[ax] = -1
    q = np.clip(np.round(a / scale.reshape(shape)), -127, 127) \
        .astype(np.int8)
    return q, scale


def dequantize_int8(q: np.ndarray, scale: np.ndarray, axis: int = -1,
                    dtype="float32") -> np.ndarray:
    """Inverse of :func:`quantize_int8` (the loader's load-time path)."""
    shape = [1] * q.ndim
    shape[axis % q.ndim] = -1
    return (q.astype(np.float32) * scale.reshape(shape)) \
        .astype(np_dtype(dtype))


def _quantizable(arr: np.ndarray) -> bool:
    """Weights-only: ≥2-D float tensors (matmul/conv weights).  1-D
    tensors — biases, BN scale/offset/running stats — ship raw fp32;
    they are tiny and precision-critical."""
    return arr.ndim >= 2 and np.issubdtype(arr.dtype, np.floating)


def quantize_weight_store(params: Dict[str, Any], dequant_dtype: str
                          ) -> Tuple[Dict[str, np.ndarray],
                                     List[Dict[str, Any]]]:
    """Build the version-2 ``weights.npz`` store + manifest entries for
    a parameter dict: quantizable tensors as ``q::name`` / ``s::name``
    (int8 + per-channel scales, dequantized to ``dequant_dtype`` at
    load), the rest raw as ``w::name``.  Entry order follows sorted
    names — the load order contract of ``loader.load_weight_entries``.
    Shared by the network int8 export and the decoder-artifact export
    (``serving/model.py``)."""
    deq_dt = np_dtype(dequant_dtype)
    store: Dict[str, np.ndarray] = {}
    entries: List[Dict[str, Any]] = []
    for name in sorted(params):
        arr = np.asarray(params[name])
        if _quantizable(arr):
            q, scale = quantize_int8(arr, axis=-1)
            store["q::" + name] = q
            store["s::" + name] = scale
            entries.append({"name": name, "shape": list(arr.shape),
                            "dtype": dtype_name(deq_dt),
                            "quantized": True, "axis": -1})
        else:
            raw = arr.astype(np.float32) \
                if np.issubdtype(arr.dtype, np.floating) else arr
            store["w::" + name] = raw
            entries.append({"name": name, "shape": list(arr.shape),
                            "dtype": dtype_name(raw.dtype),
                            "quantized": False, "axis": None})
    return store, entries


def _feed_arg_specs(examples: Dict[str, np.ndarray],
                    feed_names: Sequence[str], poly: bool):
    if not poly:
        return [jax.ShapeDtypeStruct(a.shape, a.dtype)
                for a in (examples[k] for k in feed_names)]
    scope = jax.export.SymbolicScope()
    b = jax.export.symbolic_shape("b", scope=scope)[0]
    out = []
    for k in feed_names:
        a = examples[k]
        shape = ((b,) + a.shape[1:]) if a.ndim >= 1 else a.shape
        out.append(jax.ShapeDtypeStruct(shape, a.dtype))
    return out


def _serialize_export(flat_fn, specs, examples, batch_polymorphic: bool):
    """jax.export with the batch-polymorphic-then-fixed fallback; one
    artifact serves every runtime (multi-platform cpu+tpu lowering)."""
    platforms = ("cpu", "tpu")

    def do_export(poly: bool):
        return jax.export.export(jax.jit(flat_fn),
                                 platforms=platforms)(*specs(poly))

    exported = None
    poly = batch_polymorphic
    if poly:
        try:
            exported = do_export(True)
        except Exception as e:  # shapes data-dependent on batch size
            log.warning(
                "batch-polymorphic export failed (%s: %s); falling back "
                "to fixed batch %s", type(e).__name__, e,
                {k: np.shape(v) for k, v in examples.items()})
            poly = False
    if exported is None:
        exported = do_export(False)
    return exported, poly


def export_inference_fn(fn, example_feed: Dict[str, Any], dirname: str,
                        fetch_names: Sequence[str],
                        batch_polymorphic: bool = True) -> str:
    """Export ``fn(feed_dict) -> dict[name, array]`` to ``dirname``.

    ``fn`` must be traceable (weights closed over; they are baked into
    the module).  With ``batch_polymorphic`` the leading axis of every
    feed is exported symbolically so one artifact serves any batch size.
    """
    feed_names = sorted(example_feed)
    examples = {k: np.asarray(example_feed[k]) for k in feed_names}

    def flat_fn(*args):
        out = fn(dict(zip(feed_names, args)))
        return [out[n] for n in fetch_names]

    def specs(poly: bool):
        return _feed_arg_specs(examples, feed_names, poly)

    exported, poly = _serialize_export(flat_fn, specs, examples,
                                       batch_polymorphic)

    os.makedirs(dirname, exist_ok=True)
    with open(os.path.join(dirname, MODULE_FILE), "wb") as f:
        f.write(exported.serialize())
    manifest = {
        "format": FORMAT_NAME,
        "version": FORMAT_VERSION,
        "feeds": [_feed_spec(k, examples[k], poly) for k in feed_names],
        "fetches": list(fetch_names),
        "module": MODULE_FILE,
        "batch_polymorphic": poly,
    }
    stamp_manifest(manifest, dirname, [MODULE_FILE])
    with open(os.path.join(dirname, "manifest.json"), "w") as f:
        json.dump(manifest, f, indent=2)
    return dirname


def _resolve_output_names(network, output_names):
    if output_names is None:
        output_names = []
        for n in network.output_names:
            lyr = network.layers.get(n)
            if lyr is not None and getattr(lyr, "is_cost", False) \
                    and lyr.conf.inputs:
                output_names.append(lyr.conf.inputs[0].input_layer_name)
            else:
                output_names.append(n)
    enforce(output_names, "export_network: no output names")
    return list(output_names)


def _flatten_example_feed(example_feed: Dict[str, Any]):
    """SequenceBatch feeds → two plain-array feeds (``<name>`` +
    ``<name>_len``); returns (flat examples, seq feed names)."""
    from ..core.sequence import SequenceBatch

    seq_feeds = {k for k, v in example_feed.items()
                 if isinstance(v, SequenceBatch)}
    for k in seq_feeds:
        enforce(k + "_len" not in example_feed,
                f"export_network: feed {k + '_len'!r} collides with the "
                f"flattened lengths of sequence feed {k!r}")
    flat_examples: Dict[str, Any] = {}
    for k, v in example_feed.items():
        if k in seq_feeds:
            flat_examples[k] = np.asarray(v.data)
            flat_examples[k + "_len"] = np.asarray(v.length)
        else:
            flat_examples[k] = v
    return flat_examples, seq_feeds


def export_network(network, params: Dict[str, jax.Array],
                   example_feed: Dict[str, Any], dirname: str,
                   output_names: Optional[Sequence[str]] = None,
                   buffers: Optional[Dict[str, jax.Array]] = None,
                   batch_polymorphic: bool = True,
                   quantize: Optional[str] = None,
                   dequant_dtype: str = "bfloat16") -> str:
    """Export a layer-engine :class:`NeuralNetwork` for inference.

    ``output_names`` defaults to the network's declared outputs (cost
    layers replaced by their prediction input, as ``v2.infer`` does).

    :class:`SequenceBatch` feeds are flattened into TWO artifact feeds —
    ``<name>`` (padded data) and ``<name>_len`` (int32 lengths) — so the
    standalone loader's plain-array contract covers sequence models.

    ``quantize="int8"`` writes a **version-2 weights-only quantized**
    artifact (see the module docstring): per-channel symmetric int8
    weights in ``weights.npz``, dequantized to ``dequant_dtype`` at
    load and fed to the module as runtime arguments.  Default (None)
    keeps the version-1 weights-baked artifact byte-for-byte.
    """
    from ..core.sequence import SequenceBatch, value_of

    output_names = _resolve_output_names(network, output_names)
    bufs = buffers if buffers is not None else network.init_buffers()
    flat_examples, seq_feeds = _flatten_example_feed(example_feed)

    def fwd(weights, feed):
        rebuilt = {k: SequenceBatch(feed[k], feed[k + "_len"])
                   if k in seq_feeds else feed[k] for k in example_feed}
        values, _ = network.forward(weights, rebuilt, bufs,
                                    is_training=False, only=output_names)
        return {n: value_of(values[n]) for n in output_names}

    if quantize is None:
        return export_inference_fn(
            lambda feed: fwd(params, feed), flat_examples, dirname,
            output_names, batch_polymorphic=batch_polymorphic)
    enforce(quantize == "int8",
            f"export_network: unknown quantize scheme {quantize!r} "
            "(supported: 'int8')")
    return _export_network_int8(
        fwd, params, flat_examples, dirname, output_names,
        batch_polymorphic=batch_polymorphic, dequant_dtype=dequant_dtype)


def _export_network_int8(fwd, params, flat_examples, dirname,
                         output_names, batch_polymorphic: bool,
                         dequant_dtype: str) -> str:
    """The version-2 quantized export: weights become module ARGUMENTS
    (quantized entries at ``dequant_dtype``, raw 1-D tensors at their
    own dtype), stored int8+scales / raw in ``weights.npz``."""
    wnames = sorted(params)
    feed_names = sorted(flat_examples)
    examples = {k: np.asarray(flat_examples[k]) for k in feed_names}
    deq_dt = np_dtype(dequant_dtype)

    store, entries = quantize_weight_store(params, dequant_dtype)
    warg_specs = [jax.ShapeDtypeStruct(tuple(e["shape"]), np_dtype(e["dtype"]))
                  for e in entries]

    nw = len(wnames)

    def flat_fn(*args):
        weights = dict(zip(wnames, args[:nw]))
        out = fwd(weights, dict(zip(feed_names, args[nw:])))
        return [out[n] for n in output_names]

    def specs(poly: bool):
        return warg_specs + _feed_arg_specs(examples, feed_names, poly)

    exported, poly = _serialize_export(flat_fn, specs, examples,
                                       batch_polymorphic)

    os.makedirs(dirname, exist_ok=True)
    with open(os.path.join(dirname, MODULE_FILE), "wb") as f:
        f.write(exported.serialize())
    np.savez(os.path.join(dirname, WEIGHTS_FILE), **store)
    manifest = {
        "format": FORMAT_NAME,
        "version": QUANT_FORMAT_VERSION,
        "feeds": [_feed_spec(k, examples[k], poly) for k in feed_names],
        "fetches": list(output_names),
        "module": MODULE_FILE,
        "batch_polymorphic": poly,
        "weights": {
            "file": WEIGHTS_FILE,
            "scheme": QUANT_SCHEME,
            "dequant_dtype": dtype_name(deq_dt),
            "entries": entries,
        },
    }
    stamp_manifest(manifest, dirname, [MODULE_FILE, WEIGHTS_FILE])
    with open(os.path.join(dirname, "manifest.json"), "w") as f:
        json.dump(manifest, f, indent=2)
    quant_bytes = sum(v.nbytes for k, v in store.items()
                      if k.startswith(("q::", "s::")))
    raw_bytes = sum(
        int(np.prod(e["shape"])) * 4 for e in entries if e["quantized"])
    log.info("int8 export: %d/%d tensors quantized, weight payload "
             "%.2f MB (fp32 would be %.2f MB)",
             sum(e["quantized"] for e in entries), len(entries),
             quant_bytes / 1e6, raw_bytes / 1e6)
    return dirname
